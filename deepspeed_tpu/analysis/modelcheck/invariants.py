"""fleetcheck invariants H1–H7: checker-side safety oracles.

Every invariant is recomputed HERE, from first principles, over the live
host objects — deliberately NOT by calling the scheduler's own
``assert_page_invariants`` (a mutant that forgets to assert internally
must still be caught; the seeded ``handoff_leak`` fault does exactly
that). The scheduler's internal asserts still run where production runs
them, and any AssertionError they raise surfaces as an
``INTERNAL_ASSERT`` violation in explore.py.

The registry (ids are the contract the CLI, docs, CI greps and the
--mutate smokes all name):

- **H1  pool conservation** — per PagePool: free + live == num_pages,
  the free list holds only refcount-0 pages, no negative refcounts.
- **H2  cross-tier key ledger** — per HostPageStore: the resident key
  set equals exactly {in-flight promotions} ∪ {slot host_pages keys} ∪
  {prefix-cache host-tier keys}; pins reference resident keys only.
- **H3  refcount parity** — per pool: every page's refcount equals the
  number of independently-recomputed holders (slot page tables + prefix
  cache LRU entries). A leaked page (refs with no holder) or a
  use-after-free (holder with no ref) lands here.
- **H4  reference validity** — page ids in range, no slot referencing a
  free page, ``-1`` placeholders paired with host_pages entries, and
  terminal (DONE/EVICTED) states holding no page or key references.
- **H5  handoff / slot atomicity** — a request is slotted on at most
  one replica, live states are slotted-or-queued exactly where their
  status says, and no state sits in two admission queues.
- **H6  backoff monotonicity** — the retry_after hint's backoff delta
  is positive and non-decreasing in the request's attempt count.
- **H7  penalized-bypass discipline** — a repetition-penalized request
  never reuses prefix-cache tokens, never carries draft state, is never
  scheduled with a nonzero spec window (per-plan check), and is never
  handed off across replicas (checked at the handoff event).

Liveness ids (explore.py): **LIVELOCK** (fingerprint recurrence at
equal cumulative progress during the all-EOS drain) and
**NO_QUIESCENCE** (drain horizon exhausted).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ...serving.request import RequestStatus

__all__ = ["CheckFailure", "check_world", "check_event", "INVARIANTS"]

INVARIANTS: Dict[str, str] = {
    "H1": "per-pool page conservation (free + live == num_pages)",
    "H2": "cross-tier host key ledger (store keys == referenced keys)",
    "H3": "refcount parity (pool refcounts == recomputed holders)",
    "H4": "page-reference validity (range, -1/host pairing, terminals)",
    "H5": "handoff/slot atomicity (one replica, status <-> placement)",
    "H6": "retry_after backoff positive + monotone in attempts",
    "H7": "penalized requests bypass prefix/spec/handoff",
    "LIVELOCK": "zero-progress cycle under the all-EOS drain",
    "NO_QUIESCENCE": "drain horizon exhausted before quiescence",
    "INTERNAL_ASSERT": "a production-side assertion tripped",
}


class CheckFailure(Exception):
    """One invariant violated; ``invariant`` names the registry id."""

    def __init__(self, invariant: str, message: str):
        self.invariant = invariant
        super().__init__(message)


def _holders(sched) -> Tuple[Dict[int, int], List]:
    """Recompute expected per-page refcounts from holders: slotted /
    queued request page tables + prefix-cache LRU entries."""
    exp: Dict[int, int] = {}
    live_states = [s for s in sched.slots if s is not None]
    live_states += list(sched.queue)
    for st in live_states:
        for p in st.pages:
            if p != -1:
                exp[p] = exp.get(p, 0) + 1
    if sched.prefix_cache is not None:
        for p in sched.prefix_cache.held_pages:
            exp[p] = exp.get(p, 0) + 1
    return exp, live_states


def _check_pool(world, rid: int, sched) -> None:
    pool = sched.pool
    n = pool.num_pages
    # H1: conservation
    if pool.free_count + pool.live_count != n:
        raise CheckFailure(
            "H1", f"r{rid}: pool conservation broken — free "
                  f"{pool.free_count} + live {pool.live_count} != {n}"
        )
    for p in pool._free:
        if pool.refcount[p] != 0:
            raise CheckFailure(
                "H1", f"r{rid}: page {p} on the free list with refcount "
                      f"{int(pool.refcount[p])}"
            )
    if (pool.refcount < 0).any():
        raise CheckFailure("H1", f"r{rid}: negative refcount in pool")

    # H3: refcount parity against independently recomputed holders
    exp, live_states = _holders(sched)
    for p in range(n):
        actual = int(pool.refcount[p])
        want = exp.get(p, 0)
        if actual != want:
            kind = ("page leak (refs with no holder)" if actual > want
                    else "dangling holder (holder with no ref)")
            raise CheckFailure(
                "H3", f"r{rid}: refcount parity broken on page {p}: "
                      f"pool says {actual}, holders say {want} — {kind}"
            )

    # H4: reference validity
    for st in live_states:
        rid_s = st.request.request_id
        for li, p in enumerate(st.pages):
            if p == -1:
                if li not in st.host_pages:
                    raise CheckFailure(
                        "H4", f"r{rid}: {rid_s} logical page {li} is -1 "
                              f"with no host_pages entry"
                    )
                continue
            if not (0 <= p < n):
                raise CheckFailure(
                    "H4", f"r{rid}: {rid_s} references out-of-range "
                          f"page {p}"
                )
            if pool.refcount[p] <= 0:
                raise CheckFailure(
                    "H4", f"r{rid}: {rid_s} references FREED page {p}"
                )
        for li in st.host_pages:
            if li >= len(st.pages) or st.pages[li] != -1:
                raise CheckFailure(
                    "H4", f"r{rid}: {rid_s} host_pages[{li}] not backed "
                          f"by a -1 placeholder"
                )


def _check_store(world, rid: int, sched, store) -> None:
    exp_keys = set(sched._inflight)
    for st in sched.slots:
        if st is not None:
            exp_keys.update(k for k, _ in st.host_pages.values())
    cache = sched.prefix_cache
    if cache is not None:
        exp_keys.update(skey for skey, _ in cache._host_full.values())
    actual = set(store.keys())
    if actual != exp_keys:
        leaked = sorted(actual - exp_keys)
        dangling = sorted(exp_keys - actual)
        raise CheckFailure(
            "H2", f"r{rid}: host key ledger broken — "
                  f"leaked keys {leaked}, dangling refs {dangling}"
        )
    if cache is not None:
        for skey, pins in cache._host_pins.items():
            if pins <= 0:
                raise CheckFailure(
                    "H2", f"r{rid}: non-positive pin count {pins} on "
                          f"host key {skey}"
                )
            if skey not in actual:
                raise CheckFailure(
                    "H2", f"r{rid}: pinned host key {skey} not resident"
                )


def _check_placement(world) -> None:
    for i, st in enumerate(world.states):
        if st is None:
            continue
        owner = world.replica_of(st)  # raises H5 on double-slotting
        queued_on = [
            rep.replica_id for rep in world.replicas
            if st in rep.engine.scheduler.queue
        ]
        if len(queued_on) > 1:
            raise CheckFailure(
                "H5", f"q{i} sits in {len(queued_on)} admission queues"
            )
        if st.status in (RequestStatus.PREFILL, RequestStatus.DECODE):
            if owner is None:
                raise CheckFailure(
                    "H5", f"q{i} is {st.status.value} but slotted on no "
                          f"replica"
                )
            if st.slot is None:
                raise CheckFailure("H5", f"q{i} active with slot=None")
        elif st.status is RequestStatus.QUEUED:
            if not queued_on or owner is not None:
                raise CheckFailure(
                    "H5", f"q{i} is queued but placement says "
                          f"slotted={owner} queues={queued_on}"
                )
        else:  # DONE / EVICTED
            if owner is not None or queued_on:
                raise CheckFailure(
                    "H5", f"q{i} is terminal ({st.status.value}) but "
                          f"still placed (slot on r{owner}, "
                          f"queues {queued_on})"
                )
            if st.pages or st.host_pages:
                raise CheckFailure(
                    "H4", f"q{i} is terminal but still holds "
                          f"{len(st.pages)} pages / "
                          f"{len(st.host_pages)} host keys"
                )


def _check_backoff(world) -> None:
    by_req: Dict[int, List[Tuple[int, float]]] = {}
    for (req, attempt), delta in world.backoff.items():
        by_req.setdefault(req, []).append((attempt, delta))
    for req, entries in by_req.items():
        entries.sort()
        prev = 0.0
        for attempt, delta in entries:
            if delta <= 0:
                raise CheckFailure(
                    "H6", f"q{req} attempt {attempt}: non-positive "
                          f"backoff delta {delta}"
                )
            if delta + 1e-9 < prev:
                raise CheckFailure(
                    "H6", f"q{req} attempt {attempt}: backoff delta "
                          f"{delta} shrank below previous {prev}"
                )
            prev = delta


def _check_penalized(world) -> None:
    for i, st in enumerate(world.states):
        if st is None or st.request.repetition_penalty == 1.0:
            continue
        if st.cached_tokens:
            raise CheckFailure(
                "H7", f"q{i} is penalized but reused "
                      f"{st.cached_tokens} prefix-cache tokens — its "
                      f"seen matrix would depend on cache warmth"
            )
        if st.draft_tail:
            raise CheckFailure(
                "H7", f"q{i} is penalized but carries a draft tail"
            )


def check_world(world) -> None:
    """Run the full registry over every replica + the global state.
    Raises :class:`CheckFailure` naming the first violated invariant."""
    for rep in world.replicas:
        sched = rep.engine.scheduler
        rid = rep.replica_id
        if sched.paged:
            _check_pool(world, rid, sched)
        store = world.stores[rid]
        if store is not None:
            _check_store(world, rid, sched, store)
    _check_placement(world)
    _check_backoff(world)
    _check_penalized(world)


def check_event(world, rid: int, plan) -> None:
    """Per-plan checks (things only visible at schedule time)."""
    from ...serving.paging import STAGE_SLOTS

    for w in plan.work:
        if (w.state.request.repetition_penalty != 1.0
                and w.spec_len > 0):
            raise CheckFailure(
                "H7", f"r{rid}: penalized request "
                      f"{w.state.request.request_id} scheduled with a "
                      f"{w.spec_len}-token spec window"
            )
    if len(plan.stage) > STAGE_SLOTS:
        raise CheckFailure(
            "H2", f"r{rid}: plan stages {len(plan.stage)} promotions "
                  f"(> STAGE_SLOTS={STAGE_SLOTS})"
        )
    budget = world.scenario.token_budget
    if plan.total_tokens > budget:
        raise CheckFailure(
            "INTERNAL_ASSERT",
            f"r{rid}: plan schedules {plan.total_tokens} tokens over "
            f"budget {budget}"
        )
