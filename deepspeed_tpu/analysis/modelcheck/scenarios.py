"""fleetcheck scenarios: small, exhaustively-explorable host-plane configs.

A :class:`Scenario` is the complete, deterministic description of one
bounded model-checking run: the host-plane configuration (slots, pages,
tiers, replicas), the request population, the exploration bounds, and —
for the seeded-bug smokes — the faults to arm (serving/faults.py).

Presets (the CLI surface, mirroring shardlint's rule families):

- ``oversubscription`` — 4 slots x 4 pages over an 8-page pool with a
  host tier: the PR 18 promotion-liveness shape.
- ``disaggregated_handoff`` — 1 prefill + 1 decode replica with a
  page-scarce decode pool: handoff success, deferral and rollback.
- ``tiered_cold_resume`` — prefix cache + host tier under LRU pressure:
  chains demote to host and a later identical prompt cold-resumes
  through the promotion path.
- ``spec_on`` — speculative decoding with a repetition-penalized
  request riding along (the seen-matrix bypass discipline, H7).
- ``fleet_shedding`` — 2 mixed replicas behind a fleet-level bounded
  queue: sheds, backoff hints, resubmission.

``MUTATIONS`` maps each seeded-bug smoke to (base scenario builder,
faults to arm, the invariant/liveness id the checker MUST report). The
clean twin of each mutant is the same scenario with no faults armed.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Callable, Dict, Optional, Tuple

__all__ = [
    "RequestSpec", "Scenario", "PRESETS", "MUTATIONS", "Mutation",
    "preset",
]


@dataclass(frozen=True)
class RequestSpec:
    """One abstract request in a scenario's population."""

    prompt: Tuple[int, ...]
    max_new: int = 2
    penalty: float = 1.0          # repetition_penalty (1.0 = off)
    session: Optional[str] = None  # fleet session affinity key


@dataclass
class Scenario:
    """One bounded model-checking run, fully deterministic."""

    name: str
    requests: Tuple[RequestSpec, ...]
    # ---- per-replica scheduler config (uniform unless decode_* set)
    max_slots: int = 2
    token_budget: int = 8
    queue_limit: int = 8
    request_timeout_s: float = 1e9
    eviction_backoff_s: float = 1.0
    max_tokens: int = 64
    page_size: int = 2
    num_pages: int = 4
    pages_per_slot: int = 2
    host_pages: int = 0           # 0 = no host tier (no spiller)
    prefix_cache: bool = False
    spec_max_draft: int = 0
    # ---- fleet shape (replicas == 1 -> no router, plain scheduler)
    replicas: int = 1
    prefill_replicas: int = 0
    fleet_queue_limit: int = 0
    routing: str = "least_loaded"
    affinity: bool = True
    decode_max_slots: Optional[int] = None   # decode-role overrides
    decode_num_pages: Optional[int] = None
    # ---- event alphabet bounds
    advance_dts: Tuple[float, ...] = (2.0,)  # clock jumps on "advance"
    max_advances: int = 2
    max_resubmits: int = 1        # resubmissions per request
    # ---- exploration bounds
    max_depth: int = 12
    max_states: int = 2000
    drain_horizon: int = 32       # liveness: ticks to reach quiescence
    budget_s: float = 60.0        # wall-clock bound per explore() call
    # ---- seeded bugs (serving/faults.py names) armed for the run
    mutations: Tuple[str, ...] = ()
    # ---- token alphabet of the null device
    eos_token: int = 1
    tok_token: int = 7

    def describe(self) -> str:
        fleet = (f", {self.replicas} replicas"
                 f" ({self.prefill_replicas} prefill)"
                 if self.replicas > 1 else "")
        tier = f", host={self.host_pages}" if self.host_pages else ""
        mut = f", mutations={list(self.mutations)}" if self.mutations \
            else ""
        return (f"{self.name}: {len(self.requests)} requests, "
                f"{self.max_slots} slots, {self.num_pages} pages"
                f"{tier}{fleet}{mut}")


def _prompts(n: int, length: int, base: int = 11) -> Tuple[RequestSpec, ...]:
    """``n`` distinct prompts of ``length`` tokens (token ids avoid the
    scenario's eos/tok alphabet so nothing terminates by accident)."""
    return tuple(
        RequestSpec(prompt=tuple(base + i for _ in range(length)),
                    max_new=2)
        for i in range(n)
    )


# --------------------------------------------------------------- presets
def oversubscription() -> Scenario:
    """The PR 18 shape: 4 slots of up to 4 pages over an 8-page pool
    with a host tier. Demotions, promotions, starvation evictions and
    the promotion-liveness argument all exercise here."""
    reqs = tuple(
        RequestSpec(prompt=tuple(20 + i for _ in range(5)), max_new=3)
        for i in range(4)
    )
    return Scenario(
        name="oversubscription",
        requests=reqs,
        max_slots=4, token_budget=4, queue_limit=8,
        page_size=2, num_pages=8, pages_per_slot=4, host_pages=8,
        max_tokens=8,
        # ~51k reachable states to depth 11: exhaustive in ~90s on one
        # CPU core (the CI budget); tier-1 tests shrink max_states
        max_depth=11, max_states=60000, drain_horizon=40,
        budget_s=150.0,
    )


def disaggregated_handoff() -> Scenario:
    """1 prefill + 1 decode replica; the decode pool is page-scarce so
    handoffs both succeed and defer (rollback path) in-bounds."""
    reqs = tuple(
        RequestSpec(prompt=tuple(30 + i for _ in range(3)), max_new=2)
        for i in range(3)
    )
    return Scenario(
        name="disaggregated_handoff",
        requests=reqs,
        max_slots=2, token_budget=6, queue_limit=8,
        page_size=2, num_pages=6, pages_per_slot=3, host_pages=0,
        max_tokens=6,
        replicas=2, prefill_replicas=1,
        decode_max_slots=2, decode_num_pages=3,
        max_depth=10, max_states=4000, drain_horizon=32,
    )


def tiered_cold_resume() -> Scenario:
    """Prefix cache + host tier under LRU pressure: a finished request's
    chain demotes to host, and an identical later prompt cold-resumes
    through host_chain attach + promotion staging."""
    shared = tuple(40 for _ in range(6))
    reqs = (
        RequestSpec(prompt=shared, max_new=2),
        RequestSpec(prompt=tuple(50 for _ in range(4)), max_new=2),
        RequestSpec(prompt=shared, max_new=2),
    )
    return Scenario(
        name="tiered_cold_resume",
        requests=reqs,
        max_slots=2, token_budget=6, queue_limit=8,
        page_size=2, num_pages=5, pages_per_slot=4, host_pages=6,
        max_tokens=8, prefix_cache=True,
        max_depth=11, max_states=2500, drain_horizon=32,
    )


def spec_on() -> Scenario:
    """Speculative decoding on, one repetition-penalized request in the
    mix: the penalized request must bypass drafts AND the prefix cache
    (H7) while the others draft freely."""
    reqs = (
        RequestSpec(prompt=(60, 60, 60), max_new=3),
        RequestSpec(prompt=(60, 60, 60), max_new=3, penalty=1.2),
        RequestSpec(prompt=(61, 61, 61), max_new=2),
    )
    return Scenario(
        name="spec_on",
        requests=reqs,
        max_slots=2, token_budget=6, queue_limit=8,
        page_size=2, num_pages=6, pages_per_slot=3, host_pages=0,
        max_tokens=6, prefix_cache=True, spec_max_draft=1,
        max_depth=10, max_states=6000, drain_horizon=32,
    )


def fleet_shedding() -> Scenario:
    """2 mixed replicas behind a tight fleet-wide queue bound: sheds,
    per-replica bounded queues, backoff monotonicity, resubmission."""
    reqs = tuple(
        RequestSpec(prompt=tuple(70 + i for _ in range(3)), max_new=2,
                    session=("s0" if i % 2 == 0 else None))
        for i in range(4)
    )
    return Scenario(
        name="fleet_shedding",
        requests=reqs,
        max_slots=1, token_budget=6, queue_limit=1,
        page_size=2, num_pages=3, pages_per_slot=3, host_pages=0,
        max_tokens=6,
        replicas=2, prefill_replicas=0, fleet_queue_limit=2,
        routing="least_loaded",
        max_depth=10, max_states=15000, drain_horizon=32,
        max_resubmits=1,
    )


PRESETS: Dict[str, Callable[[], Scenario]] = {
    "oversubscription": oversubscription,
    "disaggregated_handoff": disaggregated_handoff,
    "tiered_cold_resume": tiered_cold_resume,
    "spec_on": spec_on,
    "fleet_shedding": fleet_shedding,
}


def preset(name: str) -> Scenario:
    if name not in PRESETS:
        raise KeyError(
            f"unknown fleetcheck preset {name!r} (known: {sorted(PRESETS)})"
        )
    return PRESETS[name]()


# ---------------------------------------------------------- seeded bugs
@dataclass(frozen=True)
class Mutation:
    """One seeded-bug smoke: base scenario + armed faults + what the
    checker MUST report (the paritycheck --mutate contract)."""

    name: str
    base: Callable[[], Scenario]
    faults: Tuple[str, ...]
    expect: str        # violation id fleetcheck must name
    detail: str

    def scenario(self) -> Scenario:
        sc = self.base()
        return replace(
            sc,
            name=f"{sc.name}+{'+'.join(self.faults)}",
            mutations=tuple(self.faults),
        )

    def clean(self) -> Scenario:
        return self.base()


def _livelock_base() -> Scenario:
    """The promotion-livelock shape: one short decode hog plus four
    long prompts over a pool that holds barely one of them
    (page_size=1 so every written page is demotable). The hog's decode
    allocations demote the mid-prefill slots; once all four wait on
    promotions, the unsticky planner's promote-2/steal-2 rotation
    never brings any slot back to full residency — a zero-progress
    cycle with no samplers, so even the all-EOS drain policy cannot
    break it. The sticky planner heals one waiter to residency per
    ceil(n/STAGE_SLOTS) ticks and quiesces."""
    reqs = (RequestSpec(prompt=(9,), max_new=6),) + tuple(
        RequestSpec(prompt=tuple(20 + i for _ in range(7)), max_new=1)
        for i in range(4)
    )
    return Scenario(
        name="promotion_liveness",
        requests=reqs,
        max_slots=4, token_budget=2, queue_limit=8,
        page_size=1, num_pages=8, pages_per_slot=8, host_pages=24,
        max_tokens=8,
        max_advances=0, max_resubmits=0,
        # the mutant's counterexample sits at depth 7 (5 submits + 2
        # ticks); depth 8 keeps the clean twin exhaustively explorable
        max_depth=8, max_states=8000, drain_horizon=60,
        budget_s=60.0,
    )


MUTATIONS: Dict[str, Mutation] = {
    "promotion_livelock": Mutation(
        name="promotion_livelock",
        base=_livelock_base,
        faults=("promotion_unsticky",),
        expect="LIVELOCK",
        detail="PR 18 promotion livelock: stickiness guard off — "
               "fleetcheck must report a zero-progress cycle",
    ),
    "handoff_leak": Mutation(
        name="handoff_leak",
        base=disaggregated_handoff,
        faults=("handoff_leak",),
        expect="H3",
        detail="handoff rollback skips freeing dst pages on a deferred "
               "transfer — fleetcheck must pin the refcount/conservation "
               "invariant",
    ),
}
