"""NVMe tensor swapping over the C++ aio backend.

Parity: deepspeed/runtime/swap_tensor/ (partitioned_optimizer_swapper,
async_swapper). Pytree leaves stream to raw .bin files under ``swap_dir``
via the csrc/aio threadpool; reads land in preallocated host buffers so a
swap-in overlaps with TPU compute. This is the storage layer behind
ZeRO offload_optimizer {"device": "nvme", "nvme_path": ...}: optimizer
state lives on disk between steps for models whose states exceed host RAM.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict

import jax
import numpy as np

from ..ops.aio import AsyncIOHandle


class PinnedBufferPool:
    """Two-generation keyed host-buffer pool (reference: swap_tensor's
    pinned buffer_count pool), factored out so the serving KV host tier
    (``serving/paging.HostPageStore``) shares one implementation with the
    NVMe swapper.

    A generation's buffers are retired for reuse only after ITS consumers
    have fully landed (the caller blocks before calling
    ``retire_generation``), and even then one generation later. Only safe
    when the consumer COPIES off the buffer (device_put to a real
    accelerator); jaxlib's CPU client can zero-copy alias numpy arrays,
    so CPU meshes must leave pooling off (the owner decides).
    """

    def __init__(self, buffer_count: int = 4):
        self._buffer_count = int(buffer_count)
        self._free: Dict[tuple, list] = {}
        self._last_gen: list = []
        self._generation = 0

    def take(self, shape, dtype) -> np.ndarray:
        key = (tuple(shape), str(dtype))
        lst = self._free.get(key)
        if lst:
            return lst.pop()
        return np.empty(shape, dtype=np.dtype(dtype))

    def retire_generation(self, bufs: list, pending_ids=frozenset()) -> None:
        """Rotate generations: the previous fill's buffers become reusable
        now that a newer generation has fully landed.

        Read-after-overwrite guard (the shardlint R4 hazard class, at the
        host layer): a buffer may never sit in the free pool while an
        in-flight write still reads from it — the next fill would
        overwrite bytes a writer is persisting. ``pending_ids`` is the
        id() set of buffers still referenced by in-flight writes; refuse
        loudly rather than corrupt the destination.
        """
        # validate the WHOLE generation before touching the free pool, so
        # a raise leaves no buffer half-retired (in _free AND _last_gen —
        # a later successful retire would then double-free it)
        aliased = [b for b in self._last_gen if id(b) in pending_ids]
        if aliased:
            raise RuntimeError(
                "PinnedBufferPool: refusing to recycle a read buffer that "
                "an in-flight write still references (read-after-"
                "overwrite hazard)"
            )
        for b in self._last_gen:
            key = (tuple(b.shape), str(b.dtype))
            lst = self._free.setdefault(key, [])
            if len(lst) < self._buffer_count:
                lst.append(b)
        self._last_gen = bufs
        self._generation += 1

    @property
    def generation(self) -> int:
        """Completed buffer generations (observability for tests and
        stream accounting)."""
        return self._generation


class TensorSwapper:
    def __init__(self, swap_dir: str, num_threads: int = 4,
                 reuse_buffers: bool = False, buffer_count: int = 4):
        self.swap_dir = swap_dir
        os.makedirs(swap_dir, exist_ok=True)
        self.aio = AsyncIOHandle(num_threads=num_threads)
        self._meta: Dict[str, Any] = {}
        # in-flight write requests per name, plus the host buffers they read
        # from (kept alive until the write completes)
        self._pending: Dict[str, Any] = {}
        self._reuse = bool(reuse_buffers)
        self._pool = PinnedBufferPool(buffer_count=buffer_count)

    def _take_buf(self, shape, dtype) -> np.ndarray:
        return self._pool.take(shape, dtype)

    def _retire_gen(self, bufs: list) -> None:
        """Rotate generations via the shared pool; swap_out buffers are
        freshly materialized hosts (never pooled), so an alias with an
        in-flight write here is a wiring bug the pool refuses."""
        pending_ids = {
            id(h)
            for reqs_hosts in self._pending.values()
            for h in (reqs_hosts[1] or [])
        }
        self._pool.retire_generation(bufs, pending_ids=pending_ids)

    @property
    def generation(self) -> int:
        """Completed read-buffer generations (observability for tests and
        the offload stream accounting)."""
        return self._pool.generation

    @property
    def _last_gen(self) -> list:
        """The previous generation's still-referenced buffers (now owned
        by the shared :class:`PinnedBufferPool`; kept addressable here —
        tests plant aliases of them to prove the refuse-to-recycle
        contract)."""
        return self._pool._last_gen

    def _leaf_path(self, name: str, i: int) -> str:
        return os.path.join(self.swap_dir, f"{name}.leaf{i}.bin")

    def wait_pending(self, name: str) -> None:
        """Block until any in-flight writes for ``name`` have hit disk."""
        reqs, _bufs = self._pending.pop(name, ([], None))
        for r in reqs:
            self.aio.wait(r)

    def swap_out(self, name: str, tree, blocking: bool = True) -> None:
        """Write every leaf (gathered to host) to disk asynchronously.

        blocking=False returns as soon as the writes are enqueued; the next
        swap_in/wait_pending for this name blocks on them (read-after-write).
        Device→host transfers are pipelined via copy_to_host_async."""
        from .checkpointing import _to_host

        self.wait_pending(name)  # don't interleave two write generations
        leaves = jax.tree_util.tree_leaves(tree)
        for leaf in leaves:  # start all D2H copies before draining any
            if hasattr(leaf, "copy_to_host_async"):
                try:
                    leaf.copy_to_host_async()
                except Exception:
                    pass  # pinned-host/odd transports: _to_host still works
        meta = []
        reqs = []
        hosts = []
        for i, leaf in enumerate(leaves):
            # _to_host handles non-fully-addressable (multi-host sharded) and
            # pinned_host leaves; plain device_get would raise on both
            host = _to_host(leaf)
            hosts.append(host)
            meta.append({"shape": list(host.shape), "dtype": str(host.dtype)})
            reqs.append(self.aio.submit_write(self._leaf_path(name, i), host))
        self._meta[name] = {
            "leaves": meta,
            "treedef": jax.tree_util.tree_structure(tree),
        }
        with open(os.path.join(self.swap_dir, f"{name}.json"), "w") as f:
            json.dump({"leaves": meta}, f)
        if blocking:
            for r in reqs:
                self.aio.wait(r)
        else:
            self._pending[name] = (reqs, hosts)

    def swap_in(self, name: str, treedef=None, shardings=None):
        """Read leaves back; returns the reconstructed pytree."""
        self.wait_pending(name)
        meta = self._meta.get(name)
        if meta is None:
            with open(os.path.join(self.swap_dir, f"{name}.json")) as f:
                meta = {"leaves": json.load(f)["leaves"], "treedef": treedef}
        if meta["treedef"] is None:
            raise ValueError(f"swap_in({name!r}) needs a treedef")
        # pool only when the result leaves the numpy buffers (device_put
        # below copies to the accelerator); a raw-tree return aliases the
        # buffers and must never see them recycled
        use_pool = self._reuse and shardings is not None
        bufs = []
        reqs = []
        for i, lm in enumerate(meta["leaves"]):
            buf = (
                self._take_buf(lm["shape"], lm["dtype"])
                if use_pool
                else np.empty(lm["shape"], dtype=np.dtype(lm["dtype"]))
            )
            reqs.append(self.aio.submit_read(self._leaf_path(name, i), buf))
            bufs.append(buf)
        for r in reqs:
            self.aio.wait(r)
        tree = jax.tree_util.tree_unflatten(meta["treedef"], bufs)
        if shardings is not None:
            tree = jax.device_put(tree, shardings)
            if use_pool:
                # the H2D transfer may be asynchronous: the buffers are
                # only reusable once the device arrays are materialized —
                # block on THIS generation so retiring the previous one
                # (and any later overwrite of these) is provably safe
                jax.block_until_ready(tree)
                self._retire_gen(list(bufs))
        return tree

    def release(self, name: str) -> None:
        self.wait_pending(name)
        meta = self._meta.pop(name, None)
        if meta:
            for i in range(len(meta["leaves"])):
                try:
                    os.remove(self._leaf_path(name, i))
                except FileNotFoundError:
                    pass

    def close(self) -> None:
        for name in list(self._pending):
            self.wait_pending(name)
        self.aio.close()
