"""Learning-rate schedules.

Parity: deepspeed/runtime/lr_schedules.py — WarmupLR, WarmupDecayLR,
WarmupCosineLR, OneCycle, LRRangeTest, expressed as pure step→lr functions
(optax-schedule compatible, traced inside the jitted train step).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp

Schedule = Callable[[Any], Any]


def warmup_lr(warmup_min_lr=0.0, warmup_max_lr=1e-3, warmup_num_steps=1000,
              warmup_type="log", **_):
    """WarmupLR: warm up then hold at warmup_max_lr."""
    warmup_num_steps = max(warmup_num_steps, 1)

    def schedule(step):
        s = step.astype(jnp.float32) + 1.0
        if warmup_type == "log":
            frac = jnp.log(s) / math.log(max(warmup_num_steps, 2))
        else:
            frac = s / float(warmup_num_steps)
        return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * jnp.clip(frac, 0.0, 1.0)

    return schedule


def warmup_decay_lr(total_num_steps, warmup_min_lr=0.0, warmup_max_lr=1e-3,
                    warmup_num_steps=1000, warmup_type="log", **_):
    """WarmupDecayLR: warmup then linear decay to 0 at total_num_steps."""
    base = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def schedule(step):
        lr = base(step)
        decay = jnp.clip(
            (total_num_steps - step.astype(jnp.float32))
            / max(total_num_steps - warmup_num_steps, 1),
            0.0,
            1.0,
        )
        past_warmup = step.astype(jnp.float32) >= warmup_num_steps
        return jnp.where(past_warmup, warmup_max_lr * decay, lr)

    return schedule


def warmup_cosine_lr(total_num_steps, warmup_min_ratio=0.0, warmup_num_steps=1000,
                     cos_min_ratio=0.0001, lr=1e-3, **_):
    """WarmupCosineLR: linear warmup then cosine decay to cos_min_ratio*lr."""

    def schedule(step):
        s = step.astype(jnp.float32)
        warm = warmup_min_ratio + (1 - warmup_min_ratio) * jnp.minimum(
            s / max(warmup_num_steps, 1), 1.0
        )
        progress = jnp.clip(
            (s - warmup_num_steps) / max(total_num_steps - warmup_num_steps, 1), 0.0, 1.0
        )
        cos = cos_min_ratio + (1 - cos_min_ratio) * 0.5 * (1 + jnp.cos(math.pi * progress))
        return lr * jnp.where(s < warmup_num_steps, warm, cos)

    return schedule


def one_cycle(cycle_min_lr, cycle_max_lr, cycle_first_step_size=2000,
              cycle_second_step_size=None, decay_step_size=0, decay_lr_rate=0.0,
              post_cycle_decay="linear", **_):
    """OneCycle: triangular up/down then optional decay (reference semantics)."""
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    total_cycle = cycle_first_step_size + second

    def schedule(step):
        s = step.astype(jnp.float32)
        up = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * jnp.minimum(
            s / cycle_first_step_size, 1.0
        )
        down = cycle_max_lr - (cycle_max_lr - cycle_min_lr) * jnp.clip(
            (s - cycle_first_step_size) / max(second, 1), 0.0, 1.0
        )
        in_up = s < cycle_first_step_size
        lr = jnp.where(in_up, up, down)
        if decay_step_size > 0:
            post = jnp.maximum(s - total_cycle, 0.0)
            lr = jnp.where(
                s > total_cycle,
                cycle_min_lr / (1.0 + decay_lr_rate * post / decay_step_size),
                lr,
            )
        return lr

    return schedule


def lr_range_test(lr_range_test_min_lr=1e-3, lr_range_test_step_size=2000,
                  lr_range_test_step_rate=1.0, lr_range_test_staircase=False, **_):
    """LRRangeTest: linearly (or staircase) increasing LR probe."""

    def schedule(step):
        s = step.astype(jnp.float32)
        interval = jnp.floor(s / lr_range_test_step_size) if lr_range_test_staircase else (
            s / lr_range_test_step_size
        )
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)

    return schedule


_SCHEDULES = {
    "warmuplr": warmup_lr,
    "warmupdecaylr": warmup_decay_lr,
    "warmupcosinelr": warmup_cosine_lr,
    "onecycle": one_cycle,
    "lrrangetest": lr_range_test,
}


def build_schedule(name: Optional[str], params: Dict[str, Any], base_lr: float) -> Schedule:
    """Schedule factory; None → constant base_lr."""
    if not name:
        return lambda step: jnp.full((), base_lr, jnp.float32)
    key = name.lower().replace("_", "")
    if key not in _SCHEDULES:
        raise KeyError(f"unknown scheduler {name!r}; have {sorted(_SCHEDULES)}")
    params = dict(params)
    if key == "warmupcosinelr":
        params.setdefault("lr", base_lr)
    return _SCHEDULES[key](**params)
