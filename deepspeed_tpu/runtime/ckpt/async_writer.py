"""Async snapshot pipeline: device→pinned-host snapshot + background
shard writer.

``save_checkpoint(engine, ..., async_save=True)`` forks the save into
two halves:

- the **snapshot fence** (main thread, charged to the ``checkpoint``
  goodput bucket): join any previous writer, copy this process's
  replica-0 addressable shards device→host into
  :class:`~..swap_tensor.PinnedBufferPool` buffers, rotate the pool's
  generations. Step N+1's math can start the moment the fence returns —
  double-buffered, the two-generation discipline guarantees the writer
  of save N never reads a buffer save N+1 is refilling. Pool reuse is
  safe even on CPU backends here (unlike the swapper's zero-copy
  ``swap_in`` path) because the snapshot always COPIES into the buffer;
  jax never holds a reference to it.
- the **background write** (one writer thread, pure numpy + file I/O,
  no jax): serialize each shard, then land the manifest LAST
  (:mod:`.manifest` atomicity rule), advance ``latest``, prune
  ``keep_last``. Its wall seconds are reported out-of-band
  (``ckpt_write_s``), never to the goodput buckets — they overlap
  training.

A :class:`CheckpointGuard` fences the next save behind the in-flight
writer and re-raises any writer exception **on the main thread** — a
failed background save must fail the run loudly, not silently drop a
restore point.

Multi-process jobs fall back to a sync save: the commit requires a
cross-process barrier (every process's shards before the one manifest)
that a background thread cannot own safely. The sync path is this same
writer run inline, so the on-disk result is identical.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ...utils.logging import log_dist
from ..checkpointing import (
    _SHARD_FMT,
    _ZERO_TO_FP32_SCRIPT,
    _barrier,
    _bounds_token,
    _clean_component_dir,
    _device_view,
    _is_writer,
    _leaf_paths,
    _save_tree_orbax,
)
from ..swap_tensor import PinnedBufferPool
from . import manifest as _manifest

_COMPONENTS = ("params", "opt_state", "loss_scale")


def _leaf_dimspec(leaf, ndim: int) -> Tuple[int, ...]:
    """Per-dimension shard divisors of the sharding that is saving this
    leaf (analysis/cost dimspec — the same vocabulary reshard's overlap
    math speaks)."""
    from ...analysis.cost.walk import dimspec_from_sharding

    s = getattr(leaf, "sharding", None)
    if s is None:
        return (1,) * ndim
    return dimspec_from_sharding(s, ndim, {})


def _snapshot_tree(tree, pool: PinnedBufferPool):
    """Device→host snapshot of this process's replica-0 shards.

    Returns ``(entries, comp_meta)``: ``entries`` is
    ``[(filename, host_buffer)]`` ready for the writer; ``comp_meta`` is
    the component's manifest record (leaf names, global shapes, dtypes,
    dimspecs, bounds tokens per shard)."""
    leaves = jax.tree_util.tree_leaves(tree)
    names = _leaf_paths(tree)
    # start every D2H copy before draining any (pipelined transfers)
    for leaf in leaves:
        if hasattr(leaf, "copy_to_host_async"):
            try:
                leaf.copy_to_host_async()
            except Exception:  # noqa: BLE001 — pinned_host/odd transports
                pass
    entries: List[Tuple[str, np.ndarray]] = []
    shapes, dtypes, dimspecs = [], [], []
    shard_tokens: Dict[str, List[str]] = {}

    def grab(i: int, token: str, data: np.ndarray) -> None:
        buf = pool.take(data.shape, data.dtype)
        buf[...] = data
        entries.append((_SHARD_FMT.format(i, token), buf))
        shard_tokens.setdefault(str(i), []).append(token)

    for i, leaf in enumerate(leaves):
        if not hasattr(leaf, "addressable_shards"):
            arr = np.asarray(leaf)
            shapes.append(list(arr.shape))
            dtypes.append(str(arr.dtype))
            dimspecs.append([1] * arr.ndim)
            if _is_writer():  # host scalars/np arrays: tiny, process 0 only
                token = _bounds_token(
                    tuple(slice(0, d) for d in arr.shape), arr.shape
                )
                grab(i, token, arr)
            continue
        leaf = _device_view(leaf)
        shapes.append(list(leaf.shape))
        dtypes.append(str(np.dtype(leaf.dtype)))
        dimspecs.append(list(_leaf_dimspec(leaf, leaf.ndim)))
        for shard in leaf.addressable_shards:
            if shard.replica_id != 0:
                continue  # exactly one global writer per distinct shard
            grab(i, _bounds_token(shard.index, leaf.shape),
                 np.asarray(shard.data))
    comp_meta = {
        "num_leaves": len(leaves),
        "leaf_names": names,
        "leaf_shapes": shapes,
        "leaf_dtypes": dtypes,
        "leaf_dimspecs": dimspecs,
        "shards": shard_tokens,
    }
    return entries, comp_meta


class CheckpointGuard:
    """Fences async saves: at most ONE writer in flight, writer
    exceptions surface on the main thread at the next fence, and the
    pinned pool's generations rotate only after the previous writer has
    fully landed (so its buffers are provably quiescent)."""

    def __init__(self, buffer_count: int = 4,
                 on_write_done: Optional[Callable[[float], None]] = None):
        self._pool = PinnedBufferPool(buffer_count=buffer_count)
        self._thread: Optional[threading.Thread] = None
        self._exc: Optional[BaseException] = None
        self.last_write_s: Optional[float] = None
        self.writes = 0  # completed background writes (tests/observability)
        self.on_write_done = on_write_done

    @property
    def in_flight(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def fence(self) -> None:
        """Block until the in-flight writer (if any) committed; re-raise
        its failure HERE — the main thread must see a lost restore
        point."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise RuntimeError(
                f"async checkpoint writer failed: {exc!r} (the previous "
                f"save did NOT commit; restore will resolve the last "
                f"committed tag)"
            ) from exc

    def drain(self) -> None:
        """fence() that only logs a writer failure (engine.destroy —
        teardown must not raise)."""
        try:
            self.fence()
        except RuntimeError as e:
            log_dist(f"ckpt: {e}")

    def rotate(self, bufs: List[np.ndarray]) -> None:
        """Two-generation discipline: the generation before last becomes
        reusable now that this one is snapshotted. fence() ran first, so
        no write is pending against the retiring buffers."""
        self._pool.retire_generation(list(bufs), pending_ids=frozenset())

    def launch(self, fn: Callable[[], None]) -> None:
        if self.in_flight:  # save_checkpoint fences first; belt+braces
            raise RuntimeError("CheckpointGuard: a writer is already in flight")
        def body():
            t0 = time.perf_counter()
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — surfaced at fence
                self._exc = e
            finally:
                dt = time.perf_counter() - t0
                self.last_write_s = dt
                self.writes += 1
                cb = self.on_write_done
                if cb is not None:
                    try:
                        cb(dt)
                    except Exception:  # noqa: BLE001 — telemetry only
                        pass
        # non-daemon: a normal interpreter exit waits for the commit
        # instead of tearing a save mid-write
        self._thread = threading.Thread(target=body, name="ckpt-writer")
        self._thread.start()


def _build_meta(engine, tag: str, client_state: Dict[str, Any]
                ) -> Dict[str, Any]:
    state = engine.state
    return {
        "manifest_version": _manifest.MANIFEST_VERSION,
        "tag": tag,
        "global_steps": engine.global_steps,
        "micro_steps": engine.micro_steps,
        "skipped_steps": engine.skipped_steps,
        "step": int(jax.device_get(state.step)),
        "rng": np.asarray(jax.device_get(engine._rng)).tolist(),
        "client_state": client_state or {},
        "zero_stage": engine.config.zero_config.stage,
        "world_size": engine.topology.world_size,
        "components": {},
    }


def _write_entries(path: str, entries_by_comp) -> None:
    for comp, entries in entries_by_comp.items():
        cdir = os.path.join(path, comp)
        for fname, buf in entries:
            np.save(os.path.join(cdir, fname), buf)


def _commit(save_dir: str, tag: str, meta: Dict[str, Any],
            keep_last: int) -> None:
    """The manifest-last commit + root pointers (writer process only)."""
    _manifest.write_manifest(save_dir, tag, meta)
    _manifest.advance_latest(save_dir, tag)
    with open(os.path.join(save_dir, "zero_to_fp32.py"), "w") as f:
        f.write(_ZERO_TO_FP32_SCRIPT)
    _manifest.prune_keep_last(save_dir, keep_last)


def save_checkpoint(
    engine,
    save_dir: str,
    tag: Optional[str] = None,
    client_state: Optional[Dict[str, Any]] = None,
    async_save: bool = False,
    guard: Optional[CheckpointGuard] = None,
) -> str:
    """Native-engine save through the snapshot pipeline (sync = the same
    writer run inline). Returns the tag directory like the legacy API.

    The caller's ``train/checkpoint`` span should cover only this call's
    synchronous portion: for an async save that IS the snapshot fence —
    the write happens behind the returned control flow."""
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    ckpt_cfg = getattr(engine.config, "checkpoint", None)
    keep_last = int(getattr(ckpt_cfg, "keep_last", 0) or 0)
    if getattr(ckpt_cfg, "engine", "native") == "orbax":
        # Orbax engine keeps the legacy (sync, own-format) path
        from ..checkpointing import save_checkpoint as _legacy_save

        return _legacy_save(engine, save_dir, tag=tag,
                            client_state=client_state)
    if async_save and jax.process_count() > 1:
        log_dist(
            "ckpt: async_save on a multi-process job falls back to sync "
            "(the commit needs a cross-process barrier the background "
            "writer cannot own)"
        )
        async_save = False
    if guard is None:
        guard = CheckpointGuard()

    # ---- snapshot fence (main thread) -------------------------------
    guard.fence()  # one in flight; surfaces the previous writer's failure
    path = os.path.join(save_dir, str(tag))
    if _is_writer():
        os.makedirs(path, exist_ok=True)
        # re-saving over a committed tag: demote it FIRST so a torn
        # rewrite is uncommitted, not silently stale
        mpath = _manifest.manifest_path(save_dir, tag)
        if os.path.exists(mpath):
            os.remove(mpath)
    meta = _build_meta(engine, tag, client_state or {})
    state = engine.state
    trees = {
        "params": state.params,
        "opt_state": state.opt_state,
        "loss_scale": state.loss_scale,
    }
    entries_by_comp: Dict[str, list] = {}
    bufs: List[np.ndarray] = []
    for name, tree in trees.items():
        cdir = os.path.join(path, name)
        os.makedirs(cdir, exist_ok=True)
        _clean_component_dir(cdir)  # stale generation/format out first
        entries, comp_meta = _snapshot_tree(tree, guard._pool)
        meta["components"][name] = comp_meta
        entries_by_comp[name] = entries
        bufs.extend(b for _, b in entries)
    guard.rotate(bufs)

    # ---- write + commit ---------------------------------------------
    if async_save:
        def write():
            _write_entries(path, entries_by_comp)
            _commit(save_dir, tag, meta, keep_last)
            log_dist(f"saved checkpoint {path} (async)")
        guard.launch(write)
        return path

    t0 = time.perf_counter()
    _write_entries(path, entries_by_comp)
    _barrier("ckpt_shards")  # every process's shards before the ONE commit
    if _is_writer():
        _commit(save_dir, tag, meta, keep_last)
    _barrier("ckpt_commit")  # non-writers must not race ahead of the commit
    guard.last_write_s = time.perf_counter() - t0
    guard.writes += 1
    if guard.on_write_done is not None:
        try:
            guard.on_write_done(guard.last_write_s)
        except Exception:  # noqa: BLE001 — telemetry only
            pass
    log_dist(f"saved checkpoint {path}")
    return path


# ----------------------------------------------- preemption (SIGTERM)
_PREEMPT_LOCK = threading.Lock()
_PREEMPT = {"installed": False, "prev": None, "engines": []}


def install_preempt_handler(engine, save_dir: str) -> None:
    """Chain a SIGTERM handler that runs a FINAL SYNC SAVE before
    handing off to whatever was installed before (healthwatch's chain,
    when armed, dumps its postmortem next — evidence AND a restore
    point). Installed in front, so the save happens while the process
    is still healthy. Engines register once per (engine, save_dir).

    Single-process only: a preempted rank cannot complete the
    cross-process barriers a collective save needs while its peers keep
    training — multi-process jobs resume from the last interval save
    (which is the committed-tag contract anyway)."""
    import signal

    with _PREEMPT_LOCK:
        pairs = _PREEMPT["engines"]
        if not any(e is engine for e, _ in pairs):
            pairs.append((engine, save_dir))
        else:
            _PREEMPT["engines"] = [
                (e, save_dir if e is engine else d) for e, d in pairs
            ]
        if _PREEMPT["installed"]:
            return
        if threading.current_thread() is not threading.main_thread():
            return  # signal handlers only install from the main thread
        def _on_sigterm(signum, frame):
            for eng, sdir in list(_PREEMPT["engines"]):
                try:
                    if jax.process_count() > 1:
                        log_dist(
                            "ckpt: preempted on a multi-process job — "
                            "skipping the final save (peers would hang "
                            "in its barriers); resume uses the last "
                            "committed interval tag"
                        )
                        continue
                    if getattr(eng, "state", None) is None:
                        continue  # destroyed engine
                    eng.save_checkpoint(sdir, async_save=False)
                    log_dist("ckpt: preemption save committed")
                except Exception as e:  # noqa: BLE001 — best-effort
                    log_dist(f"ckpt: preemption save failed: {e}")
            prev = _PREEMPT["prev"]
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_IGN:
                return
            else:
                raise SystemExit(128 + int(signum))
        try:
            _PREEMPT["prev"] = signal.signal(signal.SIGTERM, _on_sigterm)
            _PREEMPT["installed"] = True
        except (ValueError, OSError):
            _PREEMPT["prev"] = None


def reset_preempt_handler() -> None:
    """Tests: restore the chained SIGTERM handler and drop registrations."""
    import signal

    with _PREEMPT_LOCK:
        if _PREEMPT["installed"]:
            try:
                if threading.current_thread() is threading.main_thread():
                    signal.signal(
                        signal.SIGTERM,
                        _PREEMPT["prev"] or signal.SIG_DFL,
                    )
            except (ValueError, OSError):
                pass
        _PREEMPT["installed"] = False
        _PREEMPT["prev"] = None
        _PREEMPT["engines"] = []
