"""Resharding-on-restore: load a checkpoint onto a *different* mesh.

The manifest records every leaf's global shape and the ``bounds_token``
layout of each saved shard (a rectangle of the one logical array). A
restore onto a different ``ParallelDims`` / ``MeshTopology`` / ZeRO
stage therefore never needs the source mesh: for each **destination**
shard, jax hands us its global index rectangle and we assemble it by
reading only the overlapping source byte ranges (``np.load(...,
mmap_mode="r")`` + per-dimension interval intersection), then re-put the
finished array to the engine's real target sharding. ZeRO-partitioned
optimizer state reshards the same way — its leaves are sharding
annotations on one logical array, not rank-local fragments.

The overlap math speaks the :mod:`...analysis.cost` dimspec vocabulary
(per-dimension shard divisors via :func:`dimspec_from_sharding`), the
same machinery R2/R8 use to price shardings statically — the restore's
per-device read volume is exactly ``device_bytes(shape, dtype,
dimspec)``.

The explicit ``device_put`` to the destination sharding at the end is
the load-bearing step (the shardlint R2 ``restore_drops_sharding``
hazard is this path with that line missing): rebuilding a donated
carry's tree from host arrays without re-putting to its resting
shardings silently de-shards the next step.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from ...utils.logging import log_dist
from ..checkpointing import (
    _ORBAX_SUBDIR,
    _assemble_leaf,
    _barrier,
    _index_shard_files,
    _load_tree_orbax,
)
from . import manifest as _manifest


def _stored_shape(entries) -> Optional[Tuple[int, ...]]:
    """Global shape of a stored leaf from its shard inventory (max stop
    per dimension; legacy full-array files report their own shape)."""
    bounds0, path0 = entries[0]
    if bounds0 is None:  # legacy unsharded file
        return tuple(np.load(path0, mmap_mode="r").shape)
    if bounds0 == ():  # 0-d
        return ()
    ndim = len(bounds0)
    shape = [0] * ndim
    for bounds, _ in entries:
        if bounds is None or len(bounds) != ndim:
            return None  # mixed layouts — let _assemble_leaf raise loudly
        for d, sl in enumerate(bounds):
            shape[d] = max(shape[d], sl.stop)
    return tuple(shape)


def _read_overlap(entries, dst_bounds, shape, dtype) -> np.ndarray:
    """Assemble ONE destination rectangle from the overlapping source
    rectangles, reading only the intersecting ranges of each shard file
    (mmap: untouched source bytes never leave the page cache)."""
    full = tuple(slice(0, d) for d in shape)
    dst_shape = tuple(sl.stop - sl.start for sl in dst_bounds)
    out = np.empty(dst_shape, dtype)
    covered = 0
    for bounds, path in entries:
        src_bounds = full if bounds in (None, ()) else bounds
        inter = []
        for sb, db in zip(src_bounds, dst_bounds):
            lo, hi = max(sb.start, db.start), min(sb.stop, db.stop)
            if lo >= hi:
                inter = None
                break
            inter.append((lo, hi))
        if inter is None:
            continue
        src = np.load(path, mmap_mode="r")
        src_sel = tuple(
            slice(lo - sb.start, hi - sb.start)
            for (lo, hi), sb in zip(inter, src_bounds)
        )
        dst_sel = tuple(
            slice(lo - db.start, hi - db.start)
            for (lo, hi), db in zip(inter, dst_bounds)
        )
        out[dst_sel] = src[src_sel]
        covered += int(np.prod([hi - lo for lo, hi in inter]))
    if covered != out.size:  # saved rectangles tile the array disjointly
        raise ValueError(
            f"corrupt checkpoint: destination shard {dst_bounds} of shape "
            f"{shape} only covered by {covered}/{out.size} stored elements "
            f"under {os.path.dirname(entries[0][1])} (missing shard files?)"
        )
    return out


def _resharded_leaf(entries, shape, dtype, sharding):
    """Build one destination-sharded jax.Array: per destination shard,
    read only the overlapping source ranges, then ONE explicit re-put to
    the engine's real target sharding (memory kind included)."""
    from jax.sharding import NamedSharding

    # assemble in default device memory; the re-put below moves it to the
    # target's memory kind (pinned_host offload targets can't always be
    # written through make_array_from_callback directly)
    assemble = NamedSharding(sharding.mesh, sharding.spec)
    cache: Dict[Tuple, np.ndarray] = {}

    def cb(index):
        bounds = tuple(
            slice(
                0 if sl.start is None else int(sl.start),
                dim if sl.stop is None else int(sl.stop),
            )
            for sl, dim in zip(index, shape)
        )
        key = tuple((b.start, b.stop) for b in bounds)
        if key not in cache:  # replicated axes ask for the same rectangle
            cache[key] = _read_overlap(entries, bounds, shape, dtype)
        return cache[key]

    arr = jax.make_array_from_callback(tuple(shape), assemble, cb)
    return jax.device_put(arr, sharding)  # the R2-clean re-put


def _load_tree_resharded(template, directory: str, shardings=None,
                         strict: bool = True, stored_names=None):
    """`_load_tree` with per-destination-shard overlap reads instead of
    whole-leaf assembly. Leaf matching (recorded pytree path with
    flat-index fallback) and strict=False semantics are identical."""
    from jax.sharding import NamedSharding

    from ...analysis.cost.walk import device_bytes, dimspec_from_sharding

    flat, _ = jax.tree_util.tree_flatten_with_path(template)
    names = [jax.tree_util.keystr(path) for path, _ in flat]
    leaves = [leaf for _, leaf in flat]
    shard_leaves = (
        jax.tree_util.tree_leaves(shardings)
        if shardings is not None
        else [None] * len(leaves)
    )
    files = _index_shard_files(directory)
    if stored_names and len(stored_names) == len(set(stored_names)):
        name_to_stored = {n: i for i, n in enumerate(stored_names)}
    else:
        name_to_stored = {n: i for i, n in enumerate(names)}  # positional

    loaded = []
    read_bytes = 0  # per-device restore read volume (dimspec-priced)
    for i, (name, old) in enumerate(zip(names, leaves)):
        stored_i = name_to_stored.get(name)
        entries = files.get(stored_i) if stored_i is not None else None
        s = shard_leaves[i] if i < len(shard_leaves) else None
        if not entries:
            if strict:
                raise FileNotFoundError(
                    f"checkpoint missing leaf {name!r} (index {stored_i}) "
                    f"under {directory}"
                )
            log_dist(f"strict=False: missing leaf {name}, keeping current value")
            loaded.append(old)
            continue
        shape = _stored_shape(entries)
        if shape is not None and tuple(old.shape) != shape:
            if strict:
                raise ValueError(
                    f"checkpoint leaf {name} shape {shape} != expected "
                    f"{tuple(old.shape)} (did the model/optimizer config "
                    f"change? pass strict=False to keep mismatched leaves at "
                    f"their current values)"
                )
            log_dist(
                f"strict=False: leaf {name} shape {shape} != "
                f"{tuple(old.shape)}, keeping current value"
            )
            loaded.append(old)
            continue
        dtype = np.dtype(old.dtype)
        if isinstance(s, NamedSharding) and shape:
            dimspec = dimspec_from_sharding(s, len(shape), {})
            read_bytes += device_bytes(shape, dtype, dimspec)
            loaded.append(_resharded_leaf(entries, shape, dtype, s))
        else:
            # scalars / non-mesh shardings: whole-leaf assembly is already
            # minimal, but the re-put discipline is the same
            arr = np.asarray(_assemble_leaf(entries), dtype=dtype)
            read_bytes += arr.nbytes
            loaded.append(jax.device_put(arr, s) if s is not None else arr)
    if shardings is not None:
        log_dist(
            f"reshard: {directory.rsplit(os.sep, 1)[-1]}: {len(leaves)} "
            f"leaves, {read_bytes / 2**20:.1f} MiB/device overlap reads"
        )
    treedef = jax.tree_util.tree_structure(template)
    return jax.tree_util.tree_unflatten(treedef, loaded)


def load_checkpoint(
    engine,
    load_dir: str,
    tag: Optional[str] = None,
    strict: bool = True,
) -> Tuple[Optional[str], Dict[str, Any]]:
    """Restore engine state onto the engine's OWN mesh, whatever mesh
    saved it. Torn (uncommitted) tags are refused loudly when named and
    invisible when resolving ``latest``. Returns (path, client_state)."""
    _barrier("load_checkpoint")  # don't read while a peer is mid-save
    if tag is None:
        tag = _manifest.latest_committed_tag(load_dir)
        if tag is None:
            log_dist(f"no committed checkpoint under {load_dir}; nothing loaded")
            return None, {}
    path = _manifest.require_committed(load_dir, tag)
    meta = _manifest.read_manifest(load_dir, tag)
    state = engine.state

    def stored_names(component):
        return (meta.get("components", {}).get(component) or {}).get("leaf_names")

    def load_component(template, component, shardings):
        cdir = os.path.join(path, component)
        # format auto-detected from disk, so either engine reads either layout
        if os.path.isdir(os.path.join(cdir, _ORBAX_SUBDIR)):
            return _load_tree_orbax(template, cdir, shardings, strict)
        return _load_tree_resharded(
            template, cdir, shardings, strict, stored_names(component)
        )

    params = load_component(state.params, "params", engine.param_shardings)
    opt_state = load_component(state.opt_state, "opt_state", engine.opt_shardings)
    loss_scale = load_component(
        state.loss_scale,
        "loss_scale",
        jax.tree.map(lambda _: engine._replicated, state.loss_scale),
    )

    import jax.numpy as jnp

    engine.state = type(state)(
        params,
        opt_state,
        loss_scale,
        jax.device_put(jnp.asarray(meta["step"], jnp.int32), engine._replicated),
    )
    engine.global_steps = meta["global_steps"]
    engine.micro_steps = meta["micro_steps"]
    engine.skipped_steps = meta["skipped_steps"]
    engine._rng = jnp.asarray(np.asarray(meta["rng"], dtype=np.uint32))
    log_dist(
        f"loaded checkpoint {path} (step {meta['global_steps']}, resharded "
        f"onto {engine.topology.world_size} devices)"
    )
    return path, meta.get("client_state", {})
