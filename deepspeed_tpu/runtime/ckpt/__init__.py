"""Fault-tolerance control plane: async sharded checkpointing with
resharding-on-restore (ROADMAP item 1's dynamic half; PAPER.md L5).

Three pillars, one package:

- :mod:`.async_writer` — the device→pinned-host snapshot pipeline and the
  background shard writer behind ``save_checkpoint(..., async_save=True)``,
  fenced by a :class:`~.async_writer.CheckpointGuard`.
- :mod:`.reshard` — restore onto a *different* ``ParallelDims`` /
  ``MeshTopology`` / ZeRO stage, assembling each destination shard from
  only the overlapping source byte ranges.
- :mod:`.manifest` — the committed-manifest-last atomicity rule: a tag is
  visible to restore iff its manifest landed, so a torn save (killed
  writer) can never be resumed from.

The elastic supervisor (``launcher/elastic.py`` + ``tools/elastic_run.py``)
rides these to survive preemption: SIGTERM → final sync save (chained in
front of healthwatch's postmortem hook) → relaunch on the survivor mesh →
resume from the latest *committed* tag. docs/checkpointing.md holds the
manifest schema and the contracts.
"""

from .async_writer import (
    CheckpointGuard,
    install_preempt_handler,
    reset_preempt_handler,
    save_checkpoint,
)
from .manifest import (
    MANIFEST_VERSION,
    UncommittedCheckpointError,
    is_committed,
    latest_committed_tag,
    require_committed,
)
from .reshard import load_checkpoint

__all__ = [
    "CheckpointGuard",
    "MANIFEST_VERSION",
    "UncommittedCheckpointError",
    "install_preempt_handler",
    "is_committed",
    "latest_committed_tag",
    "load_checkpoint",
    "require_committed",
    "reset_preempt_handler",
    "save_checkpoint",
]
