"""Checkpoint manifest: the commit record and the atomicity rule.

A tag directory is COMMITTED iff its ``metadata.json`` exists — shard
files land first, the manifest lands last (via write-to-temp +
``os.replace``, so it is never observable half-written), and the root
``latest`` pointer is only advanced after the commit. A writer killed
mid-save therefore leaves a torn tag that is *invisible* to restore:
``latest`` still names the previous committed tag, ``list_checkpoints``
skips the torn directory, and explicitly requesting the torn tag raises
:class:`UncommittedCheckpointError` loudly instead of assembling a
corrupt tree.

The manifest is a superset of the legacy ``metadata.json`` (so every
pre-manifest checkpoint remains readable): per component it additionally
records every leaf's **global shape**, dtype, per-dimension shard
divisors (the ``analysis/cost`` dimspec of the sharding that saved it)
and the ``bounds_token`` layout per shard — everything
:mod:`.reshard` needs to assemble a *different* mesh's shards from only
the overlapping source byte ranges. Schema in docs/checkpointing.md.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

from ...utils.logging import log_dist

MANIFEST_VERSION = 1
MANIFEST_NAME = "metadata.json"


class UncommittedCheckpointError(RuntimeError):
    """An explicitly requested tag exists on disk but never committed
    (torn save: the writer died before its manifest landed)."""


def manifest_path(save_dir: str, tag: str) -> str:
    return os.path.join(save_dir, str(tag), MANIFEST_NAME)


def is_committed(save_dir: str, tag: str) -> bool:
    return os.path.exists(manifest_path(save_dir, tag))


def require_committed(save_dir: str, tag: str) -> str:
    """The refuse-torn-saves gate: the tag's directory path, or a loud
    error naming the torn tag when shards exist without a manifest."""
    path = os.path.join(save_dir, str(tag))
    if is_committed(save_dir, tag):
        return path
    if os.path.isdir(path):
        raise UncommittedCheckpointError(
            f"checkpoint tag {tag!r} under {save_dir!r} is NOT committed "
            f"(shard files without a manifest — the writer died mid-save). "
            f"Refusing to restore a torn checkpoint; resume from the "
            f"latest committed tag instead (tag=None)."
        )
    raise FileNotFoundError(
        f"no checkpoint tag {tag!r} under {save_dir!r}"
    )


def latest_committed_tag(save_dir: str) -> Optional[str]:
    """Resolve the newest committed tag. ``latest`` is written only
    after a commit so it normally IS committed; if a crash left it
    pointing at a torn tag anyway (or at a deleted one), fall back to
    the newest committed directory rather than failing the resume."""
    latest = os.path.join(save_dir, "latest")
    if os.path.exists(latest):
        with open(latest) as f:
            tag = f.read().strip()
        if tag and is_committed(save_dir, tag):
            return tag
        log_dist(
            f"ckpt: `latest` names uncommitted tag {tag!r} (torn save?); "
            f"falling back to the newest committed tag"
        )
    from ..checkpointing import list_checkpoints

    tags = list_checkpoints(save_dir)  # committed-only by construction
    return tags[-1] if tags else None


def read_manifest(save_dir: str, tag: str) -> Dict[str, Any]:
    with open(manifest_path(save_dir, tag)) as f:
        return json.load(f)


def write_manifest(save_dir: str, tag: str, meta: Dict[str, Any]) -> str:
    """Atomically land the manifest — the commit point of a save. Must
    be called only after every shard file of the tag is on disk."""
    path = manifest_path(save_dir, tag)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def advance_latest(save_dir: str, tag: str) -> None:
    """Point ``latest`` at a freshly committed tag (atomic for the same
    reason as the manifest: a reader must never see a half-written
    pointer)."""
    path = os.path.join(save_dir, "latest")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(tag))
    os.replace(tmp, path)


def prune_keep_last(save_dir: str, keep_last: int) -> list:
    """Delete committed tags beyond the newest ``keep_last`` (0 keeps
    everything). Torn tags are also swept — they are unreachable by
    construction and only waste disk. Returns the removed tag names."""
    if keep_last <= 0:
        return []
    import shutil

    from ..checkpointing import list_checkpoints

    committed = list_checkpoints(save_dir)
    doomed = committed[:-keep_last] if len(committed) > keep_last else []
    doomed += [
        d
        for d in os.listdir(save_dir)
        if os.path.isdir(os.path.join(save_dir, d))
        and d not in committed
        and not is_committed(save_dir, d)
        # only sweep dirs that are recognizably torn TAGS (have a params
        # component) — never a foreign directory a user parked here
        and os.path.isdir(os.path.join(save_dir, d, "params"))
    ]
    for tag in doomed:
        shutil.rmtree(os.path.join(save_dir, tag), ignore_errors=True)
        log_dist(f"ckpt: pruned tag {tag} (keep_last={keep_last})")
    return doomed
