"""Activation checkpointing (rematerialisation).

Parity: deepspeed/runtime/activation_checkpointing/checkpointing.py. The
reference re-runs forward chunks in backward and can partition/offload the
saved activations across ranks; on TPU this is ``jax.checkpoint`` with a
saveable-policy — XLA re-materialises inside the fused backward, and
``offload_host`` maps saved residuals to host memory (the cpu_checkpointing
equivalent).
"""

from __future__ import annotations

import jax

_POLICIES = {}


def _register_policies():
    cp = jax.checkpoint_policies
    _POLICIES.update(
        {
            # save nothing: recompute the whole block in backward
            "full": cp.nothing_saveable,
            # save matmul outputs (cheap recompute for elementwise only)
            "dots_saveable": cp.dots_saveable,
            "dots_with_no_batch_dims": cp.dots_with_no_batch_dims_saveable,
            # save only named activations (tagged in models/transformer._block)
            "attn_only": cp.save_only_these_names("attn_out"),
            "attn_mlp": cp.save_only_these_names("attn_out", "mlp_out"),
            "nothing": cp.nothing_saveable,
            # dots_saveable + the flash-attention kernel outputs (tagged in
            # ops/pallas/flash_attention._fa_fwd): saves matmul outputs AND
            # (out, lse), so backward recomputes only elementwise chains —
            # the flash forward kernel never re-runs. Memory over plain
            # dots_saveable: +[B,H,S,D]+[B,H,S] per layer (~3% at S=2048).
            "dots_flash": cp.save_from_both_policies(
                cp.dots_saveable,
                cp.save_only_these_names("flash_out", "flash_lse"),
            ),
        }
    )
    if hasattr(cp, "save_and_offload_only_these_names"):
        _POLICIES["offload_host"] = cp.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=["attn_out", "mlp_out"],
            offload_src="device",
            offload_dst="pinned_host",
        )


_register_policies()


def policy_by_name(name: str):
    if name in ("none", None):
        return None
    if name not in _POLICIES:
        raise KeyError(f"unknown remat policy {name!r}; have {sorted(_POLICIES)}")
    return _POLICIES[name]


def checkpoint_fn(fn, policy_name: str = "full"):
    """Wrap ``fn`` with jax.checkpoint under the named policy."""
    if policy_name in ("none", None):
        return fn
    return jax.checkpoint(fn, policy=policy_by_name(policy_name), prevent_cse=False)
