"""Engine checkpointing: save/load of the full training state.

Parity: deepspeed/runtime/engine.py save_checkpoint/load_checkpoint +
deepspeed/checkpoint/ (universal checkpoint + checkpoint_engine sharded
writers). Design, TPU-first:

- Each leaf is written as **shard files**: every process writes only its
  addressable shards (replica 0 of each), with the shard's global slice
  bounds encoded in the filename (``leaf_00012.shard.128-256_0-512.npy``).
  A ZeRO-3 70B leaf therefore never materializes unsharded on any host at
  save time — the failure mode of r2's gather-then-np.save design.
- Checkpoints stay **universal**: shards are rectangles of one logical
  array, so the load path assembles whatever rectangles it finds and
  ``device_put``s with the *target* engine's shardings — any mesh shape /
  dp size / ZeRO stage. The reference needs an offline conversion step
  (ds_to_universal.py) because its ZeRO shards are rank-local optimizer
  fragments; ours are sharding annotations on one logical array.
- Leaves are matched **by recorded pytree path**, not flat index, so
  adding/reordering parameters between save and load maps correctly
  (strict=False keeps current values for unmatched leaves).
- ``latest`` tag file and ``global_step{N}`` tag directories match the
  reference's on-disk layout so downstream tooling translates directly.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from ..utils.logging import log_dist

_LEAF_FMT = "leaf_{:05d}.npy"  # legacy (r2) unsharded layout, still readable
_SHARD_FMT = "leaf_{:05d}.shard.{}.npy"
_COMPONENTS = ("params", "opt_state", "loss_scale")


def _tag_dir(save_dir: str, tag: str) -> str:
    return os.path.join(save_dir, str(tag))


def _leaf_paths(tree) -> list:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [jax.tree_util.keystr(path) for path, _ in flat]


def _to_host(leaf) -> np.ndarray:
    """Fetch a (possibly cross-host-sharded) jax.Array to host memory.

    Multi-host: a ZeRO-3 leaf is not fully addressable from one process, so
    replicate it first (jit with replicated out-sharding → XLA all-gather
    over ICI/DCN), then read the local copy. Single-host arrays skip the
    extra copy."""
    if not hasattr(leaf, "sharding"):
        return np.asarray(leaf)
    from jax.sharding import NamedSharding, PartitionSpec

    kind = getattr(leaf.sharding, "memory_kind", None)
    if kind and kind != "device" and hasattr(leaf.sharding, "mesh"):
        # offloaded (pinned_host) leaves can't be read directly through all
        # PJRT transports — bounce through device memory first (plain
        # device_put: no compilation, unlike a per-leaf jitted identity).
        # Mesh-less shardings (SingleDeviceSharding on CPU backends whose
        # default kind is a host kind) are directly readable — skip.
        dev = NamedSharding(leaf.sharding.mesh, leaf.sharding.spec)
        leaf = jax.device_put(leaf, dev)
    if getattr(leaf, "is_fully_addressable", True):
        return np.asarray(jax.device_get(leaf))

    mesh = leaf.sharding.mesh
    replicated = NamedSharding(mesh, PartitionSpec())
    gathered = jax.jit(lambda x: x, out_shardings=replicated)(leaf)
    return np.asarray(gathered.addressable_data(0))


def _is_writer() -> bool:
    """Only process 0 writes files on a multi-process pod (all processes
    still participate in the gathers inside :func:`_to_host`)."""
    return jax.process_index() == 0


def _barrier(name: str) -> None:
    from .. import comm

    comm.barrier(name)


def _bounds_token(index, shape) -> str:
    """Encode a shard's global slice bounds for its filename."""
    if not shape:
        return "0d"
    parts = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        parts.append(f"{start}-{stop}")
    return "_".join(parts)


def _parse_bounds(token: str):
    """Filename token → tuple of slices (or () for 0-d)."""
    if token == "0d":
        return ()
    return tuple(
        slice(int(a), int(b))
        for a, b in (p.split("-") for p in token.split("_"))
    )


def _device_view(leaf):
    """Offloaded (pinned_host) leaves can't always be read through PJRT —
    bounce to device memory first (plain device_put: no compilation)."""
    kind = getattr(getattr(leaf, "sharding", None), "memory_kind", None)
    if kind and kind != "device":
        from jax.sharding import NamedSharding

        return jax.device_put(
            leaf, NamedSharding(leaf.sharding.mesh, leaf.sharding.spec)
        )
    return leaf


_ORBAX_SUBDIR = "orbax"


def _clean_component_dir(directory: str) -> None:
    """Remove the previous generation of BOTH formats before a save: mixing
    old shard files or a stale orbax/ tree with a fresh save would make the
    loader's format auto-detect pick up outdated state."""
    import shutil

    if _is_writer() and os.path.isdir(directory):
        for f in os.listdir(directory):
            if f.startswith("leaf_") and f.endswith(".npy"):
                os.remove(os.path.join(directory, f))
        stale_orbax = os.path.join(directory, _ORBAX_SUBDIR)
        if os.path.isdir(stale_orbax):
            shutil.rmtree(stale_orbax)
    _barrier("save_tree_clean")


def _save_tree_orbax(tree, directory: str) -> Dict[str, Any]:
    """Orbax engine (reference-parity pluggable checkpoint_engine): tensorstore
    shard files, per-process writes, async-capable. Same universality: restore
    takes the *target* engine's shardings."""
    import orbax.checkpoint as ocp

    os.makedirs(directory, exist_ok=True)
    _clean_component_dir(directory)
    # pinned_host (offloaded) leaves bounce to device memory first — not all
    # PJRT transports can read host-memory shards directly
    tree = jax.tree.map(_device_view, tree)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(directory, _ORBAX_SUBDIR), tree, force=True)
    ckptr.wait_until_finished()
    return {
        "num_leaves": len(jax.tree_util.tree_leaves(tree)),
        "leaf_names": _leaf_paths(tree),
        "format": "orbax",
    }


def _load_tree_orbax(template, directory: str, shardings=None,
                     strict: bool = True):
    import orbax.checkpoint as ocp

    if shardings is None:
        target = jax.tree.map(
            lambda o: jax.ShapeDtypeStruct(o.shape, o.dtype), template
        )
    else:
        target = jax.tree.map(
            lambda o, s: jax.ShapeDtypeStruct(o.shape, o.dtype, sharding=s),
            template,
            shardings,
        )
    ckptr = ocp.StandardCheckpointer()
    try:
        return ckptr.restore(os.path.join(directory, _ORBAX_SUBDIR), target=target)
    except Exception:
        if strict:
            raise
        # coarser than the native engine's per-leaf fallback: Orbax restores
        # whole trees, so a structure/shape mismatch keeps the component's
        # current values wholesale
        log_dist(
            f"strict=False: orbax restore of {directory} failed "
            f"(structure/shape mismatch); keeping current values for this "
            f"component"
        )
        if shardings is None:
            return template
        return jax.device_put(template, shardings)


def _save_tree(tree, directory: str) -> Dict[str, Any]:
    """Shard-wise save: each process writes replica-0 addressable shards.

    No leaf is ever gathered unsharded (reference parity:
    deepspeed/runtime/checkpoint_engine writes rank-local shard files)."""
    os.makedirs(directory, exist_ok=True)
    # clear the previous generation (either format): a re-save under a
    # different mesh writes different bounds tokens, and mixing generations
    # or formats would assemble corrupt/stale arrays
    _clean_component_dir(directory)
    leaves = jax.tree_util.tree_leaves(tree)
    names = _leaf_paths(tree)
    for i, leaf in enumerate(leaves):
        if not hasattr(leaf, "addressable_shards"):
            if _is_writer():  # host scalars/np arrays: tiny, process 0 only
                arr = np.asarray(leaf)
                token = _bounds_token(
                    tuple(slice(0, d) for d in arr.shape), arr.shape
                )
                np.save(
                    os.path.join(directory, _SHARD_FMT.format(i, token)), arr
                )
            continue
        leaf = _device_view(leaf)
        for shard in leaf.addressable_shards:
            if shard.replica_id != 0:
                continue  # exactly one global writer per distinct shard
            token = _bounds_token(shard.index, leaf.shape)
            np.save(
                os.path.join(directory, _SHARD_FMT.format(i, token)),
                np.asarray(shard.data),
            )
    return {"num_leaves": len(leaves), "leaf_names": names}


def _index_shard_files(directory: str) -> Dict[int, list]:
    """Map stored leaf index → [(bounds, path)] for both layouts."""
    out: Dict[int, list] = {}
    if not os.path.isdir(directory):
        return out
    shard_re = re.compile(r"^leaf_(\d{5})\.shard\.([0-9d_\-]+)\.npy$")
    legacy_re = re.compile(r"^leaf_(\d{5})\.npy$")
    for f in os.listdir(directory):
        m = shard_re.match(f)
        if m:
            out.setdefault(int(m.group(1)), []).append(
                (_parse_bounds(m.group(2)), os.path.join(directory, f))
            )
            continue
        m = legacy_re.match(f)
        if m:  # r2 unsharded layout: one full-array file
            out.setdefault(int(m.group(1)), []).append(
                (None, os.path.join(directory, f))
            )
    return out


def _assemble_leaf(entries):
    """Read shard files into one host array (None bounds = full array)."""
    if any(b is None for b, _ in entries):
        if len(entries) > 1:  # legacy full-array file mixed with shards
            raise ValueError(
                f"corrupt checkpoint: legacy and shard files coexist for one "
                f"leaf: {[p for _, p in entries]}"
            )
        return np.load(entries[0][1])
    first = np.load(entries[0][1])
    if not entries[0][0]:  # 0-d
        return first
    # global shape = max stop over shards per dim
    ndim = first.ndim
    shape = [0] * ndim
    for bounds, _ in entries:
        for d, sl in enumerate(bounds):
            shape[d] = max(shape[d], sl.stop)
    out = np.empty(shape, first.dtype)
    covered = 0
    for bounds, path in entries:
        piece = np.load(path)
        out[bounds] = piece
        covered += piece.size
    if covered != out.size:  # GSPMD shards are disjoint → sizes must tile
        raise ValueError(
            f"corrupt checkpoint: shards cover {covered} of {out.size} "
            f"elements for {entries[0][1].rsplit('.shard.', 1)[0]} (missing "
            f"or duplicated shard files — partial save?)"
        )
    return out


def _load_tree(template, directory: str, shardings=None, strict: bool = True,
               stored_names=None):
    """Rebuild the tree from shard files, matching leaves by recorded pytree
    path (``stored_names`` from metadata) with flat-index fallback for
    checkpoints that predate name metadata."""
    flat, _ = jax.tree_util.tree_flatten_with_path(template)
    names = [jax.tree_util.keystr(path) for path, _ in flat]
    leaves = [leaf for _, leaf in flat]
    files = _index_shard_files(directory)
    if stored_names and len(stored_names) == len(set(stored_names)):
        name_to_stored = {n: i for i, n in enumerate(stored_names)}
    else:
        name_to_stored = {n: i for i, n in enumerate(names)}  # positional

    loaded = []
    for i, (name, old) in enumerate(zip(names, leaves)):
        stored_i = name_to_stored.get(name)
        entries = files.get(stored_i) if stored_i is not None else None
        if not entries:
            if strict:
                raise FileNotFoundError(
                    f"checkpoint missing leaf {name!r} (index {stored_i}) "
                    f"under {directory}"
                )
            log_dist(f"strict=False: missing leaf {name}, keeping current value")
            loaded.append(np.asarray(jax.device_get(old)))
            continue
        new = _assemble_leaf(entries)
        if tuple(old.shape) != tuple(new.shape):
            if strict:
                raise ValueError(
                    f"checkpoint leaf {name} shape {new.shape} != expected "
                    f"{old.shape} (did the model/optimizer config change? pass "
                    f"strict=False to keep mismatched leaves at their current "
                    f"values)"
                )
            log_dist(
                f"strict=False: leaf {name} shape {new.shape} != {old.shape}, "
                f"keeping current value"
            )
            new = np.asarray(jax.device_get(old))
        loaded.append(new)
    treedef = jax.tree_util.tree_structure(template)
    tree = jax.tree_util.tree_unflatten(treedef, loaded)
    if shardings is not None:
        # device_put with the *target* shardings: this is what makes every
        # checkpoint universal — the source mesh never constrains the load.
        tree = jax.tree.map(
            lambda x, s, o: jax.device_put(np.asarray(x, dtype=o.dtype), s),
            tree,
            shardings,
            template,
        )
    return tree


_ZERO_TO_FP32_SCRIPT = '''\
#!/usr/bin/env python
"""Assemble the full fp32 model weights from this (possibly ZeRO-sharded)
checkpoint directory — no engine, no config (parity: the zero_to_fp32.py
the reference drops into every checkpoint). Thin shim over
deepspeed_tpu.zero so the export logic has exactly one implementation.

Usage: python zero_to_fp32.py <checkpoint_dir> <output.npz> [tag]
"""
import sys

from deepspeed_tpu.zero import convert_zero_checkpoint_to_fp32_state_dict

if __name__ == "__main__":
    if len(sys.argv) < 3:
        raise SystemExit(__doc__)
    convert_zero_checkpoint_to_fp32_state_dict(
        sys.argv[1], sys.argv[2],
        tag=sys.argv[3] if len(sys.argv) > 3 else None,
    )
    print(f"wrote {sys.argv[2]}")
'''


def save_checkpoint(
    engine,
    save_dir: str,
    tag: Optional[str] = None,
    client_state: Optional[Dict[str, Any]] = None,
) -> str:
    """Write model+optimizer+loss-scale+step+rng (+client state) to disk."""
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    path = _tag_dir(save_dir, tag)
    if _is_writer():
        os.makedirs(path, exist_ok=True)

    state = engine.state
    meta: Dict[str, Any] = {
        "tag": tag,
        "global_steps": engine.global_steps,
        "micro_steps": engine.micro_steps,
        "skipped_steps": engine.skipped_steps,
        "step": int(jax.device_get(state.step)),
        "rng": np.asarray(jax.device_get(engine._rng)).tolist(),
        "client_state": client_state or {},
        "zero_stage": engine.config.zero_config.stage,
        "world_size": engine.topology.world_size,
        "components": {},
    }
    trees = {
        "params": state.params,
        "opt_state": state.opt_state,
        "loss_scale": state.loss_scale,
    }
    use_orbax = (
        getattr(getattr(engine.config, "checkpoint", None), "engine", "native")
        == "orbax"
    )
    saver = _save_tree_orbax if use_orbax else _save_tree
    for name, tree in trees.items():
        meta["components"][name] = saver(tree, os.path.join(path, name))
    if _is_writer():
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f, indent=1)
        # reference layout: `latest` at the checkpoint root names the newest tag
        with open(os.path.join(save_dir, "latest"), "w") as f:
            f.write(tag)
        # reference layout: every checkpoint root carries a runnable
        # zero_to_fp32.py so weights are recoverable with no engine and no
        # knowledge of this package's APIs (deepspeed's engine drops the
        # same script via _save_zero_checkpoint)
        with open(os.path.join(save_dir, "zero_to_fp32.py"), "w") as f:
            f.write(_ZERO_TO_FP32_SCRIPT)
    _barrier("save_checkpoint")  # non-writers must not race ahead of the files
    log_dist(f"saved checkpoint {path}")
    return path


def load_checkpoint(
    engine,
    load_dir: str,
    tag: Optional[str] = None,
    strict: bool = True,
) -> Tuple[Optional[str], Dict[str, Any]]:
    """Restore engine state. Returns (path, client_state) like the reference.

    ``strict=False`` keeps the engine's current value for any leaf that is
    missing or shape-mismatched (fine-tune with a resized head, changed
    optimizer, ...) instead of raising."""
    _barrier("load_checkpoint")  # don't read while the writer is mid-save
    if tag is None:
        latest = os.path.join(load_dir, "latest")
        if not os.path.exists(latest):
            log_dist(f"no `latest` file under {load_dir}; nothing loaded")
            return None, {}
        with open(latest) as f:
            tag = f.read().strip()
    path = _tag_dir(load_dir, tag)
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)

    state = engine.state

    def stored_names(component):
        return (meta.get("components", {}).get(component) or {}).get("leaf_names")

    def load_component(template, component, shardings):
        cdir = os.path.join(path, component)
        # format auto-detected from disk, so either engine reads either layout
        if os.path.isdir(os.path.join(cdir, _ORBAX_SUBDIR)):
            return _load_tree_orbax(template, cdir, shardings, strict)
        return _load_tree(template, cdir, shardings, strict, stored_names(component))

    params = load_component(state.params, "params", engine.param_shardings)
    opt_state = load_component(state.opt_state, "opt_state", engine.opt_shardings)
    loss_scale = load_component(
        state.loss_scale,
        "loss_scale",
        jax.tree.map(lambda _: engine._replicated, state.loss_scale),
    )

    import jax.numpy as jnp

    engine.state = type(state)(
        params,
        opt_state,
        loss_scale,
        jax.device_put(jnp.asarray(meta["step"], jnp.int32), engine._replicated),
    )
    engine.global_steps = meta["global_steps"]
    engine.micro_steps = meta["micro_steps"]
    engine.skipped_steps = meta["skipped_steps"]
    engine._rng = jnp.asarray(np.asarray(meta["rng"], dtype=np.uint32))
    log_dist(f"loaded checkpoint {path} (step {meta['global_steps']})")
    return path, meta.get("client_state", {})


def resolve_tag(load_dir: str, tag: Optional[str] = None,
                component: Optional[str] = "params") -> str:
    """Resolve a checkpoint tag (``latest`` file when None) to its directory,
    checking the requested component exists. Shared by load_params and the
    zero_to_fp32 export (deepspeed_tpu/zero.py)."""
    if tag is None:
        latest = os.path.join(load_dir, "latest")
        if not os.path.exists(latest):
            raise FileNotFoundError(
                f"no `latest` file under {load_dir!r} — not an engine "
                f"checkpoint directory (expected the layout written by "
                f"save_checkpoint)"
            )
        with open(latest) as f:
            tag = f.read().strip()
    path = _tag_dir(load_dir, tag)
    if component and not os.path.isdir(os.path.join(path, component)):
        raise FileNotFoundError(
            f"checkpoint {path!r} has no {component} component"
        )
    return path


def load_params(load_dir: str, template, tag: Optional[str] = None):
    """Load just the model-params component of an engine checkpoint.

    ``template`` is a pytree of arrays or ShapeDtypeStructs with the target
    structure (e.g. ``jax.eval_shape(model.init, key)``). Used by
    ``init_inference(checkpoint=...)`` to serve trained weights without
    constructing a training engine."""
    path = resolve_tag(load_dir, tag)
    if os.path.isdir(os.path.join(path, "params", _ORBAX_SUBDIR)):
        return _load_tree_orbax(template, os.path.join(path, "params"))
    names = None
    meta_path = os.path.join(path, "metadata.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            names = (
                json.load(f).get("components", {}).get("params") or {}
            ).get("leaf_names")
    return _load_tree(template, os.path.join(path, "params"), None, True, names)


def list_checkpoints(save_dir: str) -> list:
    """Sorted tags present under save_dir (numeric-aware, reference layout)."""
    if not os.path.isdir(save_dir):
        return []
    tags = [
        d
        for d in os.listdir(save_dir)
        if os.path.isdir(os.path.join(save_dir, d))
        and os.path.exists(os.path.join(save_dir, d, "metadata.json"))
    ]

    def key(t):
        m = re.search(r"(\d+)$", t)
        return (0, int(m.group(1))) if m else (1, t)

    return sorted(tags, key=key)
