"""Engine checkpointing: save/load of the full training state.

Parity: deepspeed/runtime/engine.py save_checkpoint/load_checkpoint +
deepspeed/checkpoint/ (universal checkpoint). Design differences, TPU-first:

- Leaves are gathered to host and stored **unsharded** (one ``.npy`` per
  leaf), so every checkpoint is already a "universal" checkpoint: it can be
  loaded into any mesh shape / dp size / ZeRO stage — the load path simply
  ``device_put``s each leaf with the *target* engine's shardings. The
  reference needs a separate offline conversion step (ds_to_universal.py)
  because its ZeRO shards are rank-local files; ours are sharding
  annotations on one logical array.
- ``latest`` tag file and ``global_step{N}`` tag directories match the
  reference's on-disk layout so downstream tooling translates directly.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from ..utils.logging import log_dist

_LEAF_FMT = "leaf_{:05d}.npy"
_COMPONENTS = ("params", "opt_state", "loss_scale")


def _tag_dir(save_dir: str, tag: str) -> str:
    return os.path.join(save_dir, str(tag))


def _leaf_paths(tree) -> list:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [jax.tree_util.keystr(path) for path, _ in flat]


def _to_host(leaf) -> np.ndarray:
    """Fetch a (possibly cross-host-sharded) jax.Array to host memory.

    Multi-host: a ZeRO-3 leaf is not fully addressable from one process, so
    replicate it first (jit with replicated out-sharding → XLA all-gather
    over ICI/DCN), then read the local copy. Single-host arrays skip the
    extra copy."""
    if not hasattr(leaf, "sharding"):
        return np.asarray(leaf)
    from jax.sharding import NamedSharding, PartitionSpec

    kind = getattr(leaf.sharding, "memory_kind", None)
    if kind and kind != "device":
        # offloaded (pinned_host) leaves can't be read directly through all
        # PJRT transports — bounce through device memory first (plain
        # device_put: no compilation, unlike a per-leaf jitted identity)
        dev = NamedSharding(leaf.sharding.mesh, leaf.sharding.spec)
        leaf = jax.device_put(leaf, dev)
    if getattr(leaf, "is_fully_addressable", True):
        return np.asarray(jax.device_get(leaf))

    mesh = leaf.sharding.mesh
    replicated = NamedSharding(mesh, PartitionSpec())
    gathered = jax.jit(lambda x: x, out_shardings=replicated)(leaf)
    return np.asarray(gathered.addressable_data(0))


def _is_writer() -> bool:
    """Only process 0 writes files on a multi-process pod (all processes
    still participate in the gathers inside :func:`_to_host`)."""
    return jax.process_index() == 0


def _barrier(name: str) -> None:
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def _save_tree(tree, directory: str) -> Dict[str, Any]:
    if _is_writer():
        os.makedirs(directory, exist_ok=True)
    leaves = jax.tree_util.tree_leaves(tree)
    names = _leaf_paths(tree)
    for i, leaf in enumerate(leaves):
        host = _to_host(leaf)
        if _is_writer():
            np.save(os.path.join(directory, _LEAF_FMT.format(i)), host)
    return {"num_leaves": len(leaves), "leaf_names": names}


def _load_tree(template, directory: str, shardings=None, strict: bool = True):
    leaves = jax.tree_util.tree_leaves(template)
    loaded = []
    for i, old in enumerate(leaves):
        fname = os.path.join(directory, _LEAF_FMT.format(i))
        if not os.path.exists(fname):
            if strict:
                raise FileNotFoundError(f"checkpoint missing leaf file {fname}")
            log_dist(f"strict=False: missing {fname}, keeping current value")
            loaded.append(np.asarray(jax.device_get(old)))
            continue
        new = np.load(fname)
        if tuple(old.shape) != tuple(new.shape):
            if strict:
                raise ValueError(
                    f"checkpoint leaf {i} shape {new.shape} != expected {old.shape} "
                    f"(did the model/optimizer config change? pass strict=False "
                    f"to keep mismatched leaves at their current values)"
                )
            log_dist(
                f"strict=False: leaf {i} shape {new.shape} != {old.shape}, "
                f"keeping current value"
            )
            new = np.asarray(jax.device_get(old))
        loaded.append(new)
    treedef = jax.tree_util.tree_structure(template)
    tree = jax.tree_util.tree_unflatten(treedef, loaded)
    if shardings is not None:
        # device_put with the *target* shardings: this is what makes every
        # checkpoint universal — the source mesh never constrains the load.
        tree = jax.tree.map(
            lambda x, s, o: jax.device_put(np.asarray(x, dtype=o.dtype), s),
            tree,
            shardings,
            template,
        )
    return tree


def save_checkpoint(
    engine,
    save_dir: str,
    tag: Optional[str] = None,
    client_state: Optional[Dict[str, Any]] = None,
) -> str:
    """Write model+optimizer+loss-scale+step+rng (+client state) to disk."""
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    path = _tag_dir(save_dir, tag)
    if _is_writer():
        os.makedirs(path, exist_ok=True)

    state = engine.state
    meta: Dict[str, Any] = {
        "tag": tag,
        "global_steps": engine.global_steps,
        "micro_steps": engine.micro_steps,
        "skipped_steps": engine.skipped_steps,
        "step": int(jax.device_get(state.step)),
        "rng": np.asarray(jax.device_get(engine._rng)).tolist(),
        "client_state": client_state or {},
        "zero_stage": engine.config.zero_config.stage,
        "world_size": engine.topology.world_size,
        "components": {},
    }
    trees = {
        "params": state.params,
        "opt_state": state.opt_state,
        "loss_scale": state.loss_scale,
    }
    for name, tree in trees.items():
        meta["components"][name] = _save_tree(tree, os.path.join(path, name))
    if _is_writer():
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f, indent=1)
        # reference layout: `latest` at the checkpoint root names the newest tag
        with open(os.path.join(save_dir, "latest"), "w") as f:
            f.write(tag)
    _barrier("save_checkpoint")  # non-writers must not race ahead of the files
    log_dist(f"saved checkpoint {path}")
    return path


def load_checkpoint(
    engine,
    load_dir: str,
    tag: Optional[str] = None,
    strict: bool = True,
) -> Tuple[Optional[str], Dict[str, Any]]:
    """Restore engine state. Returns (path, client_state) like the reference.

    ``strict=False`` keeps the engine's current value for any leaf that is
    missing or shape-mismatched (fine-tune with a resized head, changed
    optimizer, ...) instead of raising."""
    _barrier("load_checkpoint")  # don't read while the writer is mid-save
    if tag is None:
        latest = os.path.join(load_dir, "latest")
        if not os.path.exists(latest):
            log_dist(f"no `latest` file under {load_dir}; nothing loaded")
            return None, {}
        with open(latest) as f:
            tag = f.read().strip()
    path = _tag_dir(load_dir, tag)
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)

    state = engine.state
    params = _load_tree(
        state.params, os.path.join(path, "params"), engine.param_shardings, strict
    )
    opt_state = _load_tree(
        state.opt_state, os.path.join(path, "opt_state"), engine.opt_shardings, strict
    )
    loss_scale = _load_tree(
        state.loss_scale,
        os.path.join(path, "loss_scale"),
        jax.tree.map(lambda _: engine._replicated, state.loss_scale),
        strict,
    )

    import jax.numpy as jnp

    engine.state = type(state)(
        params,
        opt_state,
        loss_scale,
        jax.device_put(jnp.asarray(meta["step"], jnp.int32), engine._replicated),
    )
    engine.global_steps = meta["global_steps"]
    engine.micro_steps = meta["micro_steps"]
    engine.skipped_steps = meta["skipped_steps"]
    engine._rng = jnp.asarray(np.asarray(meta["rng"], dtype=np.uint32))
    log_dist(f"loaded checkpoint {path} (step {meta['global_steps']})")
    return path, meta.get("client_state", {})


def load_params(load_dir: str, template, tag: Optional[str] = None):
    """Load just the model-params component of an engine checkpoint.

    ``template`` is a pytree of arrays or ShapeDtypeStructs with the target
    structure (e.g. ``jax.eval_shape(model.init, key)``). Used by
    ``init_inference(checkpoint=...)`` to serve trained weights without
    constructing a training engine."""
    if tag is None:
        latest = os.path.join(load_dir, "latest")
        if not os.path.exists(latest):
            raise FileNotFoundError(
                f"no `latest` file under {load_dir!r} — not an engine "
                f"checkpoint directory (expected the layout written by "
                f"save_checkpoint)"
            )
        with open(latest) as f:
            tag = f.read().strip()
    path = _tag_dir(load_dir, tag)
    if not os.path.isdir(os.path.join(path, "params")):
        raise FileNotFoundError(f"checkpoint {path!r} has no params component")
    return _load_tree(template, os.path.join(path, "params"), None, True)


def list_checkpoints(save_dir: str) -> list:
    """Sorted tags present under save_dir (numeric-aware, reference layout)."""
    if not os.path.isdir(save_dir):
        return []
    tags = [
        d
        for d in os.listdir(save_dir)
        if os.path.isdir(os.path.join(save_dir, d))
        and os.path.exists(os.path.join(save_dir, d, "metadata.json"))
    ]

    def key(t):
        m = re.search(r"(\d+)$", t)
        return (0, int(m.group(1))) if m else (1, t)

    return sorted(tags, key=key)
