"""Optimizer factory.

Parity: deepspeed/ops/adam (FusedAdam), lion, adagrad, lamb, sgd — the
reference's fused CUDA multi-tensor kernels become optax transforms whose
update math XLA fuses into the sharded train step; the Pallas fused-adam
kernel (ops/pallas/fused_adam.py) is used on TPU for the flat update when
enabled. 1-bit optimizers live in ops/onebit.py.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax

from ..config import OptimizerConfig


def _lamb(learning_rate, b1=0.9, b2=0.999, eps=1e-6, weight_decay=0.0):
    """LAMB (reference: deepspeed/ops/lamb/fused_lamb.py semantics)."""
    return optax.chain(
        optax.scale_by_adam(b1=b1, b2=b2, eps=eps),
        optax.add_decayed_weights(weight_decay),
        optax.scale_by_trust_ratio(),
        optax.scale_by_learning_rate(learning_rate),
    )


def build_optimizer(
    cfg: OptimizerConfig,
    lr_schedule: Callable,
    *,
    use_pallas_adam: bool = False,
) -> optax.GradientTransformation:
    """Build the optax transform from an "optimizer" config section.

    The learning rate enters through ``_scale_by_schedule_positive`` (its
    state carries the update count); the engine reports the live lr by
    evaluating the same schedule at the state's step counter.
    """
    name = cfg.type.lower().replace("_", "")
    p = dict(cfg.params)
    p.pop("lr", None)
    betas = cfg.betas
    common = dict(b1=betas[0], b2=betas[1], eps=cfg.eps)

    if name in ("adam", "adamw", "fusedadam"):
        if use_pallas_adam:
            from ..ops.pallas.fused_adam import scale_by_fused_adam

            base = optax.chain(
                scale_by_fused_adam(b1=betas[0], b2=betas[1], eps=cfg.eps),
                optax.add_decayed_weights(cfg.weight_decay),
                optax.scale(-1.0),
            )
        else:
            base = optax.chain(
                optax.scale_by_adam(**common),
                optax.add_decayed_weights(cfg.weight_decay),
                optax.scale(-1.0),
            )
        tx = optax.chain(base, _scale_by_schedule_positive(lr_schedule))
    elif name == "lion":
        tx = optax.chain(
            optax.scale_by_lion(b1=betas[0], b2=betas[1]),
            optax.add_decayed_weights(cfg.weight_decay),
            optax.scale(-1.0),
            _scale_by_schedule_positive(lr_schedule),
        )
    elif name == "adagrad":
        tx = optax.chain(
            optax.scale_by_rss(initial_accumulator_value=p.get("initial_accumulator_value", 0.1)),
            optax.add_decayed_weights(cfg.weight_decay),
            optax.scale(-1.0),
            _scale_by_schedule_positive(lr_schedule),
        )
    elif name in ("lamb", "fusedlamb"):
        tx = optax.chain(
            optax.scale_by_adam(**common),
            optax.add_decayed_weights(cfg.weight_decay),
            optax.scale_by_trust_ratio(),
            optax.scale(-1.0),
            _scale_by_schedule_positive(lr_schedule),
        )
    elif name == "sgd":
        momentum = p.get("momentum", 0.0)
        tx = optax.chain(
            optax.trace(decay=momentum) if momentum else optax.identity(),
            optax.add_decayed_weights(cfg.weight_decay),
            optax.scale(-1.0),
            _scale_by_schedule_positive(lr_schedule),
        )
    elif name in ("onebitadam", "zerooneadam", "onebitlamb"):
        from ..ops.onebit import build_onebit_optimizer

        tx = build_onebit_optimizer(name, cfg, lr_schedule)
    else:
        raise KeyError(f"unknown optimizer type {cfg.type!r}")
    return tx


def _scale_by_schedule_positive(schedule: Callable) -> optax.GradientTransformation:
    """Like optax.scale_by_schedule but multiplies by +schedule(step) (sign is
    applied upstream so the live lr we report stays positive)."""

    def init_fn(params):
        del params
        return optax.ScaleByScheduleState(count=jnp.zeros([], jnp.int32))

    def update_fn(updates, state, params=None):
        del params
        lr = schedule(state.count)
        updates = jax.tree.map(lambda g: g * lr.astype(g.dtype), updates)
        return updates, optax.ScaleByScheduleState(count=state.count + 1)

    return optax.GradientTransformation(init_fn, update_fn)


def current_lr(schedule: Callable, step: int) -> float:
    return float(schedule(jnp.asarray(step, jnp.int32)))
