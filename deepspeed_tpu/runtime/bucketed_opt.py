"""Per-layer ("sub-group") optimizer stepping for offloaded state.

Parity: deepspeed/runtime/zero/stage3.py partitions parameters into
sub-groups (``sub_group_size``) and updates one group at a time precisely so
CPU-offloaded optimizer state (ops/adam/cpu_adam in the reference) streams
through a bounded device working set. The TPU-native form: the stacked
decoder-layer leaves [L, ...] step inside one ``lax.scan`` over L — XLA
schedules one layer's host→device m/v DMA, f32 update math, and
device→host writeback per tick, so peak HBM temp is ONE layer's update
working set instead of the whole tree's.

Why it's needed: a fused whole-tree ``optax`` update materializes a f32
temp per big leaf and the latency-hiding scheduler overlaps many of their
host transfers — the 1.4B bench config compiled to 13.9G of HLO temps and
OOM'd a 15.75G v5e. Scanned per-layer, the same math runs in a bounded
slice of that.

The state is ``{"rest": tx.init(non-layer leaves),
"layers": vmap(tx.init)(per-layer slices)}`` — same optax inner structure,
stacked along dim 0 for the layer part (count becomes [L], one per layer,
all equal). Checkpoints save/load it like any pytree; note the structure
differs from the unbucketed state, so toggling offload between save and
load is a config change (documented in runtime/checkpointing.py terms: the
tree must match).

Double buffering (``double_buffer=True``, config knob
``zero_optimization.offload_double_buffer`` a.k.a. ``sub_group_prefetch``):
the serial scan's body makes layer *i*'s host→HBM state DMA a data
dependency of layer *i*'s update math, so the scheduler cannot overlap
them (measured: ~43% of the 1.5B offload step is unoverlapped DMA,
docs/xprof_r5_1b_offload.md). The pipelined variant carries a two-slot
rotating buffer through the scan instead: the slice consumed at tick *i*
was prefetched at tick *i−1*, and tick *i* starts layer *i+1*'s prefetch
BEFORE the update math — the prefetch has no dependency on the update, so
XLA's latency-hiding scheduler is free to run the DMA under the compute
(the same warm-up-then-prefetch-next structure a hand-written Pallas
double-buffer loop uses). Costs one extra layer slice of HBM residency
(two slots live instead of one). The math per layer and its order are
identical, so trajectories match the serial scan bitwise on any mesh.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax


class BucketedOptimizer:
    """Wraps a GradientTransformation with per-layer scanned stepping."""

    def __init__(self, tx: optax.GradientTransformation,
                 stacked_key: str = "layers", double_buffer: bool = False):
        self.tx = tx
        self.key = stacked_key
        self.double_buffer = double_buffer

    def split(self, tree: Dict[str, Any]):
        rest = {k: v for k, v in tree.items() if k != self.key}
        return rest, tree[self.key]

    def stream_annotation(self) -> Dict[str, Any]:
        """steptrace args for the engine's ``plan/offload`` span: the
        offload-DMA phase structure (rotating-slot depth, prefetch
        on/off) that the scan hides inside one jitted program — the
        host-side trace can't bracket per-layer DMAs, so the span
        carries the declared shape instead (docs/observability.md)."""
        return {
            "offload_double_buffer": bool(self.double_buffer),
            "rotating_slots": 2 if self.double_buffer else 1,
            "stacked_key": self.key,
        }

    def init(self, params):
        rest, layers = self.split(params)
        return {
            "rest": self.tx.init(rest),
            # vmapped init: per-layer state slices stacked on dim 0
            "layers": jax.vmap(self.tx.init)(layers),
        }

    def step(
        self,
        grads,
        state,
        params,
        state_put: Optional[Tuple[Callable, Callable]] = None,
        param_put: Optional[Tuple[Callable, Callable]] = None,
    ) -> Tuple[Any, Any]:
        """One optimizer step. Returns (new_params, new_state).

        state_put/param_put: optional (to_device, to_host) per-layer-slice
        placement hooks for offloaded trees (device_put into compute
        memory on the way in, back to pinned host on the way out). They
        pin the streaming behavior explicitly so the scheduler cannot
        hoist a whole-tree transfer out of the scan; None when that tree
        is device-resident (or on CPU meshes, which have no memory kinds).
        """
        g_rest, g_layers = self.split(grads)
        p_rest, p_layers = self.split(params)
        u_rest, s_rest = self.tx.update(g_rest, state["rest"], p_rest)
        new_p_rest = optax.apply_updates(p_rest, u_rest)
        s_layers = state["layers"]

        if self.double_buffer:
            new_p_layers, new_s_layers = self._scan_double_buffered(
                g_layers, s_layers, p_layers, state_put, param_put
            )
        else:
            new_p_layers, new_s_layers = self._scan_serial(
                g_layers, s_layers, p_layers, state_put, param_put
            )
        new_params = dict(new_p_rest)
        new_params[self.key] = new_p_layers
        return new_params, {"rest": s_rest, "layers": new_s_layers}

    def _scan_serial(self, g_layers, s_layers, p_layers, state_put, param_put):
        # one lax.scan over the layer dim, placement hooks inside the body.
        # A hand-pipelined fori_loop variant (explicit one-slice prefetch +
        # per-slice dynamic_update writebacks) was built and MEASURED
        # SLOWER on-chip: 3,278 vs 4,609 tok/s at 1.5B — the manual
        # slicing/update structure cost more than the prefetch hid, so the
        # scan stays; overlapping the state DMA (29% of the step,
        # docs/xprof_r5_1b_offload.md) needs the double-buffer variant
        # below.
        def body(_, xs):
            g_l, s_l, p_l = xs
            if state_put is not None:
                s_l = state_put[0](s_l)
            if param_put is not None:
                p_l = param_put[0](p_l)
            u_l, s_new = self.tx.update(g_l, s_l, p_l)
            p_new = optax.apply_updates(p_l, u_l)
            if state_put is not None:
                s_new = state_put[1](s_new)
            if param_put is not None:
                p_new = param_put[1](p_new)
            return None, (p_new, s_new)

        _, (new_p, new_s) = lax.scan(
            body, None, (g_layers, s_layers, p_layers)
        )
        return new_p, new_s

    def _scan_double_buffered(self, g_layers, s_layers, p_layers,
                              state_put, param_put):
        """Software-pipelined layer stream with a two-slot rotating buffer.

        The carry holds the CURRENT layer's device-resident s/p slices
        (prefetched one tick earlier); each tick starts the NEXT layer's
        prefetch first — it has no data dependency on the update math, so
        the scheduler can overlap the host→HBM DMA with the compute —
        then runs the update on the carried slot and streams the result
        back through the writeback hooks. Layer order and per-layer math
        are identical to the serial scan, so trajectories match exactly.

        The stacked s/p trees stay scan-invariant closures (scan xs would
        re-serialize the slice-in against the body) and the prefetch index
        is clamped at the last tick rather than lax.cond-guarded: the
        branch-free body keeps the copy-start hoistable, at the price of
        one redundant layer re-fetch per step (~1/L of the stream).
        Gradients are device-resident already and ride as plain scan xs.
        """
        s_in = state_put[0] if state_put is not None else (lambda t: t)
        s_out = state_put[1] if state_put is not None else (lambda t: t)
        p_in = param_put[0] if param_put is not None else (lambda t: t)
        p_out = param_put[1] if param_put is not None else (lambda t: t)
        L = jax.tree_util.tree_leaves(g_layers)[0].shape[0]

        def slice_at(tree, i):
            return jax.tree.map(
                lambda x: lax.dynamic_index_in_dim(x, i, 0, keepdims=False),
                tree,
            )

        # warm-up: prefetch layer 0 into the first slot before the scan
        carry0 = (s_in(slice_at(s_layers, 0)), p_in(slice_at(p_layers, 0)))

        def body(carry, xs):
            g_l, i = xs
            s_l, p_l = carry
            # kick off layer i+1's slice-in first (independent of the math)
            nxt = jnp.minimum(i + 1, L - 1)
            s_next = s_in(slice_at(s_layers, nxt))
            p_next = p_in(slice_at(p_layers, nxt))
            u_l, s_new = self.tx.update(g_l, s_l, p_l)
            p_new = optax.apply_updates(p_l, u_l)
            return (s_next, p_next), (p_out(p_new), s_out(s_new))

        _, (new_p, new_s) = lax.scan(
            body, carry0, (g_layers, jnp.arange(L))
        )
        return new_p, new_s


def bucketed_applicable(params_shape, stacked_key: str = "layers") -> bool:
    """The scan needs the conventional stacked-layers param layout.

    Dim-0 sharding of the stacked leaves is NOT a disqualifier anymore:
    the engine re-puts the scanned groups to their resting shardings
    after the layer scan (``_apply_update``), so the carry-in ==
    carry-out closure holds for every spec shape — shardlint rule R2
    (deepspeed_tpu/analysis) checks that invariant statically."""
    return (
        isinstance(params_shape, dict)
        and stacked_key in params_shape
        and len(params_shape) > 1
    )
