"""The SPMD pipeline schedule.

Parity: deepspeed/runtime/pipe/schedule.py + engine.py (TrainSchedule,
InferenceSchedule, P2P send/recv). The reference runs an imperative 1F1B
instruction list per rank over NCCL p2p; the TPU-native schedule is one
``shard_map`` over the ``pp`` mesh axis (other axes stay auto, so dp/tp/sp
shardings keep flowing through XLA):

- Stacked layer params [L, ...] are sharded over pp on dim 0: each stage
  holds L/pp contiguous layers.
- A ``lax.scan`` over M + pp - 1 ticks implements GPipe filling/draining;
  stage outputs move to the next stage via ``lax.ppermute`` (ICI neighbor
  hop, the p2p send/recv pair).
- ``jax.grad`` through the scan+ppermute yields the reverse pipeline for
  backward automatically — with per-tick rematerialisation this is
  1F1B-equivalent activation memory (stash one activation per in-flight
  microbatch, recompute inside the tick's vjp).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ...comm.topology import MeshTopology
from ...models.transformer import TransformerConfig, apply_layer_stack


def neighbor_chain(n_stages: int):
    """The schedule's p2p fabric: stage i → i+1, NO wraparound edge.

    This is the exact shape shardlint R3 certifies as hang-free (a pure
    chain: injective, no self-loops, zero cycles —
    analysis/rules/topology.check_permutation); a ring or a stray extra
    edge here deadlocks real ICI, which the static check catches before a
    multi-chip run does."""
    return [(i, i + 1) for i in range(n_stages - 1)]


def pipelined_stack(
    cfg: TransformerConfig,
    layers,
    x: jax.Array,
    positions: jax.Array,
    segment_ids,
    topo: MeshTopology,
    train: bool,
    rng: Optional[jax.Array] = None,
    remat_policy: Optional[str] = None,
    tick_chunk: Optional[int] = None,
):
    """Run the block stack as a pp-stage pipeline over microbatches.

    layers: stacked block params [L, ...] (dim 0 sharded over pp).
    x: embedded microbatch stream [M, mb, S, D]; positions: [M, mb, S];
    segment_ids: [M, mb, S] or None. Returns (y [M, mb, S, D], moe_aux_mean).

    tick_chunk: checkpoint the schedule in chunks of this many ticks —
    grad-of-scan otherwise stashes one residual set per tick, i.e.
    O(num_microbatches) activations (measured: tools/pipe_memory.py),
    where the reference's 1F1B holds at most pp in-flight stashes
    (deepspeed/runtime/pipe/engine.py). Chunking stores only chunk-boundary
    carries and recomputes one chunk at a time during backward: peak stash
    is O(T/C + C) boundary activations (T = M + pp - 1) at ~2x forward
    compute — the scan-schedule equivalent of 1F1B's memory bound.
    """
    n_stages = topo.pp_size
    M = x.shape[0]
    num_layers = jax.tree_util.tree_leaves(layers)[0].shape[0]
    assert num_layers % n_stages == 0, (
        f"num_layers {num_layers} must divide pipeline stages {n_stages}"
    )
    # segment_ids stream alongside activations; a zeros stream when unused
    has_seg = segment_ids is not None
    seg = segment_ids if has_seg else jnp.zeros(positions.shape, jnp.int32)

    if n_stages == 1:
        def per_mb(args):
            xm, pm, sm, idx = args
            key = jax.random.fold_in(rng, idx) if rng is not None else None
            return apply_layer_stack(
                cfg, layers, xm, pm, sm if has_seg else None, key, train,
                remat_policy,
            )
        ys, auxs = lax.map(per_mb, (x, positions, seg, jnp.arange(M)))
        return ys, jnp.mean(auxs)

    fwd_perm = neighbor_chain(n_stages)

    ticks = M + n_stages - 1
    chunk = 0
    if tick_chunk:
        chunk = min(int(tick_chunk), ticks)
    padded_ticks = (
        ((ticks + chunk - 1) // chunk) * chunk if chunk else ticks
    )

    def body(local_layers, x_stream, pos_stream, seg_stream):
        stage = lax.axis_index("pp")

        def pad_stream(s):
            return jnp.pad(
                s, [(0, padded_ticks - M)] + [(0, 0)] * (s.ndim - 1)
            )

        x_pad, p_pad, s_pad = map(pad_stream, (x_stream, pos_stream, seg_stream))

        def tick(carry, inp):
            state, pstate, sstate, t = carry
            x_in, p_in, s_in = inp
            cur = jnp.where(stage == 0, x_in, state)
            pos = jnp.where(stage == 0, p_in, pstate)
            sg = jnp.where(stage == 0, s_in, sstate)
            # distinct randomness per (tick, stage): the in-flight microbatch
            # is t - stage, so fold both in (dense path splits per microbatch)
            key = (
                jax.random.fold_in(jax.random.fold_in(rng, t), stage)
                if rng is not None
                else None
            )
            out, aux = apply_layer_stack(
                cfg, local_layers, cur, pos, sg if has_seg else None, key,
                train, remat_policy,
            )
            # microbatch (t - stage) is in flight here; mask bubble ticks
            valid = (t >= stage) & (t < stage + M)
            aux = jnp.where(valid, aux, 0.0)
            y = jnp.where(stage == n_stages - 1, out, jnp.zeros_like(out))
            nxt = lax.ppermute(out, "pp", fwd_perm)
            pnxt = lax.ppermute(pos, "pp", fwd_perm)
            snxt = lax.ppermute(sg, "pp", fwd_perm)
            return (nxt, pnxt, snxt, t + 1), (y, aux)

        carry0 = (
            jnp.zeros(x_stream.shape[1:], x_stream.dtype),
            jnp.zeros(pos_stream.shape[1:], pos_stream.dtype),
            jnp.zeros(seg_stream.shape[1:], seg_stream.dtype),
            jnp.zeros((), jnp.int32),
        )
        if chunk:
            # checkpointed chunks: backward stores only the chunk-boundary
            # carries (one boundary activation each) and replays one chunk
            # of ticks at a time; ticks beyond `ticks` are bubble work the
            # valid-mask zeroes and the output slice drops
            def run_chunk(carry, inp):
                return lax.scan(tick, carry, inp)

            xs = tuple(
                a.reshape(padded_ticks // chunk, chunk, *a.shape[1:])
                for a in (x_pad, p_pad, s_pad)
            )
            _, (ys, auxs) = lax.scan(jax.checkpoint(run_chunk), carry0, xs)
            ys = ys.reshape(padded_ticks, *ys.shape[2:])
            auxs = auxs.reshape(padded_ticks)
        else:
            _, (ys, auxs) = lax.scan(tick, carry0, (x_pad, p_pad, s_pad))
        # valid outputs live on the last stage at ticks [pp-1, pp-1+M);
        # broadcast them to every stage (head/loss then run replicated-on-pp).
        # fp32 psum: XLA's CPU AllReducePromotion pass crashes on bf16
        # all-reduce under partial-manual shard_map (workaround; fp32 is
        # also the dtype the head consumes anyway).
        ys = lax.psum(
            ys[n_stages - 1:n_stages - 1 + M].astype(jnp.float32), "pp"
        ).astype(x_stream.dtype)
        aux_total = lax.psum(jnp.sum(auxs), "pp")  # sum over stages+ticks
        return ys, aux_total / M

    from ...utils.jax_compat import shard_map

    run = shard_map(
        body,
        mesh=topo.mesh,
        in_specs=(P("pp"), P(), P(), P()),
        out_specs=(P(), P()),
        axis_names={"pp"},
        check_vma=False,
    )
    return run(layers, x, positions, seg)
