"""Pipeline engine.

Parity: deepspeed/runtime/pipe/engine.py (PipelineEngine). The reference
subclasses DeepSpeedEngine and replaces train_batch with an instruction-list
schedule executor; here the only override is gradient computation — the
microbatch stream goes through the shard_map pipeline (schedule.py) in one
jitted pass, and everything else (loss scaling, clipping, optimizer, ZeRO
shardings, checkpointing) is inherited unchanged.

Constraint carried over from the reference: train_batch()'s gradient
accumulation count is the pipeline microbatch count (the reference asserts
the same), and ZeRO-2/3 don't compose with pp (grads must persist across
the schedule) — config validation enforces it.
"""

from __future__ import annotations

import jax

from ..engine import TpuEngine
from .module import PipelineModule


class PipelineEngine(TpuEngine):
    def __init__(self, model, config, topology, **kw):
        if not getattr(model, "is_pipeline_module", False):
            model = PipelineModule(
                model=model,
                num_stages=config.pipeline.stages,
                partition_method=config.pipeline.partition_method,
                activation_checkpoint_interval=(
                    config.pipeline.activation_checkpoint_interval
                ),
                pipe_schedule=config.pipeline.pipe_schedule,
                tick_chunk=config.pipeline.tick_chunk,
            )
        if topology.pp_size > 1 and config.gradient_accumulation_steps < topology.pp_size:
            from ...utils.logging import log_dist

            log_dist(
                f"warning: grad_accum ({config.gradient_accumulation_steps}) < "
                f"pipeline stages ({topology.pp_size}); bubble fraction is "
                f"{(topology.pp_size - 1) / (config.gradient_accumulation_steps + topology.pp_size - 1):.0%}"
            )
        super().__init__(model=model, config=config, topology=topology, **kw)

    def _compute_grads(self, params, batch, rng, scale, step=None,
                       ltd_keep=None):
        del ltd_keep  # random-LTD is not routed through the pipeline schedule
        def scaled_loss(p):
            with self._kernel_scope():  # tpu_kernels applies to pp steps too
                loss, _metrics = self.model.pipeline_loss(
                    p,
                    batch,
                    topology=self.topology,
                    dtype=self.compute_dtype,
                    train=True,
                    rng=rng,
                    remat_policy=self.remat_policy,
                )
            return loss * scale, loss

        (_, loss), grads = jax.value_and_grad(scaled_loss, has_aux=True)(params)
        inv = 1.0 / scale
        grads = jax.tree.map(lambda g: g.astype(jax.numpy.float32) * inv, grads)
        return grads, loss, {}  # pipeline loss reduces metrics in-schedule
