from .module import LayerSpec, PipelineModule, TiedLayerSpec  # noqa: F401
from .schedule import pipelined_stack  # noqa: F401
