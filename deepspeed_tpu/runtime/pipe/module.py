"""Pipeline module: stage partitioning + the pipelined model protocol.

Parity: deepspeed/runtime/pipe/module.py (PipelineModule, LayerSpec,
TiedLayerSpec) + topology partitioning (ds partition_balanced). Differences,
TPU-first:

- The reference materializes each rank's layer objects and moves tensors with
  p2p; here the decoder's stacked layer params [L, ...] are *sharded* over the
  pp mesh axis, and the schedule (schedule.py) is one shard_map — so the
  "module" mostly decides the stage partition and exposes the model protocol
  (init/loss/partition_specs) with pp-aware specs.
- Tied layers (embedding reused as lm_head) need no explicit grad reduction:
  both uses reference one parameter, so autodiff sums the contributions —
  the reference's TiedLayerSpec machinery collapses to weight reuse.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...models.transformer import TransformerModel, loss_fn as dense_loss_fn
from .schedule import pipelined_stack


class LayerSpec:
    """Parity: deepspeed.pipe.LayerSpec — a delayed layer constructor."""

    def __init__(self, typename: Callable, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self):
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        return f"LayerSpec({getattr(self.typename, '__name__', self.typename)})"


class TiedLayerSpec(LayerSpec):
    """Parity: deepspeed.pipe.TiedLayerSpec — layers sharing one weight.

    On TPU the tie is expressed as parameter reuse in the param pytree
    (e.g. TransformerConfig.tie_embeddings), so ``key`` only documents the
    sharing group."""

    def __init__(self, key: str, typename: Callable, *module_args,
                 forward_fn=None, tied_weight_attr="weight", **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Boundaries [p0..pN] with near-equal item counts per part."""
    parts = [0] * (num_parts + 1)
    chunk = num_items // num_parts
    residual = num_items - chunk * num_parts
    for p in range(1, num_parts + 1):
        parts[p] = parts[p - 1] + chunk + (1 if p <= residual else 0)
    return parts


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Parity: deepspeed.runtime.utils.partition_balanced — boundaries that
    minimise the max part weight (binary search over the bottleneck)."""
    n = len(weights)
    prefix = [0.0]
    for w in weights:
        prefix.append(prefix[-1] + float(w))

    def parts_needed(limit: float) -> Optional[List[int]]:
        bounds, start = [0], 0
        for _ in range(num_parts):
            end = start
            while end < n and prefix[end + 1] - prefix[start] <= limit:
                end += 1
            if end == start:  # single item exceeds limit
                return None
            bounds.append(end)
            start = end
            if end == n:
                break
        if bounds[-1] != n:
            return None
        while len(bounds) < num_parts + 1:
            bounds.append(n)
        return bounds

    lo = max(weights) if weights else 0.0
    hi = prefix[-1]
    for _ in range(50):
        mid = (lo + hi) / 2
        if parts_needed(mid) is None:
            lo = mid
        else:
            hi = mid
    return parts_needed(hi) or partition_uniform(n, num_parts)


class PipelineModule:
    """The engine-facing pipelined model.

    Two constructions:
    - ``PipelineModule(model=TransformerModel(...), num_stages=4)`` — the
      TPU-native fast path: the decoder stack is pipelined by sharding.
    - ``PipelineModule(layers=[LayerSpec...], num_stages=4)`` — reference
      API shape; requires the homogeneous-decoder pattern (specs are kept
      for partition bookkeeping, a ``model=`` must also be derivable).
    """

    is_pipeline_module = True

    def __init__(
        self,
        layers: Optional[List[Any]] = None,
        num_stages: int = 1,
        model: Optional[TransformerModel] = None,
        partition_method: str = "parameters",
        activation_checkpoint_interval: int = 0,
        loss_fn: Optional[Callable] = None,
        pipe_schedule: str = "1f1b",
        tick_chunk: int = 0,
    ):
        if model is None and layers is None:
            raise ValueError("PipelineModule needs model= or layers=")
        if model is None:
            built = [s.build() if isinstance(s, LayerSpec) else s for s in layers]
            models = [m for m in built if isinstance(m, TransformerModel)]
            if not models:
                raise ValueError(
                    "layers= must contain a TransformerModel (the TPU pipeline "
                    "shards the homogeneous decoder stack; arbitrary torch-style "
                    "nn.Sequential lists have no TPU equivalent)"
                )
            model = models[0]
        self.model = model
        self.config = model.config
        self.num_stages = num_stages
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.custom_loss_fn = loss_fn
        self.pipe_schedule = pipe_schedule
        self.tick_chunk = tick_chunk
        L = self.config.num_layers
        if num_stages > 1 and L % num_stages != 0:
            raise ValueError(
                f"num_layers {L} must be divisible by num_stages {num_stages}"
            )
        # stage boundaries over the L decoder blocks. 'parameters' and
        # 'uniform' coincide for a homogeneous stack (equal cost per block);
        # 'type:' patterns have no meaning for stacked params.
        method = partition_method.lower()
        if method in ("parameters", "uniform"):
            # homogeneous stacked blocks: balanced == uniform
            self.parts = partition_uniform(L, num_stages)
        else:
            raise ValueError(
                f"partition_method {partition_method!r} not supported "
                f"(stacked decoder blocks are homogeneous: use 'uniform' or "
                f"'parameters')"
            )

    # ---- model protocol ------------------------------------------------------
    def init(self, rng, dtype=jnp.float32):
        return self.model.init(rng, dtype)

    def num_params(self) -> int:
        return self.model.num_params()

    def partition_specs(self, topology=None):
        """Inner TP specs with the stacked-layer dim additionally pp-sharded."""
        specs = self.model.partition_specs(topology)
        pp = topology.pp_size if topology is not None else self.num_stages

        def pp_shard(spec: P) -> P:
            entries = list(spec)
            if not entries:
                entries = [None]
            first = entries[0]
            if first is None:
                entries[0] = "pp"
            elif isinstance(first, tuple):
                entries[0] = ("pp", *first)
            else:
                entries[0] = ("pp", first)
            return P(*entries)

        if pp > 1:
            specs["layers"] = jax.tree.map(
                pp_shard, specs["layers"], is_leaf=lambda x: isinstance(x, P)
            )
        return specs

    def loss(self, params, batch, **kw):
        """Non-pipelined fallback (eval_batch, single microbatch)."""
        kw.pop("topology", None)
        return self.model.loss(params, batch, **kw)

    def pipeline_loss(self, params, batch, *, topology, dtype=jnp.bfloat16,
                      train: bool = True, rng=None, remat_policy=None):
        """Loss over a microbatch stream dict of [M, mb, ...] arrays.

        Embedding/head run outside the pipelined region (replicated over pp,
        sharded over tp/dp as usual); only the block stack is pipelined.
        """
        cfg = self.config
        # XLA CPU crashes ("Invalid binary instruction opcode copy" in
        # AllReducePromotion) on bf16 all-reduce inside a partial-manual
        # shard_map region; CPU meshes (tests, driver dryrun) compute the
        # pipelined region in fp32. TPU/GPU keep the configured dtype.
        if topology.mesh.devices.flat[0].platform == "cpu":
            dtype = jnp.float32
        if remat_policy in (None, "none") and self.activation_checkpoint_interval:
            remat_policy = "full"  # ds parity: interval>0 turns on remat
        input_ids = batch["input_ids"]
        M, mb, S = input_ids.shape
        positions = batch.get("positions")
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(S, dtype=jnp.int32), (M, mb, S)
            )
        cast = lambda t: jax.tree.map(
            lambda a: a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a, t
        )
        from ...models.transformer import (
            _norm,
            embed_tokens,
            lm_head_logits,
            masked_ce,
        )

        # 1f1b (default): checkpoint the tick scan in chunks so the stash
        # stays O(T/C + C) boundary activations — the 1F1B memory bound —
        # instead of grad-of-scan's O(M) (measured: tools/pipe_memory.py).
        # gpipe: keep every tick residual (faster backward, O(M) memory).
        # pipeline.tick_chunk pins the chunk size by hand (0 = auto).
        tick_chunk = None
        if self.pipe_schedule == "1f1b" and topology.pp_size > 1:
            ticks = M + topology.pp_size - 1
            tick_chunk = (
                int(self.tick_chunk)
                if self.tick_chunk > 0
                else max(topology.pp_size, int(round((ticks / 2) ** 0.5)))
            )
        x = embed_tokens(cfg, params, input_ids, positions, dtype)  # [M,mb,S,D]
        y, aux = pipelined_stack(
            cfg, cast(params["layers"]), x, positions, batch.get("segment_ids"),
            topology, train, rng, remat_policy, tick_chunk=tick_chunk,
        )
        y = _norm(cfg, cast(params["final_norm"]), y)
        logits = lm_head_logits(cfg, params, y)
        if self.custom_loss_fn is not None:
            return self.custom_loss_fn(logits, batch)
        # per-microbatch normalization: parity with the dense engine's
        # mean-over-accumulation-steps semantics under ragged padding
        ce, denom = masked_ce(logits, batch["labels"], num_mb_dims=1)
        total = ce + cfg.moe_aux_loss_coef * aux if cfg.is_moe else ce
        return total, {"lm_loss": ce, "moe_aux_loss": aux, "tokens": denom}

    # ---- reference bookkeeping ----------------------------------------------
    def topology(self):
        return self.parts

    def stage_owner(self, layer_idx: int) -> int:
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        raise IndexError(layer_idx)
