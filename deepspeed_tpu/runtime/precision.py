"""Mixed-precision policy + dynamic loss scaling.

Parity: deepspeed/runtime/fp16/loss_scaler.py (DynamicLossScaler) and the
fp16/bf16 optimizer wrappers. The scaler is a pytree carried inside the
jitted train step (no host round-trip): overflow check → skip update, halve
scale, honor hysteresis; growth after loss_scale_window good steps.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..config import FP16Config


class LossScaleState(NamedTuple):
    scale: jax.Array  # f32 scalar
    good_steps: jax.Array  # i32
    hysteresis_left: jax.Array  # i32


def init_loss_scale(cfg: FP16Config, enabled: bool) -> LossScaleState:
    scale = cfg.initial_scale if enabled else 1.0
    return LossScaleState(
        scale=jnp.asarray(scale, jnp.float32),
        good_steps=jnp.zeros((), jnp.int32),
        hysteresis_left=jnp.asarray(cfg.hysteresis, jnp.int32),
    )


def update_loss_scale(
    state: LossScaleState, overflow: jax.Array, cfg: FP16Config, enabled: bool
) -> LossScaleState:
    """One reference-semantics scaler step (static no-op unless fp16)."""
    if not enabled or not cfg.dynamic:
        return state
    scale, good, hyst = state
    full_hyst = jnp.asarray(cfg.hysteresis, jnp.int32)

    def on_overflow():
        # reference: hysteresis absorbs overflows first; only then halve
        can_halve = hyst <= 1
        new_scale = jnp.where(can_halve, jnp.maximum(scale / 2.0, cfg.min_loss_scale), scale)
        new_hyst = jnp.where(can_halve, hyst, hyst - 1)
        return LossScaleState(new_scale, jnp.zeros((), jnp.int32), new_hyst)

    def on_good():
        grown = good + 1 >= cfg.loss_scale_window
        new_scale = jnp.where(grown, scale * 2.0, scale)
        new_good = jnp.where(grown, 0, good + 1)
        if cfg.consecutive_hysteresis:
            new_hyst = full_hyst  # refill every good step
        else:
            new_hyst = jnp.where(grown, full_hyst, hyst)  # refill only at growth
        return LossScaleState(new_scale, new_good, new_hyst)

    return jax.tree.map(
        lambda a, b: jnp.where(overflow, a, b), on_overflow(), on_good()
    )


def grads_finite(grads) -> jax.Array:
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.asarray(True)
    finite = [jnp.all(jnp.isfinite(g)) for g in leaves]
    return jnp.stack(finite).all()
