"""Data loading.

Parity: deepspeed/runtime/dataloader.py (DeepSpeedDataLoader,
RepeatingLoader). SPMD note: every host feeds the *global* batch (the jitted
step shards it over dp/fsdp/sp via in_shardings); per-rank samplers from the
reference become a deterministic global permutation here.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional

import jax
import numpy as np


class RepeatingLoader:
    """Parity: deepspeed.runtime.dataloader.RepeatingLoader — wraps an
    iterable and restarts it on StopIteration."""

    def __init__(self, loader):
        self.loader = loader
        self._iter = iter(loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._iter)
        except StopIteration:
            self._iter = iter(self.loader)
            return next(self._iter)


class DeepSpeedDataLoader:
    """Batches a dict-of-arrays (or array) dataset into global batches.

    ``curriculum_fn(step) -> seq_len`` optionally truncates sequences
    (data-efficiency curriculum parity).
    """

    def __init__(
        self,
        dataset: Any,
        batch_size: int,
        *,
        shuffle: bool = True,
        seed: int = 1234,
        drop_last: bool = True,
        curriculum_fn=None,
    ):
        if isinstance(dataset, (np.ndarray, jax.Array)):
            dataset = {"input_ids": dataset}
        if hasattr(dataset, "items"):
            self.dataset = None
            self.data = {k: np.asarray(v) for k, v in dataset.items()}
            lengths = {len(v) for v in self.data.values()}
            assert len(lengths) == 1, f"ragged dataset fields: { {k: len(v) for k, v in self.data.items()} }"
            self.n = lengths.pop()
        else:
            # map-style dataset (__getitem__/__len__ — e.g. the indexed
            # .bin/.idx MMapIndexedDataset): rows are fetched per batch,
            # via the dataset's own batched gather when it has one
            self.dataset = dataset
            self.data = None
            self.n = len(dataset)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.curriculum_fn = curriculum_fn
        self.epoch = 0
        self.global_step = 0

    def __len__(self) -> int:
        if self.drop_last:
            return self.n // self.batch_size
        return (self.n + self.batch_size - 1) // self.batch_size

    def _gather(self, idx) -> Dict[str, np.ndarray]:
        if self.data is not None:
            return {k: v[idx] for k, v in self.data.items()}
        ds = self.dataset
        if hasattr(ds, "get_batch") and getattr(ds, "seqlen", None):
            return {"input_ids": ds.get_batch(idx, ds.seqlen)}
        rows = [ds[int(i)] for i in idx]
        if rows and isinstance(rows[0], dict):
            return {k: np.stack([np.asarray(r[k]) for r in rows])
                    for k in rows[0]}
        return {"input_ids": np.stack([np.asarray(r) for r in rows])}

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        order = np.arange(self.n)
        if self.shuffle:
            order = np.random.RandomState(self.seed + self.epoch).permutation(self.n)
        self.epoch += 1
        for i in range(len(self)):
            idx = order[i * self.batch_size : (i + 1) * self.batch_size]
            batch = self._gather(idx)
            if self.curriculum_fn is not None:
                seqlen = int(self.curriculum_fn(self.global_step))
                batch = {
                    k: (v[:, :seqlen] if v.ndim >= 2 else v) for k, v in batch.items()
                }
            self.global_step += 1
            yield batch
