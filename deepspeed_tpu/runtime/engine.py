"""The training engine.

Parity: deepspeed/runtime/engine.py (DeepSpeedEngine) + deepspeed.initialize
(deepspeed/__init__.py). One jitted SPMD train step replaces the reference's
imperative forward/backward/step machinery:

- ZeRO stages are sharding rules (runtime/zero/partition.py); XLA inserts the
  all-gathers/reduce-scatters the reference hand-codes over NCCL.
- Gradient accumulation is a ``lax.scan`` over microbatches.
- fp16 dynamic loss scaling runs inside the step (no host sync); overflow
  skips the update exactly like the reference's optimizer wrapper.
- fp32 master weights live sharded (ZeRO-1+); compute casts to bf16/fp16.
- The reference's engine.forward/backward/step call protocol is emulated on
  top (micro-batch buffer, update applied at the accumulation boundary).
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import comm
from ..comm.topology import MeshTopology, ParallelDims
from ..config import DeepSpeedConfig
from ..models.sharding import use_topology
from ..utils.logging import log_dist
from ..utils.timer import SynchronizedWallClockTimer, ThroughputTimer
from ..utils.tree import global_norm, tree_cast
from .dataloader import DeepSpeedDataLoader
from .lr_schedules import build_schedule
from .optimizers import build_optimizer
from .precision import (
    LossScaleState,
    grads_finite,
    init_loss_scale,
    update_loss_scale,
)
from .zero.partition import make_shardings, opt_state_sharding, zero_specs


class TrainState:
    """Params (fp32 master), optax state, loss-scale state, step counter."""

    def __init__(self, params, opt_state, loss_scale, step):
        self.params = params
        self.opt_state = opt_state
        self.loss_scale = loss_scale
        self.step = step

    def astuple(self):
        return (self.params, self.opt_state, self.loss_scale, self.step)


def initialize(
    args=None,
    model=None,
    optimizer=None,
    model_parameters=None,
    training_data=None,
    lr_scheduler=None,
    dist_init_required=None,
    config=None,
    config_params=None,
    mpu=None,
    topology: Optional[MeshTopology] = None,
    rng: Optional[jax.Array] = None,
    abstract_init: bool = False,
):
    """Parity: deepspeed.initialize → (engine, optimizer, dataloader, lr_scheduler).

    ``model`` follows the model protocol (init/loss/partition_specs — see
    models/transformer.TransformerModel). ``optimizer`` may be an optax
    GradientTransformation to override the config-built one. ``mpu``
    (reference: Megatron model-parallel unit) is accepted as an alternate
    spelling of the mesh shape: its get_*_parallel_world_size() methods
    seed ParallelDims when no explicit ``topology`` is given.

    ``abstract_init=True`` builds the engine WITHOUT materializing any
    state: params/optimizer leaves are ShapeDtypeStructs carrying the
    exact shardings training would use. Such an engine cannot step — it
    exists so deepspeed_tpu.analysis (shardlint) can trace and lint the
    step program of arbitrarily large configs in seconds on CPU.
    """
    if config is None:
        config = config_params
    if config is None and args is not None:
        config = getattr(args, "deepspeed_config", None)
    if config is None:
        raise ValueError("initialize() requires config (dict or ds_config.json path)")
    if model is None:
        raise ValueError("initialize() requires model")

    cfg = config if isinstance(config, DeepSpeedConfig) else DeepSpeedConfig(config)

    def _mpu_reported(*names):
        for n in names:
            fn = getattr(mpu, n, None)
            if callable(fn):
                return int(fn())
        return 1

    mpu_consumed = False
    if topology is None and mpu is not None and not comm.is_initialized():
        # mpu protocol: the reference reads tensor/pipeline sizes off the
        # Megatron mpu. mpu overrides the config's tp/pp; the other mesh
        # axes (sp/ep/fsdp) still come from the config exactly like the
        # no-mpu branch below, and a pp the config can't run (no pipeline
        # section → no stage layers → TpuEngine) is an error, not a
        # silently replicated mesh axis.
        _mpu_size = _mpu_reported
        mpu_consumed = True
        mpu_pp = _mpu_size("get_pipe_parallel_world_size",
                           "get_pipeline_model_parallel_world_size")
        if mpu_pp > 1 and cfg.pipeline.stages <= 1:
            raise ValueError(
                f"mpu reports pipeline world size {mpu_pp} but the config "
                "has no pipeline section (pipeline.stages) — the engine "
                "cannot place stage layers it doesn't know about"
            )
        topology = comm.init_distributed(dims=ParallelDims(
            dp=cfg.topology.dcn_dp if cfg.topology.dcn_dp > 1 else 0,
            tp=_mpu_size("get_tensor_model_parallel_world_size",
                         "get_model_parallel_world_size"),
            pp=mpu_pp if mpu_pp > 1 else cfg.pipeline.stages,
            sp=cfg.sequence_parallel.sp_size,
            ep=cfg.moe.ep_size if cfg.moe.enabled else 1,
            fsdp=(cfg.zero_config.zero_hpz_partition_size
                  if cfg.zero_config.zero_hpz_partition_size > 1
                  else (cfg.zero_config.mics_shard_size
                        if cfg.zero_config.mics_shard_size > 0 else 1)),
        ), dcn_axes=cfg.topology.dcn_axes())
    if topology is None:
        if comm.is_initialized():
            topology = comm.get_topology()
        else:
            tp = cfg.tensor_parallel.tp_size
            pp = cfg.pipeline.stages
            sp = cfg.sequence_parallel.sp_size
            ep = cfg.moe.ep_size if cfg.moe.enabled else 1
            fsdp = 1
            if cfg.zero_config.zero_hpz_partition_size > 1:
                fsdp = cfg.zero_config.zero_hpz_partition_size
            elif cfg.zero_config.mics_shard_size > 0:
                fsdp = cfg.zero_config.mics_shard_size
            topology = comm.init_distributed(
                dims=ParallelDims(
                    dp=cfg.topology.dcn_dp if cfg.topology.dcn_dp > 1 else 0,
                    fsdp=fsdp, pp=pp, ep=ep, sp=sp, tp=tp,
                ),
                dcn_axes=cfg.topology.dcn_axes(),
            )
    else:
        comm.set_topology(topology)

    if mpu is not None and not mpu_consumed:
        # mpu arrived too late to shape the mesh (comm already initialized
        # or an explicit topology was passed); a disagreeing mpu must not
        # proceed silently — the caller's Megatron groups and this mesh
        # would split tensors differently
        mpu_tp = _mpu_reported("get_tensor_model_parallel_world_size",
                               "get_model_parallel_world_size")
        mpu_pp = _mpu_reported("get_pipe_parallel_world_size",
                               "get_pipeline_model_parallel_world_size")
        top_tp, top_pp = topology.get_dim("tp"), topology.get_dim("pp")
        # same convention as the consume branch: an mpu size of 1 (incl.
        # absent getters) defers to the config/topology — only a size the
        # mpu actively reports as parallel can conflict
        mismatch = [
            f"{name} {got} != {have}"
            for name, got, have in (("tp", mpu_tp, top_tp),
                                    ("pp", mpu_pp, top_pp))
            if got > 1 and got != have
        ]
        if mismatch:
            raise ValueError(
                f"initialize(mpu=...): mpu reports {', '.join(mismatch)} "
                f"vs the active topology (tp={top_tp}, pp={top_pp}); "
                "initialize comm from the mpu (or pass a matching topology)"
            )
        log_dist(
            "initialize(mpu=...): mesh already initialized; verified mpu "
            f"sizes match (tp={top_tp}, pp={top_pp})"
        )

    cfg.resolve_batch_sizes(topology.data_shard_size)

    # resolve every "auto" overlap/wire/spec/paged knob from the measured
    # knob-default table (config.resolve_auto_knobs) BEFORE any engine
    # code reads them — engines see concrete values only (the
    # deliberately-deferred wire/kv autos keep their downstream
    # resolution when the table has no fresh row)
    from ..config import resolve_auto_knobs

    resolve_auto_knobs(
        cfg, model_config=getattr(model, "config", None), topology=topology
    )

    if cfg.pipeline.stages > 1 or getattr(model, "is_pipeline_module", False):
        from .pipe.engine import PipelineEngine

        engine_cls = PipelineEngine
    else:
        engine_cls = TpuEngine
    engine = engine_cls(
        model=model,
        config=cfg,
        topology=topology,
        optimizer=optimizer,
        model_parameters=model_parameters,
        rng=rng,
        abstract_init=abstract_init,
    )

    dataloader = None
    if training_data is not None:
        dataloader = DeepSpeedDataLoader(
            training_data, cfg.train_batch_size, seed=cfg.seed
        )
    return engine, engine, dataloader, engine.lr_scheduler


class TpuEngine:
    """Parity surface: DeepSpeedEngine (train_batch/eval_batch/forward/
    backward/step/lr/global_steps/save_checkpoint/load_checkpoint)."""

    def __init__(
        self,
        model,
        config: DeepSpeedConfig,
        topology: MeshTopology,
        optimizer=None,
        model_parameters=None,
        rng: Optional[jax.Array] = None,
        abstract_init: bool = False,
    ):
        self.model = model
        self.config = config
        self.topology = topology
        # lint-only shell: state stays ShapeDtypeStructs (see initialize())
        self.abstract = bool(abstract_init)
        self.timers = SynchronizedWallClockTimer()
        # steady-state samples/sec: async dispatch makes per-call host time
        # track device time once the queue fills; the first steps are skipped
        self.tput = ThroughputTimer(batch_size=config.train_batch_size)
        self.global_steps = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        self.training = True
        self._micro_buffer = []
        self._metrics = {}
        self._chain_fns: Dict[Any, Any] = {}
        self.last_chain_metrics = None
        self.monitor = None
        if config.monitor.enabled:
            from ..monitor.monitor import MonitorMaster

            self.monitor = MonitorMaster(config.monitor)
        self.comm_logger = None
        # steptrace (config-gated; docs/observability.md). None is the
        # zero-overhead path: every instrumentation site guards on it,
        # so no span ever allocates. Abstract (lint) shells never trace.
        self.tracer = None
        self._steptrace_export_path = None
        if config.steptrace.enabled and not self.abstract:
            from ..profiling import steptrace as _steptrace

            self.tracer = _steptrace.configure(
                max_spans=config.steptrace.max_spans
            )
            self._steptrace_export_path = config.steptrace.export_path
        # healthwatch (config-gated; docs/observability.md "healthwatch").
        # None is the zero-overhead path: no ring buffer, no device-scalar
        # taps, no extra spans — constructed below AFTER the analytic
        # streams exist (its comm-exposed goodput bucket prices them).
        self.healthwatch = None
        if config.comms_logger.enabled:
            from ..profiling.comm_logger import CommsLogger

            self.comm_logger = CommsLogger(config.comms_logger,
                                           registry=self.tracer)

        self.fp16_enabled = config.fp16.enabled
        self.compute_dtype = config.compute_dtype
        self.remat_policy = config.activation_checkpointing.policy
        on_tpu = topology.mesh.devices.flat[0].platform == "tpu"
        # ---- TPU kernel selection (reference: op_builder CUDA-extension
        # toggles become Pallas kernel switches). Applied as *scoped*
        # overrides while tracing this engine's steps (_kernel_scope), so
        # engines with different configs in one process don't fight. --------
        tk = config.tpu_kernels.resolve(on_tpu)
        self.tpu_kernels = tk
        self._sparse_impl = None
        if config.sparse_attention.mode != "none":
            # training-time block-sparse attention (reference:
            # SparseSelfAttention driven by the "sparse_attention" section)
            from ..ops.sparse_attention import from_ds_config, make_attention_impl

            if topology.sp_size > 1 and config.sparse_attention.mode != "dense":
                # config validation only sees the config's sp_size; an
                # explicitly passed sp>1 topology must fail here, not apply
                # a chunk-local block layout silently inside the ring path
                from ..config import DeepSpeedConfigError

                raise DeepSpeedConfigError(
                    "sparse_attention is not supported on a sequence-"
                    "parallel topology (the block layout assumes full-"
                    "sequence tiles)"
                )
            sp_cfg = from_ds_config(config.sparse_attention)
            if sp_cfg is not None:
                self._sparse_impl = make_attention_impl(sp_cfg)
        # ---- decomposed TP collective matmul (tensor_parallel.overlap_comm:
        # parallel/tensor_overlap.py). Scoped at trace time like the kernel
        # selectors; the knob defaults off pending an on-chip A/B. ----------
        ov = config.tensor_parallel.overlap_comm
        self.tp_overlap = ov if (ov.enabled and topology.tp_size > 1) else None
        if ov.enabled and topology.tp_size <= 1:
            log_dist(
                "tensor_parallel.overlap_comm: tp_size == 1 on this "
                "topology — nothing to decompose, knob ignored"
            )
        if self.tp_overlap is not None:
            from ..parallel.tensor_overlap import static_widths_divide

            mc = getattr(model, "config", None)
            if mc is not None and not static_widths_divide(
                mc, topology.tp_size
            ):
                log_dist(
                    "tensor_parallel.overlap_comm: a projection width does "
                    f"not divide tp={topology.tp_size} — the rings could "
                    "never engage, so the knob is disabled (the residual "
                    "stream would otherwise pay the (sp, tp) layout for "
                    "nothing)"
                )
                self.tp_overlap = None
        # ---- decomposed MoE all-to-all (moe.overlap_a2a:
        # parallel/a2a_overlap.py). Same trace-time-scope protocol; the
        # knob defaults off pending an on-chip A/B. ----------------------
        mo = config.moe.overlap_a2a
        _model_is_moe = bool(
            getattr(getattr(model, "config", None), "is_moe", False)
        )
        self.moe_a2a = (
            mo if (mo.enabled and topology.ep_size > 1 and _model_is_moe)
            else None
        )
        if mo.enabled and self.moe_a2a is None:
            log_dist(
                "moe.overlap_a2a: "
                + ("ep_size == 1 on this topology"
                   if topology.ep_size <= 1 else "model is not MoE")
                + " — no expert exchange to decompose, knob ignored"
            )
        self.pld = None
        if config.progressive_layer_drop.enabled:
            from .progressive_layer_drop import ProgressiveLayerDrop

            self.pld = ProgressiveLayerDrop(
                theta=config.progressive_layer_drop.theta,
                gamma=config.progressive_layer_drop.gamma,
            )
        self.compression_masks = None
        self._compression_cfg = None
        self._qat = None
        cc = config.compression
        if any(
            (getattr(cc, f) or {}).get("shared_parameters", {}).get("enabled")
            for f in ("weight_quantization", "sparse_pruning", "head_pruning",
                      "row_pruning")
        ):
            self._compression_cfg = cc
        if (cc.layer_reduction or {}).get("enabled"):
            from ..config import DeepSpeedConfigError

            raise DeepSpeedConfigError(
                "compression.layer_reduction changes the model architecture; "
                "apply compression.compress.apply_layer_reduction to the "
                "params (and shrink the model config) before initialize()"
            )
        self.curriculum = None
        if config.data_efficiency.curriculum_learning.enabled:
            from ..data_pipeline.curriculum_scheduler import CurriculumScheduler

            self.curriculum = CurriculumScheduler(
                config.data_efficiency.curriculum_learning
            )
        self.random_ltd = None
        self._ltd_layers = None
        rl = config.data_efficiency.random_ltd
        if rl.enabled:
            # random-LTD (reference: data_pipeline/data_routing) — the
            # scheduler quantizes the kept-token count (one compiled program
            # per distinct value); the layer range must be contiguous because
            # the layer scan is split pre/ltd/post (models/transformer.py)
            from ..data_pipeline.random_ltd import RandomLTDScheduler

            n_layers = getattr(getattr(model, "config", None), "num_layers", 0)
            L = rl.total_layer_num or n_layers
            self.random_ltd = RandomLTDScheduler(rl, total_layers=L)
            ids = sorted(rl.random_ltd_layer_id)
            if ids:
                if ids != list(range(ids[0], ids[-1] + 1)):
                    from ..config import DeepSpeedConfigError

                    raise DeepSpeedConfigError(
                        "random_ltd_layer_id must be a contiguous range on "
                        "TPU (the layer scan is split around it); got "
                        f"{rl.random_ltd_layer_id}"
                    )
                self._ltd_layers = (ids[0], ids[-1] + 1)
            else:
                # explicit layer_num is honored exactly (lo may be 0); the
                # derived default keeps the first layer out of the drop set
                if rl.random_ltd_layer_num:
                    n_ltd = min(rl.random_ltd_layer_num, L)
                    lo = (L - n_ltd) // 2
                else:
                    n_ltd = max(L - 2, 0)
                    lo = max((L - n_ltd) // 2, 1)
                self._ltd_layers = (lo, min(lo + n_ltd, L))
            if self._ltd_layers[0] >= self._ltd_layers[1]:
                self.random_ltd = None
                self._ltd_layers = None
        if topology.sp_size > 1:
            # per-topology, so two engines with different modes don't fight
            topology.sp_mode = config.sequence_parallel.mode

        # ---- schedule + optimizer ------------------------------------------
        self.lr_schedule = build_schedule(
            config.scheduler.type, config.scheduler.params, config.optimizer.lr
        )
        self.lr_scheduler = self.lr_schedule
        self._stacked_grads_axes = None
        opt_name = (config.optimizer.type or "").lower().replace("_", "")
        data_axes_live = tuple(
            a for a in ("dp", "fsdp") if topology.sizes[a] > 1
        )
        # the wire path shard_maps ONLY the data axes; on legacy jax 0.4.x
        # a further live axis makes that partial-manual, which its SPMD
        # partitioner cannot compile (jax_compat.shard_map refuses it) —
        # degrade to the numerics-only variant instead of dying
        wire_shardable = hasattr(jax, "shard_map") or all(
            topology.sizes[a] <= 1 or a in data_axes_live
            for a in topology.sizes
        )
        if (
            opt_name in ("onebitadam", "onebitlamb")
            and optimizer is None
            and data_axes_live
            and wire_shardable
            and config.zero_config.stage <= 1
            and config.pipeline.stages <= 1
            and not getattr(model, "is_pipeline_module", False)
        ):
            # wire-compressed 1-bit path (reference: compressed_allreduce):
            # the engine hands the optimizer stacked per-member local grads
            # and the momentum crosses the wire bit-packed
            from ..ops.onebit import build_onebit_wire_optimizer

            self._stacked_grads_axes = data_axes_live
            self.optimizer_tx = build_onebit_wire_optimizer(
                opt_name, config.optimizer, self.lr_schedule, topology,
                data_axes_live,
            )
            msg = (
                f"1-bit wire compression active over {data_axes_live} "
                f"(warmup={config.optimizer.params.get('freeze_step', 100)} "
                f"steps, then bit-packed momentum all-reduce)"
            )
            if config.gradient_clipping > 0:
                msg += "; gradient_clipping is not applied in this mode"
            log_dist(msg)
        else:
            if opt_name in ("onebitadam", "onebitlamb") and optimizer is None:
                # make the semantics fork audible (r2 verdict: silent):
                # the numerics-only variant compresses nothing on the wire
                why = (
                    "no >1-size data axis" if not data_axes_live
                    else "legacy jax cannot compile the partial-manual "
                         "wire shard_map beside other live mesh axes"
                    if not wire_shardable
                    else "ZeRO stage > 1" if config.zero_config.stage > 1
                    else "pipeline parallelism"
                )
                log_dist(
                    f"{config.optimizer.type}: wire compression DISABLED "
                    f"({why}); running the numerics-only variant — momentum "
                    f"is NOT bit-packed on the network"
                )
            self.optimizer_tx = (
                optimizer
                if isinstance(optimizer, optax.GradientTransformation)
                else build_optimizer(
                    config.optimizer,
                    self.lr_schedule,
                    use_pallas_adam=tk.fused_adam,
                )
            )

        # ---- sharding specs -------------------------------------------------
        tp_specs = (
            model.partition_specs(topology)
            if hasattr(model, "partition_specs")
            else None
        )
        self._rng = rng if rng is not None else jax.random.PRNGKey(config.seed)
        params_shape = jax.eval_shape(
            lambda k: model.init(k, dtype=jnp.float32), self._rng
        )
        if tp_specs is None:
            tp_specs = jax.tree.map(lambda x: P(), params_shape)
        self.param_specs, self.grad_specs, self.opt_leaf_specs = zero_specs(
            params_shape, tp_specs, topology, config.zero_config
        )
        self._tp_specs = tp_specs
        self._params_shape = params_shape
        # ---- ZeRO-3 one-layer-ahead parameter prefetch
        # (zero_optimization.stage3_layer_prefetch: runtime/zero/prefetch.py).
        # The puts tree is one layer slice's gathered (tp-only) shardings;
        # persistence-threshold leaves come back as identity puts. --------
        # ---- wire codecs (comm/wires.py, docs/wires.md): the grad
        # reduce-scatter / param all-gather wire formats. Legacy
        # zero_quantized_* bools resolve to int8 codecs. ----------------
        zc = config.zero_config
        self._grad_wire = zc.resolved_grad_wire()
        self._param_wire = zc.resolved_param_wire()
        self._hier_wire = bool(zc.hierarchical_wire)
        if self._hier_wire and not (
            topology.sizes["dp"] > 1 and topology.sizes["fsdp"] > 1
        ):
            log_dist(
                "zero_optimization.hierarchical_wire: needs a live "
                f"factored dp x fsdp mesh (this one is {topology}); the "
                "2-hop forms have no groups to split — knob ignored, "
                "single-hop wires run"
            )
            self._hier_wire = False
        self._z3_prefetch_puts = None
        self._z3_prefetch_shapes = None
        if config.zero_config.stage3_layer_prefetch:
            if config.zero_config.stage != 3:
                log_dist(
                    "zero_optimization.stage3_layer_prefetch: stage "
                    f"{config.zero_config.stage} has no parameter gathers "
                    "to prefetch, knob ignored"
                )
            else:
                from .zero.prefetch import build_layer_puts

                self._z3_prefetch_puts = build_layer_puts(
                    params_shape, tp_specs, self.param_specs, topology,
                    param_wire=self._param_wire,
                    grad_wire=self._grad_wire,
                    hierarchical=self._hier_wire,
                )
                if self._z3_prefetch_puts is None:
                    log_dist(
                        "stage3_layer_prefetch: no data-sharded stacked "
                        "'layers' leaf on this mesh (everything persistent "
                        "or replicated) — nothing to prefetch, knob ignored"
                    )
                else:
                    self._z3_prefetch_shapes = (params_shape, tp_specs)
        self._qgather = None
        if zc.stage == 3 and (
            self._param_wire != "fp32"
            or self._grad_wire != "fp32"
            or self._hier_wire
        ):
            # ZeRO++ qwZ/qgZ/hgZ: explicit wire-codec gather replaces
            # XLA's implicit one; its custom backward is the codec grad
            # reduce-scatter (runtime/zero/quantized.py). When the layer
            # prefetch owns the stacked group's gathers, exclude it here
            # — its WirePut callables run the same per-leaf program
            # inside the scan (runtime/zero/prefetch.py).
            from .zero.quantized import make_quantized_gather

            self._qgather = make_quantized_gather(
                topology,
                self.param_specs,
                tp_specs,
                params_shape,
                param_wire=self._param_wire,
                grad_wire=self._grad_wire,
                hierarchical=self._hier_wire,
                exclude_key=(
                    "layers" if self._z3_prefetch_puts is not None else None
                ),
            )
        # stage-1/2 grad wire (qgZ at the dp reduction itself): the grad
        # computation runs per data-shard inside a shard_map and the
        # cross-member reduction becomes the explicit codec
        # reduce-scatter (stage 3's grad wire rides the gather's custom
        # backward instead — see the _qgather block above)
        self._wired_grad_axes = None
        # the wired reduction also engages for fp32 + hierarchical_wire:
        # the 2-hop topology win (only 1/n_fsdp of the bytes cross the
        # slow dp links) exists without any quantization
        _wire_wanted = self._grad_wire != "fp32" or self._hier_wire
        if _wire_wanted and zc.stage in (1, 2) and (
            config.pipeline.stages > 1
            or getattr(model, "is_pipeline_module", False)
            or self._stacked_grads_axes is not None
        ):
            log_dist(
                "zero_optimization.grad_wire: the wired reduction cannot "
                "run under pipeline parallelism / the 1-bit wire path; "
                "the full-width reduction runs"
            )
        elif _wire_wanted and zc.stage in (1, 2):
            if not data_axes_live:
                log_dist(
                    "zero_optimization.grad_wire: no >1-size data axis on "
                    "this mesh — nothing to compress, the full-width "
                    "reduction runs"
                )
            elif not wire_shardable:
                log_dist(
                    "zero_optimization.grad_wire: legacy jax cannot "
                    "compile the partial-manual wire shard_map beside "
                    "other live mesh axes; the full-width reduction runs"
                )
            else:
                self._wired_grad_axes = data_axes_live
                log_dist(
                    f"grad wire active: {self._grad_wire} reduce-scatter "
                    f"over {data_axes_live}"
                    + (" (hierarchical 2-hop)" if self._hier_wire else "")
                )
        # ---- offload (reference: zero offload_optimizer / offload_param +
        # swap_tensor/partitioned_optimizer_swapper) --------------------------
        off_opt = zc.offload_optimizer
        off_par = zc.offload_param
        self._nvme_swapper = None
        self._checkpoint_guard = None  # lazy (runtime/ckpt CheckpointGuard)
        self._opt_memory_kind = None
        if off_opt.device == "cpu":
            # XLA's CPU SPMD partitioner can't annotate memory kinds, so the
            # host-memory path is TPU-only; CPU test meshes run unoffloaded
            self._opt_memory_kind = "pinned_host" if on_tpu else None
        elif off_opt.device == "nvme":
            from .swap_tensor import TensorSwapper

            self._nvme_swapper = TensorSwapper(
                os.path.join(off_opt.nvme_path, "zero_opt_swap"),
                # host buffer reuse is only safe when device_put really
                # copies (TPU HBM); the CPU client can zero-copy alias
                reuse_buffers=on_tpu,
                buffer_count=off_opt.buffer_count,
            )
        self._param_memory_kind = (
            "pinned_host" if (off_par.enabled and on_tpu) else None
        )
        # CPU-offloaded optimizer state steps per-layer (sub_group_size
        # semantics — see runtime/bucketed_opt.py): one layer's m/v/master
        # streams through HBM per scan tick instead of the whole tree's
        # f32 update temps at once (the 1.4B config OOM'd otherwise)
        from .bucketed_opt import BucketedOptimizer, bucketed_applicable

        bucketable = (
            off_opt.device == "cpu"
            and not self._stacked_grads_axes
            # fp16's overflow skip selects over the WHOLE old/new
            # state, which would force full-width compute on the
            # pinned-host layer leaves the scan keeps resident there;
            # bf16/fp32 (the TPU-native paths) never take that select
            and not self.fp16_enabled
            and bucketed_applicable(params_shape)
        )
        # NOTE: a stacked leaf sharding its leading (layer) dim no longer
        # disables bucketing (the PR-1 gate): _apply_update re-puts the
        # scanned groups to their resting shardings after the layer scan,
        # restoring the carry-in == carry-out closure the slice hooks
        # alone cannot (shardlint rule R2 checks the invariant statically)
        self._bucketed_opt = (
            BucketedOptimizer(
                self.optimizer_tx,
                double_buffer=zc.offload_double_buffer,
            )
            if bucketable
            else None
        )
        if off_opt.device == "cpu" and self.fp16_enabled:
            log_dist(
                "offload_optimizer + fp16: per-layer bucketed stepping is "
                "disabled (the overflow-skip select needs the full state "
                "on device); prefer bf16 on TPU for large offloaded models"
            )
        if off_par.enabled and not on_tpu:
            log_dist(
                "offload_param: pinned_host memory kinds need the TPU "
                "backend; this CPU mesh runs without param offload"
            )
        if off_par.device == "nvme":
            log_dist(
                "offload_param.device=nvme: params stage in pinned host "
                "memory (disk swap applies to optimizer state via "
                "offload_optimizer.device=nvme)"
            )
        self.param_shardings = make_shardings(
            self.param_specs, topology, self._param_memory_kind
        )
        self._param_dev_shardings = (
            make_shardings(self.param_specs, topology)
            if self._param_memory_kind
            else None
        )
        self.grad_shardings = make_shardings(self.grad_specs, topology)

        # ---- materialize state (zero.Init parity: params born sharded) -----
        with use_topology(topology):
            if self.abstract:
                # shardlint tracing shell: leaves are ShapeDtypeStructs
                # carrying the exact shardings the real engine would
                # materialize — nothing executes on any device
                if self._compression_cfg is not None:
                    raise NotImplementedError(
                        "abstract_init does not support compression_training "
                        "(mask computation needs real params)"
                    )
                if model_parameters is not None:
                    raise NotImplementedError(
                        "abstract_init ignores model_parameters; pass none"
                    )
                params = jax.tree.map(
                    lambda a, s: jax.ShapeDtypeStruct(
                        a.shape, a.dtype, sharding=s
                    ),
                    params_shape,
                    self.param_shardings,
                )
            elif model_parameters is not None:
                params = jax.device_put(
                    tree_cast(model_parameters, jnp.float32), self.param_shardings
                )
            else:
                params = jax.jit(
                    lambda k: model.init(k, dtype=jnp.float32),
                    out_shardings=self.param_shardings,
                )(self._rng)
            if self._compression_cfg is not None:
                # Engine hook (reference: init_compression on module wrap):
                # pruning masks computed once here and re-imposed after every
                # optimizer step; weight QAT runs as STE fake-quant inside
                # each forward (_loss_for), masters stay full precision.
                from ..compression.compress import (
                    init_compression,
                    quantization_settings,
                )

                params, masks = init_compression(
                    params,
                    self._compression_cfg,
                    getattr(model, "config", None),
                    qat_in_forward=True,
                )
                params = jax.device_put(params, self.param_shardings)
                self.compression_masks = masks or None
                self._qat = quantization_settings(self._compression_cfg)
            if self._stacked_grads_axes:
                from ..ops.onebit import onebit_wire_state_shardings

                opt_out_shardings = onebit_wire_state_shardings(
                    jax.eval_shape(self.optimizer_tx.init, params_shape),
                    topology,
                    self._stacked_grads_axes,
                    self._opt_memory_kind,
                )
            elif self._bucketed_opt is not None:
                bshape = jax.eval_shape(self._bucketed_opt.init, params_shape)
                rest_specs = {
                    k: v for k, v in self.opt_leaf_specs.items()
                    if k != self._bucketed_opt.key
                }
                opt_out_shardings = {
                    "rest": opt_state_sharding(
                        self.optimizer_tx, bshape["rest"], rest_specs,
                        topology, self._opt_memory_kind,
                    ),
                    # vmapped per-layer state: param-shaped leaves are
                    # stacked like the params, so the stacked specs apply
                    "layers": opt_state_sharding(
                        self.optimizer_tx, bshape["layers"],
                        self.opt_leaf_specs[self._bucketed_opt.key],
                        topology, self._opt_memory_kind,
                    ),
                }
            else:
                opt_out_shardings = opt_state_sharding(
                    self.optimizer_tx,
                    jax.eval_shape(self.optimizer_tx.init, params_shape),
                    self.opt_leaf_specs,
                    topology,
                    self._opt_memory_kind,
                )
            init_fn = (
                self._bucketed_opt.init
                if self._bucketed_opt is not None
                else self.optimizer_tx.init
            )
            if self.abstract:
                opt_state = jax.tree.map(
                    lambda a, s: jax.ShapeDtypeStruct(
                        a.shape, a.dtype, sharding=s
                    ),
                    jax.eval_shape(init_fn, params),
                    opt_out_shardings,
                )
            else:
                opt_state = jax.jit(init_fn, out_shardings=opt_out_shardings)(
                    params
                )
        self.opt_shardings = jax.tree.map(lambda x: x.sharding, opt_state)
        self._opt_dev_shardings = (
            jax.tree.map(
                lambda s: NamedSharding(s.mesh, s.spec), self.opt_shardings
            )
            if self._opt_memory_kind
            else None
        )
        self._opt_treedef = jax.tree_util.tree_structure(opt_state)
        loss_scale = init_loss_scale(config.fp16, self.fp16_enabled)
        step0 = jnp.zeros((), jnp.int32)
        if not self.abstract:
            # commit the scalar state to its replicated resting sharding
            # NOW: the step's out_shardings put the new scale/step there,
            # so uncommitted host scalars here would make the SECOND
            # train_batch retrace the whole step program (fresh vs
            # donated-state shardings) — one wasted full compile per
            # engine, and the dryrun/serving "one steady trace" gates
            # would always read 2
            rep = NamedSharding(topology.mesh, P())
            loss_scale, step0 = jax.device_put((loss_scale, step0), rep)
        self.state = TrainState(params, opt_state, loss_scale, step0)
        self.offload_stream = self._compute_offload_stream()
        self._tp_overlap_streams = {}
        self.tp_overlap_stream = self._compute_tp_overlap_stream()
        self._moe_a2a_streams = {}
        self.moe_a2a_stream = self._compute_moe_a2a_stream()
        self.z3_prefetch_stream = self._compute_z3_prefetch_stream()
        self.grad_wire_stream = self._compute_grad_wire_stream()
        self.param_wire_stream = self._compute_param_wire_stream()
        if config.healthwatch.enabled and not self.abstract:
            self._build_healthwatch(config.healthwatch)
        if self._nvme_swapper is not None and not self.abstract:
            # optimizer state lives on disk between steps (reference:
            # partitioned_optimizer_swapper); swapped in around each update
            self._swap_out_opt()

        self._replicated = NamedSharding(topology.mesh, P())
        self._data_iters: Dict[int, Any] = {}
        # retrace counter (the serving engine's step_traces discipline):
        # a trace-time side effect fires once per XLA compile of the
        # jitted step programs — healthwatch's recompile watchdog and the
        # goodput compile bucket read the per-step delta
        self.step_traces = 0
        self._last_seq: Optional[int] = None
        self._mfu_cache: Dict[str, Any] = {}
        self._compile_step_fns()
        n_params = sum(int(np.prod(s.shape)) for s in jax.tree_util.tree_leaves(params_shape))
        log_dist(
            f"TpuEngine: {n_params/1e6:.1f}M params, zero_stage={config.zero_config.stage}, "
            f"dtype={self.compute_dtype.__name__}, topology={topology}, "
            f"micro_batch={config.train_micro_batch_size_per_gpu}, "
            f"accum={config.gradient_accumulation_steps}"
        )
        if config.memory_breakdown:
            # reference: memory_breakdown prints see_memory_usage around the
            # step; here at init + every steps_per_print (train_batch)
            from ..utils.memory import print_zero_memory_estimates, see_memory_usage

            print_zero_memory_estimates(
                model, topology, stages=(config.zero_config.stage,),
                compute_dtype_bytes=jnp.dtype(self.compute_dtype).itemsize,
                offload_optimizer=config.zero_config.offload_optimizer.enabled,
                offload_params=config.zero_config.offload_param.enabled,
            )
            see_memory_usage("after engine init")

    # --------------------------------------------------- offload accounting
    def _compute_offload_stream(self, assume_offload: bool = False):
        """Static per-step host↔HBM DMA byte counts for the bucketed
        offload stream (None when no pinned-host leaves stream). Every
        pinned-host stacked leaf is read in and written back once per
        optimizer step, so the counts come straight from the resting
        shardings; ``slot_bytes`` is one layer slice (the scan's in-flight
        unit — double buffering keeps ``slots`` of them resident).

        ``assume_offload=True`` prices the stream the *config declares*
        even where the mesh has no memory kinds (the CPU lint mesh):
        every stacked leaf the TPU run would pin to host counts, so the
        planner and rule R8 can budget the 1.5B offload leg without a
        chip. Per-device figures come from each leaf's shard shape."""
        if self._bucketed_opt is None or self.state is None:
            return None
        kind = self._opt_memory_kind or self._param_memory_kind
        zc = self.config.zero_config
        opt_declared = zc.offload_optimizer.device in ("cpu", "nvme")
        par_declared = zc.offload_param.enabled
        if kind is None and not (
            assume_offload and (opt_declared or par_declared)
        ):
            return None  # CPU mesh: no memory kinds, nothing streams
        key = self._bucketed_opt.key

        def stream_bytes(tree):
            total = dev = 0
            for leaf in jax.tree_util.tree_leaves(tree):
                streams_leaf = (
                    getattr(leaf.sharding, "memory_kind", None) == kind
                    if kind is not None
                    else True  # assumed: the whole stacked group would pin
                )
                if not streams_leaf:
                    continue
                nbytes = leaf.size * leaf.dtype.itemsize
                total += nbytes
                try:
                    shard = leaf.sharding.shard_shape(leaf.shape)
                    dev += int(np.prod(shard)) * leaf.dtype.itemsize
                except Exception:  # noqa: BLE001 — no sharding evidence
                    dev += nbytes
            return total, dev

        state_b, state_dev = (
            stream_bytes(self.state.opt_state[key])
            if self._opt_memory_kind or (kind is None and opt_declared)
            else (0, 0)
        )
        param_b, param_dev = (
            stream_bytes(self.state.params[key])
            if self._param_memory_kind or (kind is None and par_declared)
            else (0, 0)
        )
        total = state_b + param_b
        per_dev = state_dev + param_dev
        if total == 0:
            return None
        n_layers = jax.tree_util.tree_leaves(self.state.params[key])[0].shape[0]
        slots = 2 if self._bucketed_opt.double_buffer else 1
        return {
            "bytes_in": total,
            "bytes_out": total,
            "per_device_bytes_in": per_dev,
            "per_device_bytes_out": per_dev,
            "slot_bytes": total // max(n_layers, 1),
            "slots": slots,
            "layers": int(n_layers),
            "double_buffer": self._bucketed_opt.double_buffer,
            "assumed": kind is None,
        }

    def analytic_streams(self, seq=None, include_potential: bool = False):
        """The engine's declared analytic streams, normalized for the
        cost planner / rule R8 and the comms logger (ONE schema for every
        hidden-stream subsystem): name → ``{"kind", "bytes_per_step",
        "per_device_bytes_per_step", "overlapped", ...}``.

        ``include_potential=True`` also prices streams the config
        declares but this mesh cannot pin (the CPU lint mesh has no
        memory kinds) — what the planner budgets; the comms logger only
        ever records the actual (default) set.

        Every mesh stream carries ``axes``: the mesh axes its collective
        runs over, so per-link pricing (hybrid DCN meshes, rule R13) can
        tell which bytes cross the slow fabric."""
        streams = {}
        data_axes = tuple(
            a for a in ("dp", "fsdp") if self.topology.sizes[a] > 1
        )
        off = self.offload_stream
        if off is None and include_potential:
            off = self._compute_offload_stream(assume_offload=True)
        if off:
            total = off["bytes_in"] + off["bytes_out"]
            per_dev = (
                off.get("per_device_bytes_in", off["bytes_in"])
                + off.get("per_device_bytes_out", off["bytes_out"])
            )
            streams["offload"] = {
                "kind": "offload",
                "bytes_per_step": total,
                "per_device_bytes_per_step": per_dev,
                "per_device_inflight_bytes": off["slots"] * off["slot_bytes"]
                // max(self.topology.world_size, 1),
                "overlapped": bool(off["double_buffer"]),
                **off,
            }
        if self.tp_overlap is not None:
            ring = self._tp_overlap_stream_for(seq)
            if ring:
                streams["tp_ring"] = {
                    **ring,
                    "kind": "ici",
                    "axes": ("tp",),
                    # ring_wire_bytes_per_step is already per device
                    "bytes_per_step": ring["bytes_per_step"],
                    "per_device_bytes_per_step": ring["bytes_per_step"],
                    "overlapped": True,
                }
        # MoE dispatch/combine traffic is declared whether or not the
        # overlap knob is on (ISSUE-10 fix: the serial GSPMD path moves
        # the same logical bytes, R8/shardplan must see them either way);
        # overlapped only when the decomposed rings actually ENGAGE —
        # the knob being on with undividable shapes falls back to the
        # serial path at trace time (moe_a2a_applicable), and claiming
        # overlap for it would let R8 hide wire that runs serialized
        # (same honesty rule as ring_wire_bytes_per_step's predicates)
        a2a = self._moe_a2a_stream_for(seq)
        if a2a:
            streams["moe_a2a"] = {
                **a2a,
                "kind": "ici",
                "axes": ("ep",),
                # moe_a2a_bytes_per_step is already per device
                "bytes_per_step": a2a["bytes_per_step"],
                "per_device_bytes_per_step": a2a["bytes_per_step"],
                "overlapped": bool(
                    self.moe_a2a is not None and a2a.get("ring_engages")
                ),
            }
        z3 = self.z3_prefetch_stream
        if z3:
            streams["zero3_prefetch"] = {
                **z3,
                "kind": "ici",
                "axes": data_axes,
                "bytes_per_step": z3["bytes_per_step"],
                "per_device_bytes_per_step": z3["bytes_per_step"],
                "overlapped": True,
            }
        # wire-codec streams (comm/wires.py): the grad reduce-scatter and
        # stage-3 param gathers in codec bytes. Declared NOT overlapped —
        # they are serial collectives (the win is fewer bytes, not hidden
        # ones), except where the prefetch already owns (and overlaps)
        # the stacked layers' share via zero3_prefetch above. shardplan
        # prices them; R8 sees the codec-shrunk zero3_prefetch stream.
        gw = self.grad_wire_stream
        if gw:
            streams["grad_wire"] = {
                **gw,
                "kind": "ici",
                "axes": data_axes,
                "bytes_per_step": gw["bytes_per_step"],
                "per_device_bytes_per_step": gw["bytes_per_step"],
                "overlapped": False,
            }
        pw = self.param_wire_stream
        if pw:
            streams["param_wire"] = {
                **pw,
                "kind": "ici",
                "axes": data_axes,
                "bytes_per_step": pw["bytes_per_step"],
                "per_device_bytes_per_step": pw["bytes_per_step"],
                "overlapped": False,
            }
        # periodic checkpoint snapshots (runtime/ckpt): device→host bytes
        # amortized over the declared save cadence, so R8/shardplan price
        # the async pipeline against the roofline window like any other
        # offload stream. goodput_bucket marks its synchronous cost as
        # already charged to the `checkpoint` bucket — healthwatch must
        # not carve it out of compute spans a second time.
        ckpt_cfg = getattr(self.config, "checkpoint", None)
        interval = int(getattr(ckpt_cfg, "save_interval_steps", 0) or 0)
        if interval > 0:
            try:
                snap_total, snap_dev = self._ckpt_snapshot_bytes()
            except Exception:  # noqa: BLE001 — abstract/odd state trees
                snap_total = snap_dev = 0.0
            if snap_total > 0:
                streams["ckpt_snapshot"] = {
                    "kind": "offload",
                    "bytes_per_step": snap_total / interval,
                    "per_device_bytes_per_step": snap_dev / interval,
                    "overlapped": bool(
                        getattr(ckpt_cfg, "async_save", False)
                    ),
                    "goodput_bucket": "checkpoint",
                    "interval_steps": interval,
                    "snapshot_bytes": snap_total,
                    "per_device_snapshot_bytes": snap_dev,
                }
        return streams

    def _ckpt_snapshot_bytes(self):
        """(global, per-device) bytes of one checkpoint snapshot — the
        params + optimizer-state + loss-scale trees the ckpt writer
        serializes. Per-device uses each leaf's sharding dimspec (the
        same analysis/cost pricing reshard's overlap reads report)."""
        from ..analysis.cost.walk import device_bytes, dimspec_from_sharding

        state = self.state
        if state is None:
            return 0.0, 0.0
        world = max(self.topology.world_size, 1)
        total = per_dev = 0.0
        for tree, sh in (
            (state.params, self.param_shardings),
            (state.opt_state, self.opt_shardings),
            (state.loss_scale, None),
        ):
            leaves = jax.tree_util.tree_leaves(tree)
            shardings = (
                jax.tree_util.tree_leaves(sh)
                if sh is not None
                else [None] * len(leaves)
            )
            for i, leaf in enumerate(leaves):
                shape = tuple(getattr(leaf, "shape", ()) or ())
                dtype = np.dtype(getattr(leaf, "dtype", np.float32))
                n = float(dtype.itemsize)
                for d in shape:
                    n *= int(d)
                total += n
                s = shardings[i] if i < len(shardings) else None
                if s is not None and shape:
                    try:
                        per_dev += device_bytes(
                            shape, dtype,
                            dimspec_from_sharding(s, len(shape), {}),
                        )
                    except Exception:  # noqa: BLE001 — duck-typed shardings
                        per_dev += n / world
                else:
                    per_dev += n
        return total, per_dev

    def parity_pairs(self):
        """The declared-bitwise form pairs of this engine's train step
        (analysis/parity.py — TP ring vs XLA reference when
        overlap_comm serves, moe_a2a chunked vs stock, wire codec vs
        full-width). Each pair re-traces the step abstractly from a
        knob-flipped twin of this config; ``tools/paritycheck.py``
        proves them all statically."""
        from ..analysis.parity import config_parity_pairs

        return config_parity_pairs(self.config.raw, self.model)

    def _record_offload_stream(self, steps: int = 1, batch=None):
        if self.comm_logger is None:
            return
        # ring bytes scale with the ACTUAL batch sequence length (and
        # vanish when it stops dividing the ring) — derive it from the
        # prepared batch rather than trusting model max_seq_len
        seq = None
        if isinstance(batch, dict):
            ids = batch.get("input_ids")
            if ids is not None and getattr(ids, "shape", None):
                seq = int(ids.shape[-1])
        self.comm_logger.record_streams(
            self.analytic_streams(seq=seq), steps=steps
        )

    def _tp_overlap_stream_for(self, seq):
        """The analytic ring stream at one sequence length (cached)."""
        if seq is None:
            return self.tp_overlap_stream
        if seq not in self._tp_overlap_streams:
            self._tp_overlap_streams[seq] = self._compute_tp_overlap_stream(
                seq=seq
            )
        return self._tp_overlap_streams[seq]

    def _compute_tp_overlap_stream(self, seq=None):
        """Static per-step decomposed-ring wire bytes (None when overlap is
        off, shapes keep the rings from engaging, or the model isn't
        transformer-shaped). Reported to the comms logger per step — the
        trace-time hook bus under-counts scanned layers (a scan body
        traces once), so the analytic figure is the honest per-step
        number. ``seq`` defaults to the model's max_seq_len (the bench
        estimate); recording passes the actual batch length."""
        if self.tp_overlap is None:
            return None
        from ..parallel.tensor_overlap import ring_wire_bytes_per_step

        model_cfg = getattr(self.model, "config", None)
        if model_cfg is None:
            return None
        return ring_wire_bytes_per_step(
            model_cfg,
            self.topology,
            self.tp_overlap,
            batch=self.config.train_micro_batch_size_per_gpu
            * self.topology.data_shard_size,
            seq=seq if seq is not None
            else getattr(model_cfg, "max_seq_len", 0),
            itemsize=jnp.dtype(self.compute_dtype).itemsize,
            accum_steps=self.config.gradient_accumulation_steps,
        )

    def _moe_a2a_stream_for(self, seq):
        """The analytic MoE exchange stream at one sequence length
        (cached, the _tp_overlap_stream_for discipline)."""
        if seq is None:
            return self.moe_a2a_stream
        if seq not in self._moe_a2a_streams:
            self._moe_a2a_streams[seq] = self._compute_moe_a2a_stream(
                seq=seq
            )
        return self._moe_a2a_streams[seq]

    def _compute_moe_a2a_stream(self, seq=None):
        """Static per-step MoE dispatch/combine exchange bytes (None for
        non-MoE models or ep == 1). Declared for BOTH the serial and the
        decomposed path — capacity scales with the batch, so recording
        passes the actual sequence length like the TP ring stream."""
        model_cfg = getattr(self.model, "config", None)
        if model_cfg is None or self.topology.ep_size <= 1:
            return None
        from ..parallel.a2a_overlap import (
            moe_a2a_applicable,
            moe_a2a_bytes_per_step,
        )

        batch = (self.config.train_micro_batch_size_per_gpu
                 * self.topology.data_shard_size)
        seq = seq if seq is not None else getattr(
            model_cfg, "max_seq_len", 0
        )
        stream = moe_a2a_bytes_per_step(
            model_cfg,
            self.topology,
            batch=batch,
            seq=seq,
            itemsize=jnp.dtype(self.compute_dtype).itemsize,
            accum_steps=self.config.gradient_accumulation_steps,
        )
        if stream is not None:
            # whether the decomposed rings would ENGAGE at these shapes —
            # the moe_layer dispatch predicate evaluated statically
            stream["ring_engages"] = moe_a2a_applicable(
                self.topology, B=batch, S=seq,
                E=int(getattr(model_cfg, "num_experts", 0) or 0),
                F=int(getattr(model_cfg, "ffn", 0) or 0),
            )
        return stream

    def _compute_z3_prefetch_stream(self):
        """Static per-step all-gather wire for the prefetched layer scan
        (None when the knob/mesh leaves nothing to prefetch). Shapes, not
        batch, set this stream — no per-seq cache needed. Wire codecs
        shrink it: with ``param_wire`` / ``grad_wire`` set the prefetched
        gather moves codec bytes and R8 prices the smaller stream."""
        if self._z3_prefetch_puts is None:
            return None
        from .zero.prefetch import prefetch_wire_bytes_per_step

        params_shape, tp_specs = self._z3_prefetch_shapes
        return prefetch_wire_bytes_per_step(
            params_shape,
            tp_specs,
            self.param_specs,
            self.topology,
            itemsize=jnp.dtype(self.compute_dtype).itemsize,
            accum_steps=self.config.gradient_accumulation_steps,
            remat=bool(self.remat_policy and self.remat_policy != "none"),
            param_wire=self._param_wire,
            grad_wire=self._grad_wire,
            hierarchical=self._hier_wire,
        )

    # --------------------------------------------------- wire accounting
    def _wire_leaf_iter(self, specs_a, specs_b, exclude_key=None):
        """Yield (shape, dim, axes, n) for every leaf whose ``specs_a``
        entry carries mesh axes its ``specs_b`` entry doesn't — the
        leaves a wire collective actually touches. ``exclude_key``
        masks a top-level subtree (the stacked ``layers`` group when the
        prefetch stream already prices it)."""
        from .zero.quantized import gather_dim_and_axes

        if exclude_key is not None and isinstance(specs_a, dict) and (
            exclude_key in specs_a
        ):
            specs_a = {**specs_a, exclude_key: specs_b[exclude_key]}
        is_spec = lambda s: isinstance(s, P)
        shapes = jax.tree_util.tree_leaves(self._params_shape)
        a_flat = jax.tree_util.tree_leaves(specs_a, is_leaf=is_spec)
        b_flat = jax.tree_util.tree_leaves(specs_b, is_leaf=is_spec)
        for sh, sa, sb in zip(shapes, a_flat, b_flat):
            hit = gather_dim_and_axes(sa, sb, len(sh.shape))
            if hit is None:
                continue
            dim, axes = hit
            n = 1
            for a in axes:
                n *= self.topology.sizes[a]
            if n > 1:
                yield tuple(int(d) for d in sh.shape), dim, axes, n

    def _leaf_hier(self, axes):
        """(n_outer, n_inner) when this leaf's wire runs the 2-hop form —
        the SAME wires.hier_axes predicate the executed collective uses
        (runtime/zero/quantized.make_leaf_gather), so the priced stream
        and the traced program can never disagree on eligibility."""
        from ..comm import wires

        if not self._hier_wire:
            return None
        hier = wires.hier_axes(self.topology, axes)
        if hier is None:
            return None
        return hier[1], hier[3]

    def _compute_grad_wire_stream(self):
        """Static per-device wire bytes of the codec gradient
        reduce-scatter (qgZ/hgZ; None when no codec wire engages).
        Stage 1/2: the explicit wired reduction, once per optimizer step
        (after the accumulation scan) — stage-1 leaves add the f32
        gather-back half of the decomposed all-reduce, non-dividing
        leaves stay full-width psum and are reported as such. Stage 3:
        the gather backward's reduce-scatter, once per microbatch;
        stacked layers under the prefetch are priced by the
        zero3_prefetch stream instead (never double-counted)."""
        from ..comm import wires

        codec = self._grad_wire
        if codec == "fp32" and not self._hier_wire:
            return None
        inter = intra = fullwidth = 0.0
        hops = 1
        if self._wired_grad_axes:
            plan, _ = self._wired_grad_plan()
            shapes = jax.tree_util.tree_leaves(self._params_shape)
            axes = self._wired_grad_axes
            n = 1
            for a in axes:
                n *= self.topology.sizes[a]
            hier = self._leaf_hier(axes)
            for sh, (kind, dim) in zip(shapes, plan):
                shape = tuple(int(d) for d in sh.shape)
                if kind == "psum":
                    nb = 1
                    for d in shape:
                        nb *= d
                    fullwidth += 2.0 * nb * 4 * (n - 1) / n
                    continue
                if hier is not None:
                    n_o, n_i = hier
                    hops = 2
                    leaf_inter, leaf_intra = wires.hier_rs_nbytes(
                        shape, n_o, n_i, codec, 4, dim=dim
                    )
                    inter += leaf_inter
                    intra += leaf_intra
                else:
                    inter += wires.rs_wire_nbytes(shape, n, codec, 4,
                                                  dim=dim)
                if kind == "rs_ag":
                    fullwidth += wires.rs_wire_nbytes(shape, n, "fp32", 4,
                                                      dim=dim)
        elif self._qgather is not None:
            # stage 3: _qgather exists iff a codec or the 2-hop form
            # engages (the same disjunction the early return tested)
            accum = max(self.config.gradient_accumulation_steps, 1)
            exclude = (
                "layers" if self._z3_prefetch_puts is not None else None
            )
            for shape, dim, axes, n in self._wire_leaf_iter(
                self.param_specs, self._tp_specs, exclude
            ):
                hier = self._leaf_hier(axes)
                if hier is not None:
                    n_o, n_i = hier
                    hops = 2
                    leaf_inter, leaf_intra = wires.hier_rs_nbytes(
                        shape, n_o, n_i, codec, 4, dim=dim
                    )
                    inter += accum * leaf_inter
                    intra += accum * leaf_intra
                else:
                    inter += accum * wires.rs_wire_nbytes(
                        shape, n, codec, 4, dim=dim
                    )
        total = inter + intra + fullwidth
        if total <= 0:
            return None
        return {
            "codec": codec,
            "bytes_per_step": int(total),
            "inter_bytes_per_step": int(inter),
            "intra_bytes_per_step": int(intra),
            "fullwidth_bytes_per_step": int(fullwidth),
            "hierarchical": hops == 2,
        }

    def _compute_param_wire_stream(self):
        """Static per-device wire bytes of the codec stage-3 parameter
        all-gathers (qwZ; None when no codec gather engages). One gather
        per microbatch forward, plus the remat re-gather; stacked layers
        under the prefetch are priced by the zero3_prefetch stream."""
        from ..comm import wires

        codec = self._param_wire
        if self._qgather is None or (codec == "fp32"
                                     and not self._hier_wire):
            return None
        accum = max(self.config.gradient_accumulation_steps, 1)
        remat = bool(self.remat_policy and self.remat_policy != "none")
        passes = accum * (2 if remat else 1)
        inter = intra = 0.0
        hops = 1
        exclude = "layers" if self._z3_prefetch_puts is not None else None
        for shape, dim, axes, n in self._wire_leaf_iter(
            self.param_specs, self._tp_specs, exclude
        ):
            hier = self._leaf_hier(axes)
            if hier is not None:
                n_o, n_i = hier
                hops = 2
                leaf_inter, leaf_intra = wires.hier_ag_nbytes(
                    shape, n_o, n_i, codec, 4, dim=dim
                )
                inter += passes * leaf_inter
                intra += passes * leaf_intra
            else:
                shard = list(shape)
                shard[dim] //= n
                inter += passes * wires.ag_wire_nbytes(
                    shard, n, codec, 4, dim=dim
                )
        total = inter + intra
        if total <= 0:
            return None
        return {
            "codec": codec,
            "bytes_per_step": int(total),
            "inter_bytes_per_step": int(inter),
            "intra_bytes_per_step": int(intra),
            "hierarchical": hops == 2,
            "passes": passes,
        }

    # ------------------------------------------------------------------ step
    def _device_params(self, params):
        """Memory staging: copy offloaded (pinned_host) params to device."""
        if self._param_memory_kind:
            params = jax.tree.map(
                jax.device_put, params, self._param_dev_shardings
            )
        return params

    @staticmethod
    def _put_except(tree, shardings, key):
        """device_put every entry of ``tree`` except ``key`` (the bucketed
        stacked-layers group, which streams per-slice in the update scan
        and must keep its resting placement)."""
        return {
            **jax.tree.map(
                jax.device_put,
                {k: v for k, v in tree.items() if k != key},
                {k: v for k, v in shardings.items() if k != key},
            ),
            key: tree[key],
        }

    def _bucketed_slice_put(self, shardings_tree):
        """(to_device, to_host) placement hooks for one layer-slice of an
        offloaded stacked tree (see BucketedOptimizer.step). The slice
        shardings are the stacked leaves' with the leading (layer) spec
        entry dropped; None on meshes without memory kinds (CPU tests run
        the same scan, just without the DMA pinning)."""
        kind = self._opt_memory_kind or self._param_memory_kind
        if kind is None:
            return None
        mesh = self.topology.mesh
        stacked = shardings_tree[self._bucketed_opt.key]

        def drop_lead(ns, memory_kind=None):
            spec = tuple(ns.spec)
            spec = spec[1:] if spec else ()
            kwargs = {"memory_kind": memory_kind} if memory_kind else {}
            return NamedSharding(mesh, P(*spec), **kwargs)

        dev = jax.tree.map(drop_lead, stacked)
        # writeback respects each leaf's OWN final placement: the big
        # param-shaped leaves (m/v/masters) return to pinned host, but
        # small non-param leaves (e.g. adam's count) stay on device — a
        # host-space s32 lane-update is also unsupported by the compiler
        hst = jax.tree.map(
            lambda ns: drop_lead(
                ns, kind if getattr(ns, "memory_kind", None) == kind else None
            ),
            stacked,
        )
        return (
            lambda t: jax.device_put(t, dev),
            lambda t: jax.device_put(t, hst),
        )

    def _effective_params(self, params):
        """Differentiable staging — must run *inside* the differentiated
        function so the ZeRO++ gather's custom VJP (gradient reduce-scatter)
        and the QAT straight-through estimator shape the backward pass."""
        if self._qgather is not None:
            params = self._qgather(params)
        if self._qat is not None:
            from ..compression.compress import ste_fake_quant

            params = ste_fake_quant(params, *self._qat)
        return params

    def _kernel_scope(self):
        """Trace-time kernel selection for this engine's tpu_kernels config
        (scoped: no process-global mutation)."""
        from contextlib import ExitStack

        from ..ops.attention import attention_impl
        from ..ops.normalization import pallas_rmsnorm_scope
        from ..ops.pallas.flash_attention import block_sizes_scope

        tk = self.tpu_kernels
        stack = ExitStack()
        stack.enter_context(
            attention_impl(
                self._sparse_impl
                if self._sparse_impl is not None
                else ("flash" if tk.flash_attention else "xla")
            )
        )
        stack.enter_context(pallas_rmsnorm_scope(tk.fused_rmsnorm))
        stack.enter_context(
            block_sizes_scope(tk.flash_block_q, tk.flash_block_k,
                              tk.flash_block_q_bwd, tk.flash_block_k_bwd)
        )
        from ..ops.cross_entropy import fused_ce_scope

        stack.enter_context(fused_ce_scope(tk.fused_ce, tk.ce_chunk))
        from ..parallel.tensor_overlap import overlap_scope

        stack.enter_context(overlap_scope(self.tp_overlap))
        from ..parallel.a2a_overlap import a2a_scope

        stack.enter_context(a2a_scope(self.moe_a2a))
        from .zero.prefetch import prefetch_scope

        stack.enter_context(prefetch_scope(self._z3_prefetch_puts))
        return stack

    def _loss_for(self, params, mb, key, scale, pld_keep=None, ltd_keep=None):
        params = self._effective_params(params)
        kw = {}
        if pld_keep is not None:
            kw["pld_keep"] = pld_keep
        if ltd_keep is not None and self._ltd_layers is not None:
            kw["ltd_keep"] = ltd_keep
            kw["ltd_layers"] = self._ltd_layers
        with self._kernel_scope():
            loss, metrics = self.model.loss(
                params,
                mb,
                dtype=self.compute_dtype,
                train=True,
                rng=key,
                remat_policy=self.remat_policy,
                **kw,
            )
        return loss * scale, (loss, metrics)

    def _pld_keep(self, step):
        """[L] per-layer keep probs when progressive layer drop is on."""
        if self.pld is None:
            return None
        from .progressive_layer_drop import layer_keep_probs

        return layer_keep_probs(
            self.pld.get_theta(step), self.model.config.num_layers
        )

    def _compute_grads(self, params, batch, rng, scale, step=None, ltd_keep=None):
        """(grads fp32 mean-over-microbatches, mean loss, model metrics).
        ``batch`` has a leading grad-accum dim. Overridden by PipelineEngine
        (the pipeline schedule consumes all microbatches in one pass).

        Model metrics (lm_loss, moe_aux_loss, tokens) ride through so the
        engine can log them (reference: MoE aux loss in the step log);
        scalars are microbatch means, token counts sum."""
        accum = self.config.gradient_accumulation_steps
        grad_fn = jax.value_and_grad(self._loss_for, has_aux=True)
        pld_keep = self._pld_keep(step)
        if accum == 1:
            # fast path: no scan, no zeros-init accumulator HBM traffic
            key = jax.random.fold_in(rng, 0)
            (_, (loss, m)), grads = grad_fn(
                params, jax.tree.map(lambda x: x[0], batch), key, scale,
                pld_keep, ltd_keep,
            )
            inv = 1.0 / scale
            grads = jax.tree.map(lambda g: g.astype(jnp.float32) * inv, grads)
            return grads, loss, m

        zero_grads = jax.tree.map(
            lambda x: jnp.zeros(x.shape, jnp.float32), params
        )

        def accum_body(carry, xs):
            g_acc, loss_acc, m_acc = carry
            mb, key = xs
            (_, (loss, m)), grads = grad_fn(
                params, mb, key, scale, pld_keep, ltd_keep
            )
            g_acc = jax.tree.map(lambda a, g: a + g.astype(jnp.float32), g_acc, grads)
            m_acc = jax.tree.map(lambda a, v: a + v, m_acc, m)
            return (g_acc, loss_acc + loss, m_acc), None

        keys = jax.random.split(rng, accum)
        # zero scan-carry derived from the model's actual metric tree (shape
        # eval only — no compute), so custom models with their own metric
        # structure accumulate fine
        m_shape = jax.eval_shape(
            lambda p, mb, k: self._loss_for(p, mb, k, scale, pld_keep, ltd_keep),
            params, jax.tree.map(lambda x: x[0], batch), keys[0],
        )[1][1]
        zero_m = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), m_shape)
        (grads, loss_sum, m_sum), _ = jax.lax.scan(
            accum_body,
            (zero_grads, jnp.zeros((), jnp.float32), zero_m),
            (batch, keys),
        )
        inv = 1.0 / (accum * scale)
        grads = jax.tree.map(lambda g: g * inv, grads)
        if isinstance(m_sum, dict):
            # counts ("tokens") stay sums; everything else reports the mean
            mmetrics = {
                k: (v if k == "tokens" else v / accum) for k, v in m_sum.items()
            }
        else:
            mmetrics = jax.tree.map(lambda v: v / accum, m_sum)
        return grads, loss_sum / accum, mmetrics

    def _compute_grads_stacked(self, params, batch, rng, scale, step,
                               ltd_keep=None):
        """Per-dp-member local grads stacked on a new leading axis [n, ...]
        (sharded over the data axes) — NO cross-member reduction. Feeds the
        wire-compressed 1-bit optimizers, which own the (compressed)
        reduction (ops/onebit.py build_onebit_wire_optimizer)."""
        topo = self.topology
        axes = self._stacked_grads_axes
        ax_entry = axes if len(axes) > 1 else axes[0]
        accum = self.config.gradient_accumulation_steps
        grad_fn = jax.value_and_grad(self._loss_for, has_aux=True)
        pld = self._pld_keep(step)
        has_pld = pld is not None

        def local_fn(params, batch, key, scale, pld_keep):
            pk = pld_keep if has_pld else None
            if accum == 1:
                (_, (loss, _m)), grads = grad_fn(
                    params,
                    jax.tree.map(lambda x: x[0], batch),
                    jax.random.fold_in(key, 0),
                    scale,
                    pk,
                    ltd_keep,
                )
                inv = 1.0 / scale
                grads = jax.tree.map(
                    lambda g: g.astype(jnp.float32) * inv, grads
                )
            else:
                zero_grads = jax.tree.map(
                    lambda x: jnp.zeros(x.shape, jnp.float32), params
                )

                def accum_body(carry, xs):
                    g_acc, loss_acc = carry
                    mb, k = xs
                    (_, (loss, _m)), grads = grad_fn(
                        params, mb, k, scale, pk, ltd_keep
                    )
                    g_acc = jax.tree.map(
                        lambda a, g: a + g.astype(jnp.float32), g_acc, grads
                    )
                    return (g_acc, loss_acc + loss), None

                keys = jax.random.split(key, accum)
                (grads, loss_sum), _ = jax.lax.scan(
                    accum_body,
                    (zero_grads, jnp.zeros((), jnp.float32)),
                    (batch, keys),
                )
                inv = 1.0 / (accum * scale)
                grads = jax.tree.map(lambda g: g * inv, grads)
                loss = loss_sum / accum
            loss = jax.lax.pmean(loss, axes)
            return jax.tree.map(lambda g: g[None], grads), loss

        from ..utils.jax_compat import shard_map

        run = shard_map(
            local_fn,
            mesh=topo.mesh,
            in_specs=(P(), P(None, ax_entry), P(), P(), P()),
            out_specs=(P(ax_entry), P()),
            axis_names=set(axes),
            check_vma=False,
        )
        return run(
            params,
            batch,
            rng,
            scale,
            pld if has_pld else jnp.zeros((), jnp.float32),
        )

    def _wired_grad_plan(self):
        """Per-leaf reduction plan for the stage-1/2 grad wire, aligned
        with the flattened param tree: ``("rs", dim)`` — the leaf's grad
        spec carries the data axes (stage 2: reduce-scatter straight
        into its resting layout); ``("rs_ag", dim)`` — replicated-grad
        leaf with a dividable dim (stage 1: the decomposed all-reduce —
        codec reduce-scatter + full-width f32 gather of the reduced
        shards, the qgZ split of an all-reduce); ``("psum", None)`` —
        nothing divides, full-width psum (honest: no wire saving there).
        Second return: the shard_map out_specs tree (manual data axes
        only — tp sharding rides the automatic axes)."""
        from .zero.partition import add_data_axes
        from .zero.quantized import gather_dim_and_axes

        axes = self._wired_grad_axes
        is_spec = lambda s: isinstance(s, P)
        shapes_flat, treedef = jax.tree_util.tree_flatten(self._params_shape)
        gspecs = jax.tree_util.tree_leaves(self.grad_specs, is_leaf=is_spec)
        tspecs = jax.tree_util.tree_leaves(self._tp_specs, is_leaf=is_spec)
        plan, out_flat = [], []
        for sh, gs, ts in zip(shapes_flat, gspecs, tspecs):
            ndim = len(sh.shape)
            hit = gather_dim_and_axes(gs, ts, ndim)
            if hit is not None and set(hit[1]) == set(axes):
                dim = hit[0]
                plan.append(("rs", dim))
                entries = list(gs) + [None] * (ndim - len(gs))
                proj = []
                for e in entries:
                    es = e if isinstance(e, tuple) else ((e,) if e else ())
                    kept = tuple(a for a in es if a in axes)
                    proj.append(
                        kept if len(kept) > 1
                        else (kept[0] if kept else None)
                    )
                out_flat.append(P(*proj))
                continue
            cand = add_data_axes(ts, sh.shape, self.topology, axes)
            hit2 = gather_dim_and_axes(cand, ts, ndim)
            plan.append(
                ("rs_ag", hit2[0]) if hit2 is not None else ("psum", None)
            )
            out_flat.append(P())
        return plan, jax.tree_util.tree_unflatten(treedef, out_flat)

    def _compute_grads_wired(self, params, batch, rng, scale, step,
                             ltd_keep=None):
        """(grads fp32 in their resting layout, mean loss) with the
        cross-member gradient reduction run as the explicit wire-codec
        reduce-scatter (qgZ): member-local grads compute inside a
        shard_map over the data axes, each leaf's blocks quantize ONCE,
        the accumulate runs after dequant in f32 (master precision), and
        the f32 mean lands in the leaf's grad_specs layout. Like the
        1-bit wire path, model metrics don't ride (loss only)."""
        from ..comm import wires

        topo = self.topology
        axes = self._wired_grad_axes
        ax_entry = axes if len(axes) > 1 else axes[0]
        accum = self.config.gradient_accumulation_steps
        grad_fn = jax.value_and_grad(self._loss_for, has_aux=True)
        pld = self._pld_keep(step)
        has_pld = pld is not None
        n_members = 1
        for a in axes:
            n_members *= topo.sizes[a]
        hier = wires.hier_axes(topo, axes) if self._hier_wire else None
        plan, grads_out_specs = self._wired_grad_plan()
        codec = self._grad_wire
        inv_members = 1.0 / float(n_members)

        def reduce_leaf(g, kind, dim):
            if kind == "psum":
                return lax.psum(g, axes) * inv_members
            if hier is not None:
                o, n_o, i_ax, n_i = hier
                red = wires.rs_wire_hier_local(
                    g, o, i_ax, n_o, n_i, codec, dim=dim,
                    dtype=jnp.float32,
                )
            else:
                red = wires.rs_wire_local(
                    g, ax_entry, n_members, codec, dim=dim,
                    dtype=jnp.float32,
                )
            red = red * inv_members
            if kind == "rs_ag":
                red = jnp.moveaxis(
                    lax.all_gather(
                        jnp.moveaxis(red, dim, 0), axes, axis=0, tiled=True
                    ),
                    0, dim,
                )
            return red

        def local_fn(params, batch, key, scale, pld_keep):
            pk = pld_keep if has_pld else None
            if accum == 1:
                (_, (loss, _m)), grads = grad_fn(
                    params,
                    jax.tree.map(lambda x: x[0], batch),
                    jax.random.fold_in(key, 0),
                    scale,
                    pk,
                    ltd_keep,
                )
                inv = 1.0 / scale
                grads = jax.tree.map(
                    lambda g: g.astype(jnp.float32) * inv, grads
                )
            else:
                zero_grads = jax.tree.map(
                    lambda x: jnp.zeros(x.shape, jnp.float32), params
                )

                def accum_body(carry, xs):
                    g_acc, loss_acc = carry
                    mb, k = xs
                    (_, (loss, _m)), grads = grad_fn(
                        params, mb, k, scale, pk, ltd_keep
                    )
                    g_acc = jax.tree.map(
                        lambda a, g: a + g.astype(jnp.float32), g_acc, grads
                    )
                    return (g_acc, loss_acc + loss), None

                keys = jax.random.split(key, accum)
                (grads, loss_sum), _ = jax.lax.scan(
                    accum_body,
                    (zero_grads, jnp.zeros((), jnp.float32)),
                    (batch, keys),
                )
                inv = 1.0 / (accum * scale)
                grads = jax.tree.map(lambda g: g * inv, grads)
                loss = loss_sum / accum
            leaves = jax.tree_util.tree_structure(params).flatten_up_to(
                grads
            )
            reduced = [
                reduce_leaf(g, kind, dim)
                for g, (kind, dim) in zip(leaves, plan)
            ]
            grads = jax.tree_util.tree_structure(params).unflatten(reduced)
            return grads, jax.lax.pmean(loss, axes)

        from ..utils.jax_compat import shard_map

        run = shard_map(
            local_fn,
            mesh=topo.mesh,
            in_specs=(P(), P(None, ax_entry), P(), P(), P()),
            out_specs=(grads_out_specs, P()),
            axis_names=set(axes),
            check_vma=False,
        )
        return run(
            params,
            batch,
            rng,
            scale,
            pld if has_pld else jnp.zeros((), jnp.float32),
        )

    def _grads_and_loss(self, params, loss_scale, step, batch, rng,
                        ltd_keep=None):
        """The fwd+bwd half of the step: (grads fp32, loss). Compiled
        standalone for the NVMe-offload path so disk swap-in of the optimizer
        state overlaps with this program's device time."""
        cfg = self.config
        params = self._device_params(params)
        scale = loss_scale.scale if self.fp16_enabled else jnp.ones((), jnp.float32)
        if self._stacked_grads_axes:
            grads, loss = self._compute_grads_stacked(
                params, batch, rng, scale, step, ltd_keep
            )
            mmetrics = {}  # 1-bit wire path: loss only (local stacked grads)
        elif self._wired_grad_axes:
            grads, loss = self._compute_grads_wired(
                params, batch, rng, scale, step, ltd_keep
            )
            mmetrics = {}  # wire path: loss only (like the 1-bit path)
        else:
            grads, loss, mmetrics = self._compute_grads(
                params, batch, rng, scale, step, ltd_keep
            )

        # ZeRO>=2: materialize grads sharded (psum → reduce-scatter)
        if cfg.zero_config.stage >= 2 and self.topology.world_size > 1:
            grads = jax.tree.map(
                lambda g, s: jax.lax.with_sharding_constraint(g, s),
                grads,
                self.grad_shardings,
            )
        return grads, loss, mmetrics

    def _apply_update(self, params, opt_state, loss_scale, step, grads, loss,
                      mmetrics=None):
        """The optimizer half of the step (overflow skip, clip, update)."""
        cfg = self.config
        # offloaded state: explicit copies host→device for compute; the step's
        # out_shardings put the new state back in pinned host memory, so XLA
        # schedules the DMA both ways around the math
        if self._bucketed_opt is not None and self._param_memory_kind:
            # host-resident LAYER masters stream per layer inside the
            # bucketed scan (a whole-tree copy here would defeat it); the
            # non-layer leaves update as one group and need device copies
            params = self._put_except(
                params, self._param_dev_shardings, self._bucketed_opt.key
            )
        else:
            params = self._device_params(params)
        if self._opt_memory_kind:
            if self._bucketed_opt is not None:
                opt_state = {
                    "rest": jax.tree.map(
                        jax.device_put,
                        opt_state["rest"],
                        self._opt_dev_shardings["rest"],
                    ),
                    # layer state stays pinned_host; the scan's state_put
                    # hooks move one layer per tick
                    "layers": opt_state["layers"],
                }
            else:
                opt_state = jax.tree.map(
                    jax.device_put, opt_state, self._opt_dev_shardings
                )
        overflow = (
            ~grads_finite(grads) if self.fp16_enabled else jnp.asarray(False)
        )
        if self._stacked_grads_axes:
            # stacked locals: report sqrt(Σ_i ||g_i||²/n) ≈ mean-grad norm;
            # clipping is not applied (reference 1-bit limitation)
            n_members = 1
            for a in self._stacked_grads_axes:
                n_members *= self.topology.sizes[a]
            gnorm = global_norm(grads) / jnp.sqrt(float(n_members))
        else:
            gnorm = global_norm(grads)
            if cfg.gradient_clipping > 0:
                factor = jnp.minimum(
                    1.0, cfg.gradient_clipping / (gnorm + 1e-6)
                )
                grads = jax.tree.map(lambda g: g * factor, grads)

        if self._bucketed_opt is not None:
            new_params, new_opt = self._bucketed_opt.step(
                grads,
                opt_state,
                params,
                state_put=self._bucketed_slice_put(self.opt_shardings),
                param_put=(
                    self._bucketed_slice_put(self.param_shardings)
                    if self._param_memory_kind
                    else None
                ),
            )
        else:
            updates, new_opt = self.optimizer_tx.update(
                grads, opt_state, params
            )
            new_params = optax.apply_updates(params, updates)

        if self.fp16_enabled:
            # overflow → keep old state (skip step); bf16/fp32 never overflow
            # this way, so skip the full-state select (HBM traffic)
            def sel(new, old):
                return jax.tree.map(lambda a, b: jnp.where(overflow, b, a), new, old)

            new_params = sel(new_params, params)
            new_opt = sel(new_opt, opt_state)
        if self.compression_masks:
            # re-impose pruning masks the optimizer update just violated
            # (reference: masks enforced in every compressed forward)
            from ..compression.compress import redundancy_clean

            new_params = redundancy_clean(new_params, self.compression_masks)
        if self._bucketed_opt is not None:
            # the step must be memory-space-closed (train_batch_chain scans
            # it: carry in == carry out): the rest-group state/params were
            # device_put up top, so return them to their resting placement
            key = self._bucketed_opt.key
            if self._opt_memory_kind:
                new_opt = self._put_except(
                    new_opt, self.opt_shardings, "layers"
                )
            if self._param_memory_kind:
                new_params = self._put_except(
                    new_params, self.param_shardings, key
                )
            # the stacked groups come back with whatever sharding the layer
            # scan stacked (the slice hooks drop the leading spec entry, so
            # a dim-0 partition — L as the largest dp-divisible dim — would
            # be lost); re-put them to their resting shardings so the carry
            # closure holds for EVERY spec shape. A no-op re-put compiles
            # away; this replaced the PR-1 "disable bucketing" gate
            # (shardlint R2 proves the closure statically).
            new_params = {
                **new_params,
                key: jax.tree.map(
                    jax.device_put, new_params[key], self.param_shardings[key]
                ),
            }
            new_opt = {
                **new_opt,
                "layers": jax.tree.map(
                    jax.device_put, new_opt["layers"],
                    self.opt_shardings["layers"],
                ),
            }
        new_scale = update_loss_scale(loss_scale, overflow, cfg.fp16, self.fp16_enabled)
        # skipped steps don't advance the schedule (reference scheduler parity)
        new_step = step + jnp.where(overflow, 0, 1).astype(step.dtype)
        metrics = {
            "loss": loss,
            "grad_norm": gnorm,
            "overflow": overflow,
            "loss_scale": new_scale.scale,
            "lr": self.lr_schedule(step),
            **(mmetrics or {}),  # lm_loss / moe_aux_loss / tokens
        }
        return new_params, new_opt, new_scale, new_step, metrics

    def _train_step(self, params, opt_state, loss_scale, step, batch, rng,
                    ltd_keep=None):
        grads, loss, mmetrics = self._grads_and_loss(
            params, loss_scale, step, batch, rng, ltd_keep
        )
        return self._apply_update(
            params, opt_state, loss_scale, step, grads, loss, mmetrics
        )

    def _eval_step(self, params, batch, rng, train: bool = False):
        # eval sees the same weights the train step optimizes
        params = self._effective_params(self._device_params(params))
        with self._kernel_scope():
            loss, metrics = self.model.loss(
                params, batch, dtype=self.compute_dtype, train=train, rng=rng,
            )
        return loss, metrics

    def _compile_step_fns(self):
        state_shardings = (
            self.param_shardings,
            self.opt_shardings,
            jax.tree.map(lambda _: self._replicated, self.state.loss_scale),
            self._replicated,
        )
        self._state_shardings = state_shardings

        def _counted(fn):
            # trace-time side effect: fires once per XLA compile, so the
            # per-step delta of self.step_traces is the retrace count
            # (healthwatch recompile watchdog + goodput compile bucket).
            # wraps() keeps the compiled program's name (HLO dumps and
            # profiler traces must not all read "jit_wrapped").
            import functools

            @functools.wraps(fn)
            def wrapped(*args):
                self.step_traces += 1
                return fn(*args)

            return wrapped

        self._jit_train = jax.jit(
            _counted(self._train_step),
            donate_argnums=(0, 1, 2, 3),
            static_argnums=(6,),  # random-LTD kept-token count
            out_shardings=(*state_shardings, None),
        )
        self._jit_eval = jax.jit(self._eval_step, static_argnums=(3,))
        if self._nvme_swapper is not None:
            # NVMe overlap (reference: partitioned_optimizer_swapper's
            # async_swapper): the step splits into a grads program and an
            # update program; train_batch dispatches grads, then does the
            # disk swap-in while the device computes, then dispatches the
            # update. Swap-out writes overlap the next step.
            self._jit_grads = jax.jit(
                _counted(self._grads_and_loss), static_argnums=(5,)
            )
            self._jit_update = jax.jit(
                _counted(self._apply_update),
                donate_argnums=(0, 1, 2, 3),
                out_shardings=(*state_shardings, None),
            )

    # ------------------------------------------------------------- batching
    def _batch_sharding(self, accum_leading: bool):
        spec = self.topology.batch_spec()
        entries = ((None,) if accum_leading else ()) + tuple(spec)
        return NamedSharding(self.topology.mesh, P(*entries))

    def _prepare_batch(self, batch) -> Dict[str, jax.Array]:
        """Global batch dict → [accum, per_step_batch, ...] device arrays.

        Fields that already arrived staged (device arrays in the prepared
        [accum, micro, ...] layout with the right sharding — see
        :meth:`prepare_batch`) pass through untouched: no np.asarray
        readback, no re-upload. On a relayed backend every device_put is a
        blocking host RPC before the step can dispatch, so a steady-state
        loop re-feeding one staged batch skips that cost entirely."""
        accum = self.config.gradient_accumulation_steps
        expect = self.config.train_batch_size
        out = {}
        sharding = self._batch_sharding(accum_leading=True)
        for k, v in batch.items():
            if (
                isinstance(v, jax.Array)
                and v.ndim >= 2
                and v.shape[0] == accum
                and v.shape[1] == expect // accum
                and v.sharding == sharding
            ):
                out[k] = v  # already staged
                continue
            arr = np.asarray(v)
            b = arr.shape[0]
            if b != expect:
                raise ValueError(
                    f"batch field {k!r} has batch {b}, config train_batch_size={expect}"
                )
            arr = arr.reshape(accum, b // accum, *arr.shape[1:])
            out[k] = jax.device_put(arr, sharding)
        return out

    def prepare_batch(self, batch) -> Dict[str, jax.Array]:
        """Pre-stage a global batch on device; feeding the result back to
        :meth:`train_batch` skips the per-step host→device upload.

        For steady-state loops over a fixed batch (benchmarks, overfit
        sanity runs) or a prefetching input pipeline that stages batch N+1
        while N computes. Not for the seqlen-curriculum path (it reshapes
        the batch on host each step)."""
        if "labels" not in batch:
            from ..models.transformer import make_lm_batch

            batch = make_lm_batch(jnp.asarray(batch["input_ids"]))
        return self._prepare_batch(batch)

    def next_rng(self) -> jax.Array:
        self._rng, key = jax.random.split(self._rng)
        return key

    def _check_concrete(self, op: str) -> None:
        if self.abstract:
            raise RuntimeError(
                f"{op}: this engine was built with abstract_init=True — a "
                "shardlint tracing shell whose state is ShapeDtypeStructs; "
                "rebuild without abstract_init to run real steps"
            )

    # ---------------------------------------------------------------- API
    def train_batch(self, data_iter=None, batch=None):
        """Parity: PipelineEngine.train_batch / typical engine step loop.

        Accepts either a global-batch dict (``batch=``) or an iterator
        yielding them (``data_iter=``).
        """
        self._check_concrete("train_batch")
        hw = self.healthwatch
        tr = self.tracer
        if hw is not None:
            hw.on_step_start()
        self.tput.start()
        if batch is None:
            if data_iter is None:
                raise ValueError("train_batch needs data_iter or batch")
            # input-wait instrumentation (ISSUE 11): the iterator pull is
            # the data stall — healthwatch's stall_on_data goodput bucket
            in_sp = tr.begin("train/input_wait", "train") if tr else None
            batch = self._next_batch(data_iter)
            if in_sp is not None:
                in_sp.end()
        if "labels" not in batch:
            from ..models.transformer import make_lm_batch

            batch = make_lm_batch(jnp.asarray(batch["input_ids"]))
        if self.curriculum is not None and self.curriculum.curriculum_type == "seqlen":
            # seqlen curriculum: truncate before upload (reference parity:
            # curriculum_scheduler + the engine's seqlen reshape). Each
            # distinct difficulty compiles one program (rounding bounds it).
            # Staged (prepare_batch) inputs are [accum, micro, seq] device
            # arrays — the host-side truncate below would slice the micro
            # axis and force a device readback; fail loudly instead.
            if any(
                isinstance(v, jax.Array)
                and v.ndim >= 2
                and v.shape[0] == self.config.gradient_accumulation_steps
                for v in batch.values()
            ):
                raise ValueError(
                    "seqlen curriculum reshapes the batch on host each "
                    "step; pass the raw host batch, not prepare_batch() "
                    "output"
                )
            difficulty = self.curriculum.update_difficulty(self.global_steps)
            batch = {
                k: (np.asarray(v)[:, :difficulty] if np.asarray(v).ndim >= 2 else v)
                for k, v in batch.items()
            }
        breakdown = self.config.wall_clock_breakdown
        step_sp = (
            tr.begin("train/step", "train", {"step": self.global_steps + 1})
            if tr else None
        )
        if breakdown:
            self.timers("batch_prep").start()
        prep_sp = tr.begin("train/batch_prep", "train") if tr else None
        prepared = self._prepare_batch(batch)
        self._last_seq = int(prepared["input_ids"].shape[-1])
        if prep_sp is not None:
            prep_sp.end()
        if breakdown:
            self.timers("batch_prep").stop()
        ltd_keep = None
        if self.random_ltd is not None:
            # skipped (fp16-overflow) steps must not advance the anneal —
            # same invariant the in-step counter enforces for lr/PLD
            ltd_keep = self.random_ltd.get_seq_len(
                self.global_steps - self.skipped_steps
            )
            seq = prepared["input_ids"].shape[-1]
            if ltd_keep >= seq:
                ltd_keep = None  # schedule annealed past full length
        if breakdown:
            self.timers("step_dispatch").start()
        traces_before = self.step_traces
        with use_topology(self.topology):
            if self._nvme_swapper is not None:
                # dispatch grads async, then overlap the NVMe swap-in with
                # the device's fwd+bwd time; the update program follows.
                # Span discipline: the fwd_bwd dispatch span does NOT
                # fence (a fence here would serialize the swap-in against
                # the device work — the very overlap being traced); the
                # train/device span at the bottom owns the blocking wait.
                sp = tr.begin("train/fwd_bwd_dispatch", "train") if tr \
                    else None
                grads, loss, mmetrics = self._jit_grads(
                    self.state.params, self.state.loss_scale, self.state.step,
                    prepared, self.next_rng(), ltd_keep,
                )
                if sp is not None:
                    if self.step_traces != traces_before:
                        # a retrace happened inside this dispatch —
                        # healthwatch books the span as compile time
                        sp.annotate(traced=self.step_traces - traces_before)
                    sp.end()
                    sp = tr.begin("train/offload_swap_in", "train")
                self._swap_in_opt()
                if sp is not None:
                    sp.end()
                    sp = tr.begin("train/optimizer_dispatch", "train")
                traces_mid = self.step_traces
                p, o, s, st, metrics = self._jit_update(
                    *self.state.astuple(), grads, loss, mmetrics
                )
                if sp is not None:
                    if self.step_traces != traces_mid:
                        sp.annotate(traced=self.step_traces - traces_mid)
                    sp.end()
            else:
                sp = tr.begin("train/dispatch", "train") if tr else None
                p, o, s, st, metrics = self._jit_train(
                    *self.state.astuple(), prepared, self.next_rng(), ltd_keep
                )
                if sp is not None:
                    if self.step_traces != traces_before:
                        # a retrace happened inside this dispatch —
                        # healthwatch books the span as compile time
                        sp.annotate(traced=self.step_traces - traces_before)
                    sp.end()
        if tr is not None:
            # fence at close: the async-dispatched fwd/bwd/optimizer work
            # is charged to this span (utils/timer.py block_on
            # discipline). This runs BEFORE the state assignment below —
            # replacing the old (donated) state while the step is still
            # in flight blocks inside the assignment, which would
            # silently attribute the whole device time to host work.
            tr.begin("train/device", "train").end(fence=metrics["loss"])
        self.state = TrainState(p, o, s, st)
        if breakdown:
            # dispatch returns immediately; a second timer blocks on the
            # device so the pair splits host time from device time
            self.timers("step_dispatch").stop()
            self.timers("step_device").start()
            self.timers("step_device").stop(block_on=metrics["loss"])
            if (self.global_steps + 1) % self.config.steps_per_print == 0:
                self.timers.log(["batch_prep", "step_dispatch", "step_device"])
        if self._nvme_swapper is not None:
            sp = tr.begin("train/offload_swap_out", "train") if tr else None
            self._swap_out_opt(blocking=False)  # writes overlap next step
            if sp is not None:
                sp.end()
        self.global_steps += 1
        self.micro_steps += self.config.gradient_accumulation_steps
        self._record_offload_stream(batch=prepared)
        self._metrics = {k: v for k, v in metrics.items()}
        # only the fp16 path reads overflow on host — a host read here forces
        # a device sync every step and kills async dispatch overlap
        if self.fp16_enabled and bool(metrics["overflow"]):
            self.skipped_steps += 1
            log_dist(
                f"step {self.global_steps}: fp16 overflow, skipping update "
                f"(new scale {float(metrics['loss_scale'])})"
            )
        if (
            self.config.memory_breakdown
            and self.global_steps % self.config.steps_per_print == 0
        ):
            from ..utils.memory import see_memory_usage

            see_memory_usage(f"step {self.global_steps}")
        self._emit_step_log(metrics, self.global_steps)
        self.tput.stop()
        if step_sp is not None:
            step_sp.end()
        if hw is not None:
            # healthwatch tick AFTER the step span closed: the device
            # fence already ran, so the loss/grad taps read finished
            # values (exactly 2 host scalar transfers per step)
            hw.on_train_step(
                step=self.global_steps,
                loss=metrics["loss"],
                grad_norm=metrics["grad_norm"],
                compiled=self.step_traces - traces_before,
            )
        return metrics["loss"]

    def _emit_step_log(self, metrics, step_no: int):
        """Monitor events + steps_per_print log line for one step's metrics
        (no-op off the print boundary). Shared by train_batch and the
        scanned chain, which replays it for every boundary it crossed."""
        if step_no % self.config.steps_per_print != 0:
            return
        show_moe = "moe_aux_loss" in metrics and getattr(
            getattr(self.model, "config", None), "is_moe", False
        )
        from ..profiling.steptrace import get_registry, write_events

        if self.monitor or get_registry() is not None:
            # the documented train/* namespace, routed through the
            # steptrace registry's single monitor bridge (one coherent
            # scheme with serve/* / comm/* / plan/* / health/*); a traced
            # run records the events as registry samples even with no
            # monitor backend, so MFU/goodput land in the health export
            events = [
                ("train/loss", float(metrics["loss"]), step_no),
                ("train/lr", float(metrics["lr"]), step_no),
                ("train/grad_norm", float(metrics["grad_norm"]), step_no),
            ]
            if show_moe:
                events.append((
                    "train/moe_aux_loss", float(metrics["moe_aux_loss"]),
                    step_no,
                ))
            if self.tput.avg_samples_per_sec > 0:
                events.append((
                    "train/samples_per_sec", self.tput.avg_samples_per_sec,
                    step_no,
                ))
            mfu = self._train_mfu()
            if mfu is not None:
                # flops_profiler MFU wired through the one registry
                # (ISSUE 11 satellite): MFU, goodput and drift appear
                # side-by-side in one export
                events.append(("train/mfu", float(mfu), step_no))
            if self.healthwatch is not None:
                events.append((
                    "train/goodput",
                    self.healthwatch.goodput_fraction(), step_no,
                ))
            write_events(self.monitor, events)
            if self.comm_logger is not None and self.monitor is not None:
                self.comm_logger.write_to(self.monitor, step_no)
        if self.monitor is None:
            aux = (
                f" moe_aux={float(metrics['moe_aux_loss']):.4f}" if show_moe else ""
            )
            sps = self.tput.avg_samples_per_sec
            tput = f" samples/sec={sps:.1f}" if sps > 0 else ""
            log_dist(
                f"step {step_no}: loss={float(metrics['loss']):.4f} "
                f"lr={float(metrics['lr']):.3e} gnorm={float(metrics['grad_norm']):.3f}"
                f"{aux}{tput}"
            )

    def _chain_eligible(self):
        """Host logic that must run BETWEEN steps disqualifies the scanned
        chain; everything else (lr schedule, PLD keep-probs, fp16 scale
        updates, overflow skip) is traced from the step carry and scans
        fine."""
        reasons = []
        if self.random_ltd is not None:
            reasons.append("random-LTD anneal picks a static keep per step")
        if self.curriculum is not None and self.curriculum.curriculum_type == "seqlen":
            reasons.append("seqlen curriculum reshapes the batch on host")
        if self._nvme_swapper is not None:
            reasons.append("NVMe offload swaps optimizer shards between "
                           "the grads and update programs")
        return reasons

    def _jit_chain(self, steps: int, stacked: bool):
        key = (steps, stacked)
        fn = self._chain_fns.get(key)
        if fn is not None:
            return fn

        def chain(params, opt_state, loss_scale, step, data, rng):
            def body(carry, x):
                p, o, s, st, r = carry
                mb = x if stacked else data
                # split exactly as next_rng() does, so a chain is
                # bit-identical to the same steps dispatched one by one
                r, key = jax.random.split(r)
                p, o, s, st, m = self._train_step(p, o, s, st, mb, key, None)
                return (p, o, s, st, r), m

            xs = data if stacked else None
            (p, o, s, st, r), ms = jax.lax.scan(
                body, (params, opt_state, loss_scale, step, rng), xs,
                length=None if stacked else steps,
            )
            return p, o, s, st, r, ms

        fn = jax.jit(
            chain,
            donate_argnums=(0, 1, 2, 3),
            out_shardings=(*self._state_shardings, None, None),
        )
        self._chain_fns[key] = fn
        return fn

    def train_batch_chain(self, batch=None, data_iter=None, steps: int = 1):
        """Run ``steps`` optimizer steps as ONE jitted program: a
        ``lax.scan`` over the train step, so the whole chain costs a single
        host dispatch (and, through a network relay, a single RPC).

        The reference amortizes per-step launch overhead with CUDA graphs
        and fused multi-tensor ops; on TPU the native equivalent is
        compiling the loop itself. With ``batch=`` the same (optionally
        pre-staged) global batch feeds every step — the steady-state shape
        benchmarks measure. With ``data_iter=`` the next ``steps`` host
        batches upload as one stacked transfer and scan through.

        Features that need host logic between steps (random-LTD anneal,
        seqlen curriculum, NVMe swap windows) fall back to per-step
        ``train_batch`` calls transparently. Returns the stacked per-step
        loss array ([steps]); full stacked metrics land in
        ``engine.last_chain_metrics``.
        """
        if steps < 1:
            raise ValueError(f"steps must be >= 1, got {steps}")
        self._check_concrete("train_batch_chain")
        reasons = self._chain_eligible()
        if reasons or steps == 1:
            if reasons:
                log_dist(
                    "train_batch_chain: per-step fallback: "
                    + "; ".join(reasons)
                )
            losses = [
                self.train_batch(batch=batch, data_iter=data_iter)
                for _ in range(steps)
            ]
            self.last_chain_metrics = None
            return jnp.stack([jnp.asarray(ls) for ls in losses])

        from ..models.transformer import make_lm_batch

        stacked = data_iter is not None
        if stacked:
            # stack the N host batches FIRST and upload each field once as
            # one [steps, accum, micro, ...] transfer — per-batch device_put
            # is exactly the blocking-RPC-per-step cost the chain removes.
            # Labels shift on host for the same reason.
            accum = self.config.gradient_accumulation_steps
            expect = self.config.train_batch_size
            host_steps = []
            for _ in range(steps):
                b = {k: np.asarray(v) for k, v in
                     self._next_batch(data_iter).items()}
                if "labels" not in b:
                    ids = b["input_ids"]
                    b["labels"] = np.concatenate(
                        [ids[:, 1:],
                         np.full((ids.shape[0], 1), -1, ids.dtype)], axis=1
                    )
                host_steps.append(b)
            sharding = NamedSharding(
                self.topology.mesh, P(None, None, *tuple(self.topology.batch_spec()))
            )
            data = {}
            for k in host_steps[0]:
                arrs = [b[k] for b in host_steps]
                for a in arrs:
                    if a.shape[0] != expect:
                        raise ValueError(
                            f"batch field {k!r} has batch {a.shape[0]}, "
                            f"config train_batch_size={expect}"
                        )
                data[k] = jax.device_put(
                    np.stack([
                        a.reshape(accum, expect // accum, *a.shape[1:])
                        for a in arrs
                    ]),
                    sharding,
                )
        else:
            if batch is None:
                raise ValueError("train_batch_chain needs batch or data_iter")
            if "labels" not in batch:
                batch = make_lm_batch(jnp.asarray(batch["input_ids"]))
            data = self._prepare_batch(batch)

        self.tput.start()
        with use_topology(self.topology):
            p, o, s, st, self._rng, ms = self._jit_chain(steps, stacked)(
                *self.state.astuple(), data, self._rng
            )
        start = self.global_steps
        self.state = TrainState(p, o, s, st)
        self.global_steps += steps
        self.micro_steps += steps * self.config.gradient_accumulation_steps
        self._record_offload_stream(steps=steps, batch=data)
        self.last_chain_metrics = ms
        # expose the final step's metrics where train_batch puts them
        self._metrics = {k: v[-1] for k, v in ms.items()}
        if self.fp16_enabled:
            skipped = int(np.sum(np.asarray(ms["overflow"])))
            if skipped:
                self.skipped_steps += skipped
                log_dist(
                    f"chain of {steps}: {skipped} fp16-overflow steps skipped"
                )
        self.tput.stop(steps=steps)
        # replay monitor/print output for every boundary inside the chain
        for i in range(steps):
            if (start + i + 1) % self.config.steps_per_print == 0:
                self._emit_step_log(
                    {k: v[i] for k, v in ms.items()}, start + i + 1
                )
        return ms["loss"]

    def _next_batch(self, data_iter):
        """Pull the next batch: accepts a batch dict, an iterator, or an
        iterable (e.g. the DeepSpeedDataLoader returned by initialize();
        its iterator is cached so repeated calls advance it)."""
        if isinstance(data_iter, dict):
            return data_iter
        if hasattr(data_iter, "__next__"):
            return next(data_iter)
        if hasattr(data_iter, "__iter__"):
            key = id(data_iter)
            if key not in self._data_iters:
                self._data_iters[key] = iter(data_iter)
            try:
                return next(self._data_iters[key])
            except StopIteration:
                self._data_iters[key] = iter(data_iter)
                return next(self._data_iters[key])
        return data_iter

    def eval_batch(self, data_iter=None, batch=None):
        self._check_concrete("eval_batch")
        if batch is None:
            batch = self._next_batch(data_iter)
        if "labels" not in batch:
            from ..models.transformer import make_lm_batch

            batch = make_lm_batch(jnp.asarray(batch["input_ids"]))
        sharding = self._batch_sharding(accum_leading=False)
        prepared = {
            k: jax.device_put(np.asarray(v), sharding) for k, v in batch.items()
        }
        with use_topology(self.topology):
            loss, _ = self._jit_eval(self.state.params, prepared, self.next_rng())
        return loss

    def profile_step(self, data_iter=None, batch=None,
                     trace_dir: str = "xprof_trace"):
        """Run one train step under ``jax.profiler.trace`` and dump an xprof
        trace to ``trace_dir`` (open with xprof/tensorboard, or feed to the
        autotuner). Returns (loss, trace_dir).

        Parity: the reference's flops-profiler/wall-clock breakdown hooks —
        here the XLA profiler captures per-op device timelines instead of
        python-side module timers (the step is one fused program)."""
        os.makedirs(trace_dir, exist_ok=True)
        with jax.profiler.trace(trace_dir):
            loss = self.train_batch(data_iter=data_iter, batch=batch)
            # host-read so the device work lands inside the trace window
            jax.block_until_ready(self.state.params)
        log_dist(f"profile_step: xprof trace written to {trace_dir}")
        return loss, trace_dir

    # --------------------------------------------------------- steptrace
    def enable_tracing(self, max_spans: int = 100_000):
        """Attach the steptrace registry AFTER construction (bench.py's
        phase-table leg turns tracing on post-measurement so span fences
        never perturb the banked number). Equivalent to building with
        ``{"steptrace": {"enabled": true}}``."""
        from ..profiling import steptrace as _steptrace

        self.tracer = _steptrace.configure(max_spans=max_spans)
        if self.comm_logger is not None:
            self.comm_logger.registry = self.tracer
        return self.tracer

    def trace_export(self, path: Optional[str] = None) -> str:
        """Write the Chrome trace-event JSON (Perfetto-loadable; see
        docs/observability.md). Every declared ``analytic_streams()``
        stream is added as a ``plan/<name>`` span annotated with its
        shardplan-predicted bytes/seconds next to the measured average
        ``train/step`` wall clock — the per-component drift view."""
        if self.tracer is None:
            raise RuntimeError(
                "steptrace is not enabled on this engine — set "
                '{"steptrace": {"enabled": true}} in the config or call '
                "enable_tracing() first"
            )
        measured = self.tracer.mean_dur("train/step")
        try:
            streams = self.analytic_streams(include_potential=True)
        except Exception:  # noqa: BLE001 — a trace export must not die
            # on the analytic annotation (e.g. half-built lint shells)
            streams = {}
        for name, stream in streams.items():
            args = {}
            if name == "offload" and self._bucketed_opt is not None:
                # bucketed_opt's stream annotation: rotating-slot depth
                # rides along so Perfetto shows the prefetch structure
                args = self._bucketed_opt.stream_annotation()
            self.tracer.plan_span(
                name, {**stream, **args}, measured_step_s=measured
            )
        path = path or self._steptrace_export_path or "steptrace_train.json"
        out = self.tracer.export(path)
        log_dist(f"steptrace: wrote {out}")
        return out

    # -------------------------------------------------------- healthwatch
    def _build_healthwatch(self, hw_cfg):
        """Construct the health layer (profiling/healthwatch.py). It
        rides the steptrace registry — enabling healthwatch turns
        tracing on so the goodput buckets can be classified off this
        engine's own spans."""
        from ..profiling import healthwatch as _healthwatch
        from ..profiling import steptrace as _steptrace

        if self.tracer is None:
            self.tracer = _steptrace.configure(
                max_spans=self.config.steptrace.max_spans
            )
            if self.comm_logger is not None:
                self.comm_logger.registry = self.tracer
        self.healthwatch = _healthwatch.HealthWatch(
            hw_cfg, self.tracer, source="train",
            context={"config": self.config.to_dict()},
        )
        streams = self.analytic_streams()
        self.healthwatch.set_comm_estimate_from_streams(streams)
        snap = streams.get("ckpt_snapshot")
        if snap:
            # arm the checkpoint_stall watchdog: fence budget = snapshot
            # bytes over the host link (same static pricing as R8)
            try:
                from ..analysis.cost.hardware import HardwareModel

                host_bw = float(HardwareModel.detect().host_bw)
                if host_bw > 0:
                    self.healthwatch.set_ckpt_budget(
                        float(snap["per_device_snapshot_bytes"]) / host_bw
                    )
            except Exception as e:  # noqa: BLE001 — telemetry only
                log_dist(f"healthwatch: ckpt budget skipped: {e}")
        return self.healthwatch

    def enable_healthwatch(self, **overrides):
        """Attach healthwatch AFTER construction (bench.py's goodput leg
        turns it on post-measurement so the watchdog taps never perturb
        the banked number). ``overrides`` merge over the config's
        ``healthwatch`` section; ``enabled`` is forced on."""
        if self.healthwatch is not None:
            return self.healthwatch
        from ..config import HealthwatchConfig, _parse_dc

        section = dict(self.config.raw.get("healthwatch") or {})
        section.update(overrides)
        section["enabled"] = True
        cfg = _parse_dc(HealthwatchConfig, section)
        cfg.validate()
        return self._build_healthwatch(cfg)

    def dump_postmortem(self, path: Optional[str] = None,
                        reason: str = "explicit") -> Optional[str]:
        """Write the flight-recorder postmortem JSON (render/validate
        with tools/healthwatch.py; docs/observability.md)."""
        if self.healthwatch is None:
            raise RuntimeError(
                "healthwatch is not enabled on this engine — set "
                '{"healthwatch": {"enabled": true}} in the config or '
                "call enable_healthwatch() first"
            )
        return self.healthwatch.dump_postmortem(path=path, reason=reason)

    def _train_mfu(self) -> Optional[float]:
        """Model-flops utilization from the throughput timer plus the
        flops profiler's analytic per-step flops (fwd+bwd = 3x fwd),
        priced against the hardware table's peak — the ISSUE-11
        satellite that puts MFU next to goodput and drift in one
        export. None until the timer warms up or when the model has no
        TransformerConfig-shaped config."""
        sps = self.tput.avg_samples_per_sec
        mc = getattr(self.model, "config", None)
        if sps <= 0 or mc is None or self._last_seq is None:
            return None
        key = (self.config.train_batch_size, self._last_seq)
        if key not in self._mfu_cache:
            # dict cache per (batch, seq): bucketed-seqlen runs must not
            # re-profile the model at every print boundary
            try:
                from ..analysis.cost.hardware import HardwareModel
                from ..profiling.flops_profiler import get_model_profile

                flops, _macs, _params = get_model_profile(
                    self.model, key[0], key[1], fwd_only=False
                )
                self._mfu_cache[key] = (
                    float(flops),
                    float(HardwareModel.detect().peak_flops),
                )
            except Exception:  # noqa: BLE001 — telemetry must not
                # crash the step loop on an exotic model shape
                self._mfu_cache[key] = (0.0, 0.0)
        flops, peak = self._mfu_cache[key]
        if flops <= 0 or peak <= 0:
            return None
        step_s = self.config.train_batch_size / sps
        return flops / step_s / peak

    # -- reference imperative protocol ---------------------------------------
    def forward(self, batch):
        """Parity: engine(batch) → loss in the engine's current train/eval
        mode (engine.train()/engine.eval(); train mode also buffers the
        batch for backward/step).

        Note: the SPMD fast path is train_batch() — this protocol re-runs the
        forward inside the fused train step at the accumulation boundary, so
        it costs one extra forward per microbatch versus train_batch().
        """
        self._check_concrete("forward")
        if self.training:
            self._pending_batch = batch
        if "labels" not in batch:
            from ..models.transformer import make_lm_batch

            batch = make_lm_batch(jnp.asarray(batch["input_ids"]))
        sharding = self._batch_sharding(accum_leading=False)
        prepared = {k: jax.device_put(np.asarray(v), sharding) for k, v in batch.items()}
        with use_topology(self.topology):
            loss, _ = self._jit_eval(
                self.state.params, prepared, self.next_rng(), self.training
            )
        return loss

    def backward(self, loss=None, batch=None):
        """Parity: engine.backward(loss) — buffers the microbatch; the real
        fused fwd+bwd runs at the accumulation boundary inside step()."""
        mb = batch if batch is not None else getattr(self, "_pending_batch", None)
        if mb is None:
            raise ValueError("backward() without a pending forward batch")
        self._micro_buffer.append(mb)
        self._pending_batch = None
        return loss

    def is_gradient_accumulation_boundary(self) -> bool:
        return len(self._micro_buffer) >= self.config.gradient_accumulation_steps

    def step(self):
        """Parity: engine.step() — applies the update at the boundary."""
        if not self.is_gradient_accumulation_boundary():
            return None
        merged = {}
        for k in self._micro_buffer[0]:
            merged[k] = np.concatenate([np.asarray(mb[k]) for mb in self._micro_buffer])
        self._micro_buffer = []
        return self.train_batch(batch=merged)

    __call__ = forward

    # ------------------------------------------------- nn.Module-ish parity
    # (DeepSpeedEngine subclasses torch.nn.Module; user loops call these)
    @property
    def module(self):
        """Parity: engine.module — the wrapped model object."""
        return self.model

    def train(self, mode: bool = True):
        """Parity: engine.train() — records the mode flag. Train/eval
        behavior here is selected per call (train_batch vs eval_batch);
        the flag only answers engine.training queries."""
        self.training = bool(mode)
        return self

    def eval(self):
        return self.train(False)

    def zero_grad(self, set_to_none: bool = True):
        """Parity no-op: grads are functional values produced inside the
        jitted step, never accumulated into persistent buffers."""

    # ----------------------------------------------------------- properties
    @property
    def lr(self) -> float:
        return float(self.lr_schedule(self.state.step))

    def get_lr(self):
        return [self.lr]

    @property
    def loss_scale(self) -> float:
        return float(self.state.loss_scale.scale)

    def get_global_grad_norm(self) -> float:
        g = self._metrics.get("grad_norm")
        return float(g) if g is not None else 0.0

    @property
    def train_micro_batch_size_per_gpu(self) -> int:
        return self.config.train_micro_batch_size_per_gpu

    @property
    def gradient_accumulation_steps(self) -> int:
        return self.config.gradient_accumulation_steps

    # ------------------------------------------------------------ NVMe swap
    def _swap_in_opt(self):
        """Read optimizer state back from NVMe (no-op if already resident)."""
        if self.state.opt_state is None:
            self.state.opt_state = self._nvme_swapper.swap_in(
                "opt_state", self._opt_treedef, self.opt_shardings
            )

    def _swap_out_opt(self, blocking: bool = True):
        """Stream optimizer state to NVMe and release its device memory.

        blocking=False leaves the disk writes in flight (the swapper blocks
        the next swap_in on them), overlapping write I/O with host-side batch
        prep and the next step's dispatch."""
        self._nvme_swapper.swap_out(
            "opt_state", self.state.opt_state, blocking=blocking
        )
        self.state.opt_state = None

    def save_16bit_model(self, save_dir, save_filename="model.safetensors"):
        """Parity: DeepSpeedEngine.save_16bit_model (deepspeed/runtime/
        engine.py) — consolidate the (possibly ZeRO-sharded) weights into
        ONE bf16 safetensors file, no optimizer state. For the recognized
        model families (llama/mistral/gpt2/bloom/mixtral) the keys are the
        HF state_dict names, so transformers can load the file directly
        (the reference's stated use for a consolidated 16-bit export);
        other models fall back to the checkpoint's internal keystr names
        for same-framework reload. Every process participates in the
        gather; the writer process writes and everyone barriers so no
        process races ahead of the file."""
        from ..integrations.hf import export_hf_state_dict, write_safetensors
        from .checkpointing import _barrier, _is_writer, _leaf_paths, _to_host

        host = jax.tree.map(_to_host, self.state.params)
        fam = str(getattr(self.model.config, "name", "")).split("-")[0].lower()
        hf_families = ("llama", "mistral", "gpt2", "bloom", "mixtral")
        if fam in hf_families:
            # a recognized family must export HF names; an exporter bug
            # here should surface, not silently degrade the file
            flat = export_hf_state_dict(host, self.model.config, fam)
            log_dist(f"save_16bit_model: HF state_dict names ({fam})")
        else:
            flat = dict(zip(_leaf_paths(host),
                            jax.tree_util.tree_leaves(host)))
            log_dist(
                f"save_16bit_model: family {fam!r} has no HF exporter; "
                "writing internal keystr names (same-framework reload only)"
            )
        flat = {
            k: (np.asarray(v).astype(jnp.bfloat16)  # ml_dtypes scalar type
                if np.issubdtype(np.asarray(v).dtype, np.floating)
                else np.asarray(v))
            for k, v in flat.items()
        }
        path = os.path.join(save_dir, save_filename)
        if _is_writer():
            os.makedirs(save_dir, exist_ok=True)
            write_safetensors(path, flat)
        _barrier("save_16bit_model")
        return path

    @contextmanager
    def no_sync(self):
        """Parity shim: DeepSpeedEngine.no_sync. Gradient sync here is not
        a hook to suppress — accumulation is a jitted scan and the data-
        parallel mean happens once at the boundary inside the compiled
        step, so there is nothing to skip; micro-steps never pay a sync.
        Kept for train-loop portability. Like the reference, it refuses
        under ZeRO >= 2 (there the reduce IS the partitioning and a user
        expecting deferred sync would silently get wrong semantics)."""
        if self.config.zero_config.stage >= 2:
            raise RuntimeError(
                "no_sync is not supported with ZeRO stage >= 2 "
                "(gradient reduce-scatter is the partitioning step)"
            )
        yield

    # --------------------------------------------------------- checkpointing
    def _ckpt_guard(self):
        """Lazy per-engine CheckpointGuard: fences async saves and routes
        background write seconds to healthwatch (out-of-band, never the
        goodput buckets — the write overlaps training)."""
        if self._checkpoint_guard is None:
            from .ckpt import CheckpointGuard

            def on_write_done(seconds):
                hw = self.healthwatch
                if hw is not None:
                    hw.add_ckpt_write_s(seconds)

            self._checkpoint_guard = CheckpointGuard(
                on_write_done=on_write_done
            )
        return self._checkpoint_guard

    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        async_save=None):
        self._check_concrete("save_checkpoint")
        from .ckpt import save_checkpoint as _save
        from .ckpt.async_writer import install_preempt_handler

        ckpt_cfg = self.config.checkpoint
        if async_save is None:
            async_save = bool(getattr(ckpt_cfg, "async_save", False))
        if getattr(ckpt_cfg, "on_preempt", "save") == "save":
            # first save teaches SIGTERM where restore points live: a
            # preemption now triggers a final sync save ahead of
            # healthwatch's postmortem chain
            install_preempt_handler(self, save_dir)
        # checkpoint time is its own goodput bucket (ISSUE 11). The span
        # covers only the SYNCHRONOUS cost: swap-in, the snapshot fence
        # (device→pinned-host copy), and the swap-out. An async save's
        # shard write lands in the background and is reported separately
        # as ckpt_write_s — charging it here would bill overlap as stall.
        sp = (self.tracer.begin("train/checkpoint", "train")
              if self.tracer is not None else None)
        if self._nvme_swapper is not None:
            self._swap_in_opt()
        try:
            return _save(
                self, save_dir, tag=tag, client_state=client_state or {},
                async_save=async_save, guard=self._ckpt_guard(),
            )
        finally:
            if self._nvme_swapper is not None:
                self._swap_out_opt()  # keep "on disk between steps" invariant
            if sp is not None:
                sp.end()

    def load_checkpoint(self, load_dir, tag=None, strict=True):
        from .ckpt import load_checkpoint as _load

        guard = self._checkpoint_guard
        if guard is not None:
            guard.fence()  # never read a tag the writer is still landing
        if self._nvme_swapper is not None:
            self._swap_in_opt()  # loader needs a resident template tree
        out = _load(self, load_dir, tag=tag, strict=strict)
        if self._nvme_swapper is not None:
            self._swap_out_opt()
        return out

    def destroy(self):
        """Parity: DeepSpeedEngine.destroy — release global hooks/writers so
        engines created in a loop don't accumulate loggers."""
        if self._checkpoint_guard is not None:
            # land the in-flight async save before the state it snapshotted
            # is torn down (drain logs a writer failure instead of raising:
            # teardown must complete)
            self._checkpoint_guard.drain()
            self._checkpoint_guard = None
        if self.healthwatch is not None:
            self.healthwatch.close()  # final exporter flush + unregister
            self.healthwatch = None
        if self.comm_logger is not None:
            self.comm_logger.stop()
            self.comm_logger = None
        if self.monitor is not None:
            for m in self.monitor.monitors:
                if hasattr(m, "close"):
                    m.close()
            self.monitor = None
        if self._nvme_swapper is not None:
            self._nvme_swapper.close()
            self._nvme_swapper = None
        # Free device buffers NOW rather than at the GC's leisure: an engine
        # holds params + optimizer state (~6x param bytes at fp32 master),
        # and tuner loops that build engines back-to-back on a 16GB chip OOM
        # on the *next* candidate when the previous state lingers. Deleting
        # is safe — the engine is defunct after destroy().
        state, self.state = self.state, None
        if state is not None:
            # TrainState is not a registered pytree — walk its tuple form
            for leaf in jax.tree_util.tree_leaves(state.astuple()):
                if isinstance(leaf, jax.Array):
                    try:
                        leaf.delete()
                    except Exception:  # noqa: BLE001 — already-deleted/donated
                        pass
