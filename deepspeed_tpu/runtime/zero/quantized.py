"""ZeRO++ quantized collectives: qwZ (int8 param all-gather) and qgZ
(int8 gradient reduce-scatter).

Parity: deepspeed/runtime/zero/stage3.py quantized all-gather +
csrc/quantization kernels + the ZeRO++ paper (qwZ / qgZ). The reference
quantizes NCCL payloads with hand-written CUDA; here each stage-3-sharded
parameter is gathered through an explicit ``shard_map`` collective that
quantizes the shard to int8 (one symmetric scale per lane), moves int8 +
scales over ICI, and dequantizes on arrival — the wire carries ~1/4 the
fp32 bytes. The backward of that gather is the gradient reduce-scatter;
with ``zero_quantized_gradients`` it runs as an int8 all-to-all with
per-chunk scales followed by an fp32 local reduction (the all-to-all
formulation is what makes qgZ's single-hop quantization sound: values are
quantized once, summed in fp32 after dequant, never re-quantized).

hpZ composes for free: the gather axes come from the param's sharding spec,
which hpZ restricts to the ``fsdp`` sub-axis (runtime/zero/partition.py).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ...comm import collectives


def _spec_entries(spec: P, ndim: int) -> list:
    entries = list(spec) + [None] * (ndim - len(spec))
    return entries[:ndim]


def gather_dim_and_axes(param_spec: P, tp_spec: P, ndim: int):
    """Locate the ZeRO-sharded dim: the one entry where param_spec carries
    mesh axes that tp_spec doesn't. Returns (dim, extra_axes) or None."""
    p_entries = _spec_entries(param_spec, ndim)
    t_entries = _spec_entries(tp_spec, ndim)
    for i, (pe, te) in enumerate(zip(p_entries, t_entries)):
        p_axes = pe if isinstance(pe, tuple) else ((pe,) if pe else ())
        t_axes = te if isinstance(te, tuple) else ((te,) if te else ())
        extra = tuple(a for a in p_axes if a not in t_axes)
        if extra:
            return i, extra
    return None


def _quantize_lanewise(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """int8 symmetric quant over axis 0 (the sharded dim, moved to front):
    one fp32 scale per remaining-lane, reference csrc/quantization layout."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=0, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127).astype(
        jnp.int8
    )
    return q, scale


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def _gather_leaf(local, axes, dim, n, quant_weights, quant_grads):
    """All-gather a stage-3 shard along ``dim`` over mesh ``axes`` (size
    ``n``). Forward: int8 wire when quant_weights (qwZ). Backward: gradient
    reduce-scatter, int8 all-to-all wire when quant_grads (qgZ)."""
    x = jnp.moveaxis(local, dim, 0)
    if quant_weights:
        q, scale = _quantize_lanewise(x)
        collectives._record("all_gather", axes, (q, scale))
        qg = lax.all_gather(q, axes, axis=0, tiled=False)
        sg = lax.all_gather(scale, axes, axis=0, tiled=False)
        full = (qg.astype(jnp.float32) * sg).astype(local.dtype)
        full = full.reshape((-1,) + x.shape[1:])
    else:
        collectives._record("all_gather", axes, x)
        full = lax.all_gather(x, axes, axis=0, tiled=True)
    return jnp.moveaxis(full, 0, dim)


def _gather_leaf_fwd(local, axes, dim, n, quant_weights, quant_grads):
    return _gather_leaf(local, axes, dim, n, quant_weights, quant_grads), None


def _gather_leaf_bwd(axes, dim, n, quant_weights, quant_grads, _res, gbar):
    g = jnp.moveaxis(gbar, dim, 0)  # [d, rest...] full gradient
    if quant_grads:
        chunk = g.shape[0] // n
        gc = g.reshape((n, chunk) + g.shape[1:])
        # per-(chunk, lane) scales so a single quantization survives the
        # exchange; the reduction happens AFTER dequant, in fp32 (qgZ)
        amax = jnp.max(jnp.abs(gc.astype(jnp.float32)), axis=1, keepdims=True)
        scale = jnp.maximum(amax, 1e-12) / 127.0
        q = jnp.clip(
            jnp.round(gc.astype(jnp.float32) / scale), -127, 127
        ).astype(jnp.int8)
        collectives._record("all_to_all", axes, (q, scale))
        qx = lax.all_to_all(q, axes, split_axis=0, concat_axis=0, tiled=False)
        sx = lax.all_to_all(
            scale, axes, split_axis=0, concat_axis=0, tiled=False
        )
        local = jnp.sum(qx.astype(jnp.float32) * sx, axis=0)
    else:
        collectives._record("reduce_scatter", axes, g)
        local = lax.psum_scatter(g, axes, scatter_dimension=0, tiled=True)
    return (jnp.moveaxis(local.astype(gbar.dtype), 0, dim),)


_gather_leaf.defvjp(_gather_leaf_fwd, _gather_leaf_bwd)


def make_quantized_gather(topo, param_specs: Any, tp_specs: Any,
                          params_shape: Any, quant_weights: bool,
                          quant_grads: bool):
    """Build ``gather(params) -> full params`` applying qwZ/qgZ per leaf.

    Leaves whose spec carries no ZeRO data axes (persistence-threshold
    survivors, pure-TP leaves) pass through untouched; XLA keeps handling
    them implicitly. The returned callable runs inside jit (each gathered
    leaf is a partial-manual ``shard_map`` over just the ZeRO axes; tp/pp
    axes stay automatic)."""
    mesh = topo.mesh
    is_spec = lambda x: isinstance(x, P)

    shapes_flat, treedef = jax.tree_util.tree_flatten(params_shape)
    pspecs_flat = jax.tree_util.tree_leaves(param_specs, is_leaf=is_spec)
    tspecs_flat = jax.tree_util.tree_leaves(tp_specs, is_leaf=is_spec)
    assert len(shapes_flat) == len(pspecs_flat) == len(tspecs_flat)

    fns = []
    for shape_leaf, pspec, tpspec in zip(shapes_flat, pspecs_flat, tspecs_flat):
        ndim = len(shape_leaf.shape)
        hit = gather_dim_and_axes(pspec, tpspec, ndim)
        if hit is None:
            fns.append(None)
            continue
        dim, axes = hit
        n = 1
        for a in axes:
            n *= topo.sizes[a]
        # partial-manual specs mention only the manual (ZeRO) axes; the tp
        # sharding of the same array rides the automatic axes
        in_spec = P(*([None] * dim + [axes if len(axes) > 1 else axes[0]]))
        # custom_vjp takes positional args only — bind via default-arg closure
        def _bound(x, _axes=axes, _dim=dim, _n=n):
            return _gather_leaf(x, _axes, _dim, _n, quant_weights, quant_grads)

        from ...utils.jax_compat import shard_map

        fns.append(
            shard_map(
                _bound,
                mesh=mesh,
                in_specs=in_spec,
                out_specs=P(),
                axis_names=set(axes),
                check_vma=False,
            )
        )

    def gather(params):
        leaves = treedef.flatten_up_to(params)
        out = [w if fn is None else fn(w) for w, fn in zip(leaves, fns)]
        return jax.tree_util.tree_unflatten(treedef, out)

    return gather
