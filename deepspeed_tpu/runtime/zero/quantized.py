"""ZeRO wire-codec collectives: qwZ/qgZ/hgZ on the shared comm layer.

Parity: deepspeed/runtime/zero/stage3.py quantized all-gather +
csrc/quantization kernels + the ZeRO++ paper (qwZ / qgZ / hgZ). The
reference quantizes NCCL payloads with hand-written CUDA; here each
stage-3-sharded parameter is gathered through an explicit ``shard_map``
collective whose wire format is a :mod:`deepspeed_tpu.comm.wires` codec
(fp32 / bf16 / int8 / int4, lane-wise scales): the forward moves
``param_wire`` bytes (qwZ at int8), and its custom backward — the
gradient reduce-scatter — moves ``grad_wire`` bytes via the qgZ
all-to-all formulation (values quantize once, the accumulate runs after
dequant, in f32). With ``hierarchical_wire`` and a factored (dp, fsdp)
leaf, both directions run the 2-hop form: full width intra-group over
the fast inner links, codec bytes inter-group (hgZ).

The legacy ``zero_quantized_weights`` / ``zero_quantized_gradients``
bools map to int8 codecs (``ZeroConfig.resolved_param_wire`` /
``resolved_grad_wire``); ``_quantize_lanewise`` survives as a re-export
of the shared :func:`comm.wires.quantize_lanewise` (bitwise identical).

hpZ composes for free: the gather axes come from the param's sharding
spec, which hpZ restricts to the ``fsdp`` sub-axis
(runtime/zero/partition.py).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ...comm import collectives, wires

# shared lane-wise int8 entry (the pre-wires private helper, kept as a
# name so existing imports — parallel/tensor_overlap among them — keep
# resolving to the ONE implementation)
_quantize_lanewise = wires.quantize_lanewise


def _spec_entries(spec: P, ndim: int) -> list:
    entries = list(spec) + [None] * (ndim - len(spec))
    return entries[:ndim]


def gather_dim_and_axes(param_spec: P, tp_spec: P, ndim: int):
    """Locate the ZeRO-sharded dim: the one entry where param_spec carries
    mesh axes that tp_spec doesn't. Returns (dim, extra_axes) or None."""
    p_entries = _spec_entries(param_spec, ndim)
    t_entries = _spec_entries(tp_spec, ndim)
    for i, (pe, te) in enumerate(zip(p_entries, t_entries)):
        p_axes = pe if isinstance(pe, tuple) else ((pe,) if pe else ())
        t_axes = te if isinstance(te, tuple) else ((te,) if te else ())
        extra = tuple(a for a in p_axes if a not in t_axes)
        if extra:
            return i, extra
    return None


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def _gather_leaf(local, axes, dim, n, param_wire, grad_wire, hier):
    """All-gather a stage-3 shard along ``dim`` over mesh ``axes`` (size
    ``n``) moving ``param_wire`` codec bytes. Backward: the gradient
    reduce-scatter in ``grad_wire`` codec bytes (qgZ). ``hier`` is the
    :func:`comm.wires.hier_axes` tuple or None."""
    x = jnp.moveaxis(local, dim, 0)
    codec = wires.get_codec(param_wire)
    # hier FIRST: with hierarchical_wire on, even fp32 wires run the
    # 2-hop form (the topology win — only 1/n_inner of the bytes cross
    # the slow outer links — exists without any quantization, and the
    # engine's analytic streams declare exactly that split)
    if hier is not None:
        o, n_o, i, n_i = hier
        full = wires.ag_wire_hier_local(x, o, i, n_o, n_i, codec,
                                        dtype=local.dtype)
    elif codec.name == "fp32":
        collectives._record("all_gather", axes, x)
        full = lax.all_gather(x, axes, axis=0, tiled=True)
    else:
        full = wires.ag_wire_local(x, axes, n, codec, dtype=local.dtype)
    return jnp.moveaxis(full, 0, dim)


def _gather_leaf_fwd(local, axes, dim, n, param_wire, grad_wire, hier):
    return (
        _gather_leaf(local, axes, dim, n, param_wire, grad_wire, hier),
        None,
    )


def _gather_leaf_bwd(axes, dim, n, param_wire, grad_wire, hier, _res, gbar):
    g = jnp.moveaxis(gbar, dim, 0)  # [d, rest...] full gradient
    codec = wires.get_codec(grad_wire)
    if hier is not None:  # hier first — see _gather_leaf
        o, n_o, i, n_i = hier
        local = wires.rs_wire_hier_local(g, o, i, n_o, n_i, codec,
                                         dtype=gbar.dtype)
    elif codec.name == "fp32":
        collectives._record("reduce_scatter", axes, g)
        local = lax.psum_scatter(g, axes, scatter_dimension=0, tiled=True)
    else:
        local = wires.rs_wire_local(g, axes, n, codec, dtype=gbar.dtype)
    return (jnp.moveaxis(local.astype(gbar.dtype), 0, dim),)


_gather_leaf.defvjp(_gather_leaf_fwd, _gather_leaf_bwd)


def make_leaf_gather(topo, pspec: P, tpspec: P, shape: Tuple[int, ...],
                     param_wire: str, grad_wire: str,
                     hierarchical: bool = False):
    """One leaf's ``shard -> full`` wire gather (partial-manual shard_map
    over just its ZeRO axes), or None when the leaf carries no ZeRO data
    axes. The building block :func:`make_quantized_gather` maps over the
    tree — exposed so the stage-3 layer prefetch can compose the SAME
    wire gather into its rotating-slot scan (runtime/zero/prefetch.py)."""
    ndim = len(shape)
    hit = gather_dim_and_axes(pspec, tpspec, ndim)
    if hit is None:
        return None
    dim, axes = hit
    n = 1
    for a in axes:
        n *= topo.sizes[a]
    # ONE eligibility predicate for the 2-hop forms (wires.hier_axes) —
    # the executed collective and the engine's priced stream share it
    hier = wires.hier_axes(topo, axes) if hierarchical else None
    # partial-manual specs mention only the manual (ZeRO) axes; the tp
    # sharding of the same array rides the automatic axes
    in_spec = P(*([None] * dim + [axes if len(axes) > 1 else axes[0]]))

    # custom_vjp takes positional args only — bind via default-arg closure
    def _bound(x, _axes=axes, _dim=dim, _n=n, _hier=hier):
        return _gather_leaf(x, _axes, _dim, _n, param_wire, grad_wire,
                            _hier)

    from ...utils.jax_compat import shard_map

    return shard_map(
        _bound,
        mesh=topo.mesh,
        in_specs=in_spec,
        out_specs=P(),
        axis_names=set(axes),
        check_vma=False,
    )


def make_quantized_gather(topo, param_specs: Any, tp_specs: Any,
                          params_shape: Any, quant_weights: bool = False,
                          quant_grads: bool = False, *,
                          param_wire: Optional[str] = None,
                          grad_wire: Optional[str] = None,
                          hierarchical: bool = False,
                          exclude_key: Optional[str] = None):
    """Build ``gather(params) -> full params`` applying the wire codecs
    per leaf. ``quant_weights`` / ``quant_grads`` are the legacy bool
    spelling (True == int8); ``param_wire`` / ``grad_wire`` codec names
    take precedence. ``exclude_key``: a top-level tree key whose leaves
    pass through untouched — the stage-3 layer prefetch owns the stacked
    ``layers`` group's gathers when both knobs are on
    (runtime/zero/prefetch.py), and gathering it twice would both waste
    wire and defeat the prefetch.

    Leaves whose spec carries no ZeRO data axes (persistence-threshold
    survivors, pure-TP leaves) pass through untouched; XLA keeps handling
    them implicitly. The returned callable runs inside jit (each gathered
    leaf is a partial-manual ``shard_map`` over just the ZeRO axes; tp/pp
    axes stay automatic)."""
    param_wire = param_wire or ("int8" if quant_weights else "fp32")
    grad_wire = grad_wire or ("int8" if quant_grads else "fp32")
    is_spec = lambda x: isinstance(x, P)

    if exclude_key is not None and isinstance(param_specs, dict) and (
        exclude_key in param_specs
    ):
        # replacing the excluded subtree's param specs with its tp specs
        # makes gather_dim_and_axes report "no ZeRO axes" there — the
        # passthrough path, with zero special-casing downstream
        param_specs = {**param_specs, exclude_key: tp_specs[exclude_key]}

    shapes_flat, treedef = jax.tree_util.tree_flatten(params_shape)
    pspecs_flat = jax.tree_util.tree_leaves(param_specs, is_leaf=is_spec)
    tspecs_flat = jax.tree_util.tree_leaves(tp_specs, is_leaf=is_spec)
    assert len(shapes_flat) == len(pspecs_flat) == len(tspecs_flat)

    fns = [
        make_leaf_gather(topo, pspec, tpspec, shape_leaf.shape,
                         param_wire, grad_wire, hierarchical)
        for shape_leaf, pspec, tpspec in zip(
            shapes_flat, pspecs_flat, tspecs_flat
        )
    ]

    def gather(params):
        leaves = treedef.flatten_up_to(params)
        out = [w if fn is None else fn(w) for w, fn in zip(leaves, fns)]
        return jax.tree_util.tree_unflatten(treedef, out)

    return gather
