"""One-layer-ahead ZeRO-3 parameter all-gather prefetch.

Stage 3 shards every big parameter over the data axes and relies on
all-gather-on-use: inside the layer scan, layer *i*'s gathered weights
are a data dependency of layer *i*'s matmuls, so every layer's forward
(and its remat'd backward) stalls on its own parameter fetch — the exact
serialization PR 1 removed from the offloaded optimizer update. This
module applies the same two-slot rotating-carry pattern
(runtime/bucketed_opt._scan_double_buffered) to the fwd/bwd layer scan:

- the scan carry holds the CURRENT layer's already-gathered param slices
  (prefetched one tick earlier);
- each tick first issues layer *i+1*'s gather — a ``device_put`` to the
  tp-only (data-axes-stripped) layout, with no data dependency on layer
  *i*'s math, so XLA's latency-hiding scheduler runs the all-gather DMA
  under the compute — then runs the block on the carried slot.

Layer order and per-layer math are identical to the plain scan, so the
loss trajectory matches plain stage 3 BITWISE on any mesh
(tests/test_zero3_prefetch.py). Persistence-threshold params (replicated
by runtime/zero/partition.py) are excluded: their put targets the layout
they already have and compiles away. The carry is purely functional —
no rotating-slot ``dynamic_update_slice`` writes, so shardlint R4's
stale-slot/donation analysis stays clean by construction.

Cost, stated honestly: one extra gathered layer of HBM residency (two
slots live instead of one), and under autodiff the scan saves its carry
per step — L gathered layer slices in the compute dtype become backward
residuals that the serial gather-on-use path (whose gathers are
rematerializable intermediates) does not keep. shardplan prices both
effects from the traced program; rule R6/R8 arbitrate statically.

Wiring is the trace-time scope protocol every overlap subsystem here
uses (tensor_overlap.overlap_scope / a2a_overlap.a2a_scope): the engine
builds the per-layer gather shardings at init
(:func:`build_layer_puts`), enters :func:`prefetch_scope` while tracing
its step, and models/transformer.apply_layer_stack routes its scans
through :func:`scan_layers` whenever the scope is active.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = [
    "build_layer_puts",
    "current_prefetch",
    "prefetch_scope",
    "scan_layers",
    "prefetch_wire_bytes_per_step",
]


# --------------------------------------------------------------------- scope
_local = threading.local()


def current_prefetch():
    """The active per-layer gather shardings tree (None when off)."""
    return getattr(_local, "puts", None)


@contextlib.contextmanager
def prefetch_scope(puts):
    """Trace-time activation of the one-layer-ahead gather. ``puts`` is
    the tree :func:`build_layer_puts` returns (matching ONE layer slice
    of the stacked ``layers`` param group), or None to keep the current
    setting (off)."""
    prev = getattr(_local, "puts", None)
    if puts is not None:
        _local.puts = puts
    try:
        yield
    finally:
        _local.puts = prev


# ------------------------------------------------------------- put derivation
class WirePut:
    """A per-leaf wire-codec gather standing in for a device_put target in
    the puts tree: calling it gathers one layer slice's shard through the
    shared codec collectives (runtime/zero/quantized.make_leaf_gather —
    the SAME program the whole-tree ZeRO++ gather uses, so the prefetched
    gather moves codec bytes and its custom backward reduce-scatters the
    layer gradient in ``grad_wire`` bytes)."""

    __slots__ = ("fn",)

    def __init__(self, fn):
        self.fn = fn

    def __call__(self, x):
        return self.fn(x)


def build_layer_puts(params_shape, tp_specs, param_specs, topology,
                     stacked_key: str = "layers", *,
                     param_wire: str = "fp32", grad_wire: str = "fp32",
                     hierarchical: bool = False) -> Optional[Any]:
    """Per-layer-slice gather targets for the stacked ``layers`` group.

    For every stacked leaf [L, ...] the gathered layout is its tp spec
    with the leading (layer) entry dropped — exactly the layout the layer
    compute consumes; stage 3's added data axes are what the prefetch
    gathers away. Leaves the persistence threshold kept replicated get
    the same (identity) put, which compiles away. Returns None when the
    model has no stacked ``layers`` dict or when NO leaf is actually
    data-sharded (nothing to prefetch — the knob would buy pure
    overhead).

    With a non-fp32 ``param_wire`` / ``grad_wire`` codec
    (zero_optimization wire knobs, docs/wires.md) the data-sharded
    leaves come back as :class:`WirePut` callables instead of
    shardings: the prefetched gather then moves codec bytes over the
    wire and its backward reduce-scatters the gradient in ``grad_wire``
    bytes — composition, not a separate mechanism."""
    if not (isinstance(params_shape, dict) and stacked_key in params_shape
            and isinstance(tp_specs, dict) and stacked_key in tp_specs):
        return None
    mesh = topology.mesh

    def drop_lead(spec: P) -> P:
        entries = tuple(spec)
        return P(*entries[1:]) if entries else P()

    is_spec = lambda s: isinstance(s, P)
    t_leaves = jax.tree_util.tree_leaves(tp_specs[stacked_key],
                                         is_leaf=is_spec)
    p_leaves = jax.tree_util.tree_leaves(param_specs[stacked_key],
                                         is_leaf=is_spec)
    any_sharded = any(
        tuple(t) != tuple(p) for t, p in zip(t_leaves, p_leaves)
    )
    if not any_sharded:
        return None
    wired = param_wire != "fp32" or grad_wire != "fp32" or hierarchical
    if not wired:
        return jax.tree.map(
            lambda spec: NamedSharding(mesh, drop_lead(spec)),
            tp_specs[stacked_key],
            is_leaf=is_spec,
        )

    from .quantized import make_leaf_gather

    def put_for(shape_leaf, tpspec, pspec):
        fn = make_leaf_gather(
            topology, drop_lead(pspec), drop_lead(tpspec),
            tuple(shape_leaf.shape[1:]), param_wire, grad_wire,
            hierarchical,
        )
        if fn is None:  # persistent/replicated slice: identity put
            return NamedSharding(mesh, drop_lead(tpspec))
        return WirePut(fn)

    return jax.tree.map(
        put_for,
        params_shape[stacked_key],
        tp_specs[stacked_key],
        param_specs[stacked_key],
        is_leaf=lambda s: isinstance(s, P),
    )


# ------------------------------------------------------------ the scan itself
def scan_layers(body, carry, layers_seg, extras, puts):
    """``lax.scan`` over stacked layers with a one-layer-ahead gathered
    slot riding the carry.

    ``body(carry, (layer_slice, *per_layer_xs)) -> (carry, y)`` is the
    unmodified scan body (possibly remat-wrapped); ``layers_seg`` is the
    stacked [L, ...] param tree (kept a scan-invariant closure — as scan
    xs, the slice-in would re-serialize against the body exactly like the
    bucketed-opt case); ``extras`` are the per-layer xs arrays (rng keys,
    PLD keep probs); ``puts`` the :func:`build_layer_puts` tree. The
    prefetch index is clamped at the last tick (branch-free body keeps
    the gather hoistable; one redundant last-layer re-fetch per step,
    ~1/L of the stream — the bucketed-opt trade). Returns (carry, ys)
    like ``lax.scan``."""
    L = jax.tree_util.tree_leaves(layers_seg)[0].shape[0]

    def gather(sl):
        # puts leaves are shardings (plain device_put gather) or WirePut
        # codec gathers (zero_optimization.param_wire / grad_wire)
        return jax.tree.map(
            lambda x, p: p(x) if isinstance(p, WirePut)
            else jax.device_put(x, p),
            sl,
            puts,
        )

    def slice_at(i):
        return jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, i, 0, keepdims=False),
            layers_seg,
        )

    # warm-up: layer 0's gather issues before the scan enters
    slot0 = gather(slice_at(0))

    def wrapped(c2, xs):
        inner, slot = c2
        i, rest = xs[0], xs[1:]
        # kick off layer i+1's all-gather FIRST — independent of the math
        slot_next = gather(slice_at(jnp.minimum(i + 1, L - 1)))
        inner, y = body(inner, (slot, *rest))
        return (inner, slot_next), y

    (carry, _), ys = lax.scan(
        wrapped, (carry, slot0), (jnp.arange(L), *extras)
    )
    return carry, ys


# ----------------------------------------------------------- byte accounting
def prefetch_wire_bytes_per_step(params_shape, tp_specs, param_specs,
                                 topology, *, itemsize: int = 2,
                                 accum_steps: int = 1, remat: bool = True,
                                 stacked_key: str = "layers",
                                 param_wire: str = "fp32",
                                 grad_wire: str = "fp32",
                                 hierarchical: bool = False
                                 ) -> Optional[dict]:
    """Analytic per-device all-gather wire for the prefetched layer scan.

    Per data-sharded stacked leaf, one gather per layer per pass moves
    its encoded slice's ``(n−1)/n`` onto each device (ring all-gather,
    n = the product of the leaf's added data axes). Passes per optimizer
    step: forward + the backward's gradient reduce-scatter transpose,
    plus the remat re-gather when a checkpoint policy replays the
    forward. Gather passes are priced at the ``param_wire`` codec and
    the backward scatter pass at ``grad_wire`` (comm/wires.py byte
    accounting — the win rule R8 sees statically). ``itemsize`` is the
    COMPUTE dtype's (the scan gathers cast weights, not f32 masters).
    None when nothing is data-sharded."""
    if not (isinstance(params_shape, dict) and stacked_key in params_shape):
        return None
    from ...comm import wires

    sizes = topology.sizes
    leaves = zip(
        jax.tree_util.tree_leaves(params_shape[stacked_key]),
        jax.tree_util.tree_leaves(
            tp_specs[stacked_key], is_leaf=lambda s: isinstance(s, P)
        ),
        jax.tree_util.tree_leaves(
            param_specs[stacked_key], is_leaf=lambda s: isinstance(s, P)
        ),
    )
    gather_pass = 0.0   # one fwd traversal, param_wire bytes
    scatter_pass = 0.0  # the bwd grad reduce-scatter, grad_wire bytes
    n_layers = 0
    for leaf, tp_spec, p_spec in leaves:
        t, q = tuple(tp_spec), tuple(p_spec)
        if t == q:
            continue  # persistent / replicated: identity put, no wire
        from .quantized import gather_dim_and_axes

        slice_shape = tuple(int(d) for d in leaf.shape[1:])
        hit = gather_dim_and_axes(
            P(*q[1:]), P(*t[1:]), len(slice_shape)
        )
        if hit is None:
            continue
        dim, axes = hit
        n = 1
        for a in axes:
            n *= sizes.get(a, 1)
        if n <= 1 or slice_shape[dim] % n:
            continue
        n_layers = max(n_layers, int(leaf.shape[0]))
        L = int(leaf.shape[0])
        hier = wires.hier_axes(topology, axes) if hierarchical else None
        if hier is not None:
            _o, n_o, _i, n_i = hier
            gather_pass += L * sum(wires.hier_ag_nbytes(
                slice_shape, n_o, n_i, param_wire, itemsize, dim=dim
            ))
            scatter_pass += L * sum(wires.hier_rs_nbytes(
                slice_shape, n_o, n_i, grad_wire, itemsize, dim=dim
            ))
            continue
        shard_shape = list(slice_shape)
        shard_shape[dim] //= n
        gather_pass += L * wires.ag_wire_nbytes(
            shard_shape, n, param_wire, itemsize, dim=dim
        )
        # the bwd scatters the cotangent slice in grad_wire bytes (qgZ:
        # quantize-once blocks + f32 accumulate). The cotangent is the
        # COMPUTE dtype — the model casts the stacked layers before the
        # scan, so the gather site (and its transpose) moves cast
        # weights, hence ``itemsize`` prices the fp32-codec case
        scatter_pass += L * wires.rs_wire_nbytes(
            slice_shape, n, grad_wire, itemsize, dim=dim
        )
    if gather_pass <= 0:
        return None
    passes = 2 + (1 if remat else 0)  # fwd gather + bwd scatter (+ regather)
    gather_passes = 1 + (1 if remat else 0)
    per_step = (gather_pass * gather_passes + scatter_pass) * max(
        accum_steps, 1
    )
    return {
        "bytes_per_step": int(per_step),
        "fwd_bytes_per_step": int(gather_pass * max(accum_steps, 1)),
        "layers": n_layers,
        "slots": 2,
        "passes": passes,
        "param_wire": param_wire,
        "grad_wire": grad_wire,
    }
