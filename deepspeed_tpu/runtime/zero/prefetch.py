"""One-layer-ahead ZeRO-3 parameter all-gather prefetch.

Stage 3 shards every big parameter over the data axes and relies on
all-gather-on-use: inside the layer scan, layer *i*'s gathered weights
are a data dependency of layer *i*'s matmuls, so every layer's forward
(and its remat'd backward) stalls on its own parameter fetch — the exact
serialization PR 1 removed from the offloaded optimizer update. This
module applies the same two-slot rotating-carry pattern
(runtime/bucketed_opt._scan_double_buffered) to the fwd/bwd layer scan:

- the scan carry holds the CURRENT layer's already-gathered param slices
  (prefetched one tick earlier);
- each tick first issues layer *i+1*'s gather — a ``device_put`` to the
  tp-only (data-axes-stripped) layout, with no data dependency on layer
  *i*'s math, so XLA's latency-hiding scheduler runs the all-gather DMA
  under the compute — then runs the block on the carried slot.

Layer order and per-layer math are identical to the plain scan, so the
loss trajectory matches plain stage 3 BITWISE on any mesh
(tests/test_zero3_prefetch.py). Persistence-threshold params (replicated
by runtime/zero/partition.py) are excluded: their put targets the layout
they already have and compiles away. The carry is purely functional —
no rotating-slot ``dynamic_update_slice`` writes, so shardlint R4's
stale-slot/donation analysis stays clean by construction.

Cost, stated honestly: one extra gathered layer of HBM residency (two
slots live instead of one), and under autodiff the scan saves its carry
per step — L gathered layer slices in the compute dtype become backward
residuals that the serial gather-on-use path (whose gathers are
rematerializable intermediates) does not keep. shardplan prices both
effects from the traced program; rule R6/R8 arbitrate statically.

Wiring is the trace-time scope protocol every overlap subsystem here
uses (tensor_overlap.overlap_scope / a2a_overlap.a2a_scope): the engine
builds the per-layer gather shardings at init
(:func:`build_layer_puts`), enters :func:`prefetch_scope` while tracing
its step, and models/transformer.apply_layer_stack routes its scans
through :func:`scan_layers` whenever the scope is active.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = [
    "build_layer_puts",
    "current_prefetch",
    "prefetch_scope",
    "scan_layers",
    "prefetch_wire_bytes_per_step",
]


# --------------------------------------------------------------------- scope
_local = threading.local()


def current_prefetch():
    """The active per-layer gather shardings tree (None when off)."""
    return getattr(_local, "puts", None)


@contextlib.contextmanager
def prefetch_scope(puts):
    """Trace-time activation of the one-layer-ahead gather. ``puts`` is
    the tree :func:`build_layer_puts` returns (matching ONE layer slice
    of the stacked ``layers`` param group), or None to keep the current
    setting (off)."""
    prev = getattr(_local, "puts", None)
    if puts is not None:
        _local.puts = puts
    try:
        yield
    finally:
        _local.puts = prev


# ------------------------------------------------------------- put derivation
def build_layer_puts(params_shape, tp_specs, param_specs, topology,
                     stacked_key: str = "layers") -> Optional[Any]:
    """Per-layer-slice gather shardings for the stacked ``layers`` group.

    For every stacked leaf [L, ...] the gathered layout is its tp spec
    with the leading (layer) entry dropped — exactly the layout the layer
    compute consumes; stage 3's added data axes are what the prefetch
    gathers away. Leaves the persistence threshold kept replicated get
    the same (identity) put, which compiles away. Returns None when the
    model has no stacked ``layers`` dict or when NO leaf is actually
    data-sharded (nothing to prefetch — the knob would buy pure
    overhead)."""
    if not (isinstance(params_shape, dict) and stacked_key in params_shape
            and isinstance(tp_specs, dict) and stacked_key in tp_specs):
        return None
    mesh = topology.mesh

    def drop_lead(spec: P) -> P:
        entries = tuple(spec)
        return P(*entries[1:]) if entries else P()

    any_sharded = any(
        tuple(t) != tuple(p)
        for t, p in zip(
            jax.tree_util.tree_leaves(
                tp_specs[stacked_key], is_leaf=lambda s: isinstance(s, P)
            ),
            jax.tree_util.tree_leaves(
                param_specs[stacked_key], is_leaf=lambda s: isinstance(s, P)
            ),
        )
    )
    if not any_sharded:
        return None
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, drop_lead(spec)),
        tp_specs[stacked_key],
        is_leaf=lambda s: isinstance(s, P),
    )


# ------------------------------------------------------------ the scan itself
def scan_layers(body, carry, layers_seg, extras, puts):
    """``lax.scan`` over stacked layers with a one-layer-ahead gathered
    slot riding the carry.

    ``body(carry, (layer_slice, *per_layer_xs)) -> (carry, y)`` is the
    unmodified scan body (possibly remat-wrapped); ``layers_seg`` is the
    stacked [L, ...] param tree (kept a scan-invariant closure — as scan
    xs, the slice-in would re-serialize against the body exactly like the
    bucketed-opt case); ``extras`` are the per-layer xs arrays (rng keys,
    PLD keep probs); ``puts`` the :func:`build_layer_puts` tree. The
    prefetch index is clamped at the last tick (branch-free body keeps
    the gather hoistable; one redundant last-layer re-fetch per step,
    ~1/L of the stream — the bucketed-opt trade). Returns (carry, ys)
    like ``lax.scan``."""
    L = jax.tree_util.tree_leaves(layers_seg)[0].shape[0]

    def gather(sl):
        return jax.tree.map(jax.device_put, sl, puts)

    def slice_at(i):
        return jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, i, 0, keepdims=False),
            layers_seg,
        )

    # warm-up: layer 0's gather issues before the scan enters
    slot0 = gather(slice_at(0))

    def wrapped(c2, xs):
        inner, slot = c2
        i, rest = xs[0], xs[1:]
        # kick off layer i+1's all-gather FIRST — independent of the math
        slot_next = gather(slice_at(jnp.minimum(i + 1, L - 1)))
        inner, y = body(inner, (slot, *rest))
        return (inner, slot_next), y

    (carry, _), ys = lax.scan(
        wrapped, (carry, slot0), (jnp.arange(L), *extras)
    )
    return carry, ys


# ----------------------------------------------------------- byte accounting
def prefetch_wire_bytes_per_step(params_shape, tp_specs, param_specs,
                                 topology, *, itemsize: int = 2,
                                 accum_steps: int = 1, remat: bool = True,
                                 stacked_key: str = "layers"
                                 ) -> Optional[dict]:
    """Analytic per-device all-gather wire for the prefetched layer scan.

    Per data-sharded stacked leaf, one gather per layer per pass moves
    ``slice_bytes × (n−1)/n`` onto each device (ring all-gather, n = the
    product of the leaf's added data axes). Passes per optimizer step:
    forward + the backward's gradient reduce-scatter transpose, plus the
    remat re-gather when a checkpoint policy replays the forward.
    ``itemsize`` is the COMPUTE dtype's (the scan gathers cast weights,
    not f32 masters). None when nothing is data-sharded."""
    if not (isinstance(params_shape, dict) and stacked_key in params_shape):
        return None
    sizes = topology.sizes
    leaves = zip(
        jax.tree_util.tree_leaves(params_shape[stacked_key]),
        jax.tree_util.tree_leaves(
            tp_specs[stacked_key], is_leaf=lambda s: isinstance(s, P)
        ),
        jax.tree_util.tree_leaves(
            param_specs[stacked_key], is_leaf=lambda s: isinstance(s, P)
        ),
    )
    per_pass = 0.0
    n_layers = 0
    for leaf, tp_spec, p_spec in leaves:
        t, q = tuple(tp_spec), tuple(p_spec)
        if t == q:
            continue  # persistent / replicated: identity put, no wire
        added = set()
        for entry in q:
            for a in (entry if isinstance(entry, tuple) else (entry,)):
                if a:
                    added.add(a)
        for entry in t:
            for a in (entry if isinstance(entry, tuple) else (entry,)):
                if a:
                    added.discard(a)
        n = 1
        for a in added:
            n *= sizes.get(a, 1)
        if n <= 1:
            continue
        n_layers = max(n_layers, int(leaf.shape[0]))
        slice_elems = 1
        for d in leaf.shape[1:]:
            slice_elems *= int(d)
        per_pass += leaf.shape[0] * slice_elems * itemsize * (n - 1) / n
    if per_pass <= 0:
        return None
    passes = 2 + (1 if remat else 0)  # fwd gather + bwd scatter (+ regather)
    total = per_pass * passes * max(accum_steps, 1)
    return {
        "bytes_per_step": int(total),
        "fwd_bytes_per_step": int(per_pass * max(accum_steps, 1)),
        "layers": n_layers,
        "slots": 2,
        "passes": passes,
    }
