"""ZeRO partitioning as sharding-spec derivation.

Parity: deepspeed/runtime/zero/stage_1_and_2.py + stage3.py. The reference
hand-implements flat-buffer partitioning, parameter all-gather and gradient
reduce-scatter over NCCL; on TPU every stage is a *rule for placing arrays on
the mesh* and XLA emits exactly those collectives:

- stage 0: params/grads/opt replicated over data axes; grad psum (DDP).
- stage 1: optimizer state + fp32 master sharded over data axes.
- stage 2: + gradients materialize sharded (psum becomes reduce-scatter).
- stage 3: + parameters sharded; all-gather-on-use, FSDP semantics.
- ZeRO++ hpZ / MiCS: params shard over the inner ``fsdp`` sub-axis only and
  replicate over ``dp`` (gathers stay inside the sub-mesh / node).

Small params (< stage3_param_persistence_threshold elements) stay replicated
in stage 3, mirroring the reference's persistence threshold.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...comm.topology import MeshTopology
from ...config import ZeroConfig


def _axes_product(topo: MeshTopology, axes: Tuple[str, ...]) -> int:
    n = 1
    for a in axes:
        n *= topo.sizes[a]
    return n


def data_axes(topo: MeshTopology, zero_cfg: Optional[ZeroConfig] = None,
              params_level: bool = False) -> Tuple[str, ...]:
    """Mesh axes available for ZeRO sharding.

    For parameter sharding under hpZ/MiCS, only the inner ``fsdp`` sub-axis is
    used so all-gathers ride the fastest links (reference: zero_hpz_partition_size).
    """
    hpz = zero_cfg is not None and params_level and (
        zero_cfg.zero_hpz_partition_size > 1 or zero_cfg.mics_shard_size > 0
    )
    if hpz and topo.sizes["fsdp"] > 1:
        return ("fsdp",)
    return tuple(a for a in ("dp", "fsdp") if topo.sizes[a] > 1)


def add_data_axes(spec: P, shape: Tuple[int, ...], topo: MeshTopology,
                  axes: Tuple[str, ...]) -> P:
    """Shard the largest divisible, not-yet-sharded dim of ``shape`` over
    ``axes``; returns ``spec`` unchanged if nothing divides (stays replicated)."""
    if not axes or not shape:
        return spec
    n = _axes_product(topo, axes)
    entries = list(spec) + [None] * (len(shape) - len(spec))
    used = set()
    for e in entries:
        for a in (e if isinstance(e, tuple) else (e,)):
            if a:
                used.add(a)
    if any(a in used for a in axes):
        return spec
    # per-dim size after existing sharding
    best, best_size = None, 0
    for i, (dim, e) in enumerate(zip(shape, entries)):
        if e is not None:
            continue
        if dim % n == 0 and dim > best_size:
            best, best_size = i, dim
    if best is None:
        return spec
    entries[best] = axes if len(axes) > 1 else axes[0]
    return P(*entries)


def zero_specs(
    params_tree: Any,
    tp_specs: Any,
    topo: MeshTopology,
    zero_cfg: ZeroConfig,
) -> Tuple[Any, Any, Any]:
    """Derive (param_specs, grad_specs, optstate_leaf_specs) per stage.

    ``optstate_leaf_specs`` mirrors params (optax state leaves that match a
    param shape inherit its spec; scalars replicate).
    """
    stage = zero_cfg.stage
    d_axes = data_axes(topo, zero_cfg)
    p_axes = data_axes(topo, zero_cfg, params_level=True)
    threshold = zero_cfg.stage3_param_persistence_threshold

    def param_spec(x, tp_spec):
        if stage < 3 or int(np.prod(x.shape)) < threshold:
            return tp_spec
        return add_data_axes(tp_spec, x.shape, topo, p_axes)

    def grad_spec(x, tp_spec):
        if stage >= 3:
            return param_spec(x, tp_spec)
        if stage >= 2:
            return add_data_axes(tp_spec, x.shape, topo, d_axes)
        return tp_spec

    def opt_spec(x, tp_spec):
        if stage >= 1:
            return add_data_axes(tp_spec, x.shape, topo, d_axes)
        return tp_spec

    p_specs = jax.tree.map(param_spec, params_tree, tp_specs)
    g_specs = jax.tree.map(grad_spec, params_tree, tp_specs)
    o_specs = jax.tree.map(opt_spec, params_tree, tp_specs)
    return p_specs, g_specs, o_specs


def opt_state_sharding(tx, opt_state, opt_leaf_specs, topo: MeshTopology,
                       memory_kind: Optional[str] = None):
    """Shardings for an optax state: param-shaped leaves (moments, master
    copies) inherit the matching param's spec *by tree position* (via
    optax.tree_map_params); counts/scalars replicate."""
    import optax

    kwargs = {"memory_kind": memory_kind} if memory_kind else {}
    replicated = NamedSharding(topo.mesh, P())

    return optax.tree_map_params(
        tx,
        lambda leaf, spec: NamedSharding(topo.mesh, spec, **kwargs),
        opt_state,
        opt_leaf_specs,
        transform_non_params=lambda leaf: replicated,
    )


def make_shardings(specs_tree, topo: MeshTopology, memory_kind: Optional[str] = None):
    kwargs = {"memory_kind": memory_kind} if memory_kind else {}
    return jax.tree.map(
        lambda s: NamedSharding(topo.mesh, s, **kwargs),
        specs_tree,
        is_leaf=lambda x: isinstance(x, P),
    )
