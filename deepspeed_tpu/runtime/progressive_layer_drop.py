"""Progressive layer dropping (PLD).

Parity: deepspeed/runtime/progressive_layer_drop.py (Zhang & He 2020). The
global keep ratio follows theta(t) = (1 - theta) * exp(-gamma * t) + theta
(reference's schedule), and depth scales it linearly: layer i of L keeps
with probability 1 - i/L * (1 - theta(t)) — shallow layers almost always
run, deep layers drop progressively harder early in training.

TPU-native: the per-layer Bernoulli gate runs *inside* the jitted train
step (theta is a traced function of the step counter), so PLD costs one
[L]-sized sample per step and a select per layer — no recompilation as the
schedule anneals, unlike shape-based approaches.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class ProgressiveLayerDrop:
    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma

    def get_theta(self, global_step):
        t = jnp.asarray(global_step, jnp.float32)
        return (1.0 - self.theta) * jnp.exp(-self.gamma * t) + self.theta

    def get_state(self, global_step):
        return {"pld_theta": self.get_theta(global_step)}


def layer_keep_probs(theta_t, num_layers: int):
    """Per-layer keep probabilities [L]: 1 - i/L * (1 - theta_t)."""
    i = jnp.arange(num_layers, dtype=jnp.float32)
    return 1.0 - (i / max(num_layers, 1)) * (1.0 - theta_t)
