"""deepspeed_tpu — a TPU-native training/inference framework with the
capability surface of DeepSpeed (reference: weilianglin101/DeepSpeed).

Front door parity: deepspeed/__init__.py — ``initialize``,
``init_distributed``, ``init_inference``, ``DeepSpeedConfig``.
The compute path is JAX/XLA/Pallas over a device mesh; ZeRO, pipeline,
tensor/sequence/expert parallelism are expressed as shardings + shard_map
schedules instead of NCCL process groups.
"""

from .version import __version__  # noqa: F401
from .config import DeepSpeedConfig, DeepSpeedConfigError  # noqa: F401
from .comm import init_distributed  # noqa: F401
from . import zero  # noqa: F401  (deepspeed.zero parity surface)
from . import checkpointing  # noqa: F401  (deepspeed.checkpointing parity)
from .accelerator import get_accelerator  # noqa: F401  (deepspeed.accelerator)


def initialize(*args, **kwargs):
    """Parity: deepspeed.initialize(model=..., config=...) →
    (engine, optimizer, dataloader, lr_scheduler)."""
    from .runtime.engine import initialize as _initialize

    return _initialize(*args, **kwargs)


def init_inference(*args, **kwargs):
    """Parity: deepspeed.init_inference."""
    from .inference.engine import init_inference as _init_inference

    return _init_inference(*args, **kwargs)


def init_serving(model=None, serving=None, **kwargs):
    """Continuous-batching serving front door (DeepSpeed-MII / FastGen
    parity): model + "serving" config section → :class:`ServingEngine`
    (request queue + SplitFuse scheduler + ONE jitted slot step)."""
    from .serving import ServingEngine

    return ServingEngine(model=model, serving=serving, **kwargs)


def init_fleet(model=None, serving=None, **kwargs):
    """Replicated serving tier front door: model + "serving" section
    (with its "fleet" subsection) → :class:`~deepspeed_tpu.serving.fleet
    .Router` over N data-parallel ServingEngine replicas — fleet
    admission + load shedding, prefix-aware routing, session affinity,
    optional prefill/decode disaggregation (docs/serving.md "Fleet")."""
    from .serving.fleet import Router

    return Router(model=model, serving=serving, **kwargs)
