"""HuggingFace Transformers bridge.

Parity: the reference's HF integration surface (deepspeed.initialize over a
transformers model + AutoTP weight loading). Imports a torch-side
``state_dict`` into this package's stacked-[L] param pytree, per family:

- gpt2: Conv1D fused c_attn split into q/k/v (Conv1D stores [in, out] — no
  transpose); learned positions; tied lm_head.
- llama/mistral: torch Linear [out, in] → transposed; RoPE/GQA/SwiGLU map
  1:1 (HF's rotate_half == models/transformer._rope).
- bloom: fused query_key_value de-interleaved from
  [H, 3, hd, d] layout; ALiBi needs no weights.
- mixtral: per-expert w1/w2/w3 stacked into [L, E, ...] routed-MLP params.

Weights arrive as torch CPU tensors or numpy arrays; everything is stacked
along the layer dim to match ``models.transformer.init``'s pytree, then
``deepspeed_tpu.initialize(model_parameters=...)`` places them sharded
(zero.Init-style: the host copy is freed after device_put).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import TransformerConfig, TransformerModel


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t)


def _stack(sd: Dict[str, np.ndarray], fmt: str, L: int, transform=None):
    arrs = []
    for i in range(L):
        a = _np(sd[fmt.format(i)])
        arrs.append(transform(a) if transform else a)
    return np.stack(arrs)


def _detect_family(sd: Dict[str, Any]) -> str:
    keys = list(sd)
    joined = " ".join(keys[:50])
    if any("block_sparse_moe" in k for k in keys):
        return "mixtral"
    if any("word_embeddings_layernorm" in k for k in keys):
        return "bloom"
    if any(k.endswith("c_attn.weight") for k in keys):
        return "gpt2"
    if any("q_proj" in k for k in keys):
        return "llama"
    raise ValueError(f"cannot detect model family from keys like: {joined}")


def _strip_prefix(sd: Dict[str, Any]) -> Dict[str, Any]:
    for prefix in ("model.", "transformer.", ""):
        if prefix == "" or any(k.startswith(prefix) for k in sd):
            return {
                (k[len(prefix):] if k.startswith(prefix) else k): v
                for k, v in sd.items()
            }
    return sd


def import_hf_state_dict(
    state_dict: Dict[str, Any],
    cfg: TransformerConfig,
    family: Optional[str] = None,
) -> Dict[str, Any]:
    """torch/HF state_dict → this package's param pytree (numpy host copy)."""
    sd = _strip_prefix(dict(state_dict))
    family = family or _detect_family(sd)
    L, d = cfg.num_layers, cfg.hidden_size
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.hd

    if family == "gpt2":
        qkv = _stack(sd, "h.{}.attn.c_attn.weight", L)  # [L, d, 3d] (Conv1D)
        qkv_b = _stack(sd, "h.{}.attn.c_attn.bias", L)  # [L, 3d]
        params = {
            "embed": {"tok": _np(sd["wte.weight"]), "pos": _np(sd["wpe.weight"])},
            "final_norm": {"scale": _np(sd["ln_f.weight"]), "bias": _np(sd["ln_f.bias"])},
            "layers": {
                "ln1": {
                    "scale": _stack(sd, "h.{}.ln_1.weight", L),
                    "bias": _stack(sd, "h.{}.ln_1.bias", L),
                },
                "ln2": {
                    "scale": _stack(sd, "h.{}.ln_2.weight", L),
                    "bias": _stack(sd, "h.{}.ln_2.bias", L),
                },
                "attn": {
                    "wq": qkv[:, :, :d],
                    "wk": qkv[:, :, d:2 * d],
                    "wv": qkv[:, :, 2 * d:],
                    "wo": _stack(sd, "h.{}.attn.c_proj.weight", L),
                    "bq": qkv_b[:, :d],
                    "bk": qkv_b[:, d:2 * d],
                    "bv": qkv_b[:, 2 * d:],
                    "bo": _stack(sd, "h.{}.attn.c_proj.bias", L),
                },
                "mlp": {
                    "wi": _stack(sd, "h.{}.mlp.c_fc.weight", L),
                    "bi": _stack(sd, "h.{}.mlp.c_fc.bias", L),
                    "wo": _stack(sd, "h.{}.mlp.c_proj.weight", L),
                    "bo": _stack(sd, "h.{}.mlp.c_proj.bias", L),
                },
            },
        }
        return params

    if family in ("llama", "mistral"):
        T = lambda a: a.T
        params = {
            "embed": {"tok": _np(sd["embed_tokens.weight"])},
            "final_norm": {"scale": _np(sd["norm.weight"])},
            "layers": {
                "ln1": {"scale": _stack(sd, "layers.{}.input_layernorm.weight", L)},
                "ln2": {"scale": _stack(sd, "layers.{}.post_attention_layernorm.weight", L)},
                "attn": {
                    "wq": _stack(sd, "layers.{}.self_attn.q_proj.weight", L, T),
                    "wk": _stack(sd, "layers.{}.self_attn.k_proj.weight", L, T),
                    "wv": _stack(sd, "layers.{}.self_attn.v_proj.weight", L, T),
                    "wo": _stack(sd, "layers.{}.self_attn.o_proj.weight", L, T),
                },
                "mlp": {
                    "wg": _stack(sd, "layers.{}.mlp.gate_proj.weight", L, T),
                    "wi": _stack(sd, "layers.{}.mlp.up_proj.weight", L, T),
                    "wo": _stack(sd, "layers.{}.mlp.down_proj.weight", L, T),
                },
            },
        }
        if "lm_head.weight" in sd and not cfg.tie_embeddings:
            params["lm_head"] = _np(sd["lm_head.weight"]).T
        return params

    if family == "bloom":
        # one conversion pass over the fused qkv tensors (3x less host
        # traffic than re-reading per split)
        qkv_w = [[], [], []]
        qkv_b = [[], [], []]
        for i in range(L):
            a = _np(sd[f"h.{i}.self_attention.query_key_value.weight"])
            b = _np(sd[f"h.{i}.self_attention.query_key_value.bias"])
            w4 = a.reshape(nh, 3, hd, d)  # [H, 3, hd, d] interleaved
            b3 = b.reshape(nh, 3, hd)
            for part in range(3):
                qkv_w[part].append(w4[:, part].reshape(nh * hd, d).T)
                qkv_b[part].append(b3[:, part].reshape(nh * hd))
        qkv_w = [np.stack(p) for p in qkv_w]
        qkv_b = [np.stack(p) for p in qkv_b]

        params = {
            "embed": {"tok": _np(sd["word_embeddings.weight"])},
            "embed_norm": {
                "scale": _np(sd["word_embeddings_layernorm.weight"]),
                "bias": _np(sd["word_embeddings_layernorm.bias"]),
            },
            "final_norm": {"scale": _np(sd["ln_f.weight"]), "bias": _np(sd["ln_f.bias"])},
            "layers": {
                "ln1": {
                    "scale": _stack(sd, "h.{}.input_layernorm.weight", L),
                    "bias": _stack(sd, "h.{}.input_layernorm.bias", L),
                },
                "ln2": {
                    "scale": _stack(sd, "h.{}.post_attention_layernorm.weight", L),
                    "bias": _stack(sd, "h.{}.post_attention_layernorm.bias", L),
                },
                "attn": {
                    "wq": qkv_w[0],
                    "wk": qkv_w[1],
                    "wv": qkv_w[2],
                    "wo": _stack(sd, "h.{}.self_attention.dense.weight", L, lambda a: a.T),
                    "bq": qkv_b[0],
                    "bk": qkv_b[1],
                    "bv": qkv_b[2],
                    "bo": _stack(sd, "h.{}.self_attention.dense.bias", L),
                },
                "mlp": {
                    "wi": _stack(sd, "h.{}.mlp.dense_h_to_4h.weight", L, lambda a: a.T),
                    "bi": _stack(sd, "h.{}.mlp.dense_h_to_4h.bias", L),
                    "wo": _stack(sd, "h.{}.mlp.dense_4h_to_h.weight", L, lambda a: a.T),
                    "bo": _stack(sd, "h.{}.mlp.dense_4h_to_h.bias", L),
                },
            },
        }
        return params

    if family == "mixtral":
        E = cfg.num_experts
        T = lambda a: a.T

        def experts(i, which):
            return np.stack([
                _np(sd[f"layers.{i}.block_sparse_moe.experts.{e}.{which}.weight"]).T
                for e in range(E)
            ])

        params = {
            "embed": {"tok": _np(sd["embed_tokens.weight"])},
            "final_norm": {"scale": _np(sd["norm.weight"])},
            "layers": {
                "ln1": {"scale": _stack(sd, "layers.{}.input_layernorm.weight", L)},
                "ln2": {"scale": _stack(sd, "layers.{}.post_attention_layernorm.weight", L)},
                "attn": {
                    "wq": _stack(sd, "layers.{}.self_attn.q_proj.weight", L, T),
                    "wk": _stack(sd, "layers.{}.self_attn.k_proj.weight", L, T),
                    "wv": _stack(sd, "layers.{}.self_attn.v_proj.weight", L, T),
                    "wo": _stack(sd, "layers.{}.self_attn.o_proj.weight", L, T),
                },
                "mlp": {
                    "router": _stack(sd, "layers.{}.block_sparse_moe.gate.weight", L, T),
                    # mixtral: w1 = gate, w3 = up, w2 = down
                    "wg": np.stack([experts(i, "w1") for i in range(L)]),
                    "wi": np.stack([experts(i, "w3") for i in range(L)]),
                    "wo": np.stack([experts(i, "w2") for i in range(L)]),
                },
            },
        }
        if "lm_head.weight" in sd and not cfg.tie_embeddings:
            params["lm_head"] = _np(sd["lm_head.weight"]).T
        return params

    raise ValueError(f"unsupported family {family!r}")


def _export_llama_trunk(out, p, cfg, L):
    """Shared llama/mistral/mixtral export: embeddings, norms, attention
    projections, lm_head — everything except the MLP/MoE block."""
    out["model.embed_tokens.weight"] = p["embed"]["tok"]
    out["model.norm.weight"] = p["final_norm"]["scale"]
    if not cfg.tie_embeddings and "lm_head" in p:
        out["lm_head.weight"] = p["lm_head"].T
    at = p["layers"]["attn"]
    for i in range(L):
        pre = f"model.layers.{i}."
        out[pre + "input_layernorm.weight"] = p["layers"]["ln1"]["scale"][i]
        out[pre + "post_attention_layernorm.weight"] = (
            p["layers"]["ln2"]["scale"][i]
        )
        out[pre + "self_attn.q_proj.weight"] = at["wq"][i].T
        out[pre + "self_attn.k_proj.weight"] = at["wk"][i].T
        out[pre + "self_attn.v_proj.weight"] = at["wv"][i].T
        out[pre + "self_attn.o_proj.weight"] = at["wo"][i].T


def export_hf_state_dict(
    params: Dict[str, Any],
    cfg: TransformerConfig,
    family: str,
) -> Dict[str, np.ndarray]:
    """This package's param pytree → an HF state_dict (numpy host copy).

    The inverse of import_hf_state_dict for round-tripping trained weights
    back into transformers (reference users do this via zero_to_fp32 →
    load_state_dict). Supported: "llama"/"mistral", "gpt2", "bloom",
    "mixtral" — every import family; keys carry the causal-LM wrapper
    prefix (model. / transformer.) so load_state_dict works directly."""
    p = jax.tree.map(_np, params)
    L = cfg.num_layers
    out: Dict[str, np.ndarray] = {}

    if family in ("llama", "mistral"):
        _export_llama_trunk(out, p, cfg, L)
        ml = p["layers"]["mlp"]
        for i in range(L):
            pre = f"model.layers.{i}."
            out[pre + "mlp.gate_proj.weight"] = ml["wg"][i].T
            out[pre + "mlp.up_proj.weight"] = ml["wi"][i].T
            out[pre + "mlp.down_proj.weight"] = ml["wo"][i].T
        return out

    if family == "gpt2":
        # GPT2LMHeadModel nests the decoder under .transformer (lm_head is
        # tied to wte, so no separate head tensor)
        out["transformer.wte.weight"] = p["embed"]["tok"]
        out["transformer.wpe.weight"] = p["embed"]["pos"]
        out["transformer.ln_f.weight"] = p["final_norm"]["scale"]
        out["transformer.ln_f.bias"] = p["final_norm"]["bias"]
        at, ml = p["layers"]["attn"], p["layers"]["mlp"]
        for i in range(L):
            pre = f"transformer.h.{i}."
            out[pre + "ln_1.weight"] = p["layers"]["ln1"]["scale"][i]
            out[pre + "ln_1.bias"] = p["layers"]["ln1"]["bias"][i]
            out[pre + "ln_2.weight"] = p["layers"]["ln2"]["scale"][i]
            out[pre + "ln_2.bias"] = p["layers"]["ln2"]["bias"][i]
            out[pre + "attn.c_attn.weight"] = np.concatenate(
                [at["wq"][i], at["wk"][i], at["wv"][i]], axis=1
            )
            out[pre + "attn.c_attn.bias"] = np.concatenate(
                [at["bq"][i], at["bk"][i], at["bv"][i]]
            )
            out[pre + "attn.c_proj.weight"] = at["wo"][i]
            out[pre + "attn.c_proj.bias"] = at["bo"][i]
            out[pre + "mlp.c_fc.weight"] = ml["wi"][i]
            out[pre + "mlp.c_fc.bias"] = ml["bi"][i]
            out[pre + "mlp.c_proj.weight"] = ml["wo"][i]
            out[pre + "mlp.c_proj.bias"] = ml["bo"][i]
        return out

    if family == "bloom":
        # BloomForCausalLM nests the decoder under .transformer (lm_head is
        # tied to the word embeddings)
        nh, hd, d = cfg.num_heads, cfg.hd, cfg.hidden_size
        out["transformer.word_embeddings.weight"] = p["embed"]["tok"]
        out["transformer.word_embeddings_layernorm.weight"] = (
            p["embed_norm"]["scale"]
        )
        out["transformer.word_embeddings_layernorm.bias"] = (
            p["embed_norm"]["bias"]
        )
        out["transformer.ln_f.weight"] = p["final_norm"]["scale"]
        out["transformer.ln_f.bias"] = p["final_norm"]["bias"]
        at, ml = p["layers"]["attn"], p["layers"]["mlp"]
        for i in range(L):
            pre = f"transformer.h.{i}."
            out[pre + "input_layernorm.weight"] = p["layers"]["ln1"]["scale"][i]
            out[pre + "input_layernorm.bias"] = p["layers"]["ln1"]["bias"][i]
            out[pre + "post_attention_layernorm.weight"] = (
                p["layers"]["ln2"]["scale"][i]
            )
            out[pre + "post_attention_layernorm.bias"] = (
                p["layers"]["ln2"]["bias"][i]
            )
            # re-interleave q/k/v into bloom's fused [H, 3, hd, d] layout
            w3 = np.stack(
                [at[k][i].T.reshape(nh, hd, d) for k in ("wq", "wk", "wv")],
                axis=1,
            )  # [H, 3, hd, d]
            b3 = np.stack(
                [at[k][i].reshape(nh, hd) for k in ("bq", "bk", "bv")], axis=1
            )  # [H, 3, hd]
            out[pre + "self_attention.query_key_value.weight"] = w3.reshape(
                3 * nh * hd, d
            )
            out[pre + "self_attention.query_key_value.bias"] = b3.reshape(
                3 * nh * hd
            )
            out[pre + "self_attention.dense.weight"] = at["wo"][i].T
            out[pre + "self_attention.dense.bias"] = at["bo"][i]
            out[pre + "mlp.dense_h_to_4h.weight"] = ml["wi"][i].T
            out[pre + "mlp.dense_h_to_4h.bias"] = ml["bi"][i]
            out[pre + "mlp.dense_4h_to_h.weight"] = ml["wo"][i].T
            out[pre + "mlp.dense_4h_to_h.bias"] = ml["bo"][i]
        return out

    if family == "mixtral":
        E = cfg.num_experts
        _export_llama_trunk(out, p, cfg, L)
        ml = p["layers"]["mlp"]
        # mixtral expert naming: w1 = gate, w3 = up, w2 = down
        expert_keys = (("w1", "wg"), ("w3", "wi"), ("w2", "wo"))
        for i in range(L):
            pre = f"model.layers.{i}."
            out[pre + "block_sparse_moe.gate.weight"] = ml["router"][i].T
            for hf_name, ours in expert_keys:
                for e in range(E):
                    out[
                        pre + f"block_sparse_moe.experts.{e}.{hf_name}.weight"
                    ] = ml[ours][i, e].T
        return out

    raise ValueError(
        f"export unsupported for family {family!r} "
        f"(have llama/mistral/gpt2/bloom/mixtral)"
    )


def config_from_hf(hf_config) -> TransformerConfig:
    """Map a transformers PretrainedConfig onto TransformerConfig."""
    mt = getattr(hf_config, "model_type", "")
    if mt == "gpt2":
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.n_embd,
            num_layers=hf_config.n_layer,
            num_heads=hf_config.n_head,
            max_seq_len=hf_config.n_positions,
            pos_embedding="learned",
            norm="layernorm",
            norm_eps=hf_config.layer_norm_epsilon,
            activation="gelu_new",
            use_bias=True,
            tie_embeddings=True,
            intermediate_size=4 * hf_config.n_embd,
            name="gpt2-hf",
        )
    if mt in ("llama", "mistral"):
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None),
            intermediate_size=hf_config.intermediate_size,
            max_seq_len=hf_config.max_position_embeddings,
            pos_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            norm="rmsnorm",
            norm_eps=hf_config.rms_norm_eps,
            activation="swiglu",
            use_bias=False,
            tie_embeddings=getattr(hf_config, "tie_word_embeddings", False),
            name=f"{mt}-hf",
        )
    if mt == "bloom":
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            num_layers=hf_config.n_layer,
            num_heads=hf_config.n_head,
            max_seq_len=2048,
            pos_embedding="alibi",
            norm="layernorm",
            norm_eps=hf_config.layer_norm_epsilon,
            activation="gelu",
            use_bias=True,
            tie_embeddings=True,
            embed_norm=True,
            intermediate_size=4 * hf_config.hidden_size,
            name="bloom-hf",
        )
    if mt == "mixtral":
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None),
            intermediate_size=hf_config.intermediate_size,
            max_seq_len=hf_config.max_position_embeddings,
            pos_embedding="rope",
            rope_theta=getattr(hf_config, "rope_theta", 1e6),
            norm="rmsnorm",
            norm_eps=hf_config.rms_norm_eps,
            activation="swiglu",
            num_experts=hf_config.num_local_experts,
            moe_top_k=hf_config.num_experts_per_tok,
            name="mixtral-hf",
        )
    raise ValueError(f"unsupported HF model_type {mt!r}")


def import_hf_model(hf_model):
    """(TransformerModel, host params) from an instantiated HF model."""
    cfg = config_from_hf(hf_model.config)
    params = import_hf_state_dict(hf_model.state_dict(), cfg)
    return TransformerModel(cfg), params


class HfEngineAdapter:
    """Trainer-style helper: wrap an HF model into a TpuEngine.

    Usage:
        adapter = HfEngineAdapter(hf_model, ds_config)
        engine = adapter.engine
        engine.train_batch(batch={"input_ids": ...})
    """

    def __init__(self, hf_model, ds_config, topology=None):
        import deepspeed_tpu

        self.model, host_params = import_hf_model(hf_model)
        self.engine, _, _, self.lr_scheduler = deepspeed_tpu.initialize(
            model=self.model,
            config=ds_config,
            model_parameters=host_params,
            topology=topology,
        )

    def __getattr__(self, name):
        if name == "engine":  # __init__ failed before engine was set
            raise AttributeError(name)
        return getattr(self.engine, name)


# ---------------------------------------------------------------------------
# safetensors file I/O (dependency-free)
# ---------------------------------------------------------------------------
# Format: 8-byte LE header length, JSON header {name: {dtype, shape,
# data_offsets}, "__metadata__": ...}, then one raw little-endian buffer.
# Implemented directly (zero-egress image may lack the safetensors package);
# reference parity: the HF loading path of deepspeed's AutoTP/inference.
_ST_DTYPES = {
    "F64": np.float64, "F32": np.float32, "F16": np.float16,
    "I64": np.int64, "I32": np.int32, "I16": np.int16, "I8": np.int8,
    "U8": np.uint8, "BOOL": np.bool_,
}


def read_safetensors(path: str) -> Dict[str, np.ndarray]:
    """Read one .safetensors file into {name: numpy array} (BF16 → fp32)."""
    import json
    import struct

    out = {}
    with open(path, "rb") as f:
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen))
        base = 8 + hlen
        # seek+read per tensor: peak host memory stays one tensor, not the
        # whole multi-GB shard plus per-tensor copies
        for name, meta in header.items():
            if name == "__metadata__":
                continue
            start, end = meta["data_offsets"]
            f.seek(base + start)
            raw = f.read(end - start)
            shape = tuple(meta["shape"])
            if meta["dtype"] == "BF16":
                u16 = np.frombuffer(raw, np.uint16)
                arr = (u16.astype(np.uint32) << 16).view(np.float32)
            else:
                arr = np.frombuffer(raw, _ST_DTYPES[meta["dtype"]])
            out[name] = arr.reshape(shape)
    return out


def write_safetensors(path: str, tensors: Dict[str, np.ndarray]) -> None:
    """Write {name: numpy array} in safetensors layout (fp32/int kinds)."""
    import json
    import struct

    rev = {v: k for k, v in _ST_DTYPES.items()}
    header: Dict[str, Any] = {}
    offset = 0
    blobs = []
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        if arr.dtype == jnp.bfloat16:  # ml_dtypes: raw 2-byte LE payload
            code = "BF16"
        else:
            code = rev.get(arr.dtype.type)
        if code is None:
            arr = arr.astype(np.float32)
            code = "F32"
        blob = arr.tobytes()
        header[name] = {
            "dtype": code,
            "shape": list(arr.shape),
            "data_offsets": [offset, offset + len(blob)],
        }
        offset += len(blob)
        blobs.append(blob)
    hjson = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(hjson)))
        f.write(hjson)
        for blob in blobs:
            f.write(blob)


def load_hf_checkpoint(path: str, cfg: TransformerConfig,
                       family: Optional[str] = None) -> Dict[str, Any]:
    """Load an HF checkpoint directory (or single .safetensors file) into
    this package's param pytree — no torch/transformers needed.

    Handles single-file and sharded (model.safetensors.index.json) layouts.
    """
    import json
    import os

    if os.path.isfile(path):
        files = [path]
    else:
        index = os.path.join(path, "model.safetensors.index.json")
        if os.path.exists(index):
            with open(index) as f:
                weight_map = json.load(f)["weight_map"]
            files = sorted(
                {os.path.join(path, fn) for fn in weight_map.values()}
            )
        else:
            files = sorted(
                os.path.join(path, f)
                for f in os.listdir(path)
                if f.endswith(".safetensors")
            )
        if not files:
            raise FileNotFoundError(f"no .safetensors files under {path!r}")
    sd: Dict[str, np.ndarray] = {}
    for f in files:
        sd.update(read_safetensors(f))
    return import_hf_state_dict(sd, cfg, family)
