from .hf import HfEngineAdapter, import_hf_model, import_hf_state_dict  # noqa: F401
from .trainer import TrainerStrategyAdapter  # noqa: F401
