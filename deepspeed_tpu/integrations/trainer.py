"""Generic trainer-loop adapter: the Lightning-Strategy contract on TPU.

Parity: Lightning's ``DeepSpeedStrategy`` (lightning/pytorch/strategies/
deepspeed.py) + the reference's ``deepspeed.initialize`` front door that
Lightning calls into. The contract both sides agree on: the *trainer* owns
the loop (epochs, dataloaders, logging, early stopping); the *strategy*
owns distributed setup, precision, optimizer stepping, and checkpoint IO.

Scope decision (VERDICT r3 missing #4): PyTorch Lightning itself is
torch-bound and not importable in this image, so "Lightning launches
unchanged" is delivered as this framework-neutral adapter exposing exactly
the Strategy hook surface. A ``lightning.Strategy`` subclass wrapping it is
a mechanical shim (each hook below names its Lightning counterpart); any
other trainer loop (HF Trainer-style, a custom epoch loop) drives the same
five calls. See docs/DESIGN.md "Trainer integrations".

Usage (any trainer loop)::

    strategy = TrainerStrategyAdapter(model, ds_config)
    strategy.setup()
    for batch in loader:
        loss = strategy.training_step(batch)     # fwd+bwd+step, one call
    strategy.save_checkpoint("ckpts")
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional

__all__ = ["TrainerStrategyAdapter"]


class TrainerStrategyAdapter:
    """Strategy-shaped wrapper over :class:`TpuEngine`.

    Each method documents the Lightning Strategy / DeepSpeedStrategy hook it
    mirrors. The deliberate contract difference: on TPU, forward, backward,
    and optimizer step are ONE jitted program (`engine.train_batch`), so
    ``backward`` and ``optimizer_step`` are satisfied inside
    ``training_step`` — Lightning's DeepSpeedStrategy does the same thing
    (its ``backward`` delegates to ``deepspeed_engine.backward`` and its
    ``optimizer_step`` to ``deepspeed_engine.step``; here both are fused
    into the step program and these hooks are recorded no-ops).
    """

    def __init__(self, model, config: Dict[str, Any], topology=None,
                 model_parameters=None, lr_scheduler=None):
        self._init_args = dict(model=model, config=config, topology=topology,
                               model_parameters=model_parameters,
                               lr_scheduler=lr_scheduler)
        self.engine = None
        self.lr_scheduler = None

    # -- lifecycle ---------------------------------------------------------
    def setup(self) -> "TrainerStrategyAdapter":
        """Lightning ``Strategy.setup``: build the distributed engine.
        Idempotent, so trainers that call setup per-stage are safe."""
        if self.engine is None:
            import deepspeed_tpu

            self.engine, _, _, self.lr_scheduler = deepspeed_tpu.initialize(
                **self._init_args
            )
        return self

    def teardown(self) -> None:
        """Lightning ``Strategy.teardown``."""
        if self.engine is not None:
            self.engine.destroy()
            self.engine = None
            self.lr_scheduler = None

    # -- the loop hooks ----------------------------------------------------
    def training_step(self, batch=None, data_iter: Optional[Iterable] = None):
        """Lightning ``Strategy.training_step`` + ``backward`` +
        ``optimizer_step`` + ``lr_scheduler_step``, fused: one engine step
        (fwd, bwd, clip, optimizer, LR, loss-scale) under jit."""
        self.setup()
        return self.engine.train_batch(batch=batch, data_iter=data_iter)

    def validation_step(self, batch=None, data_iter: Optional[Iterable] = None):
        """Lightning ``Strategy.validation_step``: forward-only loss."""
        self.setup()
        return self.engine.eval_batch(batch=batch, data_iter=data_iter)

    def backward(self, loss=None) -> None:
        """No-op by contract: backward already ran inside
        :meth:`training_step` (the engine's step program is fwd+bwd+update
        in one XLA program; splitting it would force a host round-trip and
        break XLA fusion). Present so Strategy-driven loops run unchanged."""

    def optimizer_step(self, *_a, **_k) -> None:
        """No-op by contract — see :meth:`backward`."""

    def lr_scheduler_step(self, *_a, **_k) -> None:
        """No-op by contract — the schedule advances inside the step."""

    # -- checkpoint IO (Lightning CheckpointIO contract) -------------------
    def save_checkpoint(self, dirpath: str, tag: Optional[str] = None,
                        client_state: Optional[Dict[str, Any]] = None) -> str:
        """Lightning ``Strategy.save_checkpoint`` (multi-host safe: shard
        writes per process, metadata from the writer process only)."""
        self.setup()
        return self.engine.save_checkpoint(dirpath, tag=tag,
                                           client_state=client_state)

    def load_checkpoint(self, dirpath: str, tag: Optional[str] = None):
        """Lightning ``Strategy.load_checkpoint``."""
        self.setup()
        return self.engine.load_checkpoint(dirpath, tag=tag)

    # -- cluster/environment queries --------------------------------------
    def barrier(self, name: str = "trainer") -> None:
        """Lightning ``Strategy.barrier``."""
        from .. import comm

        comm.barrier(name)

    @property
    def global_rank(self) -> int:
        import jax

        return jax.process_index()

    @property
    def world_size(self) -> int:
        import jax

        return jax.process_count()

    @property
    def is_global_zero(self) -> bool:
        """Lightning ``Trainer.is_global_zero`` (gates logging/writes)."""
        return self.global_rank == 0

    @property
    def global_step(self) -> int:
        return self.engine.global_steps if self.engine is not None else 0

    def __getattr__(self, name):
        # anything else falls through to the engine, mirroring
        # HfEngineAdapter — trainers poking engine attrs keep working
        if name == "engine" or self.engine is None:
            raise AttributeError(name)
        return getattr(self.engine, name)
