"""Continuous-batching serving engine: ONE jitted step, slot-ragged KV.

Parity: DeepSpeed-MII / FastGen's continuous-batching engine. The classic
``InferenceEngine.generate`` is lockstep: one compiled program per
``(B, prompt_len, total_len)`` and a single scalar ``cache_len`` shared by
the whole batch, so ragged traffic pads to the worst case or recompiles.
This engine is slot-based:

- a static KV arena ``[L, max_slots, capacity, KV, hd]`` (int8 scales
  included) holds one region per in-flight request;
- per-slot ``cache_len``/``last_pos`` VECTORS replace the scalar
  (models/decoding.py grew the ragged form of the cache write + mask;
  ops/pallas/decode_attention.py takes the [B] frontier in SMEM);
- ONE jitted step of fixed shape ``[max_slots, token_budget]`` consumes
  whatever mix of prompt chunks and decode tokens the scheduler packed
  (Dynamic SplitFuse), with active-slot masking for sampling — arbitrary
  arrival patterns run with ZERO recompiles after the first step;
- sampling state is per-slot and deterministic per request (its own RNG
  chain, temperature/top-k/top-p/penalty vectors), so every request's
  tokens are bit-reproducible against a single-request ``generate`` call
  with the same params and key — the CPU-mesh oracle in
  tests/test_serving.py.

TP serving: the KV arena shards its head axis over ``tp`` exactly like
the lockstep engine's cache; the step carries the arena with an explicit
sharding constraint so the jit carry stays sharding-closed (shardlint R2
— the seeded corpus pair ``slot_cache_carry_drift`` shows the drifted
form).

``serving.paged`` swaps the contiguous per-slot regions for a
**block-paged arena** (vLLM / FastGen blocked-KV): a global page pool +
per-slot page tables traced as int32 vectors, host-side page
allocation/refcounts/prefix cache in the scheduler, copy-on-write folded
into the step via a ``cow_src`` vector — same ONE-jitted-step
discipline, outputs bitwise identical to the contiguous arena (see
docs/serving.md "Block-paged, prefix-shared arena" and
tests/test_serving_paged.py).

``serving.spec`` adds **speculative decoding** (serving/spec.py): each
decode slot's row may carry up to ``max_draft`` host-proposed n-gram
drafts after its committed token (a spec slot claims k+1 budget rows),
the step verifies every window at once and emits 1..k+1 tokens per slot
(``out_tokens``/``n_emit``), and sample-and-match acceptance against the
per-slot RNG chain keeps spec-on output bitwise identical to spec-off —
see docs/serving.md "Speculative decoding" and tests/test_serving_spec.py.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..comm.topology import MeshTopology, ParallelDims
from ..inference.engine import (InferenceEngine, _align_cache,
                                init_inference)
from ..models.decoding import (SCALE_LANES, forward_with_cache, init_cache,
                               init_paged_cache, paged_cow_copy,
                               staged_promote)
from ..models.sharding import use_topology
from ..utils.logging import log_dist
from .metrics import ServingMetrics
from .paging import STAGE_SLOTS
from .request import Request, RequestState, RequestStatus
from .scheduler import Scheduler, StepPlan
from .spec import spec_verify_stream, verify_window


def cache_partition_specs(quantized: bool) -> Dict[str, P]:
    """KV-arena specs: cache heads over tp (slots stay unsharded — the
    scheduler owns placement); the per-layer leading dim is stacked."""
    value = P(None, None, None, "tp", None)
    specs = {"k": value, "v": value}
    if quantized:
        scale = P(None, None, "tp", None, None)
        specs["k_scale"] = scale
        specs["v_scale"] = scale
    return specs


def serving_kv_stream(cfg, max_slots: int, capacity: int,
                      storage_itemsize: int, quantized: bool,
                      tp: int = 1) -> Dict[str, Any]:
    """Analytic per-step KV-cache HBM traffic of the slot engine, in the
    shared analytic-streams schema (comm_logger.record_streams / planner /
    rule R8). Upper bound: the dense slot design streams the whole arena
    per step (k+v read + the chunk write); the Pallas decode kernel's
    per-tile predication reads less when frontiers are short."""
    per_tok = cfg.kv_heads * cfg.hd * (1 if quantized else storage_itemsize)
    arena_tokens = cfg.num_layers * max_slots * capacity
    data = arena_tokens * per_tok * 2  # k + v
    scales = (
        arena_tokens * SCALE_LANES * 4 * 2 if quantized else 0
    )
    total = data + scales
    return {
        "kind": "hbm",
        "bytes_per_step": total,
        "per_device_bytes_per_step": total // max(tp, 1),
        "overlapped": False,  # this IS the step's compute traffic, not a
                              # hidden side stream — R8 prices it only if
                              # some config declares it overlapped
        "slots": max_slots,
        "capacity": capacity,
        "quantized": quantized,
    }


def _make_sample_one(vocab: int):
    """Per-slot sampler reproducing InferenceEngine._build_decode.sample
    on a [1, V] row — same masking composition, same categorical key
    shape — so a slot's tokens match the single-request engine bitwise.
    The static top_k/top_p gates become traced ``where`` gates (identity
    branches are bitwise identity), which is what keeps the serving step
    at one compile for every sampling mix."""

    def sample_one(row, key, temp, tk, tp_):
        l = row[None, :] / jnp.maximum(temp, 1e-6)
        # top-k: the k-th largest as threshold; identity when tk <= 0
        sorted_desc = jnp.sort(l, axis=-1)[:, ::-1]
        kth = jnp.take_along_axis(
            sorted_desc, jnp.clip(tk, 1, vocab).reshape(1, 1) - 1, axis=-1
        )
        l = jnp.where((tk > 0) & (l < kth), -1e30, l)
        # top-p nucleus over the (possibly top-k-masked) row; identity
        # when tp_ >= 1.0. Same construction as the lockstep sampler:
        # smallest prefix reaching the mass, top-1 always survives.
        nuc = jnp.sort(l, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(nuc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs) < tp_
        keep = keep.at[:, 0].set(True)
        pth = jnp.min(jnp.where(keep, nuc, jnp.inf), axis=-1, keepdims=True)
        l = jnp.where((tp_ < 1.0) & (l < pth), -1e30, l)
        greedy = jnp.argmax(l, axis=-1)
        sampled = jax.random.categorical(key, l, axis=-1)
        return jnp.where(temp == 0.0, greedy, sampled)[0]

    return sample_one


def paged_kv_stream(cfg, num_pages: int, page_size: int, max_slots: int,
                    pages_per_slot: int, token_budget: int,
                    storage_itemsize: int, quantized: bool,
                    tp: int = 1) -> Dict[str, Any]:
    """Analytic per-step HBM traffic of the PAGED serving step, in the
    shared analytic-streams schema. Upper bound: the per-slot view gather
    reads every mapped logical page (the Pallas paged kernel's frontier
    predication reads less), the chunk scatter writes token_budget
    tokens, and the COW lane copies at most one page per slot. The POOL
    bytes themselves (the R6 capacity term) are priced from the traced
    step's invars — num_pages here is reported for the summary line."""
    per_tok = cfg.kv_heads * cfg.hd * (1 if quantized else storage_itemsize)
    scale_tok = SCALE_LANES * 4 if quantized else 0
    view_tokens = cfg.num_layers * max_slots * pages_per_slot * page_size
    gather = view_tokens * (per_tok + scale_tok) * 2          # k + v reads
    scatter = cfg.num_layers * max_slots * token_budget * (
        per_tok + scale_tok
    ) * 2
    cow = cfg.num_layers * max_slots * page_size * (per_tok + scale_tok) * 2
    total = gather + scatter + cow
    pool_tokens = cfg.num_layers * (num_pages + 1) * page_size
    return {
        "kind": "hbm",
        "bytes_per_step": total,
        "per_device_bytes_per_step": total // max(tp, 1),
        "overlapped": False,  # the step's own compute traffic
        "paged": True,
        "page_size": page_size,
        "num_pages": num_pages,
        "pages_per_slot": pages_per_slot,
        "pool_bytes": pool_tokens * (per_tok + scale_tok) * 2,
        "slots": max_slots,
        "quantized": quantized,
    }


def kv_spill_page_bytes(cfg, page_size: int, codec_name: str,
                        quantized: bool) -> int:
    """At-rest bytes of ONE spilled KV page under ``codec_name`` —
    exactly what serving/paging.encode_page produces: float k/v leaves
    ride the wire codec on the canonical ``[L, rows, lanes]`` layout;
    int8 pool leaves spill raw (already 1 byte/elem) with their f32
    scales codec-compressed."""
    from ..comm.wires import get_codec

    codec = get_codec(codec_name)
    L, KV, hd = cfg.num_layers, cfg.kv_heads, cfg.hd
    if quantized:
        raw = L * page_size * KV * hd * 1 * 2  # int8 k + v, raw
        scales = codec.payload_nbytes(L, KV * page_size, SCALE_LANES) * 2
        return raw + scales
    return codec.payload_nbytes(L, page_size * KV, hd) * 2  # k + v


def kv_spill_stream(cfg, page_size: int, host_pages: int, codec_name: str,
                    quantized: bool, tp: int = 1) -> Dict[str, Any]:
    """The ``kv_spill`` analytic stream: steady-state host-DMA traffic of
    the tiered KV hierarchy, in the shared analytic-streams schema.
    Upper bound per step: STAGE_SLOTS pages promote in (the rotating
    staging buffer is that wide — serving/paging.STAGE_SLOTS) and, under
    sustained pressure, STAGE_SLOTS demotions go out to make room —
    both at the codec's AT-REST width. Declared ``overlapped``: the
    page-in rides under the decode step's math (the staged scatter runs
    before the gathers inside the ONE jitted step), so R8/R13 price it
    on the host link (``hw.host_bw``) against the step's compute
    window rather than as exposed tail."""
    page_bytes = kv_spill_page_bytes(cfg, page_size, codec_name, quantized)
    total = page_bytes * STAGE_SLOTS * 2  # in + out
    return {
        "kind": "offload",
        "bytes_per_step": total,
        "per_device_bytes_per_step": total // max(tp, 1),
        "overlapped": True,  # hidden under the decode step (double-
                             # buffered staging; R8 budgets the window)
        "stage_slots": STAGE_SLOTS,
        "page_size": page_size,
        "host_pages": host_pages,
        "codec": codec_name,
        "page_bytes_at_rest": page_bytes,
        "quantized": quantized,
    }


# the "auto" moe_a2a form's payload threshold: below this many bytes per
# ring hop the exchange is latency-bound (The Big Send-off's small-message
# regime) and stock collectives win; above it the chunked ppermute ride
# can hide under the per-chunk expert FFNs. Static per engine — the form
# never changes at run time, so neither does the compiled program.
MOE_A2A_AUTO_THRESHOLD_BYTES = 1 << 20
# ring granularity of the serving chunked form (capacity chunks whose
# hops pipeline against each other) — fixed pending an on-chip A/B; ONE
# constant so the engine and the lint trace cannot diverge
MOE_A2A_CHUNKS = 2


def serving_ep_size(moe_section, mcfg) -> int:
    """The ep mesh degree a MoE serving config serves (and lints) on:
    ``moe.ep_size`` clamped to what divides the experts; 1 for dense
    models. ONE clamp shared by trace_serving_step and
    analysis.lint_serving_config."""
    if not getattr(mcfg, "is_moe", False):
        return 1
    ep = max(int(getattr(moe_section, "ep_size", 1)), 1)
    if ep > 1 and mcfg.num_experts % ep != 0:
        return 1
    return ep


def resolve_moe_a2a_form(serving_moe_a2a: str, mcfg, topology,
                         token_budget: int, itemsize: int,
                         packed_experts: bool = False,
                         max_slots: Optional[int] = None) -> str:
    """Resolve serving.moe_a2a ("auto"|"stock"|"chunked") into the form
    the step will actually trace: "off" (dense model or no ep axis),
    "stock" (GSPMD collectives) or "chunked" (the decode-shaped
    chunked-ppermute ring, parallel/a2a_overlap.moe_decode_a2a). ONE
    resolution shared by ServingEngine and the shardlint serving trace,
    so the linted program is the served program — including the
    slot-grid divisibility gate (``max_slots`` when known: the ring
    needs max_slots · token_budget to divide ep, and the declared form
    must describe the exchange that actually runs). The planner's
    serving moe-a2a axis enumerates stock vs chunked explicitly."""
    if not getattr(mcfg, "is_moe", False):
        return "off"
    if topology.sizes.get("ep", 1) <= 1:
        return "stock"  # dense-replicated experts: nothing on the wire
    from ..parallel.a2a_overlap import moe_decode_a2a_applicable

    applicable = (
        not packed_experts
        and moe_decode_a2a_applicable(
            topology, E=mcfg.num_experts, F=mcfg.ffn,
            n_tokens=(
                int(max_slots) * int(token_budget)
                if max_slots is not None else None
            ),
        )
    )
    form = serving_moe_a2a
    if form == "auto":
        from ..moe.sharded_moe import eval_capacity

        cap = eval_capacity(mcfg, int(token_budget))
        per_hop = (
            (mcfg.num_experts // topology.sizes["ep"]) * cap
            * mcfg.hidden_size * itemsize
        )
        form = (
            "chunked" if per_hop >= MOE_A2A_AUTO_THRESHOLD_BYTES
            else "stock"
        )
    if form == "chunked" and not applicable:
        form = "stock"
    return form


def moe_a2a_scope_cfg(form: str):
    """The a2a_scope config the serving step traces under (enabled only
    for the chunked form; a DISABLED cfg forces the stock exchange so an
    ambient training scope can never leak in). ONE construction shared
    by ServingEngine and trace_serving_step."""
    from ..config import MoEOverlapA2AConfig

    return MoEOverlapA2AConfig(enabled=form == "chunked",
                               chunks=MOE_A2A_CHUNKS)


def moe_decode_stream(mcfg, topology, token_budget: int, itemsize: int,
                      form: str) -> Optional[Dict[str, Any]]:
    """The ``moe_decode_a2a`` analytic stream dict (None when no expert
    exchange exists: dense model or ep == 1) — ONE construction shared
    by ServingEngine.analytic_streams and trace_serving_step, so the
    R8-priced stream always describes the served exchange."""
    ep = topology.sizes.get("ep", 1)
    if not getattr(mcfg, "is_moe", False) or ep <= 1:
        return None
    from ..parallel.a2a_overlap import moe_decode_a2a_bytes_per_step

    ring = moe_decode_a2a_bytes_per_step(
        mcfg, topology, int(token_budget), itemsize=itemsize,
    )
    if not ring:
        return None
    return {
        **ring,
        "kind": "ici",
        "per_device_bytes_per_step": ring["bytes_per_step"],
        "overlapped": form == "chunked",
        "form": form,
        "ep": ep,
    }


def make_step_fn(cfg, dtype, vocab: int, cache_shardings=None,
                 max_draft: int = 0):
    """The ONE serving step (pure; jitted by ServingEngine, traced
    abstractly by the shardlint serving branch).

    Inputs (fixed shapes; N = max_slots, W = token_budget):
      tokens [N, W] int32   chunk tokens, 0-padded past ``num_new``; a
                            spec decode slot's row is its committed token
                            followed by ``spec_len`` drafts
      num_new [N] int32     real tokens per slot (0 = idle slot)
      start_pos [N] int32   per-slot write frontier (== cached tokens)
      fresh [N] bool        slot newly allocated → clear its seen row
      sample_flag [N] bool  slot samples this step
      spec_len [N] int32    draft tokens in the row's verify window
                            (0 = plain decode / final prefill feed)
      eos_id [N] int32      per-request eos (-1 = none): the verify
                            advance clamps at an emitted eos so the RNG
                            chain stops exactly where spec-off would
      rng [N, 2] uint32     per-slot PRNG keys (split ONLY when a token
                            is emitted, mirroring the lockstep chain)
      temperature/top_p/rep_penalty [N] f32, top_k [N] i32

    ``max_draft`` is STATIC (the step's fixed output shape
    [N, max_draft + 1]); 0 disables speculation and reduces the verify
    window to the pre-spec single-token sampling tail, bitwise.

    Returns (caches, seen, out_tokens [N, max_draft + 1] i32,
    n_emit [N] i32, new_rng [N, 2]) — MoE models append a sixth
    ``moe_stats`` output (tokens-per-expert/routed/dropped counters; the
    arity is static per engine).

    MoE models route the MLP through the expert-parallel serving path:
    ``pos < num_new`` marks each row's REAL tokens, so padded tails,
    idle slots and done rows route to the null expert and capacity stays
    a constant of the static token budget W (the scheduler never packs
    more than W real tokens per step) — occupancy changes recompile
    nothing.
    """
    sample_one = _make_sample_one(vocab)
    moe = bool(getattr(cfg, "is_moe", False))

    def step(params, caches, seen, tokens, num_new, start_pos, fresh,
             sample_flag, spec_len, eos_id, rng, temperature, top_k, top_p,
             rep_penalty):
        live = sample_flag & (num_new > 0)
        seen = _book_seen(seen, tokens, num_new, spec_len, fresh, vocab)
        token_valid = (
            jnp.arange(tokens.shape[1])[None, :] < num_new[:, None]
            if moe else None
        )
        fw = forward_with_cache(
            cfg, params, tokens, caches, start_pos, dtype=dtype,
            token_valid=token_valid, return_moe_stats=moe,
        )
        if moe:
            logits, caches, moe_stats = fw
        else:
            logits, caches = fw
        if cache_shardings is not None:
            # keep the donated arena carry sharding-closed across steps
            caches = jax.lax.with_sharding_constraint(
                caches, cache_shardings
            )
        out_tok, n_emit, new_rng = verify_window(
            sample_one, logits, tokens, seen, num_new, spec_len, live, rng,
            temperature, top_k, top_p, rep_penalty, eos_id, max_draft,
        )
        if moe:
            return caches, seen, out_tok, n_emit, new_rng, moe_stats
        return caches, seen, out_tok, n_emit, new_rng

    return step


def _book_seen(seen, tokens, num_new, spec_len, fresh, vocab):
    """seen bookkeeping BEFORE the forward, exactly where the lockstep
    engine books tokens (prompt before the first sample, each fed token
    before its successor samples); fresh slots reset first and padded
    positions never book (the ragged-batch hazard fix). DRAFT tokens
    (the last ``spec_len`` of a row) never book either: they are
    speculative, and spec is host-gated to repetition_penalty == 1.0
    requests whose ``seen`` row is never consulted — so the matrix only
    ever holds committed-fed tokens."""
    N, W = tokens.shape
    rows = jnp.arange(N)
    seen = jnp.where(fresh[:, None], jnp.zeros_like(seen), seen)
    valid = jnp.arange(W)[None, :] < (num_new - spec_len)[:, None]
    return seen.at[
        rows[:, None], jnp.clip(tokens, 0, vocab - 1)
    ].max(valid)


def make_paged_step_fn(cfg, dtype, vocab: int, cache_shardings=None,
                       max_draft: int = 0, tiered: bool = False):
    """Paged twin of :func:`make_step_fn`: same fixed [N, W] discipline,
    two extra traced int32 inputs instead of per-slot cache regions —

      page_table [N, max_pages]  physical page per logical page (unmapped
                                 entries point at the NULL page, where
                                 idle slots' and chunk tails' padded
                                 writes land)
      cow_src [N]                copy-on-write source page (-1 = none):
                                 a slot diverging from a shared prefix
                                 mid-page copies that page onto its own
                                 frontier page BEFORE the chunk write

    ``tiered`` (serving.host_pages > 0) adds the host-tier staging pair
    BETWEEN cow_src and fresh —

      stage_kv {leaf: [L, STAGE_SLOTS, ...]}  the rotating staging
                                 buffer: up to STAGE_SLOTS host pages
                                 decoded for promotion this step
      stage_dst [STAGE_SLOTS]    physical destination page per staging
                                 slot (NULL page = unused slot: its
                                 scatter lands in the sink)

    and scatters it onto the pool FIRST (models/decoding.staged_promote
    — before the COW lane and the gathers), so a page promoted this
    step is attendable this step and the page-in H2D rides under the
    step's math. The flag is STATIC per engine: an untiered engine's
    program is byte-identical to pre-tiering, and the tiered program is
    ONE trace across every spill/restore mix (stage_dst is traced,
    never baked).

    Page allocation/free/refcounts live host-side in the scheduler; the
    step only COPIES (cow), SCATTERS (the chunk + staged promotions) and
    GATHERS (per-slot views) through the tables, so every arrival/
    sharing/divergence mix runs the same compiled program — zero
    recompiles after warmup."""
    sample_one = _make_sample_one(vocab)
    moe = bool(getattr(cfg, "is_moe", False))

    def step(params, caches, seen, tokens, num_new, start_pos, page_table,
             cow_src, fresh, sample_flag, spec_len, eos_id, rng, temperature,
             top_k, top_p, rep_penalty):
        live = sample_flag & (num_new > 0)
        seen = _book_seen(seen, tokens, num_new, spec_len, fresh, vocab)
        caches = paged_cow_copy(caches, page_table, start_pos, cow_src)
        token_valid = (
            jnp.arange(tokens.shape[1])[None, :] < num_new[:, None]
            if moe else None
        )
        fw = forward_with_cache(
            cfg, params, tokens, caches, start_pos, dtype=dtype,
            page_table=page_table,
            token_valid=token_valid, return_moe_stats=moe,
        )
        if moe:
            logits, caches, moe_stats = fw
        else:
            logits, caches = fw
        if cache_shardings is not None:
            # keep the donated pool carry sharding-closed across steps
            caches = jax.lax.with_sharding_constraint(
                caches, cache_shardings
            )
        out_tok, n_emit, new_rng = verify_window(
            sample_one, logits, tokens, seen, num_new, spec_len, live, rng,
            temperature, top_k, top_p, rep_penalty, eos_id, max_draft,
        )
        if moe:
            return caches, seen, out_tok, n_emit, new_rng, moe_stats
        return caches, seen, out_tok, n_emit, new_rng

    if not tiered:
        return step

    def tiered_step(params, caches, seen, tokens, num_new, start_pos,
                    page_table, cow_src, stage_kv, stage_dst, fresh,
                    sample_flag, spec_len, eos_id, rng, temperature,
                    top_k, top_p, rep_penalty):
        # scatter-before-gather: promoted pages land in the pool before
        # the COW lane and the per-slot view gathers, so a slot whose
        # last host page promotes THIS step also schedules this step
        caches = staged_promote(caches, stage_kv, stage_dst)
        return step(params, caches, seen, tokens, num_new, start_pos,
                    page_table, cow_src, fresh, sample_flag, spec_len,
                    eos_id, rng, temperature, top_k, top_p, rep_penalty)

    return tiered_step


class ServingEngine:
    """Request-level front end over one slot-ragged jitted step.

    Drive it with :meth:`submit` + :meth:`step` (one scheduler plan + one
    device step per call), or :meth:`run_until_idle` to drain everything
    in flight. ``clock`` is injectable for tests/replay."""

    def __init__(
        self,
        model=None,
        serving=None,
        engine: Optional[InferenceEngine] = None,
        clock=time.monotonic,
        metrics: Optional[ServingMetrics] = None,
        comm_logger=None,
        steptrace=None,
        healthwatch=None,
        name: Optional[str] = None,
        **engine_kwargs,
    ):
        from ..config import ServingConfig, _parse_dc

        if serving is None:
            serving = ServingConfig()
        elif isinstance(serving, dict):
            serving = _parse_dc(ServingConfig, serving)
        # resolve "auto" spec/paged/moe_a2a/kv knobs from the measured
        # knob-default table before ANY read below (spec_enabled, paged,
        # the pre-engine kv dtype kwarg) — conservative off on a miss
        from ..config import resolve_auto_knobs

        resolve_auto_knobs(
            serving,
            model_config=(getattr(engine, "config", None)
                          if engine is not None
                          else getattr(model, "config", None)),
            topology=getattr(engine, "topology", None),
        )
        serving.validate()
        self.serving = serving
        if engine is None:
            if model is None:
                raise ValueError("ServingEngine needs a model or an engine")
            if serving.kv_cache_dtype != "auto":
                engine_kwargs.setdefault(
                    "kv_cache_dtype", serving.kv_cache_dtype
                )
            engine_kwargs.setdefault("max_tokens", serving.max_tokens)
            engine = init_inference(model, **engine_kwargs)
        self.engine = engine
        self.config = engine.config
        self.topology = engine.topology
        self.dtype = engine.dtype
        self.clock = clock
        self.comm_logger = comm_logger
        # fleet identity: the router names each replica ("r0", "r1", ...)
        # so the shared steptrace timeline's serve/step spans say which
        # replica stepped; None = the single-engine path, no annotation
        self.name = name

        N, W = serving.max_slots, serving.token_budget
        self.max_slots, self.token_budget = N, W
        # speculative decoding (serving.spec): per-slot draft-then-verify
        # in the ONE step. max_draft is STATIC (the verify-window output
        # shape); per-slot/per-step draft counts ride as the traced
        # spec_len vector, so spec never adds a compile.
        spec_cfg = serving.spec
        self.spec_enabled = bool(getattr(spec_cfg, "enabled", False))
        self.max_draft = int(spec_cfg.max_draft) if self.spec_enabled else 0
        self.spec_ngram_n = int(getattr(spec_cfg, "ngram_n", 3))
        # per-request cap; the +W margin absorbs the chunk a full slot
        # writes past its frontier (padding rows, never attendable)
        self.max_tokens = min(serving.max_tokens, engine.max_tokens)
        # ---- MoE serving (ISSUE 14): expert-parallel decode ------------
        # the step routes the MLP through the slot-ragged expert path;
        # under an ep mesh axis the expert exchange takes the form
        # resolved here (ONE resolution shared with the shardlint trace)
        mcfg = engine.config
        self.moe_serving = bool(getattr(mcfg, "is_moe", False))
        self.moe_ep = self.topology.sizes.get("ep", 1)
        self._a2a_cfg = None
        self.moe_a2a_form = "off"
        if self.moe_serving:
            from ..ops.quantizer import PackedWeight

            packed_experts = any(
                isinstance(leaf, PackedWeight) and len(leaf.shape) == 4
                for leaf in jax.tree_util.tree_leaves(
                    engine.params,
                    is_leaf=lambda a: isinstance(a, PackedWeight),
                )
            )
            self.moe_a2a_form = resolve_moe_a2a_form(
                serving.moe_a2a, mcfg, self.topology, W,
                jnp.dtype(engine.dtype).itemsize,
                packed_experts=packed_experts, max_slots=N,
            )
            # the scope is entered around every step call (trace-time
            # protocol)
            self._a2a_cfg = moe_a2a_scope_cfg(self.moe_a2a_form)
        self.paged = bool(serving.paged)
        if self.paged:
            from ..config import DeepSpeedConfigError

            self.page_size = int(serving.page_size)
            # logical pages per slot cover max_tokens + the W write margin
            # (ONE definition of the page math: ServingConfig, fed the
            # engine-clamped max_tokens)
            self.pages_per_slot = serving.pages_per_slot(self.max_tokens)
            self.capacity = self.pages_per_slot * self.page_size
            self.num_pages = (
                int(serving.num_pages) or N * self.pages_per_slot
            )
            if self.num_pages < self.pages_per_slot:
                # liveness floor: after evicting everything else, ONE
                # request must still be able to run to max_tokens —
                # otherwise forced eviction can never make progress
                raise DeepSpeedConfigError(
                    f"serving.num_pages {self.num_pages} is below the "
                    f"liveness floor ceil((max_tokens + token_budget) / "
                    f"page_size) = {self.pages_per_slot}; one request "
                    "could never finish"
                )
            self.null_page = self.num_pages  # physical id of the sink page
        else:
            self.page_size = self.num_pages = self.pages_per_slot = None
            self.capacity = _align_cache(self.max_tokens + W)
        # ---- tiered KV (serving.host_pages > 0, ISSUE 18): a pinned-
        # host second tier behind the HBM pool. The ENGINE owns the
        # store + spiller (movement needs device access: export/encode on
        # demotion, decode/stage on promotion); the SCHEDULER owns policy
        self.host_pages = int(getattr(serving, "host_pages", 0) or 0) \
            if self.paged else 0
        self.tiered = self.host_pages > 0
        self._host_store = self._spiller = None

        self.metrics = metrics or ServingMetrics(clock=clock)
        self.metrics.configure(N, num_pages=self.num_pages or 0,
                               host_pages=self.host_pages)
        if self.tiered:
            from .paging import HostPageStore, PageSpiller, export_pages

            self._host_store = HostPageStore(
                self.host_pages, codec=serving.spill_codec,
                spill_dir=serving.spill_dir,
            )
            # late-bound caches: demote only runs inside plan(), between
            # steps, when self._caches is the settled functional carry
            self._spiller = PageSpiller(
                self._host_store,
                lambda ids: export_pages(self._caches, ids),
                metrics=self.metrics,
            )
        # ---- steptrace (config-gated; None = the zero-overhead path:
        # no span objects exist and every site below guards on it) ------
        self.tracer = None
        self._serve_tracer = None
        self._steptrace_export_path = None
        if steptrace is not None:
            from ..config import SteptraceConfig

            stc = (
                steptrace if isinstance(steptrace, SteptraceConfig)
                else _parse_dc(SteptraceConfig, steptrace)
            )
            stc.validate()
            if stc.enabled:
                from ..profiling import steptrace as _steptrace

                self.tracer = _steptrace.configure(max_spans=stc.max_spans)
                self._serve_tracer = _steptrace.ServeTracer(self.tracer)
                self.metrics.tracer = self._serve_tracer
                self._steptrace_export_path = stc.export_path
        # ---- healthwatch (profiling/healthwatch.py; None = the zero-
        # overhead path: no ring buffer, no watchdog taps, no spans).
        # Enabling it implies tracing — goodput buckets classify off the
        # serve/* spans — so a missing steptrace section turns one on. --
        self.healthwatch = None
        if healthwatch is not None:
            from ..config import HealthwatchConfig

            hwc = (
                healthwatch if isinstance(healthwatch, HealthwatchConfig)
                else _parse_dc(HealthwatchConfig, healthwatch)
            )
            hwc.validate()
            if hwc.enabled:
                from ..profiling import healthwatch as _healthwatch
                from ..profiling import steptrace as _steptrace

                if self.tracer is None:
                    self.tracer = _steptrace.configure()
                    self._serve_tracer = _steptrace.ServeTracer(self.tracer)
                    self.metrics.tracer = self._serve_tracer
                self.healthwatch = _healthwatch.HealthWatch(
                    hwc, self.tracer, source="serve",
                    context={"config": {"serving": {
                        "max_slots": N, "token_budget": W,
                        "paged": self.paged,
                        "queue_limit": int(serving.queue_limit),
                        "max_tokens": int(self.max_tokens),
                        "spec_max_draft": int(self.max_draft),
                    }}},
                )
                self.metrics.healthwatch = self.healthwatch
        self.scheduler = Scheduler(
            max_slots=N,
            token_budget=W,
            queue_limit=serving.queue_limit,
            request_timeout_s=serving.request_timeout_s,
            eviction_backoff_s=serving.eviction_backoff_s,
            max_tokens=self.max_tokens,
            clock=clock,
            metrics=self.metrics,
            page_size=self.page_size if self.paged else None,
            num_pages=self.num_pages if self.paged else None,
            pages_per_slot=self.pages_per_slot if self.paged else None,
            prefix_cache=bool(serving.prefix_cache) if self.paged else False,
            spec_max_draft=self.max_draft,
            spec_ngram_n=self.spec_ngram_n,
            spiller=self._spiller,
        )

        # ---- the KV arena (contiguous slots, or a paged pool) ----------
        if self.paged:
            caches = init_paged_cache(
                self.config, self.num_pages, self.page_size,
                engine.kv_cache_storage_dtype,
                quantized=engine.kv_cache_quantized,
            )
        else:
            caches = init_cache(
                self.config, N, self.capacity, engine.kv_cache_storage_dtype,
                quantized=engine.kv_cache_quantized,
            )
        seen = jnp.zeros((N, self.config.vocab_size), jnp.bool_)
        self._cache_shardings = None
        if self.topology.world_size > 1:
            mesh = self.topology.mesh
            self._cache_shardings = {
                k: NamedSharding(mesh, spec)
                for k, spec in cache_partition_specs(
                    engine.kv_cache_quantized
                ).items()
            }
            caches = jax.device_put(caches, self._cache_shardings)
            seen = jax.device_put(seen, NamedSharding(mesh, P()))
        else:
            caches = jax.device_put(caches, self.topology.devices[0])
            seen = jax.device_put(seen, self.topology.devices[0])
        self._caches = caches
        self._seen = seen
        # tiered: the rotating in-step staging buffer (the PR-1 double-
        # buffer carry): TWO numpy fills alternate so the buffer the
        # device may still be copying from is never the one the next
        # step's promotions decode into; a zero twin serves idle steps.
        # Pool-leaf shapes with the page axis narrowed to STAGE_SLOTS.
        self._stage_idx = 0
        self._stage_np = None
        self._stage_zero_np = None
        if self.tiered:
            def stage_like():
                return {
                    k: np.zeros(
                        (v.shape[0], STAGE_SLOTS) + tuple(v.shape[2:]),
                        dtype=v.dtype,
                    )
                    for k, v in self._caches.items()
                }

            self._stage_np = [stage_like(), stage_like()]
            self._stage_zero_np = stage_like()
            self._stage_dst_null = np.full(
                STAGE_SLOTS, self.null_page, np.int32
            )

        if self.paged:
            step_fn = make_paged_step_fn(
                self.config, self.dtype, self.config.vocab_size,
                cache_shardings=self._cache_shardings,
                max_draft=self.max_draft, tiered=self.tiered,
            )
        else:
            step_fn = make_step_fn(
                self.config, self.dtype, self.config.vocab_size,
                cache_shardings=self._cache_shardings,
                max_draft=self.max_draft,
            )
        # the recompile counter: a trace-time side effect fires once per
        # XLA compile — the zero-recompiles-after-warmup assertion
        self.step_traces = 0

        def counting_step(*args):
            self.step_traces += 1
            return step_fn(*args)

        self._step = jax.jit(counting_step, donate_argnums=(1, 2))
        # lazily-jitted fleet-handoff page scatter (pool donated; one
        # compile per distinct transferred-page count, bounded by
        # pages_per_slot)
        self._import_pages_fn = None
        # static per-step wire bytes of the expert exchange (0 without an
        # ep axis) — fed to the metrics counters and declared as the
        # moe_decode_a2a analytic stream (R8 prices it)
        self._moe_a2a_step_bytes = 0
        stream = moe_decode_stream(
            self.config, self.topology, W,
            jnp.dtype(self.dtype).itemsize, self.moe_a2a_form,
        )
        if stream:
            self._moe_a2a_step_bytes = int(stream["bytes_per_step"])
        arena = (
            f"pages={self.num_pages}x{self.page_size}tok "
            f"({self.pages_per_slot}/slot)"
            + (
                f" +host={self.host_pages}@{serving.spill_codec}"
                + ("+nvme" if serving.spill_dir else "")
                if self.tiered else ""
            )
            if self.paged else f"capacity={self.capacity}/slot"
        )
        log_dist(
            f"ServingEngine{f'[{name}]' if name else ''}: "
            f"slots={N}, token_budget={W}, {arena}, kv="
            f"{'int8' if engine.kv_cache_quantized else jnp.dtype(engine.kv_cache_storage_dtype).name}, "
            f"tp={self.topology.tp_size}, spec="
            f"{f'ngram(k<={self.max_draft})' if self.max_draft else 'off'}"
            + (
                f", moe=ep{self.moe_ep}/{self.moe_a2a_form}"
                if self.moe_serving else ""
            )
        )
        if self.healthwatch is not None:
            # price comm-exposed goodput off the declared streams (only
            # unoverlapped ici/offload kinds count — the KV arena's hbm
            # stream IS the step's compute traffic, not exposed wire)
            self.healthwatch.set_comm_estimate_from_streams(
                self.analytic_streams()
            )

    # ------------------------------------------------------------- intake
    def submit(self, request: Request) -> RequestState:
        return self.scheduler.submit(request)

    # ------------------------------------------------------------- stepping
    def step(self) -> List[RequestState]:
        """One scheduler plan + one jitted device step. Returns requests
        that FINISHED this step (their slots already recycled)."""
        hw = self.healthwatch
        if hw is None:
            return self._step_inner()
        hw.on_step_start()
        traces_before = self.step_traces
        steps_before = self.metrics.steps
        finished = self._step_inner()
        if self.metrics.steps > steps_before:
            # a device step actually ran (idle ticks accrue as idle)
            hw.on_serve_step(
                step=self.metrics.steps, metrics=self.metrics,
                compiled=self.step_traces - traces_before,
            )
        return finished

    def _step_inner(self) -> List[RequestState]:
        tr = self.tracer
        if tr is None:
            plan = self.scheduler.plan()
            if plan is None:
                return []
            return self._run_plan(plan)
        # traced step: serve/step parent; serve/plan, serve/dispatch,
        # serve/device, serve/complete children cover the whole of it
        # (tools/trace_report.py --validate checks the coverage)
        step_args = {"step": self.metrics.steps + 1}
        if self.name is not None:
            step_args["replica"] = self.name
        step_sp = tr.begin("serve/step", "serve", step_args)
        plan_sp = tr.begin("serve/plan", "serve")
        plan = self.scheduler.plan()
        if plan is None:
            # idle tick: no device step ran — drop BOTH spans (an orphan
            # serve/plan with no parent step would skew the phase table)
            plan_sp.cancel()
            step_sp.cancel()
            return []
        plan_sp.end()
        step_sp.annotate(scheduled_tokens=int(plan.total_tokens))
        if plan.spec_len is not None and plan.spec_len.any():
            # spec observability: how many of this step's budget rows are
            # draft (verify-window) rows — trace_report shows it per step
            step_sp.annotate(spec_draft_tokens=int(plan.spec_len.sum()))
        try:
            return self._run_plan(plan)
        finally:
            step_sp.end()

    def _run_plan(self, plan: StepPlan) -> List[RequestState]:
        tr = self.tracer
        # dispatch span covers host-side array staging (the per-slot
        # numpy fills below, including jnp uploads) + the jit call; the
        # device span then FENCES on the outputs, so compile time lands
        # in dispatch (the first-step TTFT spike is visible as such) and
        # device wait time in device
        dispatch_sp = tr.begin("serve/dispatch", "serve") if tr else None
        N = self.max_slots
        temp = np.zeros(N, np.float32)
        top_k = np.zeros(N, np.int32)
        top_p = np.ones(N, np.float32)
        penalty = np.ones(N, np.float32)
        eos = np.full(N, -1, np.int32)
        rng = np.zeros((N, 2), np.uint32)
        for w in plan.work:
            req = w.state.request
            temp[w.slot] = req.temperature
            top_k[w.slot] = req.top_k
            top_p[w.slot] = req.top_p
            penalty[w.slot] = req.repetition_penalty
            eos[w.slot] = req.eos_token_id
            rng[w.slot] = np.asarray(w.state.rng, np.uint32)
        spec_len = (
            plan.spec_len if plan.spec_len is not None
            else np.zeros(N, np.int32)
        )
        if self.paged:
            # idle rows need no dead-tail repoint: the scheduler hands
            # them an all-NULL page-table row, so their padded W-wide
            # writes land in the NULL sink page by construction
            start_pos = plan.start_pos
            paged_args = (jnp.asarray(plan.page_table),
                          jnp.asarray(plan.cow_src))
            if self.tiered:
                paged_args += self._stage_args(plan)
        else:
            # rows the plan left idle (num_new == 0) still get a W-wide
            # padded cache write — repoint it at the DEAD TAIL margin
            # [capacity - W, capacity), which by construction never holds
            # live tokens (frontiers stop at max_tokens <= capacity - W).
            # Without this, an idle ACTIVE slot's row would write garbage
            # at its plan-default start_pos of 0, clobbering cached prompt
            # K/V the moment a scheduling policy ever skips a live slot.
            start_pos = np.where(
                plan.num_new > 0, plan.start_pos,
                self.capacity - self.token_budget,
            ).astype(np.int32)
            paged_args = ()
        traces_before = self.step_traces
        from ..parallel.a2a_overlap import a2a_scope

        moe_stats = None
        with use_topology(self.topology), self.engine._impl_ctx(), \
                a2a_scope(self._a2a_cfg):
            outs = self._step(
                self.engine.params, self._caches, self._seen,
                jnp.asarray(plan.tokens), jnp.asarray(plan.num_new),
                jnp.asarray(start_pos), *paged_args,
                jnp.asarray(plan.fresh), jnp.asarray(plan.sample),
                jnp.asarray(spec_len), jnp.asarray(eos),
                jnp.asarray(rng), jnp.asarray(temp), jnp.asarray(top_k),
                jnp.asarray(top_p), jnp.asarray(penalty),
            )
        if self.moe_serving:
            caches, seen, out_tok, n_emit, new_rng, moe_stats = outs
        else:
            caches, seen, out_tok, n_emit, new_rng = outs
        if dispatch_sp is not None:
            dispatch_sp.annotate(traced=self.step_traces - traces_before)
            dispatch_sp.end()
            device_sp = tr.begin("serve/device", "serve")
            device_sp.end(fence=out_tok)
            # prompt chunks fed this step become request-scoped spans
            # covering the dispatch+device window (statuses read BEFORE
            # complete() advances them)
            for w in plan.work:
                if w.n_tokens > 0 and \
                        w.state.status is RequestStatus.PREFILL:
                    self._serve_tracer.on_chunk(
                        w.state, w.n_tokens, dispatch_sp.t0, device_sp.t1
                    )
            complete_sp = tr.begin("serve/complete", "serve")
        self._caches, self._seen = caches, seen
        finished = self.scheduler.complete(
            plan, np.asarray(out_tok), np.asarray(new_rng),
            n_emit=np.asarray(n_emit),
        )
        self.metrics.on_step()
        if moe_stats is not None:
            # expert load-balance counters (ISSUE 14 satellite): the step
            # already computed them on device — one tiny [E] transfer
            self.metrics.on_moe(
                np.asarray(moe_stats["tokens_per_expert"]),
                float(moe_stats["drop_fraction"]),
                a2a_bytes=self._moe_a2a_step_bytes,
            )
        if self.comm_logger is not None:
            self.comm_logger.record_streams(self.analytic_streams())
        if tr is not None:
            complete_sp.end()
        return finished

    def _stage_args(self, plan: StepPlan) -> tuple:
        """Decode this step's promotions into the rotating staging buffer
        (host side) and return the ``(stage_kv, stage_dst)`` step args.
        An idle step reuses the zero twin and the all-NULL destination
        vector — same shapes, same dtypes, zero recompiles. The wall
        time spent here is the page-in STALL (the host-side slice NOT
        hidden under device math); the H2D upload + scatter themselves
        ride under the step."""
        if not plan.stage:
            return (self._stage_zero_np, self._stage_dst_null)
        tr = self.tracer
        page_in_sp = tr.begin("serve/page_in", "serve") if tr else None
        t0 = self.clock()
        # rotate: the buffer filled LAST step may still be feeding an
        # in-flight H2D copy — fill the other one (the PR-1 two-
        # generation discipline, host side)
        bufs = self._stage_np[self._stage_idx]
        self._stage_idx ^= 1
        stage_dst = np.full(STAGE_SLOTS, self.null_page, np.int32)
        at_rest = 0
        for i, s in enumerate(plan.stage):
            leaves, nbytes = self._spiller.load(s.key)
            at_rest += nbytes
            stage_dst[i] = s.dst_page
            for name, arr in leaves.items():
                bufs[name][:, i] = arr[:, 0]
        stall = self.clock() - t0
        self.metrics.on_page_in(
            pages=len(plan.stage), nbytes=at_rest, stall_s=stall,
        )
        if page_in_sp is not None:
            page_in_sp.annotate(pages=len(plan.stage),
                                at_rest_bytes=int(at_rest))
            page_in_sp.end()
        return (bufs, stage_dst)

    # ------------------------------------------------- fleet KV handoff
    def export_kv_pages(self, page_ids) -> Dict[str, Any]:
        """Snapshot the payload of physical ``page_ids`` out of this
        replica's paged pool (serving/paging.py export_pages) — the
        prefill half of the fleet's prefill→decode handoff."""
        from .paging import export_pages

        if not self.paged:
            raise RuntimeError(
                "export_kv_pages needs the paged arena (serving.paged) — "
                "the fleet KV handoff is a page transfer"
            )
        return export_pages(self._caches, page_ids)

    def import_kv_pages(self, payload: Dict[str, Any], dst_page_ids
                        ) -> None:
        """Scatter an exported payload into ``dst_page_ids`` of this
        replica's pool. The scatter runs jitted with the pool DONATED,
        so the update happens in place — O(pages moved), never an
        O(arena) copy per handoff — and the result keeps exactly the
        sharding the step compiled against (donated-buffer reuse), so
        the import never buys a step recompile (the fleet oracle
        asserts ``step_traces == 1`` per replica)."""
        from .paging import check_page_payload, scatter_pages

        if not self.paged:
            raise RuntimeError(
                "import_kv_pages needs the paged arena (serving.paged)"
            )
        ids = np.asarray(dst_page_ids, np.int32)
        check_page_payload(self._caches, payload, ids.size)
        if self._import_pages_fn is None:
            self._import_pages_fn = jax.jit(
                scatter_pages, donate_argnums=(0,)
            )
        caches = self._import_pages_fn(
            self._caches, payload, jnp.asarray(ids)
        )
        if self._cache_shardings is not None:
            # re-assert the tp sharding the step compiled against: the
            # donated scatter USUALLY reuses the input buffers (keeping
            # their placement), but nothing pins its output sharding —
            # and a drifted carry would buy a step recompile. device_put
            # onto an identical sharding is a no-op, so the in-place win
            # survives whenever the layout did.
            caches = jax.device_put(caches, self._cache_shardings)
        self._caches = caches

    def run_until_idle(self, max_steps: int = 100_000
                       ) -> List[RequestState]:
        """Drain queue + slots; returns every request finished on the way
        (DONE order). Timed-out requests surface through their states."""
        finished: List[RequestState] = []
        steps = 0
        while self.scheduler.has_work:
            if steps >= max_steps:
                raise RuntimeError(
                    f"serving did not drain within {max_steps} steps"
                )
            finished.extend(self.step())
            steps += 1
        return finished

    # --------------------------------------------------------- steptrace
    def trace_export(self, path: Optional[str] = None) -> str:
        """Write the Chrome trace-event JSON (Perfetto-loadable). Before
        exporting, every declared ``analytic_streams()`` stream is added
        as a ``plan/<name>`` span carrying its shardplan-predicted
        bytes/seconds next to the measured average step wall clock —
        the per-component drift view. Load with ``tools/trace_report.py``
        for the per-phase table and schema validation."""
        if self.tracer is None:
            raise RuntimeError(
                "steptrace is not enabled on this ServingEngine — pass "
                'steptrace={"enabled": True} (or set the "steptrace" '
                "config section) at construction"
            )
        measured = self.tracer.mean_dur("serve/step")
        for name, stream in self.analytic_streams().items():
            self.tracer.plan_span(name, stream, measured_step_s=measured)
        path = path or self._steptrace_export_path or "steptrace_serve.json"
        out = self.tracer.export(path)
        log_dist(f"steptrace: wrote {out}")
        return out

    # --------------------------------------------------- planner metadata
    def analytic_streams(self, include_potential: bool = False
                         ) -> Dict[str, Any]:
        """Shared analytic-streams schema (comm_logger.record_streams /
        cost planner / rule R8): the per-step KV arena traffic, plus the
        inner engine's declared TP ring when overlap_comm serves."""
        streams = dict(self.engine.analytic_streams(
            batch=self.max_slots, seq=self.token_budget,
            include_potential=include_potential,
        ))
        if self.paged:
            streams["kv_cache"] = paged_kv_stream(
                self.config, self.num_pages, self.page_size,
                self.max_slots, self.pages_per_slot, self.token_budget,
                jnp.dtype(self.engine.kv_cache_storage_dtype).itemsize,
                self.engine.kv_cache_quantized,
                tp=self.topology.tp_size,
            )
        else:
            streams["kv_cache"] = serving_kv_stream(
                self.config, self.max_slots, self.capacity,
                jnp.dtype(self.engine.kv_cache_storage_dtype).itemsize,
                self.engine.kv_cache_quantized,
                tp=self.topology.tp_size,
            )
        if self.tiered:
            # the host-tier page traffic (demotions out + staged
            # promotions in, codec at-rest widths) — declared overlapped
            # on the host link so R8/R13 budget it against the step
            streams["kv_spill"] = kv_spill_stream(
                self.config, self.page_size, self.host_pages,
                self.serving.spill_codec,
                self.engine.kv_cache_quantized,
                tp=self.topology.tp_size,
            )
        if self.max_draft > 0:
            # the verify-window bytes spec adds on top of the arena
            # traffic — declared so shardplan R8 prices spec statically
            streams["spec_verify"] = spec_verify_stream(
                self.config, self.max_slots, self.max_draft,
                jnp.dtype(self.engine.kv_cache_storage_dtype).itemsize,
                self.engine.kv_cache_quantized,
                tp=self.topology.tp_size,
            )
        # the decode-shaped expert exchange (combine ride): the stock
        # form moves it as one all-gather (exposed), the chunked form as
        # ppermute hops declared overlapped — R8 statically checks the
        # hops fit the compute window
        moe_stream = moe_decode_stream(
            self.config, self.topology, self.token_budget,
            jnp.dtype(self.dtype).itemsize, self.moe_a2a_form,
        )
        if moe_stream:
            streams["moe_decode_a2a"] = moe_stream
        return streams

    def parity_pairs(self):
        """The declared-bitwise form pairs of this engine's slot step
        (analysis/parity.py — the static half of the replay oracles):
        paged vs contiguous always, moe_a2a stock vs chunked when the
        ring can actually run. Each pair's thunks re-trace the step
        abstractly; ``tools/paritycheck.py`` proves them all."""
        import dataclasses

        from ..analysis.parity import config_parity_pairs

        srv = dataclasses.asdict(self.serving)
        srv.pop("fleet", None)
        raw = {
            "serving": dict(srv, enabled=True),
            "tensor_parallel": {"tp_size": self.topology.tp_size},
            "bf16": {"enabled": jnp.dtype(self.dtype) == jnp.bfloat16},
        }
        if self.moe_ep > 1:
            raw["moe"] = {"enabled": True, "ep_size": self.moe_ep,
                          "num_experts": self.config.num_experts}
        return config_parity_pairs(raw, self.engine.model)


# ----------------------------------------------------------- lint surface
def trace_serving_step(model, ds_config, topology: Optional[MeshTopology]
                       = None):
    """Abstract serving-step trace for shardlint: (closed_jaxpr,
    arg_shardings, streams, meta). Nothing materializes — params and the
    KV arena are ShapeDtypeStructs carrying the real shardings, so the
    R1–R11 registry (and the cost planner) see exactly the program the
    serving engine would compile.

    ``meta`` carries the trace-stability evidence rule R11 consumes:
    ``traced_manifest`` (argument name → flat invar index range) and
    ``required_traced`` — the per-tick host-state vectors (slot
    occupancy, frontiers, spec_len, page tables, cow_src, per-slot
    keys) that MUST be traced, never baked, for ``step_traces == 1`` to
    hold across arbitrary arrival patterns."""
    from ..config import DeepSpeedConfig

    cfg = (
        ds_config if isinstance(ds_config, DeepSpeedConfig)
        else DeepSpeedConfig(ds_config)
    )
    srv = cfg.serving
    tp = max(int(cfg.tensor_parallel.tp_size), 1)
    mcfg = model.config
    # same "auto" resolution the live engine applies — the linted program
    # and the served program must read identical knob values
    from ..config import resolve_auto_knobs

    resolve_auto_knobs(cfg, model_config=mcfg, topology=topology)
    # MoE serving configs lint on the ep mesh they would serve on: the
    # expert exchange only exists in the traced program when the ep axis
    # does (serving_ep_size — the ONE moe.ep_size clamp)
    ep = serving_ep_size(cfg.moe, mcfg)
    if topology is None:
        topology = MeshTopology(
            dims=ParallelDims(tp=tp, ep=ep),
            devices=jax.devices()[:tp * ep],
        )
    mesh = topology.mesh
    dtype = cfg.compute_dtype
    quantized = srv.kv_cache_dtype == "int8"
    storage = jnp.bfloat16 if srv.kv_cache_dtype in ("bf16", "bfloat16") \
        else dtype
    N, W = int(srv.max_slots), int(srv.token_budget)
    V = mcfg.vocab_size
    max_tokens = min(int(srv.max_tokens), mcfg.max_seq_len)
    capacity = _align_cache(max_tokens + W)
    max_draft = (
        int(srv.spec.max_draft) if getattr(srv.spec, "enabled", False) else 0
    )

    sharded = topology.world_size > 1 and hasattr(model, "partition_specs")

    def sds(shape, dt, spec=None):
        sharding = (
            NamedSharding(mesh, spec) if sharded and spec is not None else None
        )
        return jax.ShapeDtypeStruct(shape, dt, sharding=sharding)

    params_shape = jax.eval_shape(
        lambda k: model.init(k, dtype=dtype), jax.random.PRNGKey(0)
    )
    if sharded:
        tp_specs = model.partition_specs(topology)
        params = jax.tree.map(
            lambda spec, leaf: sds(leaf.shape, leaf.dtype, spec),
            tp_specs, params_shape,
            is_leaf=lambda x: isinstance(x, P),
        )
    else:
        params = jax.tree.map(
            lambda leaf: sds(leaf.shape, leaf.dtype), params_shape
        )
    paged = bool(srv.paged)
    if paged:
        from ..config import DeepSpeedConfigError

        page_size = int(srv.page_size)
        pages_per_slot = srv.pages_per_slot(max_tokens)
        num_pages = int(srv.num_pages) or N * pages_per_slot
        if num_pages < pages_per_slot:
            raise DeepSpeedConfigError(
                f"serving.num_pages {num_pages} is below the liveness "
                f"floor {pages_per_slot} for this model's clamped "
                "max_tokens; one request could never finish"
            )
        cache_shape = init_paged_cache(
            mcfg, num_pages, page_size, storage, quantized=quantized
        )
    else:
        cache_shape = init_cache(
            mcfg, N, capacity, storage, quantized=quantized
        )
    cache_specs = cache_partition_specs(quantized)
    caches = {
        k: sds(v.shape, v.dtype, cache_specs[k])
        for k, v in cache_shape.items()
    }
    cache_shardings = (
        {k: NamedSharding(mesh, cache_specs[k]) for k in cache_shape}
        if sharded else None
    )
    tiered = paged and int(getattr(srv, "host_pages", 0) or 0) > 0
    paged_args = (
        (
            ("page_table", sds((N, pages_per_slot), jnp.int32, P())),
            ("cow_src", sds((N,), jnp.int32, P())),
        )
        if paged else ()
    )
    if tiered:
        # the host-tier staging pair (serving.host_pages > 0): pool-leaf
        # shapes with the page axis narrowed to STAGE_SLOTS, sharded
        # like the pool so the linted program is the served program
        paged_args += (
            ("stage_kv", {
                k: sds((v.shape[0], STAGE_SLOTS) + tuple(v.shape[2:]),
                       v.dtype, cache_specs[k])
                for k, v in cache_shape.items()
            }),
            ("stage_dst", sds((STAGE_SLOTS,), jnp.int32, P())),
        )
    named_args = (
        ("params", params),
        ("caches", caches),
        ("seen", sds((N, V), jnp.bool_, P())),
        ("tokens", sds((N, W), jnp.int32, P())),
        ("num_new", sds((N,), jnp.int32, P())),
        ("start_pos", sds((N,), jnp.int32, P())),
        *paged_args,
        ("fresh", sds((N,), jnp.bool_, P())),
        ("sample_flag", sds((N,), jnp.bool_, P())),
        ("spec_len", sds((N,), jnp.int32, P())),
        ("eos_id", sds((N,), jnp.int32, P())),
        ("rng", sds((N, 2), jnp.uint32, P())),
        ("temperature", sds((N,), jnp.float32, P())),
        ("top_k", sds((N,), jnp.int32, P())),
        ("top_p", sds((N,), jnp.float32, P())),
        ("rep_penalty", sds((N,), jnp.float32, P())),
    )
    args = tuple(v for _, v in named_args)
    if paged:
        step_fn = make_paged_step_fn(
            mcfg, dtype, V, cache_shardings=cache_shardings,
            max_draft=max_draft, tiered=tiered,
        )
    else:
        step_fn = make_step_fn(mcfg, dtype, V,
                               cache_shardings=cache_shardings,
                               max_draft=max_draft)
    # the traced program IS the served program: resolve the expert-
    # exchange form exactly like ServingEngine.__init__ and enter the
    # scope around the trace (R3 then lints the ring's perms when the
    # chunked form is resolved)
    moe_form = resolve_moe_a2a_form(
        srv.moe_a2a, mcfg, topology, W, jnp.dtype(dtype).itemsize,
        max_slots=N,
    )
    a2a_cfg = (
        moe_a2a_scope_cfg(moe_form)
        if getattr(mcfg, "is_moe", False) else None
    )
    from ..parallel.a2a_overlap import a2a_scope
    with use_topology(topology), a2a_scope(a2a_cfg):
        closed = jax.make_jaxpr(step_fn)(*args)
    flat = jax.tree_util.tree_leaves(args)
    invars = list(closed.jaxpr.invars)
    arg_shardings = {}
    if len(flat) == len(invars):
        for v, leaf in zip(invars, flat):
            s = getattr(leaf, "sharding", None)
            if s is not None:
                arg_shardings[v] = s
    if paged:
        streams = {
            "kv_cache": paged_kv_stream(
                mcfg, num_pages, page_size, N, pages_per_slot, W,
                jnp.dtype(storage).itemsize, quantized, tp=tp,
            )
        }
    else:
        streams = {
            "kv_cache": serving_kv_stream(
                mcfg, N, capacity, jnp.dtype(storage).itemsize, quantized,
                tp=tp,
            )
        }
    if paged and tiered:
        streams["kv_spill"] = kv_spill_stream(
            mcfg, page_size, int(srv.host_pages), srv.spill_codec,
            quantized, tp=tp,
        )
    if max_draft > 0:
        streams["spec_verify"] = spec_verify_stream(
            mcfg, N, max_draft, jnp.dtype(storage).itemsize, quantized,
            tp=tp,
        )
    moe_stream = moe_decode_stream(
        mcfg, topology, W, jnp.dtype(dtype).itemsize, moe_form,
    )
    if moe_stream:
        streams["moe_decode_a2a"] = moe_stream
    # R11 evidence: argument name → flat invar range, plus the per-tick
    # host-state names the slot engine's ONE-trace contract hinges on
    manifest, lo = {}, 0
    for arg_name, leaf_tree in named_args:
        n = len(jax.tree_util.tree_leaves(leaf_tree))
        manifest[arg_name] = (lo, lo + n)
        lo += n
    required = [
        "tokens", "num_new", "start_pos", "fresh", "sample_flag",
        "spec_len", "eos_id", "rng",
    ]
    if paged:
        required += ["page_table", "cow_src"]
    if tiered:
        # which pages promote varies per tick — baking stage_dst would
        # recompile on every distinct promotion mix (R11)
        required += ["stage_dst"]
    meta = {
        "traced_manifest": manifest if lo == len(invars) else {},
        "required_traced": tuple(required) if lo == len(invars) else (),
        "moe_a2a_form": moe_form,
    }
    return closed, arg_shardings, streams, meta
