"""Test-only fault injection seams for the host control plane.

The fleetcheck model checker (analysis/modelcheck/) proves the
scheduler/paging/fleet invariants over small exhaustive state spaces —
but a prover is only trustworthy if it FINDS bugs when they exist. This
module is the seeded-bug corpus seam (the paritycheck ``--mutate``
pattern lifted to the host plane): production code consults
:func:`armed` at the exact sites where a historical (or representative)
bug lived, and re-introduces the bug ONLY while a test/CLI has armed it.

Nothing here is reachable from configuration; the armed set is
process-local, empty by default, and every consumer treats "not armed"
as the zero-cost fast path (one set-membership test).

Known faults
------------
``promotion_unsticky``
    Re-introduces the PR 18 promotion livelock: the tiered-KV promotion
    planner loses its stickiness guard — no sticky ``_promote_focus``,
    and promotion allocations run with ``stalled_only=False`` so feeding
    a waiter may demote a resident (runnable) slot. Under
    oversubscription (4 slots x 4 pages over an 8-page pool) the fleet
    thrashes pages in and out every tick with zero tokens emitted.

``handoff_leak``
    Breaks the prefill->decode handoff rollback contract: destination
    pages are allocated one-by-one straight from the pool and NOT
    returned on a deferred transfer — a failed handoff leaks refcount-1
    pages that no slot or cache references (and skips the page-invariant
    asserts that would catch it locally, which is exactly why fleetcheck
    must catch it checker-side).
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import FrozenSet, Iterator, Set

KNOWN_FAULTS: FrozenSet[str] = frozenset({
    "promotion_unsticky",
    "handoff_leak",
})

_ARMED: Set[str] = set()


def armed(name: str) -> bool:
    """Is fault ``name`` currently armed? (The production-path check —
    one set lookup, False unless a test armed it.)"""
    return name in _ARMED


def arm(name: str) -> None:
    if name not in KNOWN_FAULTS:
        raise ValueError(
            f"unknown fault {name!r} (known: {sorted(KNOWN_FAULTS)})"
        )
    _ARMED.add(name)


def disarm(name: str) -> None:
    _ARMED.discard(name)


def disarm_all() -> None:
    _ARMED.clear()


@contextmanager
def arming(*names: str) -> Iterator[None]:
    """Arm ``names`` for the duration of a with-block, restoring the
    previous armed set on exit (exception-safe — a failing check must
    not leak an armed fault into later tests)."""
    prev = set(_ARMED)
    try:
        for n in names:
            arm(n)
        yield
    finally:
        _ARMED.clear()
        _ARMED.update(prev)
