"""Replica handle: one ServingEngine behind the fleet router.

A thin identity + load wrapper — the engine keeps owning its scheduler,
arena and metrics; the handle adds the fleet-level facts the router
needs (role, load, step timing) without reaching into engine internals
from routing code.
"""

from __future__ import annotations

import time
from typing import List, Tuple

from ..engine import ServingEngine
from ..request import RequestState, RequestStatus

ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_MIXED = "mixed"


class ReplicaHandle:
    def __init__(self, replica_id: int, engine: ServingEngine,
                 role: str = ROLE_MIXED):
        if role not in (ROLE_PREFILL, ROLE_DECODE, ROLE_MIXED):
            raise ValueError(f"unknown replica role {role!r}")
        self.replica_id = int(replica_id)
        self.engine = engine
        self.role = role

    # ----------------------------------------------------------- load
    @property
    def queue_depth(self) -> int:
        return len(self.engine.scheduler.queue)

    @property
    def active(self) -> int:
        return self.engine.scheduler.active_count

    @property
    def load(self) -> int:
        """Queued + in-flight: the router's least-loaded ordering key."""
        return self.queue_depth + self.active

    @property
    def has_free_slot(self) -> bool:
        return bool(self.engine.scheduler._free)

    @property
    def has_work(self) -> bool:
        return self.engine.scheduler.has_work

    # ------------------------------------------------------- stepping
    def step(self) -> Tuple[List[RequestState], float]:
        """One engine step; returns (finished, wall_seconds). The wall
        time feeds the bench's parallel-replica virtual clock (replicas
        are data-parallel — a real deployment runs them concurrently, so
        a fleet tick costs max over replicas, not the sum)."""
        t0 = time.perf_counter()
        finished = self.engine.step()
        return finished, time.perf_counter() - t0

    # ------------------------------------------------------- handoff
    def decode_candidates(self) -> List[RequestState]:
        """In-flight requests this PREFILL replica has finished
        prefilling (status DECODE: the final prompt feed sampled their
        first token) that are eligible to move to a decode replica.
        Requests with a repetition penalty stay: their ``seen`` matrix is
        rebuilt from FED tokens only, which a handoff would truncate —
        correctness over placement, the same rule as the prefix-cache and
        spec bypasses."""
        out = []
        for st in self.engine.scheduler.slots:
            if st is None or st.status is not RequestStatus.DECODE:
                continue
            if st.request.repetition_penalty != 1.0:
                continue
            out.append(st)
        return out

    def __repr__(self) -> str:
        return (f"ReplicaHandle(r{self.replica_id}, {self.role}, "
                f"load={self.load})")
