"""Fleet: a disaggregated, replicated serving tier (ISSUE 13).

A :class:`Router` over N data-parallel ServingEngine replicas — fleet
admission + load shedding, prefix-cache-aware routing over the
chained-crc32 block keys (:class:`GlobalPrefixIndex`), session affinity,
and DistServe-style prefill/decode disaggregation whose KV handoff is a
page transfer (:func:`handoff`). See docs/serving.md "Fleet".
"""

from .handoff import handoff, pages_needed
from .index import HOST_TIER_WEIGHT, GlobalPrefixIndex
from .replica import (ROLE_DECODE, ROLE_MIXED, ROLE_PREFILL,
                      ReplicaHandle)
from .router import Router

__all__ = [
    "GlobalPrefixIndex",
    "HOST_TIER_WEIGHT",
    "ROLE_DECODE",
    "ROLE_MIXED",
    "ROLE_PREFILL",
    "ReplicaHandle",
    "Router",
    "handoff",
    "pages_needed",
]
