"""Prefill→decode KV handoff: a page transfer, not a tensor reshape.

DistServe / DeepSpeed-MII-style disaggregation on top of the block-paged
arena (serving/paging.py): when a dedicated prefill replica finishes a
request's prefill (the final prompt feed sampled its first token), the
slot's KV moves to a decode replica as

  1. a **page-table read** — the logical pages covering the written
     frontier (prompt + generated-but-last; the newest sampled token was
     never fed, so its KV does not exist yet),
  2. a **page-payload transfer** — ``export_pages`` snapshots those
     physical pages out of the prefill pool, ``import_pages`` scatters
     them into pages freshly allocated from the decode pool,
  3. a **state adoption** — the RequestState (tokens, RNG chain,
     draft tail) re-slots on the decode replica and continues decoding
     exactly where a single-replica run would.

Invariant (asserted after EVERY transfer, success or deferral): both
pools satisfy ``free + live == num_pages`` and per-page refcounts match
their holders. Deferral is graceful — when the destination lacks a free
slot or enough pages (after LRU prefix-cache eviction), the request
simply keeps decoding on the prefill replica; the router retries next
tick. Determinism never depends on where a request decodes.
"""

from __future__ import annotations

from typing import Optional

from .. import faults
from ..request import RequestState
from .replica import ReplicaHandle


def pages_needed(state: RequestState, page_size: int) -> int:
    """Physical pages covering the written KV frontier: prompt + every
    generated token except the newest (not fed yet, so never written)."""
    frontier = state.prompt_len + max(len(state.tokens) - 1, 0)
    return -(-frontier // int(page_size))


def handoff(state: RequestState, src: ReplicaHandle, dst: ReplicaHandle,
            ) -> Optional[int]:
    """Move one DECODE-status request from ``src`` to ``dst``. Returns
    the pages transferred, or None when the destination cannot take it
    yet (no free slot / page pool exhausted even after LRU eviction) —
    in which case NOTHING changed on either side."""
    src_sched = src.engine.scheduler
    dst_sched = dst.engine.scheduler
    if not (src_sched.paged and dst_sched.paged):
        raise RuntimeError("KV handoff needs paged arenas on both sides")
    if src_sched.page_size != dst_sched.page_size:
        raise RuntimeError(
            f"page_size mismatch across replicas: {src_sched.page_size} "
            f"vs {dst_sched.page_size}"
        )
    if state.slot is None or src_sched.slots[state.slot] is not state:
        raise ValueError("handoff: state is not slotted on src")

    need = pages_needed(state, src_sched.page_size)
    if not dst_sched._free:
        return None
    if faults.armed("handoff_leak"):
        # seeded-bug seam (serving/faults.py): the broken rollback twin
        # fleetcheck's --mutate smoke must catch — pages allocated
        # one-by-one and NOT returned on a deferred transfer, with the
        # local invariant asserts skipped (the leak is only visible to
        # a checker-side conservation test). Never armed outside tests.
        dst_pages = []
        for _ in range(need):
            p = dst_sched.pool.alloc()
            if p is None:
                return None  # leaks every page in dst_pages (refcount 1)
            dst_pages.append(p)
    else:
        dst_pages = dst_sched.alloc_pages(need)
        if dst_pages is None:
            # destination pool exhausted even after LRU eviction: defer.
            # alloc_pages already rolled its partial allocation back, so
            # the invariant holds on both sides — assert it anyway (the
            # leak test forces exactly this path).
            src_sched.assert_page_invariants()
            dst_sched.assert_page_invariants()
            return None

    # payload snapshot BEFORE the src release: the physical ids are about
    # to be decref'd (release may free them into the src pool)
    src_pages = list(state.pages[:need])
    payload = src.engine.export_kv_pages(src_pages)

    # src side: publish the prompt KV to the src prefix cache (future
    # prompts sharing the prefix skip their prefill — and the router's
    # global index learns the chain), then recycle slot + references
    src_sched.release(state.slot, insert_prefix=True)
    state.slot = None

    # dst side: scatter the payload and adopt. The imported pages hold
    # byte-identical KV, the RNG chain rides in the state, and the
    # adopted slot's first feed clears its stale seen row — so decoding
    # continues bitwise where the single-replica replay would.
    dst.engine.import_kv_pages(payload, dst_pages)
    state.pages = list(dst_pages)
    state.owned_from = 0
    dst_sched.adopt(state)

    src_sched.assert_page_invariants()
    dst_sched.assert_page_invariants()
    return need
