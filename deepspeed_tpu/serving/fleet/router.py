"""Fleet router: one admission gate over N ServingEngine replicas.

The millions-of-users tier (ROADMAP item 2, DistServe / DeepSpeed-MII
parity): a :class:`Router` owns fleet-level admission and dispatches
requests across ``serving.fleet.replicas`` data-parallel
:class:`~deepspeed_tpu.serving.engine.ServingEngine` replicas — one
process, shared params, each replica its own scheduler + KV arena +
metrics. Routing is

- **session affinity** first (``Request.session_id`` stickiness — a
  session's prefix reuse stays local),
- then **prefix-aware**: the replica whose PrefixCache holds the longest
  matching block chain, looked up in the
  :class:`~.index.GlobalPrefixIndex` (chained-crc32 keys mirrored from
  replica cache events — no polling, no locks),
- falling back to least-loaded (or round-robin / least-loaded as the
  configured policy).

**Load shedding** lifts the scheduler's bounded-queue semantics to fleet
level: past ``fleet.queue_limit`` total queued (or while the recent
fleet p95 TTFT exceeds ``fleet.shed_ttft_p95_s``) new arrivals are
gracefully EVICTED with the same exponential ``retry_after`` backoff a
replica's own bounded queue hands out. Replicas whose own queue is full
are simply not routed to while any open replica exists.

**Prefill/decode disaggregation** (``fleet.prefill_replicas > 0``):
requests are routed to dedicated prefill replicas; once the final prompt
feed samples a request's first token, the router moves its KV to a
decode replica as a page transfer (serving/fleet/handoff.py) and the
request continues decoding there — bitwise where a single replica would.

The correctness anchor for all of it: ANY routing of a trace replays
token-for-token equal to a single-replica serial replay (deterministic
per-request RNG chains; tests/test_serving_fleet.py), with
``step_traces == 1`` per replica.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ...utils.logging import log_dist
from ..engine import ServingEngine
from ..metrics import FleetMetrics, ServingMetrics, recent_percentile
from ..request import Request, RequestState, RequestStatus
from .handoff import handoff
from .index import GlobalPrefixIndex
from .replica import (ROLE_DECODE, ROLE_MIXED, ROLE_PREFILL, ReplicaHandle)


class Router:
    """Admission + routing + disaggregation over N serving replicas.

    Drive it exactly like a ServingEngine: :meth:`submit` requests,
    :meth:`step` ticks (one routing pass + one step on every replica
    with work), :meth:`run_until_idle` drains. ``clock`` is injectable
    and SHARED by every replica (virtual-clock replays stay coherent)."""

    def __init__(
        self,
        model=None,
        serving=None,
        engine=None,
        clock=time.monotonic,
        comm_logger=None,
        steptrace=None,
        healthwatch=None,
        **engine_kwargs,
    ):
        import dataclasses

        from ...config import (FleetConfig, HealthwatchConfig,
                               ServingConfig, _parse_dc)
        from ...inference.engine import init_inference

        if serving is None:
            serving = ServingConfig()
        elif isinstance(serving, dict):
            serving = _parse_dc(ServingConfig, serving)
        # "auto" knobs resolve ONCE here, before replicas are built —
        # every replica must read the same concrete values (and paged is
        # forced on under prefill/decode disaggregation)
        from ...config import resolve_auto_knobs

        resolve_auto_knobs(
            serving,
            model_config=(getattr(engine, "config", None)
                          if engine is not None
                          else getattr(model, "config", None)),
            topology=getattr(engine, "topology", None),
        )
        serving.validate()
        fleet = serving.fleet
        # constructing a Router IS opting into the fleet: validate the
        # section even when "enabled" was left false in the raw config
        fleet.validate()
        if int(fleet.prefill_replicas) > 0 and not serving.paged:
            from ...config import DeepSpeedConfigError

            raise DeepSpeedConfigError(
                "serving.fleet.prefill_replicas > 0 requires serving."
                "paged: the prefill→decode KV handoff is a page transfer"
            )
        self.serving = serving
        self.fleet = fleet
        self.clock = clock

        # healthwatch implies tracing (goodput classifies off serve/*
        # spans) — resolve the sections BEFORE replicas are built so the
        # replicas land on the shared registry
        hwc = None
        if healthwatch is not None:
            hwc = (
                healthwatch if isinstance(healthwatch, HealthwatchConfig)
                else _parse_dc(HealthwatchConfig, healthwatch)
            )
            hwc.validate()
            if hwc.enabled and steptrace is None:
                steptrace = {"enabled": True}

        # ---- the shared inference engine (params are read-only across
        # replicas; each replica owns its own KV arena + scheduler) -----
        if engine is None:
            if model is None:
                raise ValueError("Router needs a model or an engine")
            if serving.kv_cache_dtype != "auto":
                engine_kwargs.setdefault(
                    "kv_cache_dtype", serving.kv_cache_dtype
                )
            engine_kwargs.setdefault("max_tokens", serving.max_tokens)
            engine = init_inference(model, **engine_kwargs)
        self.engine = engine

        n = int(fleet.replicas)
        k = int(fleet.prefill_replicas)
        self.replicas: List[ReplicaHandle] = []
        for i in range(n):
            role = (
                ROLE_PREFILL if i < k else (ROLE_DECODE if k else ROLE_MIXED)
            )
            # decode replicas never prefill, so a prefix cache there
            # would only hold dead weight against the pool — disable it
            rep_serving = dataclasses.replace(
                serving,
                fleet=FleetConfig(),
                prefix_cache=bool(serving.prefix_cache)
                and role != ROLE_DECODE,
            )
            srv = ServingEngine(
                engine=engine,
                serving=rep_serving,
                clock=clock,
                metrics=ServingMetrics(clock=clock),
                comm_logger=comm_logger,
                steptrace=steptrace,
                name=f"r{i}",
            )
            self.replicas.append(ReplicaHandle(i, srv, role))
        self._intake = [
            r for r in self.replicas
            if r.role in (ROLE_PREFILL, ROLE_MIXED)
        ]
        self._decode = [r for r in self.replicas if r.role == ROLE_DECODE]

        # one ServeTracer across the fleet: a request's span tree crosses
        # replicas on handoff (PREFILL opens on r0, DONE lands on r2) and
        # the open-phase bookkeeping must follow it
        self.tracer = self.replicas[0].engine.tracer
        self._steptrace_export_path = \
            self.replicas[0].engine._steptrace_export_path
        if self.tracer is not None:
            shared = self.replicas[0].engine._serve_tracer
            for r in self.replicas[1:]:
                r.engine._serve_tracer = shared
                r.engine.metrics.tracer = shared

        # ---- the global prefix index (paged + prefix-cache mode) -------
        self.index: Optional[GlobalPrefixIndex] = None
        if serving.paged and serving.prefix_cache:
            self.index = GlobalPrefixIndex(int(serving.page_size))
            for r in self._intake:
                self.index.attach(
                    r.replica_id, r.engine.scheduler.prefix_cache
                )

        self.metrics = FleetMetrics(
            [r.engine.metrics for r in self.replicas], clock=clock
        )
        self._sessions: Dict[str, int] = {}   # session_id -> replica_id
        self._rr = 0                          # round-robin cursor
        self.last_tick_durations: Dict[int, float] = {}
        self.last_tick_overhead_s = 0.0

        # ---- fleet-level healthwatch: the queue/TTFT watchdogs read the
        # AGGREGATED metrics, so breaches are fleet facts ----------------
        self.healthwatch = None
        if hwc is not None and hwc.enabled:
            from ...profiling import healthwatch as _healthwatch

            self.healthwatch = _healthwatch.HealthWatch(
                hwc, self.tracer, source="serve",
                context={"config": {"serving": {
                    "max_slots": int(serving.max_slots),
                    "token_budget": int(serving.token_budget),
                    "paged": bool(serving.paged),
                    "fleet": {
                        "replicas": n, "prefill_replicas": k,
                        "routing": fleet.routing,
                        "affinity": bool(fleet.affinity),
                        "queue_limit": int(fleet.queue_limit),
                    },
                }}},
            )

        log_dist(
            f"fleet Router: {n} replicas ({k} prefill), routing="
            f"{fleet.routing}, affinity={bool(fleet.affinity)}, "
            f"queue_limit={int(fleet.queue_limit) or 'per-replica'}"
        )

    # ------------------------------------------------------------ intake
    def submit(self, request: Request) -> RequestState:
        """Route one request (or shed it gracefully). Always returns the
        state; EVICTED means shed/rejected with ``retry_after`` set."""
        now = self.clock()
        reason = self._shed_reason()
        if reason is not None:
            state = RequestState(request=request, arrival_t=now)
            state.attempts = 1
            return self._shed(state, now, reason)
        rep, via = self._route(request)
        state = rep.engine.submit(request)
        self._record_route(request, rep, via, state)
        return state

    def resubmit(self, state: RequestState) -> RequestState:
        """Retry an evicted request (router-shed or replica-evicted) —
        the fleet twin of Scheduler.resubmit; re-routes from scratch."""
        if state.status is not RequestStatus.EVICTED:
            raise ValueError(
                f"resubmit needs an EVICTED state, got {state.status.value}"
            )
        now = self.clock()
        reason = self._shed_reason()
        if reason is not None:
            state.attempts += 1
            return self._shed(state, now, reason, already_evicted=True)
        rep, via = self._route(state.request)
        out = rep.engine.scheduler.resubmit(state)
        self._record_route(state.request, rep, via, out)
        return out

    def _shed(self, state: RequestState, now: float, reason: str,
              already_evicted: bool = False) -> RequestState:
        """Fleet-level graceful rejection: the scheduler's bounded-queue
        semantics (EVICTED + exponential retry_after) lifted up a tier."""
        if not already_evicted:
            state.transition(RequestStatus.EVICTED)
        state.retry_after = now + float(self.serving.eviction_backoff_s) * (
            2 ** max(state.attempts - 1, 0)
        )
        state.evict_reason = reason
        state.finish_t = now
        self.metrics.on_shed(reason)
        log_dist(f"fleet: shed {state.request.request_id}: {reason}")
        return state

    def _shed_reason(self) -> Optional[str]:
        ql = int(self.fleet.queue_limit)
        # the LIVE depth (scheduler queues), not the metrics gauge — the
        # gauge snapshots at hook time and lags the current arrival
        depth = sum(r.queue_depth for r in self.replicas)
        if ql and depth >= ql:
            return f"fleet queue full ({depth} >= {ql})"
        thr = float(self.fleet.shed_ttft_p95_s)
        if thr > 0:
            p95 = recent_percentile(self.metrics.ttft_s, 95)
            if p95 is not None and p95 > thr:
                return f"fleet ttft p95 {p95:.3f}s > {thr:.3f}s"
        return None

    # ------------------------------------------------------------ routing
    def _open(self, reps: List[ReplicaHandle]) -> List[ReplicaHandle]:
        """Replicas whose own bounded queue still admits; when every one
        is full, all stay candidates — the chosen replica's scheduler
        rejects with its own retry_after (the graceful path)."""
        ql = int(self.serving.queue_limit)
        if not ql:
            return reps
        open_ = [r for r in reps if r.queue_depth < ql]
        return open_ or reps

    def _route(self, request: Request):
        """(replica, via) for one request. Precedence: session affinity →
        prefix-aware (the configured policy) → load/round-robin."""
        pool = self._open(self._intake)
        by_id = {r.replica_id: r for r in pool}
        sid = request.session_id
        if self.fleet.affinity and sid is not None \
                and sid in self._sessions and self._sessions[sid] in by_id:
            return by_id[self._sessions[sid]], "affinity"
        if self.fleet.routing == "prefix" and self.index is not None:
            rid, depth = self.index.best(
                request.prompt, list(by_id.keys())
            )
            if rid is not None and depth > 0:
                # cache locality vs balance: a prefix hit saves at most
                # the matched prefill (tier-weighted: host-resident
                # chains count at HOST_TIER_WEIGHT per block since they
                # pay a page-in first), so it only wins while the matched
                # replica isn't meaningfully busier than the idlest one —
                # a fully-shared system prompt must not serialize the
                # whole fleet onto one replica (every replica's cache
                # learns the hot prefix within a few requests anyway)
                slack = int(self.fleet.prefix_balance_slack)
                if slack < 0:
                    slack = max(1, int(self.serving.max_slots) // 2)
                min_load = min(r.load for r in pool)
                if by_id[rid].load - min_load <= slack:
                    return by_id[rid], "prefix"
        if self.fleet.routing == "round_robin":
            rep = pool[self._rr % len(pool)]
            self._rr += 1
            return rep, "round_robin"
        rep = min(pool, key=lambda r: (r.load, r.replica_id))
        return rep, "least_loaded"

    def _record_route(self, request: Request, rep: ReplicaHandle,
                      via: str, state: RequestState) -> None:
        if state.status is RequestStatus.EVICTED:
            # the replica's own bounded queue rejected — its retry_after
            # semantics carry through; count it as a fleet shed too
            self.metrics.on_shed("replica queue full")
            return
        self.metrics.on_route(via)
        if request.session_id is not None:
            self._sessions[request.session_id] = rep.replica_id

    # ----------------------------------------------------------- stepping
    @property
    def has_work(self) -> bool:
        return any(r.has_work for r in self.replicas)

    @property
    def step_traces(self) -> List[int]:
        """Per-replica step-trace counters (zero-recompiles criterion:
        every stepped replica shows exactly 1)."""
        return [r.engine.step_traces for r in self.replicas]

    def step(self) -> List[RequestState]:
        """One fleet tick: attempted prefill→decode handoffs, then one
        engine step on every replica with work (data-parallel replicas —
        a real deployment runs them concurrently, so the tick's latency
        model is router overhead + max over replica step times, which is
        what ``last_tick_durations``/``last_tick_overhead_s`` report).
        Returns every request that finished this tick."""
        if not self.has_work:
            return []
        hw = self.healthwatch
        if hw is not None:
            hw.on_step_start()
        traces_before = sum(self.step_traces)
        t0 = time.perf_counter()
        tr = self.tracer
        if tr is None:
            self._run_handoffs()
            finished = self._step_replicas()
        else:
            tick_sp = tr.begin("fleet/tick", "fleet",
                               {"tick": self.metrics.ticks + 1})
            route_sp = tr.begin("fleet/route", "fleet")
            moved = self._run_handoffs()
            if moved:
                route_sp.annotate(handoffs=moved)
            route_sp.end()
            rep_sp = tr.begin("fleet/replicas", "fleet")
            finished = self._step_replicas()
            rep_sp.annotate(stepped=len(self.last_tick_durations))
            rep_sp.end()
            tick_sp.end()
        self.last_tick_overhead_s = max(
            time.perf_counter() - t0 - sum(
                self.last_tick_durations.values()
            ),
            0.0,
        )
        if self.last_tick_durations:
            self.metrics.on_tick()
            if hw is not None:
                hw.on_serve_step(
                    step=self.metrics.ticks, metrics=self.metrics,
                    compiled=sum(self.step_traces) - traces_before,
                )
        return finished

    def _step_replicas(self) -> List[RequestState]:
        finished: List[RequestState] = []
        durs: Dict[int, float] = {}
        for r in self.replicas:
            if not r.has_work:
                continue
            fin, dur = r.step()
            finished.extend(fin)
            durs[r.replica_id] = dur
        self.last_tick_durations = durs
        for st in finished:
            # fleet completion-order TTFT window (shed gate + watchdog)
            if st.first_token_t is not None:
                self.metrics.on_finish_ttft(
                    st.first_token_t - st.arrival_t
                )
        return finished

    def _run_handoffs(self) -> int:
        """Move every eligible finished-prefill request from the prefill
        replicas to the least-loaded decode replica that can take it.
        Deferred transfers (no slot / no pages) stay put — the request
        keeps decoding on its prefill replica and the router retries next
        tick; correctness never depends on placement."""
        if not self._decode:
            return 0
        moved = 0
        for src in self.replicas:
            if src.role != ROLE_PREFILL:
                continue
            for state in src.decode_candidates():
                targets = sorted(
                    (d for d in self._decode if d.has_free_slot),
                    key=lambda d: (d.load, d.replica_id),
                )
                done = False
                for dst in targets:
                    pages = handoff(state, src, dst)
                    if pages is not None:
                        self.metrics.on_handoff(True, pages=pages)
                        moved += 1
                        done = True
                        break
                if not done:
                    self.metrics.on_handoff(False)
        return moved

    def run_until_idle(self, max_steps: int = 100_000
                       ) -> List[RequestState]:
        """Drain every replica; returns every request finished on the
        way (fleet completion order)."""
        finished: List[RequestState] = []
        steps = 0
        while self.has_work:
            if steps >= max_steps:
                raise RuntimeError(
                    f"fleet did not drain within {max_steps} ticks"
                )
            finished.extend(self.step())
            steps += 1
        return finished

    # --------------------------------------------------------- steptrace
    def trace_export(self, path: Optional[str] = None) -> str:
        """Export the AGGREGATED fleet trace: every replica's serve/step
        spans and request trees already share the one registry timeline;
        this adds each replica's analytic streams as ``plan/r<i>/...``
        spans (per-replica predicted bytes/seconds next to the fleet's
        measured mean step) before writing the Chrome trace JSON."""
        if self.tracer is None:
            raise RuntimeError(
                "steptrace is not enabled on this Router — pass "
                'steptrace={"enabled": True} at construction'
            )
        measured = self.tracer.mean_dur("serve/step")
        for r in self.replicas:
            for name, stream in r.engine.analytic_streams().items():
                self.tracer.plan_span(
                    f"r{r.replica_id}/{name}", stream,
                    measured_step_s=measured,
                )
        path = path or self._steptrace_export_path or "steptrace_fleet.json"
        out = self.tracer.export(path)
        log_dist(f"steptrace: wrote fleet trace {out}")
        return out

    # --------------------------------------------------- planner metadata
    def analytic_streams(self, include_potential: bool = False
                         ) -> Dict[str, Any]:
        """Fleet streams: each replica's declared streams under an
        ``r<i>/`` prefix (one schema with the single-engine form, so the
        comm_logger / planner intakes need no fleet special case)."""
        out: Dict[str, Any] = {}
        for r in self.replicas:
            for name, stream in r.engine.analytic_streams(
                include_potential=include_potential
            ).items():
                out[f"r{r.replica_id}/{name}"] = stream
        return out
