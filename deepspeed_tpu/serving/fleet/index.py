"""Global prefix index: which replica holds the longest block chain.

The chained-crc32 page keys (serving/paging.py ``chain_hashes``) commit
to the entire token prefix before them, which makes them GLOBALLY
comparable: replica A and replica B holding the same key hold KV for the
same prefix. The :class:`GlobalPrefixIndex` mirrors every replica's
full-page chain keys — maintained push-style from each
:class:`~deepspeed_tpu.serving.paging.PrefixCache`'s event listener, so
routing never polls or locks a replica's cache — and scores a prompt per
replica with the SAME longest-chain walk the replica-local
``PrefixCache.longest_chain`` runs.

Collisions: the index is hash-only, so a crc32 collision can over-score
a replica. That mis-routes at worst — the chosen replica's token-verified
``PrefixCache.match`` then degrades the hit to a miss, and the fleet
oracle (any routing == serial replay, token-for-token) is unaffected.

Tiers: replicas with a tiered KV arena (``serving.host_pages > 0``)
demote evicted chains to their host tier instead of dropping them. The
index mirrors those too (the cache emits ``kind == "host"`` events) and
scores them at :data:`HOST_TIER_WEIGHT` per block — a host-resident hit
still saves the prefill flops but pays a page-in before the first decode
step, so it outranks a miss and loses to an HBM-resident chain of the
same depth.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..paging import PrefixCache, chain_hashes, longest_chain_walk

# Per-block routing value of a host-resident chain link relative to an
# HBM-resident one (1.0). Strictly inside (0, 1): host hit > miss, and
# any HBM block beats any host block at equal depth.
HOST_TIER_WEIGHT = 0.5


class GlobalPrefixIndex:
    """Per-replica mirrors of full-page chain keys + the scoring walk."""

    def __init__(self, page_size: int):
        self.page_size = int(page_size)
        self._hashes: Dict[int, Set[int]] = {}
        self._host_hashes: Dict[int, Set[int]] = {}

    def attach(self, replica_id: int, cache: PrefixCache) -> None:
        """Subscribe to one replica's cache events. Attach happens at
        fleet construction, before any request runs, so the mirror never
        needs a catch-up replay; only FULL-page entries index (partial
        tails shift routing by less than one page — not worth the
        cross-replica bookkeeping)."""
        if cache.page_size != self.page_size:
            raise ValueError(
                f"replica {replica_id} page_size {cache.page_size} != "
                f"index page_size {self.page_size}: chain keys would not "
                "be comparable across replicas"
            )
        mirror = self._hashes.setdefault(int(replica_id), set())
        host = self._host_hashes.setdefault(int(replica_id), set())

        def listener(event: str, kind: str, h: int, page: int) -> None:
            if kind == "full":
                tier = mirror
            elif kind == "host":
                tier = host
            else:
                return  # partial tails don't index (sub-page routing)
            if event == "insert":
                tier.add(h)
            else:
                tier.discard(h)

        cache.listener = listener

    def longest_chain(self, replica_id: int,
                      token_block_hashes: Sequence[int]) -> int:
        """Chain depth of ``token_block_hashes`` on one replica — the
        same walk as ``PrefixCache.longest_chain``, over the HBM
        mirror (host-resident links extend it: the replica can attach
        them through its host tier just as ``match`` + ``host_chain``
        would)."""
        mirror = self._hashes.get(int(replica_id), set())
        host = self._host_hashes.get(int(replica_id), set())
        return longest_chain_walk(
            token_block_hashes, lambda h: h in mirror or h in host
        )

    def weighted_chain(self, replica_id: int,
                       token_block_hashes: Sequence[int]) -> float:
        """Tier-weighted chain value: the same leading-run walk, each
        HBM-resident link worth 1.0 and each host-resident link worth
        :data:`HOST_TIER_WEIGHT`. The run still breaks at the first
        block resident in NEITHER tier — a host link deeper in the
        chain keeps counting (the scheduler promotes through it)."""
        mirror = self._hashes.get(int(replica_id), set())
        host = self._host_hashes.get(int(replica_id), set())
        w = 0.0
        for h in token_block_hashes:
            if h in mirror:
                w += 1.0
            elif h in host:
                w += HOST_TIER_WEIGHT
            else:
                break
        return w

    def score(self, prompt, eligible: Sequence[int]
              ) -> List[Tuple[int, float]]:
        """(replica_id, tier-weighted chain value) for every eligible
        replica, prompt hashed once."""
        hashes = chain_hashes(prompt, self.page_size)
        return [(rid, self.weighted_chain(rid, hashes))
                for rid in eligible]

    def best(self, prompt, eligible: Sequence[int]
             ) -> Tuple[Optional[int], float]:
        """The eligible replica with the highest tier-weighted chain
        value, or (None, 0) when nothing matches anywhere (the router
        then falls back to its load-based tie-break). Ties break toward
        the first eligible replica — stable under mirror churn."""
        best_rid, best_depth = None, 0.0
        for rid, depth in self.score(prompt, eligible):
            if depth > best_depth:
                best_rid, best_depth = rid, depth
        return best_rid, best_depth

    def entries(self, replica_id: int) -> int:
        return len(self._hashes.get(int(replica_id), set()))

    def host_entries(self, replica_id: int) -> int:
        return len(self._host_hashes.get(int(replica_id), set()))
