"""Host-side KV page management: pool allocator + prefix cache.

Parity: vLLM's PagedAttention block manager / DeepSpeed-FastGen's blocked
KV cache, host-side only. The device never sees this module — the jitted
serving step consumes the *result* (per-slot page-table int32 vectors and
an optional copy-on-write source vector) and keeps its ONE fixed shape.

- :class:`PagePool` — refcounted free-list over ``num_pages`` physical
  page ids. A page is *live* while any slot or prefix-cache entry holds a
  reference; ``free + live == num_pages`` is the leak invariant the
  scheduler asserts after every tick.
- :class:`PrefixCache` — chained-hash map from token prefixes to pages a
  finished request left behind. Full pages chain with
  ``crc32(block_bytes, prev_hash)``; the partial tail page is stored with
  its valid-token run. Matches verify actual token equality (hash
  collisions degrade to misses, never to wrong KV). Entries hold one pool
  reference each; LRU eviction under pool pressure drops that reference,
  freeing the page once no slot shares it.

Sharing is read-only: a slot whose write frontier lands inside a shared
page never writes it in place — the scheduler allocates a fresh page and
the step copies the shared page's KV into it before the chunk write
(copy-on-write, in-step, fixed shape).
"""

from __future__ import annotations

import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def chain_hash(prev: int, block) -> int:
    """Chained block hash: crc32 of the token block seeded by the previous
    link, so a page's key commits to the ENTIRE prefix before it (KV at a
    position depends on every earlier token)."""
    return zlib.crc32(np.asarray(block, np.int32).tobytes(), prev)


class PagePool:
    """Refcounted physical-page allocator (host side, O(1) ops)."""

    def __init__(self, num_pages: int):
        if num_pages < 1:
            raise ValueError(f"PagePool needs >= 1 page, got {num_pages}")
        self.num_pages = int(num_pages)
        self.refcount = np.zeros(self.num_pages, np.int64)
        self._free: List[int] = list(range(self.num_pages - 1, -1, -1))

    def alloc(self) -> Optional[int]:
        """One fresh page with refcount 1, or None when exhausted."""
        if not self._free:
            return None
        page = self._free.pop()
        self.refcount[page] = 1
        return page

    def incref(self, page: int) -> None:
        if self.refcount[page] <= 0:
            raise AssertionError(f"incref on dead page {page}")
        self.refcount[page] += 1

    def decref(self, page: int) -> None:
        if self.refcount[page] <= 0:
            raise AssertionError(f"decref on dead page {page}")
        self.refcount[page] -= 1
        if self.refcount[page] == 0:
            self._free.append(page)

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def live_count(self) -> int:
        return int((self.refcount > 0).sum())

    def check_leaks(self, expected: Optional[Dict[int, int]] = None) -> None:
        """The leak invariant: ``free + live == num_pages``, and (when the
        caller supplies its own view) the pool's refcounts match the
        references the scheduler believes exist, page for page."""
        if self.free_count + self.live_count != self.num_pages:
            raise AssertionError(
                f"page leak: free {self.free_count} + live "
                f"{self.live_count} != num_pages {self.num_pages}"
            )
        if expected is not None:
            mine = {
                int(p): int(self.refcount[p])
                for p in np.nonzero(self.refcount)[0]
            }
            if mine != expected:
                raise AssertionError(
                    f"page refcount drift: pool {mine} != holders {expected}"
                )


class PrefixCache:
    """Token-prefix → shared KV pages, refcounted through a PagePool.

    Full pages key on the chain hash of all tokens up to and including the
    page; the partial tail keys on (chain hash so far, tail token run).
    ``match`` walks a prompt greedily and returns the shared pages plus
    how many tokens they cover; the caller caps the hit (a request must
    always feed at least its final prompt token to sample) and increfs.
    """

    def __init__(self, pool: PagePool, page_size: int):
        self.pool = pool
        self.page_size = int(page_size)
        # full pages: chain_hash -> (page, block_tuple); tails:
        # chain_hash -> [(tail_tuple, page), ...]. One LRU order over both
        # (key -> ("full"|"tail", chain_hash, page, tokens_tuple)).
        self._full: "OrderedDict[int, Tuple[int, Tuple[int, ...]]]" = (
            OrderedDict()
        )
        self._tails: Dict[int, List[Tuple[Tuple[int, ...], int]]] = {}
        self._lru: "OrderedDict[Tuple, None]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._lru)

    @property
    def held_pages(self) -> List[int]:
        return [key[2] for key in self._lru]

    # ---------------------------------------------------------------- match
    def match(self, prompt: Sequence[int]) -> Tuple[List[int], int]:
        """Longest cached prefix of ``prompt``: (pages, covered_tokens).
        Pages are NOT incref'd — the caller takes references for the ones
        it keeps. Token equality is verified block-for-block."""
        toks = [int(t) for t in np.asarray(prompt).reshape(-1)]
        ps = self.page_size
        pages: List[int] = []
        covered = 0
        h = 0
        while covered + ps <= len(toks):
            block = tuple(toks[covered: covered + ps])
            nh = chain_hash(h, block)
            entry = self._full.get(nh)
            if entry is None or entry[1] != block:
                break
            pages.append(entry[0])
            self._lru.move_to_end(("full", nh, entry[0], block))
            covered += ps
            h = nh
        # partial tail: use the stored run's leading tokens that match the
        # remaining prompt (KV beyond the match is never attendable — the
        # joining slot's frontier stops at the match)
        rest = toks[covered:]
        best: Tuple[int, Tuple[Tuple[int, ...], int]] = (0, None)
        for tail, page in self._tails.get(h, ()):
            n = 0
            for a, b in zip(tail, rest):
                if a != b:
                    break
                n += 1
            if n > best[0]:
                best = (n, (tail, page))
        if best[0] > 0:
            tail, page = best[1]
            pages.append(page)
            self._lru.move_to_end(("tail", h, page, tail))
            covered += best[0]
        return pages, covered

    # --------------------------------------------------------------- insert
    def insert(self, tokens: Sequence[int], pages: Sequence[int]) -> int:
        """Publish a finished request's pages for reuse. ``tokens`` is the
        run whose KV the pages hold (prompt + generated-but-last);
        ``pages`` the physical pages covering it in order. Each entry the
        cache keeps takes ONE pool reference; duplicates of existing
        entries are skipped (the caller's own references are its business).
        Returns the number of entries inserted."""
        toks = [int(t) for t in np.asarray(tokens).reshape(-1)]
        ps = self.page_size
        inserted = 0
        h = 0
        full = len(toks) // ps
        for i in range(full):
            block = tuple(toks[i * ps: (i + 1) * ps])
            nh = chain_hash(h, block)
            if nh not in self._full:
                self._full[nh] = (int(pages[i]), block)
                self._lru[("full", nh, int(pages[i]), block)] = None
                self.pool.incref(int(pages[i]))
                inserted += 1
            # ALSO register the full page's run for partial matching: a
            # prompt diverging mid-page (the shared-system-prompt shape)
            # still shares this page's leading tokens, copy-on-write at
            # the divergence point
            inserted += self._add_tail(h, block, int(pages[i]))
            h = nh
        tail = tuple(toks[full * ps:])
        if tail and full < len(pages):
            inserted += self._add_tail(h, tail, int(pages[full]))
        return inserted

    def _add_tail(self, h: int, run: Tuple[int, ...], page: int) -> int:
        runs = self._tails.setdefault(h, [])
        if any(existing == run for existing, _ in runs):
            return 0
        runs.append((run, page))
        self._lru[("tail", h, page, run)] = None
        self.pool.incref(page)
        return 1

    # --------------------------------------------------------------- evict
    def evict_lru(self) -> bool:
        """Drop the least-recently-used entry (its pool reference with it).
        Returns False when the cache is empty."""
        if not self._lru:
            return False
        key, _ = self._lru.popitem(last=False)
        kind, h, page, toks = key
        if kind == "full":
            self._full.pop(h, None)
        else:
            runs = self._tails.get(h, [])
            self._tails[h] = [r for r in runs if r != (toks, page)]
            if not self._tails[h]:
                del self._tails[h]
        self.pool.decref(page)
        return True

    def clear(self) -> None:
        while self.evict_lru():
            pass
