"""Host-side KV page management: pool allocator + prefix cache + tiers.

Parity: vLLM's PagedAttention block manager / DeepSpeed-FastGen's blocked
KV cache, host-side only. The jitted serving step never sees this module
— it consumes the *result* (per-slot page-table int32 vectors, an
optional copy-on-write source vector and, tiered, the promotion staging
buffer) and keeps its ONE fixed shape. The only device-touching
functions here are :func:`export_pages` / :func:`import_pages`, the
eager page-payload transfer the fleet's prefill→decode KV handoff runs
BETWEEN steps (serving/fleet/handoff.py), and the spiller's demote
export.

- :class:`PagePool` — refcounted free-list over ``num_pages`` physical
  page ids. A page is *live* while any slot or prefix-cache entry holds a
  reference; ``free + live == num_pages`` is the leak invariant the
  scheduler asserts after every tick.
- :class:`PrefixCache` — chained-hash map from token prefixes to pages a
  finished request left behind. Full pages chain with
  ``crc32(block_bytes, prev_hash)``; the partial tail page is stored with
  its valid-token run. Matches verify actual token equality (hash
  collisions degrade to misses, never to wrong KV). Entries hold one pool
  reference each; LRU eviction under pool pressure DEMOTES full-chain
  entries to the host tier instead of dropping them (when a spiller is
  attached) — a fleet-wide shared system prompt survives HBM pressure.
- :class:`HostPageStore` — the second tier: codec-compressed page blobs
  in pinned-host buffers (the ``runtime/swap_tensor`` two-generation
  buffer-pool pattern), with an optional NVMe third tier through
  ``ops/aio`` behind the same put/get/drop interface.
- :class:`PageSpiller` — the engine↔host bridge: ``demote`` exports one
  physical page and codec-encodes it at rest (``comm/wires``: fp32 spill
  is bitwise, int8 within the codec's stated lane-wise bound); ``load``
  decodes one page back for the step's promotion staging buffer. WHICH
  pages move is the scheduler's decision; key lifecycle too.

Sharing is read-only: a slot whose write frontier lands inside a shared
page never writes it in place — the scheduler allocates a fresh page and
the step copies the shared page's KV into it before the chunk write
(copy-on-write, in-step, fixed shape).
"""

from __future__ import annotations

import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def chain_hash(prev: int, block) -> int:
    """Chained block hash: crc32 of the token block seeded by the previous
    link, so a page's key commits to the ENTIRE prefix before it (KV at a
    position depends on every earlier token)."""
    return zlib.crc32(np.asarray(block, np.int32).tobytes(), prev)


def chain_hashes(tokens, page_size: int) -> List[int]:
    """The chained hash of every FULL page-sized block of ``tokens``, in
    order. Because each link commits to the whole prefix before it, these
    keys are globally comparable: two caches (on two replicas) holding the
    same chain hash hold KV for the same token prefix — modulo crc32
    collisions, which every consumer must let degrade to misses (the
    router's index may mis-route on one; the replica's token-verified
    ``match`` then treats it as a miss, never as wrong KV)."""
    toks = np.asarray(tokens, np.int32).reshape(-1)
    ps = int(page_size)
    out: List[int] = []
    h = 0
    for i in range(toks.size // ps):
        h = chain_hash(h, toks[i * ps: (i + 1) * ps])
        out.append(h)
    return out


def longest_chain_walk(token_block_hashes, contains) -> int:
    """The ONE definition of "longest matching block chain": the length of
    the leading run of ``token_block_hashes`` for which ``contains(hash)``
    holds. Shared by :meth:`PrefixCache.longest_chain` (the replica-local
    cache view) and the fleet router's :class:`GlobalPrefixIndex` (the
    event-maintained cross-replica mirror), so routing and matching agree
    on what "longest chain" means. Accepts any iterable and consumes only
    up to the first miss — ``match`` feeds it a lazy hash generator, so a
    cold cache never pays for hashing a whole long prompt. Hash-presence
    only — callers that hand out KV must still verify token equality."""
    n = 0
    for h in token_block_hashes:
        if not contains(h):
            break
        n += 1
    return n


# ------------------------------------------------------- page payload I/O
def export_pages(cache: Dict[str, "object"], page_ids: Sequence[int]
                 ) -> Dict[str, "object"]:
    """Gather the payload of physical ``page_ids`` out of a paged KV pool
    (``init_paged_cache`` layout: the page axis is axis 1 of every leaf,
    scales included). Returns ``{leaf: [L, n_pages, ...]}`` device arrays
    — an immutable snapshot (the pool is updated functionally by the
    step, so later steps can never mutate an exported payload). This is
    the prefill half of the fleet's prefill→decode KV handoff: a page
    TRANSFER, not a tensor reshape."""
    import jax.numpy as jnp

    ids = jnp.asarray(np.asarray(page_ids, np.int32))
    return {k: jnp.take(v, ids, axis=1) for k, v in cache.items()}


def check_page_payload(cache: Dict[str, "object"],
                       payload: Dict[str, "object"], n_pages: int) -> None:
    """Validate an :func:`export_pages` payload against a destination
    pool: every leaf present, ``n_pages`` wide, page geometry matching."""
    for k, v in cache.items():
        if k not in payload:
            raise KeyError(f"import_pages: payload missing leaf {k!r}")
        p = payload[k]
        if p.shape[1] != n_pages or p.shape[0] != v.shape[0] \
                or p.shape[2:] != v.shape[2:]:
            raise ValueError(
                f"import_pages: payload {k} shape {p.shape} does not fit "
                f"{n_pages} pages of a pool leaf shaped {v.shape}"
            )


def scatter_pages(cache: Dict[str, "object"],
                  payload: Dict[str, "object"],
                  ids) -> Dict[str, "object"]:
    """The traceable scatter core of :func:`import_pages`. The serving
    engine jits this with the pool DONATED (import_kv_pages), so a
    handoff updates the destination arena in place — O(pages moved), not
    an O(arena) copy per transfer."""
    return {
        k: v.at[:, ids].set(payload[k].astype(v.dtype))
        for k, v in cache.items()
    }


def import_pages(cache: Dict[str, "object"], payload: Dict[str, "object"],
                 dst_page_ids: Sequence[int]) -> Dict[str, "object"]:
    """Scatter an :func:`export_pages` payload into ``dst_page_ids`` of a
    (possibly different) pool with the same page geometry. Returns the new
    pool dict; the caller owns re-asserting device placement/sharding
    (ServingEngine.import_kv_pages does, so the jitted step's donated
    carry keeps the layout it compiled against). Host-side refcounts of
    the destination pages are the destination scheduler's business —
    the leak invariant ``free + live == num_pages`` must hold on BOTH
    pools after every transfer (asserted by the fleet handoff)."""
    import jax.numpy as jnp

    ids = np.asarray(dst_page_ids, np.int32)
    check_page_payload(cache, payload, ids.size)
    return scatter_pages(cache, payload, jnp.asarray(ids))


# --------------------------------------------- tiered host spill (ISSUE 18)
# staging-buffer width: pages promoted back per step. TWO slots — the
# PR-1 rotating double-buffer carry applied to the paged gather: slot A's
# page-in rides under the step consuming slot B, and the step's staged
# scatter runs BEFORE its gathers so a promoted page is attendable the
# same step it lands. Static: the stage arrays' shape is part of the ONE
# compiled program.
STAGE_SLOTS = 2


def encode_page(payload: Dict[str, "object"], codec
                ) -> Dict[str, Tuple[str, dict, Dict[str, np.ndarray]]]:
    """Codec-compress one single-page :func:`export_pages` payload at
    rest. Float leaves reshape to the wire codec's canonical ``[B, R, L]``
    operand (B = layers, L = the innermost lane axis) and encode; integer
    leaves (an int8-quantized pool's q arrays) are stored raw — they are
    already at storage width. The fp32 codec is the identity, so an fp32
    spill round-trips bitwise; int8 stays within the codec's stated
    lane-wise bound (``codec.bound``)."""
    import jax.numpy as jnp

    blob: Dict[str, Tuple[str, dict, Dict[str, np.ndarray]]] = {}
    for k, v in payload.items():
        arr = np.asarray(v)
        meta = {"shape": tuple(arr.shape), "dtype": str(arr.dtype)}
        if arr.dtype.kind == "f":
            x3 = jnp.asarray(arr, jnp.float32).reshape(
                arr.shape[0], -1, arr.shape[-1]
            )
            parts = {
                pk: np.ascontiguousarray(np.asarray(pv))
                for pk, pv in codec.encode(x3).items()
            }
            blob[k] = ("codec", meta, parts)
        else:
            blob[k] = ("raw", meta, {"x": np.ascontiguousarray(arr)})
    return blob


def decode_page(blob, codec) -> Dict[str, np.ndarray]:
    """Invert :func:`encode_page` back to the pool's leaf shapes/dtypes
    (numpy — the promotion staging buffer fills from this host-side)."""
    import jax.numpy as jnp

    out: Dict[str, np.ndarray] = {}
    for k, (mode, meta, parts) in blob.items():
        shape = tuple(meta["shape"])
        dt = np.dtype(meta["dtype"])
        if mode == "raw":
            out[k] = parts["x"]
            continue
        rows = 1
        for d in shape[1:-1]:
            rows *= d
        dec = codec.decode(
            {pk: jnp.asarray(pv) for pk, pv in parts.items()},
            rows, jnp.float32,
        )
        out[k] = np.asarray(dec).reshape(shape).astype(dt)
    return out


def blob_nbytes(blob) -> int:
    """At-rest bytes of one encoded page blob (what the host tier — and
    the ``kv_spill`` analytic stream — actually pays per page)."""
    return sum(
        int(p.nbytes)
        for _mode, _meta, parts in blob.values()
        for p in parts.values()
    )


class HostPageStore:
    """Tier 2 (+3): codec-compressed page blobs in pinned-host buffers,
    overflowing to NVMe through ``ops/aio`` when ``spill_dir`` is set.

    ``capacity_pages`` bounds the pinned-host tier (the
    ``serving.host_pages`` knob); the NVMe tier behind it is bounded only
    by disk. ``put`` returns an opaque int key, or None when every tier
    is full — in which case nothing was stored (the caller's demotion
    rolls back to the plain drop path). Buffers recycle through the
    :class:`runtime.swap_tensor.PinnedBufferPool` two-generation
    discipline: a dropped blob's buffers become reusable only after the
    NEXT drop generation retires, so a consumer still decoding the
    previous generation never sees them overwritten."""

    def __init__(self, capacity_pages: int, codec: str = "fp32",
                 spill_dir: Optional[str] = None,
                 buffer_count: int = 4 * STAGE_SLOTS):
        from ..comm.wires import get_codec
        from ..runtime.swap_tensor import PinnedBufferPool

        self.capacity = int(capacity_pages)
        self.codec = get_codec(codec)
        self.spill_dir = spill_dir
        self._blobs: Dict[int, dict] = {}   # key -> blob (pinned-host tier)
        self._disk: Dict[int, dict] = {}    # key -> file skeleton (NVMe)
        self._next_key = 0
        self._pool = PinnedBufferPool(buffer_count=buffer_count)
        self._aio = None
        self.bytes_resident = 0

    # ------------------------------------------------------------ tiers
    def _nvme(self):
        if self._aio is None:
            import os

            from ..ops.aio import AsyncIOHandle

            os.makedirs(self.spill_dir, exist_ok=True)
            self._aio = AsyncIOHandle(num_threads=2)
        return self._aio

    def _to_pinned(self, blob):
        """Copy a blob's parts into pooled host buffers (the arrays
        handed in may alias device buffers on a CPU client — the store
        must own its bytes)."""
        out = {}
        for k, (mode, meta, parts) in blob.items():
            pp = {}
            for pk, pv in parts.items():
                buf = self._pool.take(pv.shape, pv.dtype)
                np.copyto(buf, pv)
                pp[pk] = buf
            out[k] = (mode, meta, pp)
        return out

    def put(self, blob) -> Optional[int]:
        """Store one encoded page; returns its key, or None when full
        (host tier at capacity and no NVMe tier configured). On None
        NOTHING was stored — demotion failure is atomic."""
        if len(self._blobs) < self.capacity:
            stored = self._to_pinned(blob)
            key = self._next_key
            self._next_key += 1
            self._blobs[key] = stored
            self.bytes_resident += blob_nbytes(stored)
            return key
        if self.spill_dir is not None:
            return self._put_disk(blob)
        return None

    def _put_disk(self, blob) -> int:
        import os

        aio = self._nvme()
        key = self._next_key
        self._next_key += 1
        skel = {}
        reqs = []
        for k, (mode, meta, parts) in blob.items():
            pp = {}
            for pk, pv in parts.items():
                path = os.path.join(
                    self.spill_dir, f"page{key}.{k}.{pk}.bin"
                )
                arr = np.ascontiguousarray(pv)
                reqs.append((aio.submit_write(path, arr), arr))
                pp[pk] = (path, tuple(arr.shape), str(arr.dtype))
            skel[k] = (mode, meta, pp)
        for r, _buf in reqs:  # buffers stay referenced until the write lands
            aio.wait(r)
        self._disk[key] = skel
        return key

    def get(self, key: int):
        """The blob for ``key`` (reads the NVMe tier back into fresh host
        buffers when it overflowed there). Does NOT remove it."""
        blob = self._blobs.get(key)
        if blob is not None:
            return blob
        skel = self._disk.get(key)
        if skel is None:
            raise KeyError(f"HostPageStore: unknown page key {key}")
        aio = self._nvme()
        out = {}
        for k, (mode, meta, pp) in skel.items():
            parts = {}
            reqs = []
            for pk, (path, shape, dt) in pp.items():
                buf = np.empty(shape, np.dtype(dt))
                reqs.append(aio.submit_read(path, buf))
                parts[pk] = buf
            for r in reqs:
                aio.wait(r)
            out[k] = (mode, meta, parts)
        return out

    def drop(self, key: int) -> None:
        blob = self._blobs.pop(key, None)
        if blob is not None:
            self.bytes_resident -= blob_nbytes(blob)
            dropped = [
                p for _m, _meta, parts in blob.values()
                for p in parts.values()
            ]
            self._pool.retire_generation(dropped)
            return
        skel = self._disk.pop(key, None)
        if skel is not None:
            import os

            for _m, _meta, pp in skel.values():
                for path, _shape, _dt in pp.values():
                    try:
                        os.remove(path)
                    except FileNotFoundError:
                        pass
            return
        raise KeyError(f"HostPageStore: dropping unknown page key {key}")

    # ------------------------------------------------------- accounting
    def __contains__(self, key: int) -> bool:
        return key in self._blobs or key in self._disk

    def keys(self) -> List[int]:
        """Every resident key (host + NVMe tiers), SORTED — iteration
        over the store must be order-deterministic so state fingerprints
        (analysis/modelcheck) and counterexample replays are stable
        across runs."""
        return sorted(set(self._blobs) | set(self._disk))

    @property
    def host_count(self) -> int:
        return len(self._blobs)

    @property
    def disk_count(self) -> int:
        return len(self._disk)

    @property
    def resident_count(self) -> int:
        return len(self._blobs) + len(self._disk)

    def close(self) -> None:
        if self._aio is not None:
            self._aio.close()
            self._aio = None


class PageSpiller:
    """Demote/load bridge between the device pool and a HostPageStore.

    ``export_fn(page_ids) -> {leaf: [L, n, ...]}`` is late-bound to the
    engine's CURRENT pool arrays (functional updates: an export after
    step t reads exactly step t's settled content). Pure data movement —
    the scheduler decides which pages move and owns key lifecycle."""

    def __init__(self, store: HostPageStore, export_fn, metrics=None):
        self.store = store
        self._export = export_fn
        self.metrics = metrics
        self.pages_spilled = 0
        self.pages_loaded = 0

    def demote(self, page_id: int) -> Optional[int]:
        """Export + codec-encode one physical page into the store.
        Returns the store key, or None when the store is full — in which
        case nothing was mutated anywhere (put-before-free: the caller
        only releases the HBM page on success, so a mid-demotion failure
        rolls back to the plain drop path atomically)."""
        blob = encode_page(self._export([page_id]), self.store.codec)
        key = self.store.put(blob)
        if key is not None:
            self.pages_spilled += 1
            if self.metrics is not None:
                self.metrics.on_spill(blob_nbytes(blob))
        return key

    def load(self, key: int) -> Tuple[Dict[str, np.ndarray], int]:
        """Decode one stored page for the promotion staging buffer:
        ``({leaf: [L, 1, ...]} numpy in pool dtypes, at-rest bytes)``."""
        blob = self.store.get(key)
        self.pages_loaded += 1
        return decode_page(blob, self.store.codec), blob_nbytes(blob)

    def drop(self, key: int) -> None:
        self.store.drop(key)


class PagePool:
    """Refcounted physical-page allocator (host side, O(1) ops)."""

    def __init__(self, num_pages: int):
        if num_pages < 1:
            raise ValueError(f"PagePool needs >= 1 page, got {num_pages}")
        self.num_pages = int(num_pages)
        self.refcount = np.zeros(self.num_pages, np.int64)
        self._free: List[int] = list(range(self.num_pages - 1, -1, -1))

    def alloc(self) -> Optional[int]:
        """One fresh page with refcount 1, or None when exhausted."""
        if not self._free:
            return None
        page = self._free.pop()
        self.refcount[page] = 1
        return page

    def incref(self, page: int) -> None:
        if self.refcount[page] <= 0:
            raise AssertionError(f"incref on dead page {page}")
        self.refcount[page] += 1

    def decref(self, page: int) -> None:
        if self.refcount[page] <= 0:
            raise AssertionError(f"decref on dead page {page}")
        self.refcount[page] -= 1
        if self.refcount[page] == 0:
            self._free.append(page)

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def live_count(self) -> int:
        return int((self.refcount > 0).sum())

    def check_leaks(self, expected: Optional[Dict[int, int]] = None) -> None:
        """The leak invariant: ``free + live == num_pages``, and (when the
        caller supplies its own view) the pool's refcounts match the
        references the scheduler believes exist, page for page."""
        if self.free_count + self.live_count != self.num_pages:
            raise AssertionError(
                f"page leak: free {self.free_count} + live "
                f"{self.live_count} != num_pages {self.num_pages}"
            )
        if expected is not None:
            mine = {
                int(p): int(self.refcount[p])
                for p in np.nonzero(self.refcount)[0]
            }
            if mine != expected:
                raise AssertionError(
                    f"page refcount drift: pool {mine} != holders {expected}"
                )


class PrefixCache:
    """Token-prefix → shared KV pages, refcounted through a PagePool.

    Full pages key on the chain hash of all tokens up to and including the
    page; the partial tail keys on (chain hash so far, tail token run).
    ``match`` walks a prompt greedily and returns the shared pages plus
    how many tokens they cover; the caller caps the hit (a request must
    always feed at least its final prompt token to sample) and increfs.
    """

    def __init__(self, pool: PagePool, page_size: int, spiller=None):
        self.pool = pool
        self.page_size = int(page_size)
        # full pages: chain_hash -> (page, block_tuple); tails:
        # chain_hash -> [(tail_tuple, page), ...]. One LRU order over both
        # (key -> ("full"|"tail", chain_hash, page, tokens_tuple)).
        self._full: "OrderedDict[int, Tuple[int, Tuple[int, ...]]]" = (
            OrderedDict()
        )
        self._tails: Dict[int, List[Tuple[Tuple[int, ...], int]]] = {}
        self._lru: "OrderedDict[Tuple, None]" = OrderedDict()
        # cache-event listener: ``listener(event, kind, chain_hash, page)``
        # with event in {"insert", "evict"} and kind in {"full", "tail",
        # "host"}. The fleet router's GlobalPrefixIndex subscribes here to
        # mirror each replica's full-page chain keys (HBM- and host-tier)
        # without polling; None (the default) is the zero-overhead
        # single-engine path.
        self.listener = None
        # ---- host tier (ISSUE 18): evicted FULL chains demote to the
        # spiller's HostPageStore instead of dropping. chain_hash ->
        # (store_key, block); its own LRU; pins protect keys whose
        # promotion a slot is waiting on from host-tier eviction.
        self.spiller = spiller
        self._host_full: Dict[int, Tuple[int, Tuple[int, ...]]] = {}
        self._host_lru: "OrderedDict[int, None]" = OrderedDict()
        self._host_pins: Dict[int, int] = {}

    def _emit(self, event: str, kind: str, h: int, page: int) -> None:
        if self.listener is not None:
            self.listener(event, kind, h, page)

    def __len__(self) -> int:
        return len(self._lru)

    @property
    def held_pages(self) -> List[int]:
        return [key[2] for key in self._lru]

    # ---------------------------------------------------------------- match
    def longest_chain(self, token_block_hashes) -> int:
        """Public longest-matching-block-chain lookup: how many leading
        chained-crc32 FULL-page keys (:func:`chain_hashes`, or any lazy
        iterable of them — only the matched prefix is ever consumed) this
        cache holds. Hash-presence only — a crc32 collision can overstate
        the depth, which is exactly why :meth:`match` re-verifies token
        equality before handing out pages (collisions degrade to misses,
        never to wrong KV). Used by the scheduler's match path and by the
        fleet router's global index (the same :func:`longest_chain_walk`
        over its event-maintained per-replica mirror)."""
        return longest_chain_walk(token_block_hashes,
                                  self._full.__contains__)

    def match(self, prompt: Sequence[int]) -> Tuple[List[int], int]:
        """Longest cached prefix of ``prompt``: (pages, covered_tokens).
        Pages are NOT incref'd — the caller takes references for the ones
        it keeps. The hash walk is :meth:`longest_chain` over a LAZY
        chain-hash generator (a miss at block i stops hashing — a cold
        cache costs one crc32, not one per prompt page); token equality
        is then verified block-for-block (hash collisions shrink the
        match — a miss, never wrong KV)."""
        toks = [int(t) for t in np.asarray(prompt).reshape(-1)]
        ps = self.page_size
        hashes: List[int] = []

        def lazy_hashes():
            h = 0
            for i in range(len(toks) // ps):
                h = chain_hash(h, toks[i * ps: (i + 1) * ps])
                hashes.append(h)
                yield h

        depth = self.longest_chain(lazy_hashes())
        pages: List[int] = []
        covered = 0
        h = 0
        for i in range(depth):
            block = tuple(toks[covered: covered + ps])
            nh = hashes[i]
            entry = self._full[nh]
            if entry[1] != block:
                break  # crc32 collision: stop the walk — a miss
            pages.append(entry[0])
            self._lru.move_to_end(("full", nh, entry[0], block))
            covered += ps
            h = nh
        # partial tail: use the stored run's leading tokens that match the
        # remaining prompt (KV beyond the match is never attendable — the
        # joining slot's frontier stops at the match)
        rest = toks[covered:]
        best: Tuple[int, Tuple[Tuple[int, ...], int]] = (0, None)
        for tail, page in self._tails.get(h, ()):
            n = 0
            for a, b in zip(tail, rest):
                if a != b:
                    break
                n += 1
            if n > best[0]:
                best = (n, (tail, page))
        if best[0] > 0:
            tail, page = best[1]
            pages.append(page)
            self._lru.move_to_end(("tail", h, page, tail))
            covered += best[0]
        return pages, covered

    # --------------------------------------------------------------- insert
    def insert(self, tokens: Sequence[int], pages: Sequence[int]) -> int:
        """Publish a finished request's pages for reuse. ``tokens`` is the
        run whose KV the pages hold (prompt + generated-but-last);
        ``pages`` the physical pages covering it in order. Each entry the
        cache keeps takes ONE pool reference; duplicates of existing
        entries are skipped (the caller's own references are its business).
        Returns the number of entries inserted."""
        toks = [int(t) for t in np.asarray(tokens).reshape(-1)]
        ps = self.page_size
        inserted = 0
        h = 0
        full = len(toks) // ps
        for i in range(full):
            block = tuple(toks[i * ps: (i + 1) * ps])
            nh = chain_hash(h, block)
            if nh not in self._full:
                self._full[nh] = (int(pages[i]), block)
                self._lru[("full", nh, int(pages[i]), block)] = None
                self.pool.incref(int(pages[i]))
                self._emit("insert", "full", nh, int(pages[i]))
                inserted += 1
            # ALSO register the full page's run for partial matching: a
            # prompt diverging mid-page (the shared-system-prompt shape)
            # still shares this page's leading tokens, copy-on-write at
            # the divergence point
            inserted += self._add_tail(h, block, int(pages[i]))
            h = nh
        tail = tuple(toks[full * ps:])
        if tail and full < len(pages):
            inserted += self._add_tail(h, tail, int(pages[full]))
        return inserted

    def _add_tail(self, h: int, run: Tuple[int, ...], page: int) -> int:
        runs = self._tails.setdefault(h, [])
        if any(existing == run for existing, _ in runs):
            return 0
        runs.append((run, page))
        self._lru[("tail", h, page, run)] = None
        self.pool.incref(page)
        self._emit("insert", "tail", h, page)
        return 1

    # --------------------------------------------------------------- evict
    def evict_lru(self) -> bool:
        """Evict the least-recently-used entry (its pool reference with
        it). With a spiller attached, FULL chain entries DEMOTE to the
        host tier (codec-compressed at rest) instead of vanishing — a
        later match promotes them back; tails and collisions still drop.
        Returns False when the cache is empty."""
        if not self._lru:
            return False
        key, _ = self._lru.popitem(last=False)
        kind, h, page, toks = key
        if kind == "full":
            self._full.pop(h, None)
            if self.spiller is not None and h not in self._host_full:
                self._demote_full(h, page, toks)
        else:
            runs = self._tails.get(h, [])
            self._tails[h] = [r for r in runs if r != (toks, page)]
            if not self._tails[h]:
                del self._tails[h]
        self.pool.decref(page)
        self._emit("evict", kind, h, page)
        return True

    # ----------------------------------------------------------- host tier
    def _demote_full(self, h: int, page: int,
                     block: Tuple[int, ...]) -> Optional[int]:
        """Demote one evicted full page to the host tier. On a full
        store, unpinned host-LRU chains make room first; a still-full
        store falls back to the plain drop (demotion failure is atomic —
        :meth:`PageSpiller.demote` mutates nothing on None)."""
        skey = self.spiller.demote(page)
        while skey is None and self._evict_host_lru():
            skey = self.spiller.demote(page)
        if skey is not None:
            self._host_full[h] = (skey, block)
            self._host_lru[h] = None
            self._emit("insert", "host", h, -1)
        return skey

    def _evict_host_lru(self) -> bool:
        """Drop the oldest UNPINNED host-tier chain (pinned keys have a
        slot's promotion in flight — never yank those)."""
        for h in list(self._host_lru):
            skey, _block = self._host_full[h]
            if self._host_pins.get(skey, 0) == 0:
                del self._host_lru[h]
                del self._host_full[h]
                self.spiller.drop(skey)
                self._emit("evict", "host", h, -1)
                return True
        return False

    def host_chain(self, tokens: Sequence[int], start: int,
                   max_pages: int) -> List[Tuple[int, int]]:
        """Continue a chain walk into the host tier: from page-aligned
        token offset ``start``, the leading run of full blocks whose
        chained hash has a host-resident entry — token-verified, like
        :meth:`match` (collisions degrade to misses). Returns
        ``[(store_key, chain_hash)]`` per matched block; the caller pins
        each key (:meth:`pin_host`) until its promotion lands."""
        if self.spiller is None or start % self.page_size != 0:
            return []
        toks = [int(t) for t in np.asarray(tokens).reshape(-1)]
        ps = self.page_size
        h = 0
        for i in range(start // ps):
            h = chain_hash(h, toks[i * ps: (i + 1) * ps])
        out: List[Tuple[int, int]] = []
        pos = start
        while len(out) < max_pages and pos + ps <= len(toks):
            block = tuple(toks[pos: pos + ps])
            nh = chain_hash(h, block)
            ent = self._host_full.get(nh)
            if ent is None or ent[1] != block:
                break
            out.append((ent[0], nh))
            self._host_lru.move_to_end(nh)
            h = nh
            pos += ps
        return out

    def pin_host(self, key: int) -> None:
        self._host_pins[key] = self._host_pins.get(key, 0) + 1

    def unpin_host(self, key: int) -> None:
        n = self._host_pins.get(key, 0) - 1
        if n <= 0:
            self._host_pins.pop(key, None)
        else:
            self._host_pins[key] = n

    @property
    def host_keys(self) -> List[int]:
        return [skey for skey, _block in self._host_full.values()]

    @property
    def host_entries(self) -> int:
        return len(self._host_full)

    def clear(self) -> None:
        while self.evict_lru():
            pass
        # the LRU drain above DEMOTES full chains when tiered — now drop
        # the host tier too (pins should be empty at clear time; a pinned
        # key here is a scheduler lifecycle bug surfaced by the store)
        for h in list(self._host_lru):
            skey, _block = self._host_full.pop(h)
            del self._host_lru[h]
            self.spiller.drop(skey)
            self._emit("evict", "host", h, -1)
