"""Host-side KV page management: pool allocator + prefix cache.

Parity: vLLM's PagedAttention block manager / DeepSpeed-FastGen's blocked
KV cache, host-side only. The jitted serving step never sees this module
— it consumes the *result* (per-slot page-table int32 vectors and an
optional copy-on-write source vector) and keeps its ONE fixed shape.
The only device-touching functions here are :func:`export_pages` /
:func:`import_pages`, the eager page-payload transfer the fleet's
prefill→decode KV handoff runs BETWEEN steps (serving/fleet/handoff.py).

- :class:`PagePool` — refcounted free-list over ``num_pages`` physical
  page ids. A page is *live* while any slot or prefix-cache entry holds a
  reference; ``free + live == num_pages`` is the leak invariant the
  scheduler asserts after every tick.
- :class:`PrefixCache` — chained-hash map from token prefixes to pages a
  finished request left behind. Full pages chain with
  ``crc32(block_bytes, prev_hash)``; the partial tail page is stored with
  its valid-token run. Matches verify actual token equality (hash
  collisions degrade to misses, never to wrong KV). Entries hold one pool
  reference each; LRU eviction under pool pressure drops that reference,
  freeing the page once no slot shares it.

Sharing is read-only: a slot whose write frontier lands inside a shared
page never writes it in place — the scheduler allocates a fresh page and
the step copies the shared page's KV into it before the chunk write
(copy-on-write, in-step, fixed shape).
"""

from __future__ import annotations

import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def chain_hash(prev: int, block) -> int:
    """Chained block hash: crc32 of the token block seeded by the previous
    link, so a page's key commits to the ENTIRE prefix before it (KV at a
    position depends on every earlier token)."""
    return zlib.crc32(np.asarray(block, np.int32).tobytes(), prev)


def chain_hashes(tokens, page_size: int) -> List[int]:
    """The chained hash of every FULL page-sized block of ``tokens``, in
    order. Because each link commits to the whole prefix before it, these
    keys are globally comparable: two caches (on two replicas) holding the
    same chain hash hold KV for the same token prefix — modulo crc32
    collisions, which every consumer must let degrade to misses (the
    router's index may mis-route on one; the replica's token-verified
    ``match`` then treats it as a miss, never as wrong KV)."""
    toks = np.asarray(tokens, np.int32).reshape(-1)
    ps = int(page_size)
    out: List[int] = []
    h = 0
    for i in range(toks.size // ps):
        h = chain_hash(h, toks[i * ps: (i + 1) * ps])
        out.append(h)
    return out


def longest_chain_walk(token_block_hashes, contains) -> int:
    """The ONE definition of "longest matching block chain": the length of
    the leading run of ``token_block_hashes`` for which ``contains(hash)``
    holds. Shared by :meth:`PrefixCache.longest_chain` (the replica-local
    cache view) and the fleet router's :class:`GlobalPrefixIndex` (the
    event-maintained cross-replica mirror), so routing and matching agree
    on what "longest chain" means. Accepts any iterable and consumes only
    up to the first miss — ``match`` feeds it a lazy hash generator, so a
    cold cache never pays for hashing a whole long prompt. Hash-presence
    only — callers that hand out KV must still verify token equality."""
    n = 0
    for h in token_block_hashes:
        if not contains(h):
            break
        n += 1
    return n


# ------------------------------------------------------- page payload I/O
def export_pages(cache: Dict[str, "object"], page_ids: Sequence[int]
                 ) -> Dict[str, "object"]:
    """Gather the payload of physical ``page_ids`` out of a paged KV pool
    (``init_paged_cache`` layout: the page axis is axis 1 of every leaf,
    scales included). Returns ``{leaf: [L, n_pages, ...]}`` device arrays
    — an immutable snapshot (the pool is updated functionally by the
    step, so later steps can never mutate an exported payload). This is
    the prefill half of the fleet's prefill→decode KV handoff: a page
    TRANSFER, not a tensor reshape."""
    import jax.numpy as jnp

    ids = jnp.asarray(np.asarray(page_ids, np.int32))
    return {k: jnp.take(v, ids, axis=1) for k, v in cache.items()}


def check_page_payload(cache: Dict[str, "object"],
                       payload: Dict[str, "object"], n_pages: int) -> None:
    """Validate an :func:`export_pages` payload against a destination
    pool: every leaf present, ``n_pages`` wide, page geometry matching."""
    for k, v in cache.items():
        if k not in payload:
            raise KeyError(f"import_pages: payload missing leaf {k!r}")
        p = payload[k]
        if p.shape[1] != n_pages or p.shape[0] != v.shape[0] \
                or p.shape[2:] != v.shape[2:]:
            raise ValueError(
                f"import_pages: payload {k} shape {p.shape} does not fit "
                f"{n_pages} pages of a pool leaf shaped {v.shape}"
            )


def scatter_pages(cache: Dict[str, "object"],
                  payload: Dict[str, "object"],
                  ids) -> Dict[str, "object"]:
    """The traceable scatter core of :func:`import_pages`. The serving
    engine jits this with the pool DONATED (import_kv_pages), so a
    handoff updates the destination arena in place — O(pages moved), not
    an O(arena) copy per transfer."""
    return {
        k: v.at[:, ids].set(payload[k].astype(v.dtype))
        for k, v in cache.items()
    }


def import_pages(cache: Dict[str, "object"], payload: Dict[str, "object"],
                 dst_page_ids: Sequence[int]) -> Dict[str, "object"]:
    """Scatter an :func:`export_pages` payload into ``dst_page_ids`` of a
    (possibly different) pool with the same page geometry. Returns the new
    pool dict; the caller owns re-asserting device placement/sharding
    (ServingEngine.import_kv_pages does, so the jitted step's donated
    carry keeps the layout it compiled against). Host-side refcounts of
    the destination pages are the destination scheduler's business —
    the leak invariant ``free + live == num_pages`` must hold on BOTH
    pools after every transfer (asserted by the fleet handoff)."""
    import jax.numpy as jnp

    ids = np.asarray(dst_page_ids, np.int32)
    check_page_payload(cache, payload, ids.size)
    return scatter_pages(cache, payload, jnp.asarray(ids))


class PagePool:
    """Refcounted physical-page allocator (host side, O(1) ops)."""

    def __init__(self, num_pages: int):
        if num_pages < 1:
            raise ValueError(f"PagePool needs >= 1 page, got {num_pages}")
        self.num_pages = int(num_pages)
        self.refcount = np.zeros(self.num_pages, np.int64)
        self._free: List[int] = list(range(self.num_pages - 1, -1, -1))

    def alloc(self) -> Optional[int]:
        """One fresh page with refcount 1, or None when exhausted."""
        if not self._free:
            return None
        page = self._free.pop()
        self.refcount[page] = 1
        return page

    def incref(self, page: int) -> None:
        if self.refcount[page] <= 0:
            raise AssertionError(f"incref on dead page {page}")
        self.refcount[page] += 1

    def decref(self, page: int) -> None:
        if self.refcount[page] <= 0:
            raise AssertionError(f"decref on dead page {page}")
        self.refcount[page] -= 1
        if self.refcount[page] == 0:
            self._free.append(page)

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def live_count(self) -> int:
        return int((self.refcount > 0).sum())

    def check_leaks(self, expected: Optional[Dict[int, int]] = None) -> None:
        """The leak invariant: ``free + live == num_pages``, and (when the
        caller supplies its own view) the pool's refcounts match the
        references the scheduler believes exist, page for page."""
        if self.free_count + self.live_count != self.num_pages:
            raise AssertionError(
                f"page leak: free {self.free_count} + live "
                f"{self.live_count} != num_pages {self.num_pages}"
            )
        if expected is not None:
            mine = {
                int(p): int(self.refcount[p])
                for p in np.nonzero(self.refcount)[0]
            }
            if mine != expected:
                raise AssertionError(
                    f"page refcount drift: pool {mine} != holders {expected}"
                )


class PrefixCache:
    """Token-prefix → shared KV pages, refcounted through a PagePool.

    Full pages key on the chain hash of all tokens up to and including the
    page; the partial tail keys on (chain hash so far, tail token run).
    ``match`` walks a prompt greedily and returns the shared pages plus
    how many tokens they cover; the caller caps the hit (a request must
    always feed at least its final prompt token to sample) and increfs.
    """

    def __init__(self, pool: PagePool, page_size: int):
        self.pool = pool
        self.page_size = int(page_size)
        # full pages: chain_hash -> (page, block_tuple); tails:
        # chain_hash -> [(tail_tuple, page), ...]. One LRU order over both
        # (key -> ("full"|"tail", chain_hash, page, tokens_tuple)).
        self._full: "OrderedDict[int, Tuple[int, Tuple[int, ...]]]" = (
            OrderedDict()
        )
        self._tails: Dict[int, List[Tuple[Tuple[int, ...], int]]] = {}
        self._lru: "OrderedDict[Tuple, None]" = OrderedDict()
        # cache-event listener: ``listener(event, kind, chain_hash, page)``
        # with event in {"insert", "evict"} and kind in {"full", "tail"}.
        # The fleet router's GlobalPrefixIndex subscribes here to mirror
        # each replica's full-page chain keys without polling; None (the
        # default) is the zero-overhead single-engine path.
        self.listener = None

    def _emit(self, event: str, kind: str, h: int, page: int) -> None:
        if self.listener is not None:
            self.listener(event, kind, h, page)

    def __len__(self) -> int:
        return len(self._lru)

    @property
    def held_pages(self) -> List[int]:
        return [key[2] for key in self._lru]

    # ---------------------------------------------------------------- match
    def longest_chain(self, token_block_hashes) -> int:
        """Public longest-matching-block-chain lookup: how many leading
        chained-crc32 FULL-page keys (:func:`chain_hashes`, or any lazy
        iterable of them — only the matched prefix is ever consumed) this
        cache holds. Hash-presence only — a crc32 collision can overstate
        the depth, which is exactly why :meth:`match` re-verifies token
        equality before handing out pages (collisions degrade to misses,
        never to wrong KV). Used by the scheduler's match path and by the
        fleet router's global index (the same :func:`longest_chain_walk`
        over its event-maintained per-replica mirror)."""
        return longest_chain_walk(token_block_hashes,
                                  self._full.__contains__)

    def match(self, prompt: Sequence[int]) -> Tuple[List[int], int]:
        """Longest cached prefix of ``prompt``: (pages, covered_tokens).
        Pages are NOT incref'd — the caller takes references for the ones
        it keeps. The hash walk is :meth:`longest_chain` over a LAZY
        chain-hash generator (a miss at block i stops hashing — a cold
        cache costs one crc32, not one per prompt page); token equality
        is then verified block-for-block (hash collisions shrink the
        match — a miss, never wrong KV)."""
        toks = [int(t) for t in np.asarray(prompt).reshape(-1)]
        ps = self.page_size
        hashes: List[int] = []

        def lazy_hashes():
            h = 0
            for i in range(len(toks) // ps):
                h = chain_hash(h, toks[i * ps: (i + 1) * ps])
                hashes.append(h)
                yield h

        depth = self.longest_chain(lazy_hashes())
        pages: List[int] = []
        covered = 0
        h = 0
        for i in range(depth):
            block = tuple(toks[covered: covered + ps])
            nh = hashes[i]
            entry = self._full[nh]
            if entry[1] != block:
                break  # crc32 collision: stop the walk — a miss
            pages.append(entry[0])
            self._lru.move_to_end(("full", nh, entry[0], block))
            covered += ps
            h = nh
        # partial tail: use the stored run's leading tokens that match the
        # remaining prompt (KV beyond the match is never attendable — the
        # joining slot's frontier stops at the match)
        rest = toks[covered:]
        best: Tuple[int, Tuple[Tuple[int, ...], int]] = (0, None)
        for tail, page in self._tails.get(h, ()):
            n = 0
            for a, b in zip(tail, rest):
                if a != b:
                    break
                n += 1
            if n > best[0]:
                best = (n, (tail, page))
        if best[0] > 0:
            tail, page = best[1]
            pages.append(page)
            self._lru.move_to_end(("tail", h, page, tail))
            covered += best[0]
        return pages, covered

    # --------------------------------------------------------------- insert
    def insert(self, tokens: Sequence[int], pages: Sequence[int]) -> int:
        """Publish a finished request's pages for reuse. ``tokens`` is the
        run whose KV the pages hold (prompt + generated-but-last);
        ``pages`` the physical pages covering it in order. Each entry the
        cache keeps takes ONE pool reference; duplicates of existing
        entries are skipped (the caller's own references are its business).
        Returns the number of entries inserted."""
        toks = [int(t) for t in np.asarray(tokens).reshape(-1)]
        ps = self.page_size
        inserted = 0
        h = 0
        full = len(toks) // ps
        for i in range(full):
            block = tuple(toks[i * ps: (i + 1) * ps])
            nh = chain_hash(h, block)
            if nh not in self._full:
                self._full[nh] = (int(pages[i]), block)
                self._lru[("full", nh, int(pages[i]), block)] = None
                self.pool.incref(int(pages[i]))
                self._emit("insert", "full", nh, int(pages[i]))
                inserted += 1
            # ALSO register the full page's run for partial matching: a
            # prompt diverging mid-page (the shared-system-prompt shape)
            # still shares this page's leading tokens, copy-on-write at
            # the divergence point
            inserted += self._add_tail(h, block, int(pages[i]))
            h = nh
        tail = tuple(toks[full * ps:])
        if tail and full < len(pages):
            inserted += self._add_tail(h, tail, int(pages[full]))
        return inserted

    def _add_tail(self, h: int, run: Tuple[int, ...], page: int) -> int:
        runs = self._tails.setdefault(h, [])
        if any(existing == run for existing, _ in runs):
            return 0
        runs.append((run, page))
        self._lru[("tail", h, page, run)] = None
        self.pool.incref(page)
        self._emit("insert", "tail", h, page)
        return 1

    # --------------------------------------------------------------- evict
    def evict_lru(self) -> bool:
        """Drop the least-recently-used entry (its pool reference with it).
        Returns False when the cache is empty."""
        if not self._lru:
            return False
        key, _ = self._lru.popitem(last=False)
        kind, h, page, toks = key
        if kind == "full":
            self._full.pop(h, None)
        else:
            runs = self._tails.get(h, [])
            self._tails[h] = [r for r in runs if r != (toks, page)]
            if not self._tails[h]:
                del self._tails[h]
        self.pool.decref(page)
        self._emit("evict", kind, h, page)
        return True

    def clear(self) -> None:
        while self.evict_lru():
            pass
