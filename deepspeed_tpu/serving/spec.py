"""Speculative decoding for the serving engines — ONE implementation.

Parity: the DeepSpeed serving stack's speculative path (draft-then-verify
with a cheap proposer and a single verifier forward per window). Two
engines consume this module:

- the **lockstep** engine (inference/engine.py ``_build_spec_decode``):
  B=1 greedy, the whole draft/verify loop inside one jitted
  ``lax.while_loop`` — it calls :func:`ngram_propose`,
  :func:`longest_accepted_prefix` and :func:`clamp_advance_at_eos` from
  its traced body;
- the **slot** engine (serving/engine.py): batched-ragged spec over the
  continuous-batching step. Draft proposal runs HOST-side per decode
  slot (:func:`propose_drafts` over the slot's committed token buffer),
  the existing ONE jitted ``[max_slots, token_budget]`` step verifies
  every slot's window at once (:func:`verify_window` — each spec slot's
  row carries its committed token + up to ``k`` drafts, so a spec slot
  consumes ``k+1`` budget rows), and acceptance advances the per-slot
  frontier by ``n_accepted + 1`` tokens per step.

Losslessness — the oracle the tests assert: acceptance is
**sample-and-match** against the slot's own deterministic RNG chain.
For window position ``j`` the verifier samples exactly the token the
spec-OFF engine would have sampled there (same logits — the conditioning
prefix matched — same chain key ``j``), and a draft is accepted only
when it EQUALS that token. Emitted tokens are therefore bit-identical to
the spec-off run for greedy AND sampled-with-shared-keys; drafts only
change how many verifier steps the generation needs, never its content.
(This is stricter than Leviathan/Chen modified rejection sampling, which
is lossless in distribution but not token-for-token; the serving
engine's contract since PR 5 is bitwise reproducibility, so the stricter
rule is the only admissible one.)

Cache discipline: a verify window writes K/V for its drafts at
positions ``frontier+1 .. frontier+k``. Rejected drafts leave garbage
there, which is dead by the frontier invariant (docs/serving.md): a
later query at position ``q`` only attends ``kpos <= q``, and every
position in ``[frontier', q]`` is rewritten by that query's own step
before it can be attended. Under the paged arena the pages backing a
rejected window stay owned by the slot (refcounted — ``free + live ==
num_pages`` keeps holding) and are simply rewritten as the frontier
catches up; rollback never frees or leaks a page.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = [
    "ngram_propose",
    "propose_drafts",
    "longest_accepted_prefix",
    "clamp_advance_at_eos",
    "advance_rng",
    "verify_window",
    "spec_verify_stream",
]


# --------------------------------------------------------------- proposing
def ngram_propose(buf, pos, k: int, n: int):
    """n-gram / prompt-lookup draft: propose ``k`` tokens for positions
    ``pos+1 .. pos+k`` of a ``[T]`` token buffer.

    The most recent earlier occurrence of the trailing ``n`` tokens at
    ``pos`` supplies the continuation (prompt-lookup decoding — zero
    parameters, a few VPU ops). With no match, the slice past ``pos``
    is returned instead: the lockstep engine keeps stale verifier
    predictions there, the slot engine appends the previous window's
    rejected targets (``RequestState.draft_tail``) — free, plausible
    proposals either way.

    Works traced (the lockstep jitted body: ``pos`` is a traced scalar)
    and host-side (the slot scheduler calls it per decode slot with
    concrete numpy inputs — that path runs pure NumPy, no device
    dispatch on the scheduling hot loop; SAME algorithm, the backends
    only differ in the final slice primitive). ``buf`` must have length
    >= pos + 1 + k so the fallback slice stays in bounds. The roll is
    safe: the ``idx >= n - 1`` guard keeps every compared index
    in-bounds, no wraparound match.
    """
    host = isinstance(buf, np.ndarray) and isinstance(pos, (int, np.integer))
    xp = np if host else jnp
    buf = xp.asarray(buf).astype(xp.int32)
    idx = xp.arange(buf.shape[0])
    match = (idx >= n - 1) & (idx < pos)
    for t in range(n):
        match &= xp.roll(buf, t) == xp.take(buf, pos - t)
    e = xp.max(xp.where(match, idx, -1))
    start = xp.where(e >= 0, e + 1, pos + 1)
    if host:
        start = int(start)
        return buf[start: start + k]
    return lax.dynamic_slice(buf, (start,), (k,))


def propose_drafts(prompt: Sequence[int], tokens: Sequence[int],
                   draft_tail: Sequence[int], k: int, n: int) -> np.ndarray:
    """Host-side draft proposal for one decode slot: ``k`` int tokens for
    the positions after the slot's last committed token.

    The lookup buffer is the committed stream (prompt + generated tokens,
    the last of which is the token this step feeds) with the previous
    verify's rejected targets appended as the no-match fallback run —
    exactly the lockstep buffer layout, through exactly the same
    :func:`ngram_propose`."""
    committed = np.concatenate([
        np.asarray(prompt, np.int32).reshape(-1),
        np.asarray(tokens, np.int32).reshape(-1),
    ])
    pos = int(committed.size - 1)
    tail = np.asarray(list(draft_tail), np.int32)
    pad = max(pos + 1 + k - (committed.size + tail.size), 0)
    buf = np.concatenate([committed, tail, np.zeros(pad, np.int32)])
    return np.asarray(ngram_propose(buf, pos, k, n), np.int32)


# -------------------------------------------------------------- acceptance
def longest_accepted_prefix(match):
    """Accepted-draft count from a ``[..., k]`` bool match vector: the
    length of the leading all-True run (a draft is only conditioned
    correctly when every draft before it was accepted)."""
    return jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=-1), axis=-1)


def clamp_advance_at_eos(targets, adv, eos_id):
    """Clamp a window advance at the first emitted eos: of the ``adv``
    tokens about to be emitted from ``targets [..., k]``, an eos at
    emitted index ``j`` cuts the advance to ``j + 1`` (the eos itself is
    emitted, nothing after it). Returns ``(adv, has_eos)``; ``eos_id``
    may be -1 (no eos — token ids are non-negative, nothing matches).
    Batched (``targets [N, k]``, ``adv``/``eos_id`` ``[N]``) and scalar
    (the lockstep body) forms share this one definition."""
    targets = jnp.asarray(targets)
    k = targets.shape[-1]
    adv_b = jnp.asarray(adv)[..., None]
    eos_b = jnp.asarray(eos_id)[..., None]
    acc = jnp.arange(k) < adv_b
    is_eos = (targets == eos_b) & acc
    has_eos = jnp.any(is_eos, axis=-1)
    adv = jnp.where(has_eos, jnp.argmax(is_eos, axis=-1) + 1,
                    jnp.asarray(adv))
    return adv, has_eos


# ------------------------------------------------------- the verify window
def advance_rng(key, flag):
    """One per-slot RNG chain advance: split ONLY when ``flag`` (the slot
    samples), mirroring the lockstep engine's chain. Returns
    ``(sample_key, next_chain)`` — both equal to ``key`` when gated."""
    pair = jax.random.split(key)  # [2, 2]: (sample key, next chain)
    use = jnp.broadcast_to(flag, key.shape)
    return (jnp.where(use, pair[0], key),
            jnp.where(use, pair[1], key))


def verify_window(sample_one, logits, tokens, seen, num_new, spec_len, live,
                  rng, temperature, top_k, top_p, rep_penalty, eos_id,
                  max_draft: int):
    """Batched-ragged verification inside the ONE jitted serving step.

    Every live slot's row ends with a verify window: its committed token
    followed by ``spec_len`` drafts (``spec_len = 0`` is plain decode /
    the final prefill feed — bitwise the pre-spec sampling tail). For
    each of the ``spec_len + 1`` window positions this samples the
    target token with the slot's advancing RNG chain (position ``j``
    uses chain key ``j`` — exactly the key the spec-off engine would
    burn on that token), accepts the longest draft prefix that matches
    the targets, clamps the advance at an emitted eos, and restores the
    chain to the state after exactly ``n_emit`` advances.

    Shapes (N = max_slots, W = token_budget, Kw = max_draft + 1):
      logits [N, W, V], tokens [N, W], seen [N, V],
      num_new/spec_len/eos_id [N] i32, live [N] bool, rng [N, 2] u32,
      temperature/top_p/rep_penalty [N] f32, top_k [N] i32.

    Returns ``(out_tokens [N, Kw] i32, n_emit [N] i32, new_rng [N, 2])``
    — ``out_tokens[:, :n_emit]`` are the slot's emitted tokens this
    step; ``n_emit`` is 0 for non-sampling rows. ``max_draft`` is STATIC
    (the step's fixed output shape); ``spec_len`` is traced, so any
    per-slot/per-step draft count runs the same compiled program.
    """
    from ..inference.engine import apply_repetition_penalty
    from ..models.decoding import gather_verify_window

    N, W = tokens.shape
    kw = max_draft + 1
    win = gather_verify_window(logits, num_new, spec_len, max_draft)
    # repetition penalty over the whole window with the pre-forward seen
    # matrix. Spec rows are penalty == 1.0 by the scheduler gate (the
    # seen matrix is built from FED tokens and spec-accepted tokens are
    # never re-fed — same reasoning as the prefix-cache bypass), so the
    # penalty math is bitwise identity there; spec_len == 0 rows take
    # exactly the pre-spec single-position path.
    win = apply_repetition_penalty(
        win, seen, rep_penalty[:, None, None], active=live
    )
    # the RNG chain, advanced kw times (live rows only): chains[j] is the
    # state after j advances, keys[j] the sample key position j uses.
    # n_emit <= spec_len + 1 restores the chain to chains[n_emit], so
    # keys past the emitted run are never consumed — the next step's
    # first sample reuses exactly the key spec-off would.
    chains = [rng]
    targets = []
    for j in range(kw):
        key_j, nxt = jax.vmap(advance_rng)(chains[-1], live)
        chains.append(nxt)
        targets.append(jax.vmap(sample_one)(
            win[:, j], key_j, temperature, top_k, top_p
        ))
    out_tokens = jnp.stack(targets, axis=1).astype(jnp.int32)  # [N, kw]
    # drafts ride in the row right after the committed token: window
    # position j's draft is tokens[base + 1 + j]
    base = num_new - 1 - spec_len
    draft_idx = jnp.clip(
        base[:, None] + 1 + jnp.arange(max_draft, dtype=jnp.int32)[None, :],
        0, W - 1,
    )
    drafts = jnp.take_along_axis(tokens, draft_idx, axis=1)  # [N, max_draft]
    in_window = jnp.arange(max_draft)[None, :] < spec_len[:, None]
    match = (drafts == out_tokens[:, :max_draft]) & in_window
    n_acc = longest_accepted_prefix(match)
    adv, _ = clamp_advance_at_eos(out_tokens, n_acc + 1, eos_id)
    n_emit = jnp.where(live, adv, 0).astype(jnp.int32)
    chain_stack = jnp.stack(chains, axis=1)  # [N, kw + 1, 2]
    new_rng = jnp.take_along_axis(
        chain_stack, n_emit[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]
    return out_tokens, n_emit, new_rng


# ------------------------------------------------------- planner metadata
def spec_verify_stream(cfg, max_slots: int, max_draft: int,
                       storage_itemsize: int, quantized: bool,
                       tp: int = 1) -> Dict[str, Any]:
    """Analytic per-step HBM traffic the verify windows ADD to the
    serving step, in the shared analytic-streams schema
    (comm_logger.record_streams / cost planner / rule R8). Upper bound at
    full draft occupancy: every slot's ``max_draft`` draft rows write
    K/V at every layer and are re-read by the window logits gather
    ``[N, max_draft + 1, V]`` (fp32). The bulk arena traffic itself is
    already priced by the ``kv_cache`` stream — this entry prices what
    turning spec ON costs on top, so shardplan sees the verify-window
    bytes statically."""
    from ..models.decoding import SCALE_LANES

    per_tok = cfg.kv_heads * cfg.hd * (1 if quantized else storage_itemsize)
    scale_tok = SCALE_LANES * 4 if quantized else 0
    draft_tokens = cfg.num_layers * max_slots * max_draft
    kv = draft_tokens * (per_tok + scale_tok) * 2  # k + v write + re-read
    window_logits = max_slots * (max_draft + 1) * cfg.vocab_size * 4
    total = kv + window_logits
    return {
        "kind": "hbm",
        "bytes_per_step": total,
        "per_device_bytes_per_step": total // max(tp, 1),
        "overlapped": False,  # part of the step's own compute traffic
        "spec": True,
        "max_draft": max_draft,
        "slots": max_slots,
        "quantized": quantized,
    }
