"""Continuous-batching serving runtime (DeepSpeed-MII / FastGen parity).

Request queue + Dynamic-SplitFuse scheduler + a slot-based engine whose
ONE jitted step of fixed shape ``[max_slots, token_budget]`` serves
arbitrary arrival patterns with zero recompiles after warmup. See
docs/serving.md for architecture, scheduler invariants, config keys and
the metrics glossary.
"""

from .engine import (ServingEngine, make_paged_step_fn, make_step_fn,
                     trace_serving_step)
from .fleet import GlobalPrefixIndex, ReplicaHandle, Router
from .metrics import FleetMetrics, ServingMetrics
from .paging import (PagePool, PrefixCache, chain_hashes, export_pages,
                     import_pages)
from .request import Request, RequestState, RequestStatus, request_rng
from .scheduler import Scheduler, StepPlan
from .spec import (clamp_advance_at_eos, longest_accepted_prefix,
                   ngram_propose, propose_drafts, verify_window)

__all__ = [
    "FleetMetrics",
    "GlobalPrefixIndex",
    "PagePool",
    "PrefixCache",
    "ReplicaHandle",
    "Router",
    "Request",
    "RequestState",
    "RequestStatus",
    "Scheduler",
    "ServingEngine",
    "ServingMetrics",
    "StepPlan",
    "chain_hashes",
    "clamp_advance_at_eos",
    "export_pages",
    "import_pages",
    "longest_accepted_prefix",
    "make_paged_step_fn",
    "make_step_fn",
    "ngram_propose",
    "propose_drafts",
    "request_rng",
    "trace_serving_step",
    "verify_window",
]
