"""Continuous-batching serving runtime (DeepSpeed-MII / FastGen parity).

Request queue + Dynamic-SplitFuse scheduler + a slot-based engine whose
ONE jitted step of fixed shape ``[max_slots, token_budget]`` serves
arbitrary arrival patterns with zero recompiles after warmup. See
docs/serving.md for architecture, scheduler invariants, config keys and
the metrics glossary.
"""

from .engine import (ServingEngine, make_paged_step_fn, make_step_fn,
                     trace_serving_step)
from .metrics import ServingMetrics
from .paging import PagePool, PrefixCache
from .request import Request, RequestState, RequestStatus, request_rng
from .scheduler import Scheduler, StepPlan
from .spec import (clamp_advance_at_eos, longest_accepted_prefix,
                   ngram_propose, propose_drafts, verify_window)

__all__ = [
    "PagePool",
    "PrefixCache",
    "Request",
    "RequestState",
    "RequestStatus",
    "Scheduler",
    "ServingEngine",
    "ServingMetrics",
    "StepPlan",
    "clamp_advance_at_eos",
    "longest_accepted_prefix",
    "make_paged_step_fn",
    "make_step_fn",
    "ngram_propose",
    "propose_drafts",
    "request_rng",
    "trace_serving_step",
    "verify_window",
]
