"""Serving requests: lifecycle, sampling state, deterministic RNG.

Parity: DeepSpeed-MII / FastGen's request objects (the continuous-batching
front door). A :class:`Request` is what a client submits; the scheduler
wraps it in a :class:`RequestState` that tracks the status lifecycle

    QUEUED -> PREFILL -> DECODE -> DONE
        \\______________________-> EVICTED   (timeout / queue overflow)

plus the per-request RNG chain. The RNG is DETERMINISTIC: a request's
sampled tokens depend only on (its key, its prompt, the params) — never
on what else shares the batch — which is what makes the slot engine
oracle-testable against N independent single-request ``generate`` calls.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np


class RequestStatus(str, Enum):
    QUEUED = "queued"      # admitted, waiting for a slot
    PREFILL = "prefill"    # slot assigned, prompt chunks streaming in
    DECODE = "decode"      # prompt cached, generating tokens
    DONE = "done"          # eos or max_new_tokens reached
    EVICTED = "evicted"    # timed out / rejected; retry after backoff


# legal lifecycle edges (EVICTED is reachable from any live state)
_TRANSITIONS = {
    RequestStatus.QUEUED: {RequestStatus.PREFILL, RequestStatus.EVICTED},
    RequestStatus.PREFILL: {RequestStatus.DECODE, RequestStatus.DONE,
                            RequestStatus.EVICTED},
    RequestStatus.DECODE: {RequestStatus.DONE, RequestStatus.EVICTED},
    RequestStatus.DONE: set(),
    RequestStatus.EVICTED: {RequestStatus.QUEUED},  # resubmission
}


def request_rng(request_id, seed: int = 0) -> jax.Array:
    """Deterministic per-request PRNG key: stable across processes and
    independent of submission order (fold the request id's CRC into a
    base key). Requests that want bit-reproducible sampled parity with a
    single-request ``generate(rng=...)`` call pass an explicit key
    instead."""
    h = zlib.crc32(str(request_id).encode()) & 0x7FFFFFFF
    return jax.random.fold_in(jax.random.PRNGKey(seed), h)


@dataclass
class Request:
    """One generation request (the client surface)."""

    request_id: str
    prompt: np.ndarray  # [S] int token ids
    max_new_tokens: int = 32
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    repetition_penalty: float = 1.0
    eos_token_id: int = -1
    rng: Optional[jax.Array] = None  # default: request_rng(request_id)
    session_id: Optional[str] = None  # fleet session affinity: requests
    #   sharing a session_id route to the same replica (their KV prefix
    #   reuse stays local); None = no stickiness. Single-engine serving
    #   ignores it — determinism never depends on placement.

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError(f"request {self.request_id}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"request {self.request_id}: max_new_tokens must be >= 1"
            )

    def rng_key(self) -> jax.Array:
        return self.rng if self.rng is not None else request_rng(
            self.request_id
        )


@dataclass
class RequestState:
    """Scheduler-side view of one request: status, slot, progress,
    timing. All timestamps come from the scheduler's injected clock."""

    request: Request
    status: RequestStatus = RequestStatus.QUEUED
    slot: Optional[int] = None
    arrival_t: float = 0.0
    prefill_start_t: Optional[float] = None
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    prompt_pos: int = 0          # prompt tokens already fed (chunked prefill)
    tokens: List[int] = field(default_factory=list)  # generated tokens
    attempts: int = 0            # submissions (eviction backoff input)
    retry_after: Optional[float] = None  # set on eviction
    evict_reason: Optional[str] = None
    rng: Optional[jax.Array] = None  # CURRENT key (advances as tokens sample)
    # ---- block-paged KV arena (scheduler-owned; empty on the contiguous
    # arena) ------------------------------------------------------------
    pages: List[int] = field(default_factory=list)  # physical page per
    #   logical page, in order; pages[:owned_from] are SHARED (read-only,
    #   prefix-cache refs) — a write into one triggers copy-on-write
    owned_from: int = 0          # first logical page this request owns
    cached_tokens: int = 0       # prompt tokens skipped via the prefix cache
    # ---- tiered KV (host spill; empty when serving.host_pages == 0) ----
    host_pages: Dict[int, Tuple[int, bool]] = field(default_factory=dict)
    #   logical page index -> (HostPageStore key, owned). While any entry
    #   exists the matching pages[li] is -1 (NULL sink) and the slot is
    #   unschedulable — the prefetcher promotes <= STAGE_SLOTS per tick
    #   until the map drains. owned=True keys are dropped from the store
    #   after promotion; owned=False keys belong to the prefix cache's
    #   host tier (pinned while referenced here, never dropped by us).
    last_planned: int = 0        # scheduler tick this slot last made
    #   progress (demotion victim ordering: coldest slot spills first)
    # ---- speculative decoding (serving/spec.py) -----------------------
    draft_tail: List[int] = field(default_factory=list)  # the previous
    #   verify window's REJECTED targets: stale-but-plausible verifier
    #   predictions that seed the next n-gram draft's no-match fallback
    #   (never emitted; cleared on eviction rollback)

    def __post_init__(self):
        if self.rng is None:
            self.rng = self.request.rng_key()

    # ----------------------------------------------------------- lifecycle
    def transition(self, new: RequestStatus) -> None:
        if new not in _TRANSITIONS[self.status]:
            raise ValueError(
                f"request {self.request.request_id}: illegal transition "
                f"{self.status.value} -> {new.value}"
            )
        self.status = new

    @property
    def prompt_len(self) -> int:
        return int(self.request.prompt.size)

    @property
    def prompt_remaining(self) -> int:
        return self.prompt_len - self.prompt_pos

    @property
    def finished(self) -> bool:
        return self.status in (RequestStatus.DONE, RequestStatus.EVICTED)

    def output(self) -> np.ndarray:
        """[prompt + max_new_tokens] ids, eos-padded past the last real
        token — the same layout single-request ``generate`` returns."""
        req = self.request
        fill = req.eos_token_id if req.eos_token_id >= 0 else 0
        out = np.full(self.prompt_len + req.max_new_tokens, fill, np.int32)
        out[: self.prompt_len] = req.prompt
        gen = np.asarray(self.tokens, np.int32)
        out[self.prompt_len: self.prompt_len + gen.size] = gen
        return out
