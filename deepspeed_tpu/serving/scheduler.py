"""Continuous-batching scheduler: admission, SplitFuse interleave, slots.

Parity: DeepSpeed-MII / FastGen's Dynamic SplitFuse scheduler. Every
engine step gets a :class:`StepPlan` of fixed shape
``[max_slots, token_budget]`` built under three invariants:

1. **Token budget** — at most ``token_budget`` REAL tokens are scheduled
   per step (sum of per-slot ``num_new``). Decode slots are served first
   (one committed feed each — they are latency-critical and starving them
   inflates every in-flight request's TPOT); with speculative decoding on
   (serving.spec) each decode slot then claims up to ``max_draft`` extra
   DRAFT rows — a spec slot costs ``k + 1`` budget rows, and under
   pressure ``k`` shrinks toward 0 (plain decode) before any slot loses
   its feed; leftover budget goes to prompt chunks FCFS, so long prompts
   "split" across steps and "fuse" with running decodes instead of
   monopolizing a step.
2. **Frontier** — a slot's ``start_pos`` always equals its cached token
   count; the engine writes the chunk there, so cache contents beyond a
   slot's frontier are never attendable (see models/decoding.py).
3. **Bounded queue** — admission beyond ``queue_limit`` is rejected
   GRACEFULLY (an EVICTED state with a ``retry_after`` backoff hint, not
   an exception); queued requests older than ``request_timeout_s`` are
   evicted the same way with exponential backoff on resubmission.

The clock is injected (``clock=``) so eviction and timing are unit
testable with a fake clock.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from ..utils.logging import log_dist
from . import faults
from .paging import STAGE_SLOTS, PagePool, PrefixCache
from .request import Request, RequestState, RequestStatus
from .spec import propose_drafts


@dataclass
class ScheduledWork:
    """One slot's share of a step."""

    slot: int
    state: RequestState
    n_tokens: int          # real tokens fed this step (committed + drafts)
    sample: bool           # does this step produce tokens for the slot?
    spec_len: int = 0      # draft tokens in the row's verify window: the
    #   slot emits 1..spec_len+1 tokens this step depending on acceptance


@dataclass
class StagedPage:
    """One host→HBM page promotion riding under this step's math.

    The engine decodes ``key``'s blob into the rotating staging buffer
    and the jitted step scatters it onto physical page ``dst_page``
    BEFORE the gathers (models/decoding.staged_promote) — the promoted
    page is attendable the same step. ``owned`` keys are dropped from
    the host store once the step lands (complete()); shared keys belong
    to the prefix cache's host tier and are merely unpinned."""

    dst_page: int
    key: int
    owned: bool
    state: RequestState


@dataclass
class StepPlan:
    """Fixed-shape arrays for ONE jitted engine step."""

    tokens: np.ndarray      # [max_slots, token_budget] int32 (0-padded)
    num_new: np.ndarray     # [max_slots] int32 (0 = slot idle this step)
    start_pos: np.ndarray   # [max_slots] int32 (slot frontier)
    fresh: np.ndarray       # [max_slots] bool (slot newly allocated)
    sample: np.ndarray      # [max_slots] bool
    # paged arena only (None on the contiguous arena):
    page_table: Optional[np.ndarray] = None  # [max_slots, pages_per_slot]
    #   int32 physical page per logical page; unmapped entries (and whole
    #   idle rows) point at the NULL sink page
    cow_src: Optional[np.ndarray] = None     # [max_slots] int32 physical
    #   page to copy-on-write onto the slot's frontier page (-1 = none)
    spec_len: Optional[np.ndarray] = None    # [max_slots] int32 draft
    #   tokens per row (speculative decoding; None/zeros = plain)
    work: List[ScheduledWork] = field(default_factory=list)
    stage: List[StagedPage] = field(default_factory=list)  # tiered KV:
    #   <= STAGE_SLOTS host pages promoting under this step (may be
    #   non-empty with an otherwise idle work list — a promote-only step
    #   still dispatches so waiting slots become schedulable)

    @property
    def total_tokens(self) -> int:
        return int(self.num_new.sum())


class Scheduler:
    def __init__(
        self,
        max_slots: int,
        token_budget: int,
        queue_limit: int = 64,
        request_timeout_s: float = 60.0,
        eviction_backoff_s: float = 1.0,
        max_tokens: int = 1024,
        clock: Callable[[], float] = time.monotonic,
        metrics=None,
        page_size: Optional[int] = None,
        num_pages: Optional[int] = None,
        pages_per_slot: Optional[int] = None,
        prefix_cache: bool = False,
        spec_max_draft: int = 0,
        spec_ngram_n: int = 3,
        spiller=None,
    ):
        self.max_slots = int(max_slots)
        self.token_budget = int(token_budget)
        self.queue_limit = int(queue_limit)
        self.request_timeout_s = float(request_timeout_s)
        self.eviction_backoff_s = float(eviction_backoff_s)
        self.max_tokens = int(max_tokens)
        self.clock = clock
        self.metrics = metrics
        self.queue: List[RequestState] = []           # FCFS admission queue
        self.slots: List[Optional[RequestState]] = [None] * self.max_slots
        self._free: List[int] = list(range(self.max_slots - 1, -1, -1))
        self._fresh: set = set()  # slots allocated since their first step
        self._decode_rr = 0  # rotating decode start: fairness when the
                             # token budget cannot cover every decode slot
        # ---- speculative decoding (serving.spec): each decode slot may
        # claim up to spec_max_draft draft rows on top of its committed
        # feed — a spec slot costs k+1 budget rows; under pressure k
        # shrinks toward 0 (plain decode) before any slot loses its feed
        self.spec_max_draft = int(spec_max_draft)
        self.spec_ngram_n = int(spec_ngram_n)
        # ---- block-paged arena bookkeeping (host side; the device only
        # sees the per-step page_table / cow_src int32 vectors) ----------
        self.paged = page_size is not None
        # ---- tiered KV (serving.host_pages > 0): the engine owns the
        # HostPageStore + PageSpiller (movement needs device access); the
        # scheduler owns POLICY — which pages demote under pressure,
        # which promote into the step's staging slots — plus the key
        # lifecycle (owned keys drop at complete(); shared prefix keys
        # stay pinned while a slot's promotion is in flight) -------------
        self.spiller = spiller if self.paged else None
        self._ticks = 0               # plan() counter (coldness ordering)
        self._inflight: Dict[int, bool] = {}  # store key -> owned, for
        #   promotions between plan() and complete() (invariant checks)
        self._plan_protect: set = set()  # id(state)s whose pages must not
        #   demote THIS tick (already planned / promoting — their pages
        #   are read or written by the step being built)
        self._promote_focus: Optional[int] = None  # slot index the
        #   promotion planner is committed to filling to full residency
        #   (sticky across ticks — see _plan_promotions)
        if self.paged:
            self.page_size = int(page_size)
            self.num_pages = int(num_pages)
            self.pages_per_slot = int(pages_per_slot)
            self.null_page = self.num_pages  # physical id of the sink page
            self.pool = PagePool(self.num_pages)
            self.prefix_cache = (
                PrefixCache(self.pool, self.page_size, spiller=self.spiller)
                if prefix_cache else None
            )
        else:
            self.pool = self.prefix_cache = None

    # -------------------------------------------------------------- intake
    def submit(self, request: Request) -> RequestState:
        """Admit (or gracefully reject) one request. Always returns the
        state; check ``state.status`` — EVICTED means rejected, with
        ``retry_after``/``evict_reason`` saying when/why."""
        now = self.clock()
        state = RequestState(request=request, arrival_t=now)
        state.attempts = 1
        return self._enqueue(state, now)

    def resubmit(self, state: RequestState) -> RequestState:
        """Retry a previously evicted request (backoff already elapsed is
        the caller's business; the scheduler only counts attempts)."""
        if state.status is not RequestStatus.EVICTED:
            raise ValueError(
                f"resubmit needs an EVICTED state, got {state.status.value}"
            )
        now = self.clock()
        state.transition(RequestStatus.QUEUED)
        state.arrival_t = now
        state.attempts += 1
        state.retry_after = None
        state.evict_reason = None
        return self._enqueue(state, now)

    def _enqueue(self, state: RequestState, now: float) -> RequestState:
        req = state.request
        # every submission counts as submitted, including the ones the
        # checks below reject — 'submitted >= rejected' must always hold
        if self.metrics is not None:
            self.metrics.on_submit(state, now, queue_depth=len(self.queue))
        if req.prompt.size + req.max_new_tokens > self.max_tokens:
            return self._evict(
                state, now,
                f"prompt+max_new_tokens {req.prompt.size + req.max_new_tokens}"
                f" exceeds serving.max_tokens {self.max_tokens}",
            )
        # admission is EAGER: drain waiters into free slots before judging
        # the bound, so a bounded queue never rejects while capacity idles
        self._admit_to_slots(now)
        if self.queue_limit and len(self.queue) >= self.queue_limit:
            return self._evict(state, now, "queue full")
        self.queue.append(state)
        self._admit_to_slots(now)  # the arrival itself may slot immediately
        return state

    def _evict(self, state: RequestState, now: float,
               reason: str) -> RequestState:
        if state.status is RequestStatus.QUEUED and state in self.queue:
            self.queue.remove(state)
        if state.status is not RequestStatus.EVICTED:
            state.transition(RequestStatus.EVICTED)
        # exponential backoff: each failed attempt doubles the retry hint
        state.retry_after = now + self.eviction_backoff_s * (
            2 ** max(state.attempts - 1, 0)
        )
        state.evict_reason = reason
        state.finish_t = now
        if state.slot is not None:
            self.release(state.slot)
            state.slot = None
            # mid-flight eviction (page-pool starvation) loses the slot's
            # KV: restart cleanly on resubmission — progress, generated
            # tokens and the RNG chain rewind to the request's origin so
            # a retried request still reproduces its deterministic output
            state.prompt_pos = 0
            state.tokens = []
            state.draft_tail = []
            state.rng = state.request.rng_key()
            state.first_token_t = None  # the retry's TTFT is its own
        if self.metrics is not None:
            self.metrics.on_evict(state, now)
        log_dist(f"serving: evicted {state.request.request_id}: {reason}")
        return state

    # ------------------------------------------------------------- slots
    def release(self, slot: int, *, insert_prefix: bool = False) -> None:
        """Recycle a slot (its KV range is dead past the next frontier).
        Paged arena: drop the slot's page references — and, for finished
        requests (``insert_prefix``), publish its pages to the prefix
        cache first so identical prompts skip their prefill entirely."""
        state = self.slots[slot]
        if state is not None:
            self.slots[slot] = None
            self._free.append(slot)
            self._fresh.discard(slot)
            if self.paged:
                self._release_pages(state, insert=insert_prefix)

    # ------------------------------------------------------------- pages
    def _release_pages(self, state: RequestState, insert: bool) -> None:
        pages, state.pages = state.pages, []
        host, state.host_pages = state.host_pages, {}
        state.owned_from = 0
        # tiered: entries still waiting on promotion hold store keys, not
        # HBM pages. Owned keys (slot demotions) die with the slot;
        # shared keys belong to the prefix cache's host tier — unpin so
        # host-LRU pressure may reclaim them again
        for key, owned in host.values():
            if owned:
                self.spiller.drop(key)
            elif self.prefix_cache is not None:
                self.prefix_cache.unpin_host(key)
        if not pages:
            return
        if insert and self.prefix_cache is not None:
            # KV exists for prompt + generated-but-last (the final sampled
            # token was never fed back, so its K/V was never written).
            # A -1 placeholder (unpromoted host page) truncates the
            # publishable run — its HBM content does not exist
            pub = pages
            if -1 in pages:
                pub = pages[: pages.index(-1)]
            frontier = state.prompt_len + max(len(state.tokens) - 1, 0)
            seq = np.concatenate([
                np.asarray(state.request.prompt, np.int32),
                np.asarray(state.tokens[:-1], np.int32),
            ])[:frontier]
            covered = min(len(seq), len(pub) * self.page_size)
            self.prefix_cache.insert(seq[:covered], pub)
        for p in pages:
            if p != -1:
                self.pool.decref(p)

    def _attach_prefix(self, state: RequestState) -> None:
        """Prefix-cache lookup at slot admission: the longest cached
        prefix becomes shared (refcounted, read-only) pages and its
        tokens skip prefill. Capped at prompt_len - 1 — a request must
        always feed its final prompt token to sample the first output, so
        a full-prompt hit enters decode with ONE single-token feed (and a
        copy-on-write of the shared tail page) instead of prefill
        chunks."""
        state.pages = []
        state.owned_from = 0
        state.cached_tokens = 0
        if self.prefix_cache is None:
            return
        if state.request.repetition_penalty != 1.0:
            # the repetition-penalty ``seen`` matrix is built from FED
            # tokens; a cache hit skips feeding the cached prompt, so a
            # penalized request's sampling would depend on cache warmth.
            # Penalized requests therefore always prefill — correctness
            # (bitwise parity with the single-request oracle) over reuse.
            return
        pages, covered = self.prefix_cache.match(state.request.prompt)
        covered = min(covered, state.prompt_len - 1)
        npages = -(-covered // self.page_size) if covered > 0 else 0
        pages = pages[:npages]
        for p in pages:
            self.pool.incref(p)
        state.pages = list(pages)
        state.owned_from = len(pages)
        # tiered: the chain may continue in the HOST tier past the
        # resident hit. Attach those blocks as -1 placeholders + pinned
        # store keys — the slot waits on promotion instead of refeeding
        # the prompt. Host pages are whole blocks, so the extension keeps
        # ``covered`` page-aligned and the write frontier lands exactly
        # on the first un-promoted page (promoted pages are never
        # written: no COW interaction).
        n_host = 0
        if self.spiller is not None and covered == npages * self.page_size:
            cap = min(
                self.pages_per_slot - npages,
                # the final prompt token must still be FED (sampling):
                # never cover past prompt_len - 1
                (state.prompt_len - 1 - covered) // self.page_size,
            )
            for key, _h in self.prefix_cache.host_chain(
                    state.request.prompt, covered, cap):
                state.host_pages[len(state.pages)] = (key, False)
                self.prefix_cache.pin_host(key)
                state.pages.append(-1)
                covered += self.page_size
                n_host += 1
        state.cached_tokens = covered
        state.prompt_pos = covered
        if self.metrics is not None:
            self.metrics.on_prefix_lookup(
                covered, state.prompt_len,
                host_tokens=n_host * self.page_size,
            )

    def _alloc_page(self, protect=(), stalled_only=False) -> Optional[int]:
        """One fresh page, evicting LRU prefix-cache entries under
        pressure — and, tiered, demoting cold live-slot pages to the
        host store; None when every tier is truly exhausted.

        ``protect`` lists RequestStates whose pages must not demote
        (typically the state the page is being allocated FOR).
        ``stalled_only`` restricts demotion victims to slots that are
        ALREADY waiting on host pages — the promotion planner's mode:
        feeding a waiter must never un-run a resident slot (see
        :meth:`_plan_promotions` for the liveness argument)."""
        p = self.pool.alloc()
        while p is None and self.prefix_cache is not None \
                and self.prefix_cache.evict_lru():
            p = self.pool.alloc()
        while p is None and self.spiller is not None \
                and self._demote_for_page(protect, stalled_only):
            p = self.pool.alloc()
        return p

    def _written_tokens(self, state: RequestState) -> int:
        """KV positions this slot has actually WRITTEN: the chunked
        prefill frontier, plus — in decode — everything before the
        current position (the latest sampled token was never fed)."""
        if state.status is RequestStatus.DECODE:
            return state.prompt_len + len(state.tokens) - 1
        return state.prompt_pos

    def _demote_for_page(self, protect=(), stalled_only=False) -> bool:
        """Spill ONE cold page to the host tier to relieve pool pressure.

        Victim order: coldest slot first (oldest ``last_planned``), its
        lowest fully-written OWNED page (refcount 1 — shared prefix pages
        are the cache's to evict, and the frontier page is excluded by
        the fully-written test so COW never meets a demoted page). The
        put-before-free contract lives in PageSpiller.demote: on a full
        host store nothing was mutated and we report failure — the
        caller falls through to the forced-eviction backstop.

        ``stalled_only`` limits victims to slots already waiting on host
        pages (they cannot decode this tick anyway, so taking more of
        their pages costs no progress)."""
        skip = {id(s) for s in protect} | self._plan_protect
        victims = sorted(
            (s for s in self.slots
             if s is not None and id(s) not in skip
             and not (stalled_only and not s.host_pages)),
            key=lambda s: (s.last_planned, s.slot),
        )
        ps = self.page_size
        for state in victims:
            full = self._written_tokens(state) // ps
            for li in range(state.owned_from, min(len(state.pages), full)):
                if state.pages[li] == -1 or li in state.host_pages:
                    continue
                key = self.spiller.demote(state.pages[li])
                if key is None:
                    return False  # host store full: nothing was mutated
                page = state.pages[li]
                state.host_pages[li] = (key, True)
                state.pages[li] = -1
                self.pool.decref(page)  # refcount 1 -> frees the page
                return True
        return False

    def alloc_pages(self, n: int) -> Optional[List[int]]:
        """``n`` fresh pages all-or-nothing (LRU prefix-cache eviction
        under pressure, like :meth:`_alloc_page`): the fleet KV handoff's
        destination-side allocation. On exhaustion every page already
        taken is returned to the pool — a failed transfer must leave
        ``free + live == num_pages`` intact on this side too."""
        got: List[int] = []
        for _ in range(int(n)):
            p = self._alloc_page()
            if p is None:
                for q in got:
                    self.pool.decref(q)
                return None
            got.append(p)
        return got

    def adopt(self, state: RequestState) -> int:
        """Adopt an in-flight DECODE request whose KV this scheduler's
        arena already holds (the fleet's prefill→decode handoff: the
        caller imported the page payload and set ``state.pages`` to pages
        allocated FROM THIS scheduler's pool via :meth:`alloc_pages`).
        Returns the slot. The slot is marked fresh so its first decode
        feed clears the previous occupant's stale ``seen`` row."""
        if not self._free:
            raise RuntimeError("adopt: no free slot")
        if state.status is not RequestStatus.DECODE:
            raise ValueError(
                f"adopt needs a DECODE state, got {state.status.value}"
            )
        if self.paged and len(state.pages) > self.pages_per_slot:
            raise ValueError(
                f"adopt: {len(state.pages)} pages exceed pages_per_slot "
                f"{self.pages_per_slot}"
            )
        slot = self._free.pop()
        state.slot = slot
        self.slots[slot] = state
        self._fresh.add(slot)
        return slot

    def _prepare_pages(self, state: RequestState, start: int,
                       n: int) -> tuple:
        """Make [start, start + n) writable for one slot: allocate fresh
        pages covering the span and copy-on-write the frontier page when
        it is shared. Returns ``(n_writable, cow_src)`` — pool pressure
        may shrink the chunk (0 = skip the slot this step); ``cow_src``
        is the physical page the step must copy onto the slot's frontier
        page, or -1."""
        ps = self.page_size
        need = min(-(-(start + n) // ps), self.pages_per_slot)
        while len(state.pages) < need:
            p = self._alloc_page(protect=(state,))
            if p is None:
                break
            state.pages.append(p)
        n = min(n, len(state.pages) * ps - start)
        if n <= 0:
            return 0, -1
        cow = -1
        fp = start // ps
        if fp < state.owned_from:
            # the write frontier sits inside a shared page: divergence.
            # Remap to a fresh page; the step copies the shared page's KV
            # onto it BEFORE the chunk write. Decref-ing the shared page
            # immediately is safe even if it frees: the step's COW gather
            # reads pre-step pool content, and any new owner's writes land
            # in the later scatter phase.
            newp = self._alloc_page(protect=(state,))
            if newp is None:
                return 0, -1
            cow = state.pages[fp]
            state.pages[fp] = newp
            state.owned_from = fp
            self.pool.decref(cow)
            if self.metrics is not None:
                self.metrics.on_cow()
        return n, cow

    def assert_page_invariants(self) -> None:
        """The leak invariant after every tick: ``free + live ==
        num_pages``, and every live page's refcount equals exactly the
        slot + prefix-cache references the scheduler knows about.

        Tiered, the ledger spans BOTH tiers: every host-store key must be
        accounted for by exactly the references the scheduler knows —
        owned slot demotions, in-flight promotions, and the prefix
        cache's host chains — and HBM free + HBM live + host-resident
        must equal the total logical page count. A mid-demotion failure
        (full host store) mutates nothing, so this holds on every tick
        including the rollback path."""
        if not self.paged:
            return
        expected: dict = {}
        for st in self.slots:
            if st is None:
                continue
            for p in st.pages:
                if p != -1:
                    expected[p] = expected.get(p, 0) + 1
        if self.prefix_cache is not None:
            for p in self.prefix_cache.held_pages:
                expected[p] = expected.get(p, 0) + 1
        self.pool.check_leaks(expected)
        if self.spiller is not None:
            store = self.spiller.store
            exp_keys = set(self._inflight)
            for st in self.slots:
                if st is None:
                    continue
                exp_keys.update(k for k, _ in st.host_pages.values())
            if self.prefix_cache is not None:
                exp_keys.update(self.prefix_cache.host_keys)
            actual = set(store.keys())
            assert actual == exp_keys, (
                f"host page leak: store holds {sorted(actual - exp_keys)} "
                f"unreferenced / missing {sorted(exp_keys - actual)}"
            )
            total = (self.pool.free_count + self.pool.live_count
                     + store.resident_count)
            assert total == self.num_pages + len(exp_keys), (
                f"cross-tier page leak: HBM free {self.pool.free_count} + "
                f"live {self.pool.live_count} + host {store.resident_count}"
                f" != {self.num_pages} + {len(exp_keys)} logical pages"
            )

    def evict_timeouts(self) -> List[RequestState]:
        """Evict queued requests that waited past request_timeout_s."""
        now = self.clock()
        timed_out = [
            s for s in self.queue
            if now - s.arrival_t > self.request_timeout_s
        ]
        return [self._evict(s, now, "queue timeout") for s in timed_out]

    def _admit_to_slots(self, now: float) -> None:
        while self._free and self.queue:
            state = self.queue.pop(0)  # FCFS
            slot = self._free.pop()
            state.slot = slot
            state.transition(RequestStatus.PREFILL)
            state.prefill_start_t = now
            self.slots[slot] = state
            self._fresh.add(slot)
            if self.paged:
                self._attach_prefix(state)
            if self.metrics is not None:
                self.metrics.on_admit(state, now,
                                      queue_depth=len(self.queue))

    # -------------------------------------------------------------- plan
    @property
    def active_count(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    @property
    def has_work(self) -> bool:
        return bool(self.queue) or self.active_count > 0

    def _plan_promotions(self) -> List[StagedPage]:
        """Drain waiting host pages into this step's staging slots
        (<= STAGE_SLOTS per tick — the rotating in-step staging buffer is
        that wide).

        The liveness argument, in three parts. (1) Promotion allocations
        run ``stalled_only``: a waiter is only ever fed from free pages,
        LRU prefix chains, or OTHER stalled slots' pages — never by
        demoting a resident (runnable) slot, so whatever is running keeps
        running. (2) The planner is STICKY: the slot it started filling
        (``_promote_focus``) goes first every tick until it has no host
        pages left — a slot needing more than STAGE_SLOTS pages reaches
        full residency in ceil(n / STAGE_SLOTS) consecutive ticks instead
        of round-robining with the other waiters forever. (3) A promoted
        slot is warmed (``last_planned``) so the victim ordering doesn't
        eat its pages before it decodes. Without (1)+(2), 4 slots of 4
        pages over an 8-page pool livelock: 2 pages in, 2 pages out,
        every tick, zero tokens."""
        stage: List[StagedPage] = []
        if self.spiller is None:
            return stage
        # seeded-bug seam (serving/faults.py): fleetcheck's --mutate
        # smoke re-introduces the pre-guard planner — no stickiness, and
        # waiter feeds may demote resident slots — to prove the checker
        # finds the PR 18 livelock. (Warming stays on: it is exactly
        # what rotates the unsticky planner's focus, so each waiter gets
        # STAGE_SLOTS pages and then yields before reaching residency.)
        # Never armed outside tests.
        sticky = not faults.armed("promotion_unsticky")
        waiting = sorted(
            (s for s in self.slots if s is not None and s.host_pages),
            key=lambda s: (s.last_planned, s.slot),
        )
        if self._promote_focus is not None and sticky:
            focus = next(
                (s for s in waiting if s.slot == self._promote_focus), None
            )
            if focus is None:
                self._promote_focus = None  # drained or slot turned over
            else:
                waiting.remove(focus)
                waiting.insert(0, focus)
        for state in waiting:
            if len(stage) >= STAGE_SLOTS:
                break
            self._plan_protect.add(id(state))
            promoted = False
            for li in sorted(state.host_pages):
                if len(stage) >= STAGE_SLOTS:
                    break
                dst = self._alloc_page(protect=(state,),
                                       stalled_only=sticky)
                if dst is None:
                    break  # pool bound even after demotions: wait a tick
                key, owned = state.host_pages.pop(li)
                state.pages[li] = dst
                self._inflight[key] = owned
                stage.append(StagedPage(dst, key, owned, state))
                promoted = True
            if promoted:
                # a promotion IS progress: warm the slot so the next
                # tick's victim ordering doesn't re-demote these pages
                # before the slot ever decodes through them (the other
                # half of the liveness argument — _plan_protect only
                # covers THIS tick)
                state.last_planned = self._ticks
                if state.host_pages:
                    if sticky:
                        # sticky: keep filling THIS slot next tick until
                        # it is fully resident
                        self._promote_focus = state.slot
                        break
                elif state.slot == self._promote_focus:
                    self._promote_focus = None
        return stage

    def plan(self) -> Optional[StepPlan]:
        """Build the next step's fixed-shape work, or None when idle."""
        now = self.clock()
        self._ticks += 1
        self._plan_protect = set()
        self.evict_timeouts()
        self._admit_to_slots(now)
        stage = self._plan_promotions()
        plan = self._build_plan(stage)
        # paged arena: an empty plan while slots are live means page-pool
        # starvation (a live slot always schedules otherwise). Evict the
        # NEWEST in-flight request — gracefully, it can resubmit after
        # backoff — and retry, so the oldest requests always finish. The
        # config floor num_pages >= pages_per_slot makes this terminate
        # with at least one schedulable request.
        while plan is None and self.paged and self.active_count > 0:
            victim = max(
                (s for s in self.slots if s is not None),
                key=lambda s: (s.prefill_start_t or 0.0, s.slot),
            )
            self._evict(victim, now, "page pool exhausted")
            self._admit_to_slots(now)
            plan = self._build_plan(stage)
        if plan is not None and plan.stage:
            # a promotion planned for a slot the starvation loop evicted
            # must not scatter into its (freed) destination page: consume
            # the key here — _release_pages already dropped the slot's
            # un-promoted keys, but THESE were popped into the stage list
            live = [s for s in plan.stage if s.state.slot is not None]
            for s in plan.stage:
                if s.state.slot is None:
                    self._inflight.pop(s.key, None)
                    if s.owned:
                        self.spiller.drop(s.key)
                    elif self.prefix_cache is not None:
                        self.prefix_cache.unpin_host(s.key)
            plan.stage = live
        if self.paged:
            self.assert_page_invariants()
            if self.metrics is not None:
                self.metrics.on_pages(
                    self.pool,
                    len(self.prefix_cache) if self.prefix_cache else 0,
                    host_resident=(
                        self.spiller.store.resident_count
                        if self.spiller is not None else 0
                    ),
                )
        if plan is not None and self.metrics is not None:
            self.metrics.on_plan(plan, now, queue_depth=len(self.queue),
                                 occupancy=self.active_count)
        return plan

    def _build_plan(self, stage: Optional[List[StagedPage]] = None
                    ) -> Optional[StepPlan]:
        N, W = self.max_slots, self.token_budget
        plan = StepPlan(
            tokens=np.zeros((N, W), np.int32),
            num_new=np.zeros(N, np.int32),
            start_pos=np.zeros(N, np.int32),
            fresh=np.zeros(N, np.bool_),
            sample=np.zeros(N, np.bool_),
            page_table=(
                np.full((N, self.pages_per_slot), self.null_page, np.int32)
                if self.paged else None
            ),
            cow_src=np.full(N, -1, np.int32) if self.paged else None,
            spec_len=np.zeros(N, np.int32),
            stage=list(stage) if stage else [],
        )
        budget = W
        # decodes first: latency-critical, one committed feed each. The
        # scan starts at a ROTATING index so a budget smaller than the
        # decode count round-robins across steps instead of
        # deterministically starving the high-index slots.
        decodes: List[list] = []  # [slot, state, pos, cow, k]
        for off in range(N):
            slot = (self._decode_rr + off) % N
            state = self.slots[slot]
            if state is None or state.status is not RequestStatus.DECODE:
                continue
            if state.host_pages:
                continue  # tiered: waiting on promotion — attention
                #   gathers the whole sequence, so a slot with ANY page
                #   still on host cannot schedule this step
            if budget < 1:
                break
            pos = state.prompt_len + len(state.tokens) - 1
            cow = -1
            if self.paged:
                ok, cow = self._prepare_pages(state, pos, 1)
                if ok < 1:
                    continue  # page pressure: this decode waits a step
            self._plan_protect.add(id(state))
            state.last_planned = self._ticks
            decodes.append([slot, state, pos, cow, 0])
            budget -= 1
        self._decode_rr = (self._decode_rr + 1) % N
        # speculative drafts ride WITH the decode pass: a spec slot's row
        # claims k+1 budget rows (committed feed + k drafts), assigned
        # round-robin one draft at a time so budget pressure shrinks k
        # toward 0 uniformly — plain decode is the graceful floor, and the
        # step shape never changes
        if self.spec_max_draft > 0 and budget > 0 and decodes:
            budget = self._assign_drafts(decodes, budget)
        for slot, state, pos, cow, k in decodes:
            row = [state.tokens[-1]]
            if k > 0:
                drafts = propose_drafts(
                    state.request.prompt, state.tokens, state.draft_tail,
                    k, self.spec_ngram_n,
                )
                row.extend(int(t) for t in drafts)
            n = len(row)
            plan.tokens[slot, :n] = row
            plan.num_new[slot] = n
            plan.start_pos[slot] = pos
            plan.sample[slot] = True
            plan.spec_len[slot] = n - 1
            # an ADOPTED slot (fleet handoff) enters decode directly: its
            # first feed clears the previous occupant's stale seen row
            plan.fresh[slot] = slot in self._fresh
            self._fresh.discard(slot)
            if self.paged:
                plan.cow_src[slot] = cow
                plan.page_table[slot, :len(state.pages)] = state.pages
            plan.work.append(ScheduledWork(slot, state, n, True,
                                           spec_len=n - 1))
        # leftover budget to prompt chunks, FCFS by prefill start
        prefills = sorted(
            (
                (slot, state) for slot, state in enumerate(self.slots)
                if state is not None
                and state.status is RequestStatus.PREFILL
            ),
            key=lambda it: (it[1].prefill_start_t, it[0]),
        )
        for slot, state in prefills:
            if budget < 1:
                break
            if state.host_pages:
                continue  # tiered: prefix tail still on host — the write
                #   frontier sits past pages that must promote first
            chunk = min(budget, state.prompt_remaining, W)
            lo = state.prompt_pos
            cow = -1
            if self.paged:
                chunk, cow = self._prepare_pages(state, lo, chunk)
                if chunk < 1:
                    continue  # page pressure: the prompt waits a step
            self._plan_protect.add(id(state))
            state.last_planned = self._ticks
            plan.tokens[slot, :chunk] = state.request.prompt[lo: lo + chunk]
            plan.num_new[slot] = chunk
            plan.start_pos[slot] = lo
            final = lo + chunk == state.prompt_len
            plan.sample[slot] = final
            plan.fresh[slot] = slot in self._fresh
            self._fresh.discard(slot)
            if self.paged:
                plan.cow_src[slot] = cow
                plan.page_table[slot, :len(state.pages)] = state.pages
            if self.metrics is not None:
                # a fully-cached prompt's only feed is its final token
                # (the sampling feed) — that is NOT a prefill chunk
                self.metrics.on_prefill_chunk(
                    cached_tail=(
                        state.cached_tokens >= state.prompt_len - 1
                        and lo == state.prompt_len - 1
                    ),
                )
            plan.work.append(ScheduledWork(slot, state, chunk, final))
            budget -= chunk
        # inactive slots keep num_new=0 and start_pos=0; the ENGINE
        # repoints their padded W-wide cache write at the dead tail
        # margin (ServingEngine._run_plan) — or, paged, their all-NULL
        # page-table row sinks it — so an idle-but-active slot never
        # clobbers its own cached tokens
        if not plan.work and not plan.stage:
            return None
        return plan

    def _assign_drafts(self, decodes: List[list], budget: int) -> int:
        """Distribute leftover budget as draft rows over the scheduled
        decode slots, one draft per slot per round (round-robin in the
        same rotating order as the feed pass), until every slot hits its
        cap or the budget runs out. Caps: ``spec_max_draft``, the
        request's remaining token allowance minus one (the device then
        never emits past ``max_new_tokens``, which keeps the RNG chain
        exactly where spec-off would leave it), and — paged — the pages
        actually allocatable for the widened window (pool pressure
        shrinks k instead of failing; pages stay slot-owned on
        rejection, so rollback never leaks). Requests with
        ``repetition_penalty != 1.0`` never draft: their ``seen`` matrix
        is built from fed tokens and accepted spec tokens are never
        re-fed — correctness over speed, same as the prefix-cache
        bypass."""
        grew = True
        while budget > 0 and grew:
            grew = False
            for item in decodes:
                if budget < 1:
                    break
                slot, state, pos, cow, k = item
                req = state.request
                if req.repetition_penalty != 1.0:
                    continue
                cap = min(
                    self.spec_max_draft,
                    req.max_new_tokens - len(state.tokens) - 1,
                    self.token_budget - 1,
                )
                if k >= cap:
                    continue
                if self.paged:
                    ok, _ = self._prepare_pages(state, pos, k + 2)
                    if ok < k + 2:
                        continue  # page pressure: this slot stops growing
                item[4] = k + 1
                budget -= 1
                grew = True
        return budget

    # ---------------------------------------------------------- complete
    def complete(self, plan: StepPlan, next_tokens: np.ndarray,
                 new_rng: Optional[np.ndarray] = None,
                 n_emit: Optional[np.ndarray] = None
                 ) -> List[RequestState]:
        """Fold one executed step back into request state. Returns the
        requests that finished this step (slots already recycled).

        ``next_tokens`` is the engine's verify-window output
        ``[max_slots, max_draft + 1]`` with ``n_emit`` tokens emitted
        per sampling slot (speculative decoding: accepted drafts + the
        bonus token advance a slot by >1 per step). The legacy 1-D form
        ``[max_slots]`` (one token per sampling slot) is still accepted —
        scheduler unit tests and pre-spec callers pass that."""
        next_tokens = np.asarray(next_tokens)
        if next_tokens.ndim == 1:
            next_tokens = next_tokens[:, None]
        now = self.clock()
        finished: List[RequestState] = []
        for w in plan.work:
            st = w.state
            if w.n_tokens and st.status is RequestStatus.PREFILL:
                st.prompt_pos += w.n_tokens
            if not w.sample:
                continue
            n = int(n_emit[w.slot]) if n_emit is not None else 1
            if new_rng is not None:
                st.rng = new_rng[w.slot]
            req = st.request
            emitted = 0
            for j in range(n):
                tok = int(next_tokens[w.slot, j])
                if st.first_token_t is None:
                    st.first_token_t = now
                st.tokens.append(tok)
                emitted += 1
                if st.status is RequestStatus.PREFILL:
                    st.transition(RequestStatus.DECODE)
                if self.metrics is not None:
                    self.metrics.on_token(st, now)
                hit_eos = req.eos_token_id >= 0 and tok == req.eos_token_id
                if hit_eos or len(st.tokens) >= req.max_new_tokens:
                    st.transition(RequestStatus.DONE)
                    st.finish_t = now
                    # finished requests publish their pages to the prefix
                    # cache (paged arena) before the slot recycles
                    self.release(st.slot, insert_prefix=True)
                    finished.append(st)
                    # the device clamps n_emit at eos and the planner caps
                    # drafts at the remaining allowance, so termination
                    # can only land on the window's last emitted token —
                    # the RNG chain is exactly where spec-off stopped
                    assert j == n - 1, (
                        f"request {req.request_id}: terminated at emitted "
                        f"token {j + 1} of {n} — device/planner clamp drift"
                    )
                    break
            if w.spec_len > 0:
                # the rejected tail of the verify window feeds the next
                # step's no-match draft fallback (stale-but-plausible
                # verifier predictions, the lockstep engine's trick)
                st.draft_tail = [
                    int(next_tokens[w.slot, j])
                    for j in range(emitted, w.spec_len + 1)
                ]
                if self.metrics is not None:
                    self.metrics.on_spec(
                        st, proposed=w.spec_len,
                        accepted=max(emitted - 1, 0), emitted=emitted,
                    )
        # tiered: the step consumed its staging buffer — the promoted
        # pages are HBM-resident now. Owned keys (slot demotions) leave
        # the host store; shared keys (prefix host tier) merely unpin, so
        # host-LRU pressure may reclaim them again
        for s in plan.stage:
            self._inflight.pop(s.key, None)
            if s.owned:
                self.spiller.drop(s.key)
            elif self.prefix_cache is not None:
                self.prefix_cache.unpin_host(s.key)
        if self.paged:
            self.assert_page_invariants()
        if self.metrics is not None:
            for st in finished:
                self.metrics.on_finish(st, now)
        return finished
