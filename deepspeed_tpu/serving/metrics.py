"""Serving metrics: TTFT/TPOT, queue depth, occupancy, tokens/s.

Parity: the serving-side telemetry DeepSpeed-MII exposes per deployment,
comm_logger-styled: cheap counters updated by scheduler/engine hooks, a
``summary()`` table on demand, and a ``write_to(monitor, step)`` bridge
into the monitor/ backends (TensorBoard/W&B/CSV).

Glossary (docs/serving.md):

- **TTFT** — time to first token: first sampled token minus arrival.
- **TPOT** — time per output token: (finish - first token) / (tokens - 1)
  for requests that produced more than one token. The denominator is
  TOKENS ACTUALLY EMITTED, never decode steps: with speculative decoding
  a step emits 1..k+1 tokens per slot and ``on_token`` fires once per
  emitted token, so spec-on TPOT (and tokens/s) stay honest.
- **queue depth** — requests admitted but not yet slotted (gauge).
- **slot occupancy** — in-flight requests / max_slots (gauge).
- **tokens/s** — sampled tokens over the engine-step window.
- **acceptance rate** — accepted draft tokens / proposed draft tokens
  (speculative decoding; 0.0 with spec off).
- **mean accepted tokens/step** — tokens emitted per verify window
  (accepted drafts + the bonus token); 1.0 means no draft ever accepted,
  > 1 is the speculative speedup multiplier on decode steps.
"""

from __future__ import annotations

import math
import time
from collections import defaultdict, deque
from typing import Dict, List, Optional


def _finite(v, default: float = 0.0):
    """Sanitize one reported value: NaN/inf (or an unconvertible input)
    becomes ``default`` so the summary line and the CSV/monitor bridge
    NEVER carry a NaN — an empty window reports 0, not poison. Integer
    counters pass through unchanged (the snapshot JSON keeps its
    shape: ``"submitted": 3``, not ``3.0``)."""
    if isinstance(v, int):  # bool is an int too; both are finite
        return v
    try:
        f = float(v)
    except (TypeError, ValueError):
        return default
    return f if math.isfinite(f) else default


def percentile(values: List[float], p: float) -> float:
    """Nearest-rank percentile over the FINITE samples; 0.0 on an empty
    (or all-non-finite) window — the summary never dies and never
    reports NaN before the first request completes."""
    xs = sorted(v for v in values if isinstance(v, (int, float))
                and math.isfinite(v))
    if not xs:
        return 0.0
    idx = min(len(xs) - 1, max(0, int(round(p / 100.0 * (len(xs) - 1)))))
    return xs[idx]


def recent_percentile(values: List[float], p: float,
                      window: int = 32) -> Optional[float]:
    """Percentile over the trailing ``window`` finite samples, or None
    when the window is empty — the healthwatch TTFT watchdog needs the
    tri-state (None = "no evidence yet", never a fake 0 that would mask
    a breach or fire one)."""
    xs = [v for v in values[-int(window):]
          if isinstance(v, (int, float)) and math.isfinite(v)]
    if not xs:
        return None
    return percentile(xs, p)


class ServingMetrics:
    def __init__(self, clock=time.monotonic):
        self.clock = clock
        self._t0 = clock()
        # optional steptrace request tracer (profiling/steptrace.py
        # ServeTracer): the lifecycle hooks below forward to it so a
        # traced replay gets per-request QUEUED→PREFILL→DECODE→DONE span
        # trees for free; None (default) is the zero-overhead path
        self.tracer = None
        # optional healthwatch (profiling/healthwatch.py): when the
        # serving engine attaches one, snapshot()/summary() report its
        # running goodput fraction; None is the zero-overhead path
        self.healthwatch = None
        # counters
        self.submitted = 0
        self.admitted = 0
        self.rejected = 0
        self.evicted = 0
        self.finished = 0
        self.steps = 0
        self.tokens_out = 0
        self.scheduled_tokens = 0     # real tokens fed (prefill + decode)
        # paged arena / prefix cache
        self.prefix_lookups = 0       # slot admissions that consulted it
        self.prefix_hits = 0          # admissions with >= 1 cached token
        self.cached_prompt_tokens = 0  # prompt tokens skipped via cache
        self.prompt_tokens_seen = 0   # prompt tokens over those lookups
        self.cow_copies = 0           # in-step copy-on-write page copies
        self.prefill_chunks = 0       # scheduled prompt chunks (a fully-
        #   cached prompt's lone final-token feed does not count)
        self.cached_tail_feeds = 0    # those excluded final-token feeds
        # tiered KV (serving.host_pages > 0, ISSUE 18)
        self.pages_spilled = 0        # HBM pages demoted to the host tier
        self.pages_promoted = 0       # host pages staged back under steps
        self.spill_bytes = 0          # at-rest (codec-compressed) bytes out
        self.promote_bytes = 0        # at-rest bytes decoded back in
        self.page_in_stall_s = 0.0    # host-side blob decode + staging
        #   time (the part of page-in NOT hidden under device math)
        self.host_prefix_hits = 0     # admissions that extended a prefix
        #   hit with >= 1 HOST-tier page (chains that survived eviction)
        self.host_cached_prompt_tokens = 0  # prompt tokens covered by
        #   those host-resident blocks (promoted instead of refed)
        # speculative decoding
        self.spec_steps = 0           # verify windows executed (slot-steps
        #   that carried >= 1 draft row)
        self.draft_tokens_proposed = 0
        self.draft_tokens_accepted = 0
        self.spec_tokens_out = 0      # tokens emitted by verify windows
        #   (accepted drafts + bonus tokens)
        # MoE serving (expert-parallel decode, ISSUE 14)
        self.moe_steps = 0            # steps that routed through experts
        self.moe_tokens_per_expert: List[int] = []  # cumulative histogram
        #   of capacity slots landed per expert (summed over layers)
        self.moe_routed_tokens = 0    # token-expert assignments kept
        self.moe_dropped_fraction = 0.0  # last step's dropped fraction
        #   (valid token-expert assignments that overflowed capacity)
        self.moe_a2a_bytes = 0        # cumulative expert-exchange wire
        #   bytes (the analytic moe_decode_a2a stream; 0 without ep)
        # gauges (last observed)
        self.queue_depth = 0
        self.slot_occupancy = 0.0
        self.pages_in_use = 0
        self.pages_free = 0
        self.arena_utilization = 0.0
        self.prefix_cache_entries = 0
        self.host_pages_resident = 0  # host-store keys alive (gauge)
        self._max_slots = 1
        self._num_pages = 0
        self._host_pages = 0
        # per-request samples
        self.ttft_s: List[float] = []
        self.tpot_s: List[float] = []
        self.queue_wait_s: List[float] = []
        self.evict_reasons: Dict[str, int] = defaultdict(int)

    # ------------------------------------------------- scheduler hooks
    def on_submit(self, state, now: float, queue_depth: int = 0) -> None:
        self.submitted += 1
        self.queue_depth = queue_depth
        if self.tracer is not None:
            self.tracer.on_submit(state)

    def on_admit(self, state, now: float, queue_depth: int = 0) -> None:
        self.admitted += 1
        self.queue_depth = queue_depth
        self.queue_wait_s.append(now - state.arrival_t)
        if self.tracer is not None:
            self.tracer.on_admit(state)

    def on_evict(self, state, now: float) -> None:
        # graceful admission rejection and timeout eviction both land
        # here; the reason string separates them
        self.evicted += 1
        if (state.evict_reason or "").startswith("queue full"):
            self.rejected += 1
        self.evict_reasons[state.evict_reason or "unknown"] += 1
        if self.tracer is not None:
            self.tracer.on_evict(state)

    def on_plan(self, plan, now: float, queue_depth: int = 0,
                occupancy: int = 0) -> None:
        self.queue_depth = queue_depth
        self.slot_occupancy = occupancy / max(self._max_slots, 1)
        self.scheduled_tokens += plan.total_tokens

    def on_token(self, state, now: float) -> None:
        """One EMITTED token (fires once per token, not per step — a
        speculative verify window calls this 1..k+1 times, keeping
        tokens/s and TPOT divided by tokens actually emitted)."""
        self.tokens_out += 1
        if self.tracer is not None:
            self.tracer.on_token(state)

    def on_spec(self, state, proposed: int, accepted: int,
                emitted: int) -> None:
        """One executed verify window: ``proposed`` draft rows scheduled,
        ``accepted`` drafts matched the verifier's targets, ``emitted``
        = accepted + the bonus token (possibly eos-clamped)."""
        self.spec_steps += 1
        self.draft_tokens_proposed += int(proposed)
        self.draft_tokens_accepted += int(accepted)
        self.spec_tokens_out += int(emitted)
        if self.tracer is not None:
            self.tracer.on_spec(state, proposed, accepted)

    def on_finish(self, state, now: float) -> None:
        self.finished += 1
        if self.tracer is not None:
            self.tracer.on_finish(state)
        if state.first_token_t is not None:
            self.ttft_s.append(state.first_token_t - state.arrival_t)
            n = len(state.tokens)
            if n > 1 and state.finish_t is not None:
                self.tpot_s.append(
                    (state.finish_t - state.first_token_t) / (n - 1)
                )

    def on_prefix_lookup(self, cached_tokens: int, prompt_len: int,
                         host_tokens: int = 0) -> None:
        """One slot admission's cache consult. ``cached_tokens`` counts
        EVERY skipped prompt token (HBM-resident hit + host-tier
        extension); ``host_tokens`` is the host-tier share of it."""
        self.prefix_lookups += 1
        self.prompt_tokens_seen += int(prompt_len)
        if cached_tokens > 0:
            self.prefix_hits += 1
            self.cached_prompt_tokens += int(cached_tokens)
        if host_tokens > 0:
            self.host_prefix_hits += 1
            self.host_cached_prompt_tokens += int(host_tokens)

    def on_cow(self) -> None:
        self.cow_copies += 1

    def on_spill(self, nbytes: int = 0) -> None:
        """One page demoted HBM → host (at-rest, codec-compressed
        ``nbytes``); fired by PageSpiller.demote AFTER the put succeeded
        — a full-store failure mutates nothing and counts nothing."""
        self.pages_spilled += 1
        self.spill_bytes += int(_finite(nbytes))

    def on_page_in(self, pages: int = 1, nbytes: int = 0,
                   stall_s: float = 0.0) -> None:
        """One step's promotion staging: ``pages`` host pages decoded
        into the rotating staging buffer (``nbytes`` at rest),
        ``stall_s`` the host-side decode+staging time — the slice of
        page-in that is NOT hidden under the device step."""
        self.pages_promoted += int(pages)
        self.promote_bytes += int(_finite(nbytes))
        self.page_in_stall_s += float(_finite(stall_s))

    def on_prefill_chunk(self, cached_tail: bool = False) -> None:
        if cached_tail:
            self.cached_tail_feeds += 1
        else:
            self.prefill_chunks += 1

    def on_moe(self, tokens_per_expert, dropped_fraction,
               a2a_bytes: int = 0) -> None:
        """One MoE serving step's expert load-balance counters (ISSUE 14
        satellite): ``tokens_per_expert`` is the step's [E] capacity-slot
        histogram (summed over layers), ``dropped_fraction`` the valid
        token-expert assignments that overflowed capacity, ``a2a_bytes``
        the analytic expert-exchange wire bytes. NaN-hardened like the
        TTFT percentiles — a poisoned device value can never reach the
        summary line or the serve/* bridge."""
        self.moe_steps += 1
        hist = [int(_finite(v)) for v in list(tokens_per_expert)]
        if len(self.moe_tokens_per_expert) != len(hist):
            self.moe_tokens_per_expert = [0] * len(hist)
        self.moe_tokens_per_expert = [
            a + b for a, b in zip(self.moe_tokens_per_expert, hist)
        ]
        self.moe_routed_tokens += sum(hist)
        self.moe_dropped_fraction = float(_finite(dropped_fraction))
        self.moe_a2a_bytes += int(_finite(a2a_bytes))

    @property
    def moe_load_imbalance(self) -> float:
        """max/mean of the cumulative tokens-per-expert histogram — 1.0
        is perfect balance, E is total collapse onto one expert; 0.0
        before any MoE step ran."""
        hist = self.moe_tokens_per_expert
        total = sum(hist)
        if not hist or total <= 0:
            return 0.0
        return max(hist) / (total / len(hist))

    def on_pages(self, pool, cache_entries: int = 0,
                 host_resident: int = 0) -> None:
        """Pool gauges from the scheduler's PagePool after a tick."""
        self.pages_free = pool.free_count
        self.pages_in_use = pool.num_pages - pool.free_count
        self.arena_utilization = self.pages_in_use / max(pool.num_pages, 1)
        self.prefix_cache_entries = int(cache_entries)
        self.host_pages_resident = int(host_resident)

    @property
    def prefix_hit_rate(self) -> float:
        """Cached prompt tokens over prompt tokens admitted (the token-
        weighted hit rate; 0.0 before any lookup)."""
        return (
            self.cached_prompt_tokens / self.prompt_tokens_seen
            if self.prompt_tokens_seen else 0.0
        )

    @property
    def host_prefix_hit_rate(self) -> float:
        """HOST-tier share of the token-weighted hit rate: prompt tokens
        covered by host-resident blocks (chains that survived HBM
        eviction) over prompt tokens admitted; 0.0 before any lookup."""
        return (
            self.host_cached_prompt_tokens / self.prompt_tokens_seen
            if self.prompt_tokens_seen else 0.0
        )

    @property
    def acceptance_rate(self) -> float:
        """Accepted draft tokens over proposed draft tokens (0.0 before
        any verify window ran)."""
        return (
            self.draft_tokens_accepted / self.draft_tokens_proposed
            if self.draft_tokens_proposed else 0.0
        )

    @property
    def mean_accepted_tokens_per_step(self) -> float:
        """Tokens emitted per verify window (accepted drafts + bonus);
        1.0 = no acceptance, 0.0 before any window ran."""
        return (
            self.spec_tokens_out / self.spec_steps if self.spec_steps
            else 0.0
        )

    # --------------------------------------------------- engine hooks
    def configure(self, max_slots: int, num_pages: int = 0,
                  host_pages: int = 0) -> None:
        self._max_slots = max(int(max_slots), 1)
        self._num_pages = max(int(num_pages), 0)
        self._host_pages = max(int(host_pages), 0)

    def on_step(self) -> None:
        self.steps += 1

    # ------------------------------------------------------ reporting
    @property
    def elapsed(self) -> float:
        return self.clock() - self._t0

    def tokens_per_s(self, window_s: Optional[float] = None) -> float:
        dur = self.elapsed if window_s is None else window_s
        return self.tokens_out / dur if dur > 0 else 0.0

    def snapshot(self) -> Dict[str, float]:
        snap = {
            "submitted": self.submitted,
            "admitted": self.admitted,
            "rejected": self.rejected,
            "evicted": self.evicted,
            "finished": self.finished,
            "steps": self.steps,
            "tokens_out": self.tokens_out,
            "scheduled_tokens": self.scheduled_tokens,
            "queue_depth": self.queue_depth,
            "slot_occupancy": self.slot_occupancy,
            "tokens_per_s": self.tokens_per_s(),
            "ttft_p50_s": percentile(self.ttft_s, 50),
            "ttft_p95_s": percentile(self.ttft_s, 95),
            "tpot_p50_s": percentile(self.tpot_s, 50),
            "tpot_p95_s": percentile(self.tpot_s, 95),
            "queue_wait_p95_s": percentile(self.queue_wait_s, 95),
            "prefix_hit_rate": self.prefix_hit_rate,
            "prefix_hits": self.prefix_hits,
            "cached_prompt_tokens": self.cached_prompt_tokens,
            "cow_copies": self.cow_copies,
            "prefill_chunks": self.prefill_chunks,
            "pages_in_use": self.pages_in_use,
            "arena_utilization": self.arena_utilization,
            "prefix_cache_entries": self.prefix_cache_entries,
            "spec_steps": self.spec_steps,
            "draft_tokens_proposed": self.draft_tokens_proposed,
            "draft_tokens_accepted": self.draft_tokens_accepted,
            "acceptance_rate": self.acceptance_rate,
            "mean_accepted_tokens_per_step":
                self.mean_accepted_tokens_per_step,
        }
        if (self._host_pages or self.pages_spilled or self.pages_promoted
                or self.host_pages_resident):
            snap.update({
                "pages_spilled": self.pages_spilled,
                "pages_promoted": self.pages_promoted,
                "spill_bytes": self.spill_bytes,
                "promote_bytes": self.promote_bytes,
                "page_in_stall_s": self.page_in_stall_s,
                "host_pages_resident": self.host_pages_resident,
                "host_prefix_hits": self.host_prefix_hits,
                "host_cached_prompt_tokens": self.host_cached_prompt_tokens,
                "host_prefix_hit_rate": self.host_prefix_hit_rate,
            })
        if self.moe_steps:
            snap.update({
                "moe_steps": self.moe_steps,
                "moe_routed_tokens": self.moe_routed_tokens,
                "moe_dropped_fraction": self.moe_dropped_fraction,
                "moe_load_imbalance": self.moe_load_imbalance,
                "moe_a2a_bytes": self.moe_a2a_bytes,
            })
            # the per-expert histogram rides the snapshot (and the
            # serve/* bridge) as bounded scalar keys — E is small
            snap.update({
                f"moe_tokens_expert_{i}": v
                for i, v in enumerate(self.moe_tokens_per_expert)
            })
        if self.healthwatch is not None:
            snap["goodput"] = self.healthwatch.goodput_fraction()
        # empty-window hardening: every reported value is finite — no
        # NaN ever reaches the summary line or the CSV/monitor bridge
        return {k: _finite(v) for k, v in snap.items()}

    def summary(self) -> str:
        """comm_logger-style table."""
        s = self.snapshot()
        lines = [
            "serving metrics",
            f"{'requests':<18}submitted={self.submitted} "
            f"admitted={self.admitted} finished={self.finished} "
            f"rejected={self.rejected} evicted={self.evicted}",
            f"{'throughput':<18}{s['tokens_per_s']:.1f} tok/s over "
            f"{self.elapsed:.2f}s ({self.steps} steps, "
            f"{self.scheduled_tokens} scheduled tokens)",
            f"{'ttft':<18}p50={s['ttft_p50_s'] * 1e3:.1f}ms "
            f"p95={s['ttft_p95_s'] * 1e3:.1f}ms",
            f"{'tpot':<18}p50={s['tpot_p50_s'] * 1e3:.1f}ms "
            f"p95={s['tpot_p95_s'] * 1e3:.1f}ms",
            f"{'gauges':<18}queue_depth={self.queue_depth} "
            f"slot_occupancy={self.slot_occupancy:.2f}"
            + (f" goodput={s['goodput']:.2f}" if "goodput" in s else ""),
        ]
        if self._num_pages:
            lines.append(
                f"{'paged arena':<18}pages_in_use={self.pages_in_use}/"
                f"{self._num_pages} (util {self.arena_utilization:.2f}), "
                f"prefix hit rate {self.prefix_hit_rate:.2f} "
                f"({self.prefix_hits}/{self.prefix_lookups} requests, "
                f"{self.cached_prompt_tokens} tokens), "
                f"cow_copies={self.cow_copies}, "
                f"prefill_chunks={self.prefill_chunks} "
                f"(+{self.cached_tail_feeds} cached-tail feeds)"
            )
        if self._host_pages or self.pages_spilled or self.pages_promoted:
            lines.append(
                f"{'kv tiering':<18}spilled={self.pages_spilled} pages "
                f"({self.spill_bytes / (1 << 20):.2f} MiB at rest), "
                f"promoted={self.pages_promoted} "
                f"({self.promote_bytes / (1 << 20):.2f} MiB), "
                f"host_resident={self.host_pages_resident}/"
                f"{self._host_pages}, host prefix hit rate "
                f"{self.host_prefix_hit_rate:.2f} "
                f"({self.host_cached_prompt_tokens} tokens), "
                f"page_in_stall={self.page_in_stall_s * 1e3:.1f}ms"
            )
        if self.spec_steps:
            lines.append(
                f"{'speculative':<18}acceptance "
                f"{self.acceptance_rate:.2f} "
                f"({self.draft_tokens_accepted}/"
                f"{self.draft_tokens_proposed} drafts), mean accepted "
                f"tokens/step {self.mean_accepted_tokens_per_step:.2f} "
                f"over {self.spec_steps} verify windows"
            )
        if self.moe_steps:
            hist = "/".join(str(v) for v in self.moe_tokens_per_expert)
            lines.append(
                f"{'moe serving':<18}tokens/expert [{hist}] over "
                f"{self.moe_steps} steps, load imbalance "
                f"{self.moe_load_imbalance:.2f}, dropped "
                f"{self.moe_dropped_fraction:.3f}, a2a "
                f"{self.moe_a2a_bytes / (1 << 20):.2f} MiB"
            )
        if self.evict_reasons:
            reasons = ", ".join(
                f"{k}: {v}" for k, v in sorted(self.evict_reasons.items())
            )
            lines.append(f"{'evictions':<18}{reasons}")
        return "\n".join(lines)

    def write_to(self, monitor, step: int) -> None:
        """Feed the monitor/ backends through the steptrace registry's
        single ``write_events`` bridge, under the documented ``serve/*``
        namespace (one coherent scheme with ``train/*``/``comm/*``/
        ``plan/*`` — docs/observability.md)."""
        from ..profiling.steptrace import write_events

        write_events(monitor, [
            (f"serve/{k}", float(v), int(step))
            for k, v in self.snapshot().items()
        ])


class FleetMetrics:
    """Aggregate view over a fleet's per-replica :class:`ServingMetrics`
    plus the router's own counters (serving/fleet/router.py). Counters
    sum across replicas; latency percentiles merge the per-replica sample
    lists (a request's TTFT is a fleet-level fact — it does not matter
    which replica served it); gauges that are depths sum, ratios average
    over replicas. Duck-types the attributes the healthwatch serving
    watchdogs read (``queue_depth``, ``ttft_s``, and the zero_progress
    trio ``tokens_out``/``scheduled_tokens``/``slot_occupancy``), so
    the queue/TTFT/livelock rules evaluate FLEET-wide when the router
    owns the healthwatch.

    Exported under the ``serve/fleet/*`` namespace (per-replica metrics
    keep ``serve/*`` on their own engines) — docs/observability.md."""

    # replica counters that sum into the fleet snapshot
    _SUM_KEYS = (
        "submitted", "admitted", "rejected", "evicted", "finished",
        "steps", "tokens_out", "scheduled_tokens", "prefix_hits",
        "cached_prompt_tokens", "cow_copies", "prefill_chunks",
        "cached_tail_feeds", "spec_steps", "draft_tokens_proposed",
        "draft_tokens_accepted", "pages_in_use", "pages_spilled",
        "pages_promoted", "spill_bytes", "promote_bytes",
        "host_prefix_hits", "host_cached_prompt_tokens",
        "host_pages_resident",
    )

    def __init__(self, replica_metrics: List["ServingMetrics"],
                 clock=time.monotonic):
        self.replicas = list(replica_metrics)
        self.clock = clock
        self._t0 = clock()
        # router counters (fed by Router, not by replicas)
        self.routed = 0             # requests dispatched to a replica
        self.shed = 0               # fleet-level graceful rejections
        self.shed_reasons: Dict[str, int] = defaultdict(int)
        self.handoffs = 0           # completed prefill→decode transfers
        self.handoff_failures = 0   # attempts deferred (no slot/pages)
        self.handoff_pages = 0      # pages moved across pools
        self.affinity_routed = 0    # routed by session stickiness
        self.prefix_routed = 0      # routed by a non-zero chain match
        self.ticks = 0              # router ticks that stepped >= 1 replica
        # fleet-level TTFT samples in true COMPLETION order (the router
        # appends as requests finish, whichever replica served them) —
        # bounded, because its only consumers are recent-window reads:
        # the shed_ttft_p95_s gate and the ttft_breach watchdog. A
        # replica-order concatenation of the full per-replica lists
        # would make a trailing window read mostly the LAST replica's
        # history (and cost O(total requests) per submit).
        self.recent_ttft_s: "deque[float]" = deque(maxlen=256)

    # ------------------------------------------------------ router hooks
    def on_route(self, via: str) -> None:
        self.routed += 1
        if via == "affinity":
            self.affinity_routed += 1
        elif via == "prefix":
            self.prefix_routed += 1

    def on_shed(self, reason: str) -> None:
        self.shed += 1
        self.shed_reasons[reason] += 1

    def on_handoff(self, ok: bool, pages: int = 0) -> None:
        if ok:
            self.handoffs += 1
            self.handoff_pages += int(pages)
        else:
            self.handoff_failures += 1

    def on_tick(self) -> None:
        self.ticks += 1

    def on_finish_ttft(self, ttft_s: float) -> None:
        """One finished request's TTFT, appended by the router in fleet
        completion order."""
        self.recent_ttft_s.append(float(ttft_s))

    # ----------------------------------------- healthwatch duck-typing
    @property
    def queue_depth(self) -> int:
        """Fleet queue depth: requests admitted but not yet slotted,
        summed across replicas (the queue_depth_breach watchdog input)."""
        return sum(int(m.queue_depth) for m in self.replicas)

    @property
    def ttft_s(self) -> List[float]:
        """Recent TTFT samples in fleet COMPLETION order (bounded) — the
        ttft_breach watchdog's recent-window input. All-time percentiles
        live in :meth:`snapshot`, which merges the full per-replica
        lists."""
        return list(self.recent_ttft_s)

    @property
    def tokens_out(self) -> int:
        """Fleet-wide emitted tokens (zero_progress watchdog input)."""
        return sum(int(m.tokens_out) for m in self.replicas)

    @property
    def scheduled_tokens(self) -> int:
        """Fleet-wide scheduled tokens — prefill chunks count as
        progress for the zero_progress watchdog even before a request's
        first sampled token."""
        return sum(int(m.scheduled_tokens) for m in self.replicas)

    @property
    def slot_occupancy(self) -> float:
        """Mean slot occupancy across replicas: the zero_progress
        watchdog only treats frozen counters as a stall while work is
        actually slotted somewhere."""
        return (sum(float(m.slot_occupancy) for m in self.replicas)
                / max(len(self.replicas), 1))

    # ------------------------------------------------------ reporting
    @property
    def elapsed(self) -> float:
        return self.clock() - self._t0

    def tokens_per_s(self) -> float:
        total = sum(m.tokens_out for m in self.replicas)
        dur = self.elapsed
        return total / dur if dur > 0 else 0.0

    def snapshot(self) -> Dict[str, float]:
        snap: Dict[str, float] = {
            k: sum(getattr(m, k) for m in self.replicas)
            for k in self._SUM_KEYS
        }
        ttft: List[float] = []
        tpot: List[float] = []
        qwait: List[float] = []
        for m in self.replicas:
            ttft.extend(m.ttft_s)
            tpot.extend(m.tpot_s)
            qwait.extend(m.queue_wait_s)
        snap.update({
            "replicas": len(self.replicas),
            "queue_depth": self.queue_depth,
            "slot_occupancy": (
                sum(m.slot_occupancy for m in self.replicas)
                / max(len(self.replicas), 1)
            ),
            "tokens_per_s": self.tokens_per_s(),
            "ttft_p50_s": percentile(ttft, 50),
            "ttft_p95_s": percentile(ttft, 95),
            "tpot_p50_s": percentile(tpot, 50),
            "tpot_p95_s": percentile(tpot, 95),
            "queue_wait_p95_s": percentile(qwait, 95),
            "routed": self.routed,
            "shed": self.shed,
            "handoffs": self.handoffs,
            "handoff_failures": self.handoff_failures,
            "handoff_pages": self.handoff_pages,
            "affinity_routed": self.affinity_routed,
            "prefix_routed": self.prefix_routed,
            "ticks": self.ticks,
        })
        return {k: _finite(v) for k, v in snap.items()}

    def per_replica(self) -> List[Dict[str, float]]:
        """The un-aggregated view: one ServingMetrics snapshot per
        replica, in replica order."""
        return [m.snapshot() for m in self.replicas]

    def summary(self) -> str:
        s = self.snapshot()
        lines = [
            f"fleet metrics ({len(self.replicas)} replicas)",
            f"{'requests':<18}submitted={s['submitted']} "
            f"routed={self.routed} finished={s['finished']} "
            f"shed={self.shed} evicted={s['evicted']}",
            f"{'throughput':<18}{s['tokens_per_s']:.1f} tok/s over "
            f"{self.elapsed:.2f}s ({s['steps']} replica steps, "
            f"{self.ticks} router ticks)",
            f"{'ttft':<18}p50={s['ttft_p50_s'] * 1e3:.1f}ms "
            f"p95={s['ttft_p95_s'] * 1e3:.1f}ms",
            f"{'tpot':<18}p50={s['tpot_p50_s'] * 1e3:.1f}ms "
            f"p95={s['tpot_p95_s'] * 1e3:.1f}ms",
            f"{'routing':<18}affinity={self.affinity_routed} "
            f"prefix={self.prefix_routed} "
            f"handoffs={self.handoffs} "
            f"(+{self.handoff_failures} deferred, "
            f"{self.handoff_pages} pages moved)",
        ]
        per_rep = " ".join(
            f"r{i}={m.tokens_out}" for i, m in enumerate(self.replicas)
        )
        lines.append(f"{'tokens by replica':<18}{per_rep}")
        if self.shed_reasons:
            reasons = ", ".join(
                f"{k}: {v}" for k, v in sorted(self.shed_reasons.items())
            )
            lines.append(f"{'shed':<18}{reasons}")
        return "\n".join(lines)

    def write_to(self, monitor, step: int) -> None:
        """Fleet aggregates under ``serve/fleet/*`` through the one
        write_events bridge; each replica's own engine keeps writing its
        ``serve/*`` series (docs/observability.md, "Fleet namespace")."""
        from ..profiling.steptrace import write_events

        write_events(monitor, [
            (f"serve/fleet/{k}", float(v), int(step))
            for k, v in self.snapshot().items()
        ])
