"""Serving metrics: TTFT/TPOT, queue depth, occupancy, tokens/s.

Parity: the serving-side telemetry DeepSpeed-MII exposes per deployment,
comm_logger-styled: cheap counters updated by scheduler/engine hooks, a
``summary()`` table on demand, and a ``write_to(monitor, step)`` bridge
into the monitor/ backends (TensorBoard/W&B/CSV).

Glossary (docs/serving.md):

- **TTFT** — time to first token: first sampled token minus arrival.
- **TPOT** — time per output token: (finish - first token) / (tokens - 1)
  for requests that produced more than one token.
- **queue depth** — requests admitted but not yet slotted (gauge).
- **slot occupancy** — in-flight requests / max_slots (gauge).
- **tokens/s** — sampled tokens over the engine-step window.
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Dict, List, Optional


def percentile(values: List[float], p: float) -> float:
    """Nearest-rank percentile; 0.0 on empty input (summary never dies)."""
    if not values:
        return 0.0
    xs = sorted(values)
    idx = min(len(xs) - 1, max(0, int(round(p / 100.0 * (len(xs) - 1)))))
    return xs[idx]


class ServingMetrics:
    def __init__(self, clock=time.monotonic):
        self.clock = clock
        self._t0 = clock()
        # counters
        self.submitted = 0
        self.admitted = 0
        self.rejected = 0
        self.evicted = 0
        self.finished = 0
        self.steps = 0
        self.tokens_out = 0
        self.scheduled_tokens = 0     # real tokens fed (prefill + decode)
        # gauges (last observed)
        self.queue_depth = 0
        self.slot_occupancy = 0.0
        self._max_slots = 1
        # per-request samples
        self.ttft_s: List[float] = []
        self.tpot_s: List[float] = []
        self.queue_wait_s: List[float] = []
        self.evict_reasons: Dict[str, int] = defaultdict(int)

    # ------------------------------------------------- scheduler hooks
    def on_submit(self, state, now: float, queue_depth: int = 0) -> None:
        self.submitted += 1
        self.queue_depth = queue_depth

    def on_admit(self, state, now: float, queue_depth: int = 0) -> None:
        self.admitted += 1
        self.queue_depth = queue_depth
        self.queue_wait_s.append(now - state.arrival_t)

    def on_evict(self, state, now: float) -> None:
        # graceful admission rejection and timeout eviction both land
        # here; the reason string separates them
        self.evicted += 1
        if (state.evict_reason or "").startswith("queue full"):
            self.rejected += 1
        self.evict_reasons[state.evict_reason or "unknown"] += 1

    def on_plan(self, plan, now: float, queue_depth: int = 0,
                occupancy: int = 0) -> None:
        self.queue_depth = queue_depth
        self.slot_occupancy = occupancy / max(self._max_slots, 1)
        self.scheduled_tokens += plan.total_tokens

    def on_token(self, state, now: float) -> None:
        self.tokens_out += 1

    def on_finish(self, state, now: float) -> None:
        self.finished += 1
        if state.first_token_t is not None:
            self.ttft_s.append(state.first_token_t - state.arrival_t)
            n = len(state.tokens)
            if n > 1 and state.finish_t is not None:
                self.tpot_s.append(
                    (state.finish_t - state.first_token_t) / (n - 1)
                )

    # --------------------------------------------------- engine hooks
    def configure(self, max_slots: int) -> None:
        self._max_slots = max(int(max_slots), 1)

    def on_step(self) -> None:
        self.steps += 1

    # ------------------------------------------------------ reporting
    @property
    def elapsed(self) -> float:
        return self.clock() - self._t0

    def tokens_per_s(self, window_s: Optional[float] = None) -> float:
        dur = self.elapsed if window_s is None else window_s
        return self.tokens_out / dur if dur > 0 else 0.0

    def snapshot(self) -> Dict[str, float]:
        return {
            "submitted": self.submitted,
            "admitted": self.admitted,
            "rejected": self.rejected,
            "evicted": self.evicted,
            "finished": self.finished,
            "steps": self.steps,
            "tokens_out": self.tokens_out,
            "scheduled_tokens": self.scheduled_tokens,
            "queue_depth": self.queue_depth,
            "slot_occupancy": self.slot_occupancy,
            "tokens_per_s": self.tokens_per_s(),
            "ttft_p50_s": percentile(self.ttft_s, 50),
            "ttft_p95_s": percentile(self.ttft_s, 95),
            "tpot_p50_s": percentile(self.tpot_s, 50),
            "tpot_p95_s": percentile(self.tpot_s, 95),
            "queue_wait_p95_s": percentile(self.queue_wait_s, 95),
        }

    def summary(self) -> str:
        """comm_logger-style table."""
        s = self.snapshot()
        lines = [
            "serving metrics",
            f"{'requests':<18}submitted={self.submitted} "
            f"admitted={self.admitted} finished={self.finished} "
            f"rejected={self.rejected} evicted={self.evicted}",
            f"{'throughput':<18}{s['tokens_per_s']:.1f} tok/s over "
            f"{self.elapsed:.2f}s ({self.steps} steps, "
            f"{self.scheduled_tokens} scheduled tokens)",
            f"{'ttft':<18}p50={s['ttft_p50_s'] * 1e3:.1f}ms "
            f"p95={s['ttft_p95_s'] * 1e3:.1f}ms",
            f"{'tpot':<18}p50={s['tpot_p50_s'] * 1e3:.1f}ms "
            f"p95={s['tpot_p95_s'] * 1e3:.1f}ms",
            f"{'gauges':<18}queue_depth={self.queue_depth} "
            f"slot_occupancy={self.slot_occupancy:.2f}",
        ]
        if self.evict_reasons:
            reasons = ", ".join(
                f"{k}: {v}" for k, v in sorted(self.evict_reasons.items())
            )
            lines.append(f"{'evictions':<18}{reasons}")
        return "\n".join(lines)

    def write_to(self, monitor, step: int) -> None:
        """Feed the monitor/ backends (Monitor.write_events event triples)."""
        monitor.write_events([
            (f"Serving/{k}", float(v), step)
            for k, v in self.snapshot().items()
        ])
