"""Indexed binary token dataset: the pretraining-data backbone.

Parity: fills the role of Megatron-style .bin/.idx indexed datasets in the
reference's data pipeline (megatron/data/indexed_dataset.py
MMapIndexedDataset + its C gather backend; deepspeed/runtime/data_pipeline
reads them for curriculum/analysis). The on-disk layout is this package's
OWN format (magic ``DSTPUIDX``; write with IndexedDatasetBuilder, read
back with MMapIndexedDataset) — it is NOT byte-compatible with
Megatron/DeepSpeed ``MMIDIDX`` files; pointing this reader at one raises
"bad magic". Tokens live in one flat .bin; the .idx carries cumulative
offsets, so a dataset of millions of variable-length documents costs two
mmaps and zero Python objects per document.

The gather hot path (a batch of documents → one padded [n, seqlen] int32
array) runs in C++ (csrc/data/indexed_reader.cpp, built on first use like
the aio backend); a pure-numpy fallback keeps every feature available
when a toolchain isn't (same files, same results).

Format (version 1):
  <name>.idx : b"DSTPUIDX" | u32 version=1 | u32 dtype (0=u16, 1=i32)
               | u64 count | u64 cum-offsets [count+1]
  <name>.bin : tokens little-endian, back to back.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

from ..utils.logging import log_dist, warning_once

_MAGIC = b"DSTPUIDX"
_CSRC = os.path.join(
    os.path.dirname(__file__), "..", "..", "csrc", "data"
)
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LIB_FAILED = False


def _build_lib() -> str:
    src = os.path.abspath(os.path.join(_CSRC, "indexed_reader.cpp"))
    out = os.path.abspath(os.path.join(_CSRC, "libdsidx.so"))
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    cmd = ["g++", "-O2", "-shared", "-fPIC", src, "-o", out]
    subprocess.run(cmd, check=True, capture_output=True)
    return out


def _lib() -> Optional[ctypes.CDLL]:
    """The C++ reader, or None when it can't build (numpy fallback)."""
    global _LIB, _LIB_FAILED
    with _LOCK:
        if _LIB is None and not _LIB_FAILED:
            try:
                lib = ctypes.CDLL(_build_lib())
            except Exception as e:  # no g++ / sandboxed: numpy fallback
                _LIB_FAILED = True
                warning_once(
                    f"indexed_dataset: C++ reader unavailable ({e}); "
                    "using the numpy fallback"
                )
                return None
            lib.dsidx_open.restype = ctypes.c_void_p
            lib.dsidx_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
            lib.dsidx_close.argtypes = [ctypes.c_void_p]
            lib.dsidx_len.restype = ctypes.c_int64
            lib.dsidx_len.argtypes = [ctypes.c_void_p]
            lib.dsidx_seq_len.restype = ctypes.c_int64
            lib.dsidx_seq_len.argtypes = [ctypes.c_void_p, ctypes.c_int64]
            lib.dsidx_fill_batch.restype = ctypes.c_int
            lib.dsidx_fill_batch.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
                ctypes.c_void_p,
            ]
            lib.dsidx_get.restype = ctypes.c_int64
            lib.dsidx_get.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_int64,
            ]
            _LIB = lib
    return _LIB


class IndexedDatasetBuilder:
    """Stream documents into the .bin/.idx pair.

    u16 storage is picked automatically while every token fits (vocab
    < 65536 — half the disk/IO of i32); the first larger token upgrades
    the .bin in place."""

    def __init__(self, prefix: str):
        self.prefix = prefix
        self._bin = open(prefix + ".bin", "wb")
        self._offsets = [0]
        self._dtype = np.uint16

    def add_document(self, tokens: Sequence[int]) -> None:
        arr = np.asarray(tokens)
        if self._dtype == np.uint16 and (arr.max(initial=0) > 65535
                                         or arr.min(initial=0) < 0):
            self._upgrade_to_i32()
        self._bin.write(arr.astype(self._dtype).tobytes())
        self._offsets.append(self._offsets[-1] + len(arr))

    def _upgrade_to_i32(self) -> None:
        # stream the u16 -> i32 rewrite in bounded chunks: the .bin may be
        # many GB by the time the first >65535 token arrives
        self._bin.close()
        old_path = self.prefix + ".bin"
        tmp_path = old_path + ".i32tmp"
        chunk = 1 << 22  # 4M tokens = 8 MiB read / 16 MiB write per step
        with open(old_path, "rb") as src, open(tmp_path, "wb") as dst:
            while True:
                buf = src.read(chunk * 2)
                if not buf:
                    break
                dst.write(
                    np.frombuffer(buf, np.uint16).astype(np.int32).tobytes()
                )
        os.replace(tmp_path, old_path)
        self._dtype = np.int32
        self._bin = open(old_path, "ab")

    def finalize(self) -> None:
        self._bin.close()
        count = len(self._offsets) - 1
        with open(self.prefix + ".idx", "wb") as f:
            f.write(_MAGIC)
            f.write(np.uint32(1).tobytes())
            f.write(np.uint32(0 if self._dtype == np.uint16 else 1).tobytes())
            f.write(np.uint64(count).tobytes())
            f.write(np.asarray(self._offsets, np.uint64).tobytes())
        log_dist(
            f"indexed_dataset: wrote {count} docs, "
            f"{self._offsets[-1]} tokens ({np.dtype(self._dtype).name}) "
            f"to {self.prefix}.bin/.idx"
        )


class MMapIndexedDataset:
    """Read side. ``ds[i]`` → the i-th document (int32 1-D);
    ``ds.get_batch(indices, seqlen)`` → padded [n, seqlen] int32 via the
    C++ gather (or the numpy fallback). With ``seqlen`` set at
    construction, ``ds[i]`` returns {"input_ids": padded row} — the shape
    the engine's dataloader feeds straight into train_batch."""

    def __init__(self, prefix: str, seqlen: Optional[int] = None,
                 pad_id: int = 0):
        self.prefix = prefix
        self.seqlen = seqlen
        self.pad_id = int(pad_id)
        bin_path, idx_path = prefix + ".bin", prefix + ".idx"
        if not (os.path.exists(bin_path) and os.path.exists(idx_path)):
            raise FileNotFoundError(f"{prefix}.bin/.idx not found")
        self._h = None
        lib = _lib()
        if lib is not None:
            self._h = lib.dsidx_open(bin_path.encode(), idx_path.encode())
            if not self._h:
                raise ValueError(f"{prefix}: bad or corrupt index file")
            self._count = int(lib.dsidx_len(self._h))
        if self._h is None:
            self._np_open(bin_path, idx_path)

    # ------------------------------------------------- numpy fallback side
    def _np_open(self, bin_path: str, idx_path: str) -> None:
        with open(idx_path, "rb") as f:
            head = f.read(24)
            if head[:8] != _MAGIC:
                raise ValueError(f"{idx_path}: bad magic")
            version = np.frombuffer(head, np.uint32, 1, 8)[0]
            dtype_code = np.frombuffer(head, np.uint32, 1, 12)[0]
            count = int(np.frombuffer(head, np.uint64, 1, 16)[0])
            if version != 1 or dtype_code > 1:
                raise ValueError(f"{idx_path}: unsupported version/dtype")
            self._np_offsets = np.fromfile(f, np.uint64, count + 1)
        dtype = np.uint16 if dtype_code == 0 else np.int32
        if os.path.getsize(bin_path) == 0:  # zero-token dataset is valid
            self._np_tokens = np.empty(0, dtype)
        else:
            self._np_tokens = np.memmap(bin_path, dtype=dtype, mode="r")
        self._count = count

    def __len__(self) -> int:
        return self._count

    def seq_len(self, i: int) -> int:
        if self._h is not None:
            n = int(_lib().dsidx_seq_len(self._h, i))
            if n < 0:
                raise IndexError(i)
            return n
        o = self._np_offsets
        if not 0 <= i < self._count:
            raise IndexError(i)
        return int(o[i + 1] - o[i])

    def get(self, i: int) -> np.ndarray:
        """Raw (unpadded) document tokens, int32."""
        n = self.seq_len(i)
        if self._h is not None:
            out = np.empty(n, np.int32)
            got = _lib().dsidx_get(
                self._h, i, out.ctypes.data_as(ctypes.c_void_p), n
            )
            if got < 0:
                raise IndexError(i)
            return out[:got]
        o = self._np_offsets
        return np.asarray(
            self._np_tokens[int(o[i]):int(o[i + 1])], np.int32
        )

    def get_batch(self, indices, seqlen: int, start: int = 0,
                  pad_id: Optional[int] = None) -> np.ndarray:
        """[n, seqlen] int32: tokens [start, start+seqlen) of each doc,
        truncated at the doc's end, padded with pad_id."""
        if start < 0 or seqlen < 0:
            # the C++ side rejects these too; validating here keeps both
            # backends on one contract (no Python negative-slice semantics)
            raise ValueError(f"start/seqlen must be >= 0, got {start}/{seqlen}")
        idx = np.ascontiguousarray(indices, np.int64)
        pad = self.pad_id if pad_id is None else int(pad_id)
        out = np.empty((len(idx), seqlen), np.int32)
        if self._h is not None:
            rc = _lib().dsidx_fill_batch(
                self._h, idx.ctypes.data_as(ctypes.c_void_p), len(idx),
                seqlen, start, pad, out.ctypes.data_as(ctypes.c_void_p),
            )
            if rc != 0:
                raise IndexError(f"index out of range in {list(idx[:5])}...")
            return out
        for k, i in enumerate(idx):
            doc = self.get(int(i))[start:start + seqlen]
            out[k, : len(doc)] = doc
            out[k, len(doc):] = pad
        return out

    def __getitem__(self, i: int):
        if self.seqlen is None:
            return self.get(int(i))
        return {"input_ids": self.get_batch([int(i)], self.seqlen)[0]}

    def close(self) -> None:
        if self._h is not None:
            _lib().dsidx_close(self._h)
            self._h = None

    def __del__(self):  # best effort; mmaps also die with the process
        try:
            self.close()
        except Exception:
            pass
