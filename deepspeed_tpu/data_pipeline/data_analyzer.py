"""Offline data analysis for curriculum learning.

Parity: deepspeed/runtime/data_pipeline/data_analyzer.py (DataAnalyzer) —
the offline pass that scores every sample's difficulty and writes an index
the curriculum sampler consumes. The reference shards the scan over torch
ranks and writes memory-mapped index files; here the scan is a vectorized
numpy pass (the dataset fits host memory in this framework's dataloader
contract) producing one ``.npz`` index.

Metrics (reference names):
- ``seqlen``: non-pad token count per sample.
- ``vocabularyrarity``: mean negative log frequency of a sample's tokens —
  rarer vocabulary = harder sample.

``CurriculumSampler`` orders samples easy→hard to follow the scheduler's
difficulty pacing: at each step it draws from the easiest fraction whose
difficulty quantile matches ``current_difficulty / max_difficulty``.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import numpy as np

METRICS = ("seqlen", "vocabularyrarity")


def analyze_dataset(
    input_ids: np.ndarray,
    metrics: Sequence[str] = METRICS,
    pad_id: int = -1,
    vocab_size: Optional[int] = None,
) -> Dict[str, np.ndarray]:
    """Score [N, S] token samples; returns {metric: [N] float64 scores}."""
    ids = np.asarray(input_ids)
    if ids.ndim != 2:
        raise ValueError(f"input_ids must be [N, S], got {ids.shape}")
    valid = ids != pad_id
    out: Dict[str, np.ndarray] = {}
    for m in metrics:
        if m == "seqlen":
            out[m] = valid.sum(axis=1).astype(np.float64)
        elif m == "vocabularyrarity":
            V = max(vocab_size or int(ids.max()) + 1, 1)
            # masked positions go to a dedicated sentinel slot V (one past
            # the vocab) so real token 0 never shares a count with padding
            flat = np.where(valid, ids.clip(0, V - 1), V).ravel()
            counts = np.bincount(flat, minlength=V + 1)[:V].astype(np.float64)
            total = max(counts.sum(), 1.0)
            freq = np.maximum(counts / total, 1e-12)
            nll = -np.log(freq)
            per_tok = np.where(valid, nll[ids.clip(0, V - 1)], 0.0)
            out[m] = per_tok.sum(axis=1) / np.maximum(valid.sum(axis=1), 1)
        else:
            raise ValueError(f"unknown metric {m!r}; have {METRICS}")
    return out


def write_index(path: str, scores: Dict[str, np.ndarray]) -> str:
    """Persist the difficulty index (one .npz; reference: index map files)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez(path, **scores)
    return path if path.endswith(".npz") else path + ".npz"


def load_index(path: str) -> Dict[str, np.ndarray]:
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


class DataAnalyzer:
    """Parity-surface wrapper: analyze → write index → build a sampler."""

    def __init__(self, metrics: Sequence[str] = METRICS, pad_id: int = -1):
        self.metrics = tuple(metrics)
        self.pad_id = pad_id

    def run(self, input_ids, save_path: Optional[str] = None):
        """Returns the scores; with ``save_path``, also writes the index and
        records the actual file written (np.savez appends .npz) in
        ``self.index_path``."""
        scores = analyze_dataset(input_ids, self.metrics, self.pad_id)
        self.index_path = write_index(save_path, scores) if save_path else None
        return scores


class CurriculumSampler:
    """Easy→hard sample ordering following the scheduler's pacing.

    At difficulty d (of max D), batches draw uniformly from the easiest
    ``d / D`` fraction of samples — the reference's difficulty-based data
    sampling, minus its distributed index plumbing (the dp shard split
    happens downstream in the dataloader)."""

    def __init__(self, scores: np.ndarray, scheduler, seed: int = 0):
        order = np.argsort(np.asarray(scores), kind="stable")
        self.order = order  # easy → hard
        self.scheduler = scheduler
        self.rng = np.random.RandomState(seed)

    def sample_indices(self, step: int, batch_size: int) -> np.ndarray:
        d = self.scheduler.get_difficulty(step)
        frac = min(max(d / self.scheduler.max_difficulty, 0.0), 1.0)
        n_avail = max(int(round(frac * len(self.order))), batch_size)
        n_avail = min(n_avail, len(self.order))
        # without replacement when the pool allows (reference: shuffled
        # partition of the eligible samples)
        if n_avail >= batch_size:
            pick = self.rng.choice(n_avail, size=batch_size, replace=False)
        else:
            pick = self.rng.randint(0, n_avail, size=batch_size)
        return self.order[pick]
