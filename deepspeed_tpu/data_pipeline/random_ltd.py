"""Random layerwise token dropping (random-LTD).

Parity: deepspeed/runtime/data_pipeline/data_routing/basic_layer.py +
csrc/random_ltd (gather/scatter kernels). Middle layers process a random
subset of tokens; dropped tokens bypass the layer (identity) and are
scattered back, so sequence shape is preserved end-to-end.

TPU-native: the kept-token count per step comes from a *schedule of static
values* (each value = one compiled program; the schedule quantizes like the
curriculum), and gather/scatter are one-hot-free ``jnp.take_along_axis`` /
``segment``-style scatters that XLA fuses — no custom kernel needed until
profiling says otherwise.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp


def sample_token_subset(rng, batch: int, seq_len: int, keep: int):
    """[B, keep] sorted random token indices (sorted keeps RoPE monotone)."""
    def one(key):
        return jnp.sort(jax.random.permutation(key, seq_len)[:keep])

    return jax.vmap(one)(jax.random.split(rng, batch))


def gather_tokens(x: jax.Array, idx: jax.Array) -> jax.Array:
    """x [B, S, ...], idx [B, K] → [B, K, ...]."""
    extra = x.ndim - 2
    idx_e = idx.reshape(*idx.shape, *([1] * extra))
    return jnp.take_along_axis(x, jnp.broadcast_to(idx_e, (*idx.shape, *x.shape[2:])), axis=1)


def scatter_tokens(x_full: jax.Array, x_kept: jax.Array, idx: jax.Array) -> jax.Array:
    """Place processed kept tokens back into the full sequence."""
    extra = x_full.ndim - 2
    idx_e = idx.reshape(*idx.shape, *([1] * extra))
    idx_b = jnp.broadcast_to(idx_e, (*idx.shape, *x_full.shape[2:]))
    return jnp.put_along_axis(x_full, idx_b, x_kept, axis=1, inplace=False)


def random_ltd_layer(
    layer_fn: Callable[[jax.Array, jax.Array], jax.Array],
    x: jax.Array,
    positions: jax.Array,
    keep: int,
    rng,
) -> jax.Array:
    """Run ``layer_fn(x_kept, positions_kept)`` on a random token subset;
    dropped tokens pass through unchanged (reference basic_layer semantics).
    """
    B, S = x.shape[:2]
    if keep >= S:
        return layer_fn(x, positions)
    idx = sample_token_subset(rng, B, S, keep)
    x_kept = gather_tokens(x, idx)
    pos_kept = jnp.take_along_axis(positions, idx, axis=1)
    out_kept = layer_fn(x_kept, pos_kept)
    return scatter_tokens(x, out_kept, idx)


class RandomLTDScheduler:
    """Parity: deepspeed/runtime/data_pipeline/data_routing/scheduler.py.

    Linear schedule of kept-token count from min_value → seq length over
    total steps, quantized to ``step_size`` (distinct values = distinct
    compiled programs)."""

    def __init__(self, config=None, total_layers: int = 0):
        sched = dict(getattr(config, "random_ltd_schedule", None) or {})
        self.min_value = int(sched.get("min_value", 128))
        self.max_value = int(sched.get("max_value", 2048))
        self.total_steps = int(
            sched.get("schedule_config", {}).get("total_layer_drop_step", 10000)
            if isinstance(sched.get("schedule_config"), dict)
            else sched.get("total_layer_drop_step", 10000)
        )
        self.step_size = int(sched.get("seq_step", 64))
        self.total_layers = total_layers or int(
            getattr(config, "total_layer_num", 0) or 0
        )
        self.ltd_layers = list(getattr(config, "random_ltd_layer_id", None) or [])

    def get_seq_len(self, global_steps: int) -> int:
        frac = min(max(global_steps, 0), self.total_steps) / max(self.total_steps, 1)
        v = self.min_value + (self.max_value - self.min_value) * frac
        v = int(v // self.step_size) * self.step_size
        return max(self.min_value, min(self.max_value, v))
