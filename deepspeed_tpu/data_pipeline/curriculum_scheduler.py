"""Curriculum learning scheduler.

Parity: deepspeed/runtime/data_pipeline/curriculum_scheduler.py. Computes a
difficulty (e.g. sequence length) per step; the engine applies a seqlen
curriculum by truncating the batch before `device_put`.

TPU note: every distinct difficulty is a distinct compiled program shape.
``rounding`` quantizes the difficulty (reference's
difficulty_step) — keep it >= 64 so a run compiles a handful of programs,
not hundreds.
"""

from __future__ import annotations

import math
from typing import Any, Dict


class CurriculumScheduler:
    """Schedule types (reference parity): fixed_linear, fixed_root,
    fixed_discrete."""

    def __init__(self, config):
        # accepts CurriculumConfig or a raw dict
        if hasattr(config, "curriculum_type"):
            self.curriculum_type = config.curriculum_type
            self.min_difficulty = config.min_difficulty
            self.max_difficulty = config.max_difficulty
            self.schedule_type = config.schedule_type
            cfg: Dict[str, Any] = dict(config.schedule_config)
        else:
            self.curriculum_type = config.get("curriculum_type", "seqlen")
            self.min_difficulty = config["min_difficulty"]
            self.max_difficulty = config["max_difficulty"]
            self.schedule_type = config["schedule_type"]
            cfg = dict(config.get("schedule_config", {}))
        self.total_steps = int(cfg.get("total_curriculum_step", 10000))
        self.rounding = int(cfg.get("difficulty_step", 8))
        self.root_degree = int(cfg.get("root_degree", 2))
        self.discrete_difficulties = list(cfg.get("difficulty", []))
        self.discrete_steps = list(cfg.get("max_step", []))
        self.current_difficulty = self.min_difficulty

    def _round(self, d: float) -> int:
        r = self.rounding
        return max(self.min_difficulty, min(self.max_difficulty, int(d // r) * r))

    def get_difficulty(self, global_steps: int) -> int:
        s = min(max(global_steps, 0), self.total_steps)
        frac = s / max(self.total_steps, 1)
        if self.schedule_type == "fixed_linear":
            d = self.min_difficulty + (self.max_difficulty - self.min_difficulty) * frac
        elif self.schedule_type == "fixed_root":
            d = self.min_difficulty + (
                self.max_difficulty - self.min_difficulty
            ) * frac ** (1.0 / self.root_degree)
        elif self.schedule_type == "fixed_discrete":
            d = self.discrete_difficulties[-1]
            for diff, until in zip(self.discrete_difficulties, self.discrete_steps):
                if global_steps <= until:
                    d = diff
                    break
            return int(d)
        else:
            raise ValueError(f"unknown curriculum schedule {self.schedule_type!r}")
        self.current_difficulty = self._round(d)
        return self.current_difficulty

    def update_difficulty(self, global_steps: int) -> int:
        self.current_difficulty = self.get_difficulty(global_steps)
        return self.current_difficulty
