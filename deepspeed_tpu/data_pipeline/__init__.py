from .curriculum_scheduler import CurriculumScheduler  # noqa: F401
from .indexed_dataset import (  # noqa: F401
    IndexedDatasetBuilder,
    MMapIndexedDataset,
)
from .data_analyzer import (  # noqa: F401
    CurriculumSampler,
    DataAnalyzer,
    analyze_dataset,
    load_index,
    write_index,
)
from .random_ltd import RandomLTDScheduler, random_ltd_layer  # noqa: F401
