from .curriculum_scheduler import CurriculumScheduler  # noqa: F401
from .random_ltd import RandomLTDScheduler, random_ltd_layer  # noqa: F401
