"""Autotuner: micro-batch-size / remat-policy search.

Parity: deepspeed/autotuning/autotuner.py (+ the "autotuning" config
section). The reference launches separate ranked experiments; on TPU one
process owns the chips, so each candidate is a fresh engine in-process:
compile → run measured steps → throughput; OOM (XLA RESOURCE_EXHAUSTED)
prunes the candidate and, in fast mode, everything larger.

Search space: micro-batch sizes (powers of two up to
max_train_micro_batch_size_per_gpu) × remat policies (none is tried first
at each batch — cheapest when it fits, per the memory/compute tradeoff),
then a flash-attention tile sweep (block_q × block_k) refines the winner —
the "tpu_kernels" knob the engine exposes for exactly this loop.

Planner mode (ISSUE 7, default whenever an HBM budget is resolvable —
``autotuning.hbm_gb``, ``SHARDPLAN_HBM_GB``, or a detected TPU
generation's capacity; ``autotuning.planner`` forces it either way):
instead of walking the ladder by compiling, the whole candidate space is
priced through analysis/cost abstract traces (planner_search.py), rule
R6 statically prunes what cannot fit, survivors are ranked by roofline
throughput, and only a top-k (``autotuning.top_k``, default 3) is
compiled and measured. Each measured survivor banks its
(predicted, measured) step pair into the drift ledger
(analysis/cost/drift.py; ``autotuning.drift_ledger`` overrides the
path) so systematic cost-model drift surfaces as a recalibration
suggestion instead of silently rotting the ranking. The runtime
RESOURCE_EXHAUSTED catch in ``_measure`` stays as the backstop for what
the static estimate misses.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils.logging import log_dist

REMAT_POLICIES = ("none", "dots_flash", "attn_mlp", "full")
# phase-0 memory ladder (reference: the DeepSpeed autotuner's core job is
# picking the ZeRO stage — deepspeed/autotuning/autotuner.py tuning space
# z0→z3+offload): escalate until the model fits, then tune micro/remat at
# that stage. Lower stages go first — less collective traffic when they fit.
ZERO_LADDER = (
    {"stage": 0},
    {"stage": 1},
    {"stage": 2},
    {"stage": 3},
    {"stage": 3, "offload_optimizer": {"device": "cpu"}},
)
# (512, 512) is NOT a candidate: it equals the kernel defaults (see
# flash_attention.DEFAULT_BLOCK_*) so phase 2 would re-measure the (0, 0)
# phase-1 winner; 512x1024 is the measured v5e S=2048 winner
FLASH_BLOCKS = ((0, 0), (512, 1024), (512, 256), (256, 512), (128, 128))
# phase-3 backward-tile candidates (dq/dkv kernels); fwd tiles stay at the
# phase-2 winner. Excludes (0, 0): that IS the phase-2 result (inherit).
FLASH_BLOCKS_BWD = ((512, 512), (256, 512), (512, 256))


def _is_oom(err: Exception) -> bool:
    # match XLA's OOM signatures only — a generic "hbm" substring would also
    # swallow unrelated compiler diagnostics that merely mention the memory
    # space, hiding the real failure from the user
    s = str(err)
    return "RESOURCE_EXHAUSTED" in s or "Ran out of memory" in s


class Autotuner:
    def __init__(self, model, base_config: Dict[str, Any], topology=None,
                 sample_batch_fn=None):
        self.model = model
        self.base_config = dict(base_config)
        self.topology = topology
        self.sample_batch_fn = sample_batch_fn
        at = dict(self.base_config.get("autotuning") or {})
        self.metric = at.get("metric", "throughput")
        self.fast = bool(at.get("fast", True))
        self.start_step = int(at.get("start_profile_step", 3))
        self.end_step = int(at.get("end_profile_step", 5))
        self.max_micro = int(at.get("max_train_micro_batch_size_per_gpu", 64))
        self.trials = int(at.get("trials", 3))  # medians beat noisy pools
        self.fixed_global_batch = bool(at.get("fixed_global_batch", False))
        # phase 0 (ZeRO ladder) runs by default only when the user left the
        # zero_optimization section unset — an explicit stage is a pin the
        # tuner must respect; "tune_zero_stage" overrides either way
        self.tune_zero = bool(
            at.get("tune_zero_stage",
                   "zero_optimization" not in self.base_config)
        )
        # planner mode (planner_search.py): None → auto (on when an HBM
        # budget is resolvable), True/False forces it
        self.planner: Optional[bool] = at.get("planner")
        self.top_k = int(at.get("top_k", 3))
        self.hbm_gb = at.get("hbm_gb")
        self.drift_ledger_path = at.get("drift_ledger")
        self._zero_patch: Optional[Dict[str, Any]] = None
        self.results: List[Dict[str, Any]] = []
        self.last_search = None      # SearchResult of the planner phase
        self.n_compiles = 0          # engines actually built + compiled

    def _candidates(self) -> List[Tuple[int, str]]:
        mbs = []
        m = 1
        while m <= self.max_micro:
            mbs.append(m)
            m *= 2
        return [(mb, pol) for mb in mbs for pol in REMAT_POLICIES]

    def _settled_zero(self, rung) -> Dict[str, Any]:
        """The zero section phases 1+ measure once the ladder settles:
        the winning rung plus the user's non-conflicting zero keys.
        stage and the offload subsections come from the rung — they ARE
        what phase 0 decided."""
        user = dict(self.base_config.get("zero_optimization") or {})
        for k in ("stage", "offload_optimizer", "offload_param"):
            user.pop(k, None)
        return {**user, **dict(rung)}

    def _candidate_config(self, micro_batch: int, remat: str,
                          blocks: Tuple[int, ...] = (0, 0)) -> Dict[str, Any]:
        """The exact ds_config one candidate measures (split out so tests
        can assert what a probe runs without spinning an engine)."""
        cfg = dict(self.base_config)
        cfg.pop("autotuning", None)
        if self._zero_patch is not None:
            # the ladder rung REPLACES the section wholesale: merging the
            # base config's keys in (dict.update) leaked user settings
            # like offload_optimizer into lower-stage probes — stage 0 +
            # cpu offload is a config the ladder never intends to measure
            cfg["zero_optimization"] = dict(self._zero_patch)
        if self.topology is not None:
            dp = self.topology.data_shard_size
        else:
            # initialize() will build a pure-dp topology over every visible
            # device; the batch triangle must be computed against that same
            # dp or every candidate fails config validation
            import jax

            dp = max(len(jax.devices()), 1)
        cfg["train_micro_batch_size_per_gpu"] = micro_batch
        if self.fixed_global_batch:
            # hold the global batch constant and let accumulation absorb
            # the micro change (operator-sweep semantics: every point sees
            # identical data and optimizer dynamics)
            tbs = int(cfg["train_batch_size"])
            cfg["gradient_accumulation_steps"] = max(tbs // (micro_batch * dp), 1)
        else:
            accum = int(cfg.get("gradient_accumulation_steps", 1))
            cfg["train_batch_size"] = micro_batch * dp * accum
        cfg["activation_checkpointing"] = {"policy": remat}
        blocks = tuple(blocks) + (0,) * (4 - len(blocks))  # (bq,bk[,bqb,bkb])
        if any(blocks):
            tk = dict(cfg.get("tpu_kernels") or {})
            # fwd keys only when the candidate names them: a bwd-only
            # candidate (0,0,bqb,bkb) must keep the base config's fwd
            # tiles, or the measurement and the emitted patch describe
            # different configurations. bwd keys assigned whenever the
            # candidate is non-default: its 0 means "inherit fwd" and
            # must overwrite a stale base-config bwd override.
            if blocks[0] or blocks[1]:
                tk["flash_block_q"], tk["flash_block_k"] = blocks[:2]
            tk["flash_block_q_bwd"], tk["flash_block_k_bwd"] = blocks[2:]
            cfg["tpu_kernels"] = tk
        cfg.setdefault("steps_per_print", 10**9)
        return cfg

    def _measure(self, micro_batch: int, remat: str,
                 blocks: Tuple[int, int] = (0, 0),
                 cfg: Optional[Dict[str, Any]] = None) -> Optional[float]:
        """One candidate: fresh engine → compile+warmup → chained-dispatch
        timing → tokens/sec. This is THE compile+measure loop — the operator
        sweep (tools/sweep_train.py) is a CLI over it, so the two tuners
        cannot drift.

        Timing: the chip may sit behind a network relay where every host
        readback pays the tunnel RTT, so each trial dispatches a chain of
        steps with ONE blocking read at the end, and trials are reduced by
        median (shared pools are noisy)."""
        import deepspeed_tpu

        # planner mode passes the candidate's FULL config (extra axes
        # like tp_overlap differ from what (micro, remat) alone rebuilds)
        cfg = cfg or self._candidate_config(micro_batch, remat, blocks)
        engine = None
        self.n_compiles += 1  # the planner-mode contract: ≤ top-k of these
        try:
            engine, *_ = deepspeed_tpu.initialize(
                model=self.model, config=cfg, topology=self.topology
            )
            batch = self.sample_batch_fn(cfg["train_batch_size"])
            # stage once: per-step device_put is a blocking relay RPC
            staged = engine.prepare_batch(dict(batch))
            # the scanned chain is the program bench.py times: one dispatch
            # and one readback per trial, and only ONE compile per candidate
            # (the single-step program never compiles)
            chain = max(self.end_step - self.start_step, 1)
            engine.train_batch_chain(batch=staged, steps=chain)  # compile
            float(engine.state.step)  # settle before the timed region
            trials = []
            for _ in range(self.trials):
                t0 = time.perf_counter()
                engine.train_batch_chain(batch=staged, steps=chain)
                float(engine.state.step)  # one readback per chain
                trials.append((time.perf_counter() - t0) / chain)
            dt = float(np.median(trials))
            tokens = np.asarray(batch["input_ids"]).size
            return tokens / dt
        except Exception as e:  # noqa: BLE001 — OOM pruning is the point
            if _is_oom(e):
                log_dist(f"autotune: mb={micro_batch} remat={remat} OOM, pruned")
                return None
            raise
        finally:
            if engine is not None:
                engine.destroy()  # release logger hooks even on failure

    def measure_grid(self, grid) -> List[Dict[str, Any]]:
        """Measure an explicit [(micro, remat_policy, (bq, bk)), ...] grid
        through the same engine as :meth:`tune`. Returns one record per
        point ({micro_batch, remat_policy, flash_block_*, throughput} or
        {... , error}); OOM points record throughput None. Non-OOM failures
        are recorded, not raised — an operator grid survives bad rungs."""
        records = []
        for micro, pol, blocks in grid:
            rec: Dict[str, Any] = {
                "micro_batch": int(micro), "remat_policy": pol,
                "flash_block_q": int(blocks[0]), "flash_block_k": int(blocks[1]),
            }
            if len(blocks) > 2 and (blocks[2] or blocks[3]):
                rec["flash_block_q_bwd"] = int(blocks[2])
                rec["flash_block_k_bwd"] = int(blocks[3])
            try:
                rec["throughput"] = self._measure(micro, pol, tuple(blocks))
            except Exception as e:  # noqa: BLE001
                rec["error"] = (str(e).splitlines() or [repr(e)])[0][:160]
            records.append(rec)
            if rec.get("throughput") is not None:
                self.results.append(rec)
        return records

    def _flash_tunable(self) -> bool:
        """Phase 2 only makes sense when the flash tile knobs are live."""
        import jax

        if jax.default_backend() != "tpu":
            return False  # interpret-mode tiles all time the same
        tk = dict(self.base_config.get("tpu_kernels") or {})
        if tk.get("flash_attention") is False:
            return False  # xla impl never reads the tile scope
        sa = dict(self.base_config.get("sparse_attention") or {})
        if sa.get("mode", "none") != "none":
            return False  # sparse pins block_q/block_k to its layout block
        return True

    def _pick_zero_stage(self) -> Optional[Dict[str, Any]]:
        """Phase 0: walk ZERO_LADDER until a probe fits (micro_batch=1 at
        max remat — if THAT OOMs, nothing at the stage will run), leaving
        the winning patch active in self._zero_patch for every later
        measurement. Answers the reference autotuner's core question: which
        ZeRO stage do I need for this model to fit at all."""
        if not self.tune_zero:
            return None
        pipe = dict(self.base_config.get("pipeline") or {})
        ladder = ZERO_LADDER
        if int(pipe.get("stages", 1)) > 1:
            # grads must persist across the pipeline schedule: config
            # validation rejects ZeRO>=2 + pp, so the ladder stops at 1
            ladder = tuple(z for z in ladder if z["stage"] <= 1)
        self._probe_tput = None
        for z in ladder:
            self._zero_patch = dict(z)  # probes measure the rung EXACTLY
            tput = self._measure(1, REMAT_POLICIES[-1])
            if tput is not None:
                log_dist(f"autotune: zero ladder settled on {z}")
                self._probe_tput = tput
                # later phases (micro/remat/tiles) measure the winning
                # rung ENRICHED with the user's non-conflicting zero keys
                # (bucket sizes etc.) — stage/offload stay the ladder's
                # decision, but dropping e.g. reduce_bucket_size would
                # rank candidates on a config the user won't run
                settled = self._settled_zero(z)
                if settled != dict(z):
                    # the probe ran the BARE rung; its tput must not be
                    # recorded against the enriched section — phase 1
                    # re-measures the (mb=1, max-remat) point
                    self._probe_tput = None
                self._zero_patch = settled
                return dict(settled)
            log_dist(f"autotune: zero={z} OOM at mb=1/full; escalating")
        self._zero_patch = None
        raise RuntimeError(
            "autotuning: no ZeRO stage (0-3, +cpu offload) fits even at "
            "micro_batch=1 with full rematerialisation"
        )

    # ------------------------------------------------------- planner mode
    def _resolved_budget(self) -> Optional[float]:
        """The per-device HBM budget planner mode prunes against:
        explicit ``autotuning.hbm_gb``, then the ``SHARDPLAN_HBM_GB``
        env, then — only when the chips are real — the detected
        generation's capacity. On a CPU mesh with nothing armed there is
        no budget (R6's never-guess-the-machine contract) and the tuner
        stays on the runtime ladder unless ``planner`` forces it."""
        import os

        if self.hbm_gb is not None:
            return float(self.hbm_gb) * float(1 << 30)
        env = os.environ.get("SHARDPLAN_HBM_GB")
        if env:
            return float(env) * float(1 << 30)
        import jax

        if jax.default_backend() == "tpu":
            from ..analysis.cost import HardwareModel

            return HardwareModel.detect().hbm_bytes
        return None

    def _planner_mode(self) -> bool:
        if self.planner is not None:
            return bool(self.planner)
        return self._resolved_budget() is not None

    def _tune_planner(self) -> Dict[str, Any]:
        """Phase 0+1, planner-driven: enumerate the whole (zero × remat
        × micro) space through analysis.cost, R6-prune statically, rank
        by roofline, compile + measure only the top-k. Banks one drift
        pair per measured survivor."""
        from ..analysis.cost import drift
        from ..config import DeepSpeedConfig
        from .planner_search import PlannerSearch

        if DeepSpeedConfig(dict(self.base_config)).serving.enabled:
            # the measurement loop below times a TRAIN step; a serving
            # config's token_budget axis is static-only for now
            raise NotImplementedError(
                "planner-mode measurement covers training candidates; "
                "the serving token_budget search is static-only — rank "
                "it with tools/autoplan.py and A/B the survivors with "
                "tools/bench_serve.py"
            )
        search = PlannerSearch(
            self.model, self.base_config, self.topology,
            top_k=self.top_k, hbm_budget_bytes=self._resolved_budget(),
            tuner=self,
        )
        self.last_search = result = search.search()
        if not result.survivors:
            raise RuntimeError(
                "autotuning: every candidate is statically over the HBM "
                "budget (planner_search R6) — shard further, offload, or "
                "raise autotuning.hbm_gb\n" + result.explain()
            )
        ledger = drift.DriftLedger(self.drift_ledger_path)
        best = None
        for pc in result.top_k:
            self._zero_patch = pc.cand.zero_dict
            # the EXACT planned config (incl. axes _candidate_config
            # alone cannot rebuild, e.g. tp_overlap) is what measures —
            # the drift pair must compare prediction and wall clock of
            # the same program
            cfg = search._candidate_config(pc.cand)
            tput = self._measure(pc.cand.micro, pc.cand.remat, cfg=cfg)
            if tput is None:
                # the static estimate missed: the runtime OOM catch is
                # still the backstop, the rung just loses its slot
                log_dist(f"autotune: planner survivor {pc.cand.label()} "
                         "OOMed at runtime (backstop prune)")
                continue
            rec = {
                "micro_batch": pc.cand.micro,
                "remat_policy": pc.cand.remat,
                "throughput": tput,
                "predicted_step_s": pc.predicted_step_s,
                "predicted_tokens_per_s": pc.predicted_tput,
            }
            if pc.cand.zero_dict is not None:
                rec["zero_optimization"] = pc.cand.zero_dict
            if pc.cand.tp_overlap is not None:
                # carry the full resolved section: result_to_config_patch
                # replaces sections wholesale, so a bare flag would wipe
                # tp_size on merge
                rec["tensor_parallel"] = cfg["tensor_parallel"]
            if pc.cand.moe_a2a is not None:
                rec["moe"] = cfg["moe"]  # same wholesale-section rule
            if pc.cand.z3_prefetch is not None:
                rec["zero_optimization"] = cfg["zero_optimization"]
            self.results.append(rec)
            log_dist(f"autotune: planner top-k {pc.cand.label()}: "
                     f"{tput:.0f} tok/s (predicted "
                     f"{pc.predicted_tput or 0:.0f})")
            if best is None or tput > best["throughput"]:
                best = rec
            try:  # the ledger is evidence, never a point of failure
                measured_step_s = pc.tokens_per_step / tput
                ledger.append(drift.make_entry(
                    pc.plan, measured_step_s,
                    source=f"autotune:{pc.cand.label()}",
                    extra={"throughput": round(tput, 1)},
                ))
            except Exception as e:  # noqa: BLE001
                log_dist(f"autotune: drift ledger append failed: {e}")
        if best is None:
            raise RuntimeError(
                "autotuning: all planner-ranked top-k candidates failed "
                "at runtime; re-run with a lower autotuning.hbm_gb or "
                "planner=false\n" + result.explain()
            )
        # later phases (tile sweep) must measure the winner's sections:
        # zero via the patch mechanism, tensor_parallel by pinning the
        # winning section into the base config _candidate_config copies
        self._zero_patch = best.get("zero_optimization")
        if "tensor_parallel" in best:
            self.base_config["tensor_parallel"] = dict(
                best["tensor_parallel"]
            )
        return best

    def tune(self) -> Dict[str, Any]:
        """Returns the best config patch: {micro_batch, remat_policy,
        throughput} plus, when the flash tile sweep improved on it,
        tpu_kernels-style {flash_block_q, flash_block_k} keys, and the
        zero_optimization section phase 0 settled on (when it ran).
        Planner mode (see module docstring) replaces the
        compile-and-time ladder with a static search + top-k measure."""
        if self._planner_mode():
            best = self._tune_planner()
            return self._sweep_tiles(best)
        return self._sweep_tiles(self._tune_ladder())

    def _tune_ladder(self) -> Dict[str, Any]:
        """Phases 0+1, classic: walk the ZeRO ladder and the (micro,
        remat) grid by compiling, pruning on runtime OOM."""
        best = None
        oom_at = None
        zero = self._pick_zero_stage()
        # every record carries the phase-0 section so best == the max
        # record and each rec round-trips through result_to_config_patch
        zrec = {} if zero is None else {"zero_optimization": zero}
        for mb, pol in self._candidates():
            if (zero is not None and (mb, pol) == (1, REMAT_POLICIES[-1])
                    and self._probe_tput is not None):
                # the phase-0 probe already measured this exact point
                tput = self._probe_tput
                rec = {"micro_batch": mb, "remat_policy": pol,
                       "throughput": tput, **zrec}
                self.results.append(rec)
                if best is None or tput > best["throughput"]:
                    best = rec
                continue
            if oom_at is not None and self.fast and mb >= oom_at:
                continue
            tput = self._measure(mb, pol)
            if tput is None:
                if pol == REMAT_POLICIES[-1]:  # OOM even at max remat
                    oom_at = mb
                continue
            rec = {"micro_batch": mb, "remat_policy": pol,
                   "throughput": tput, **zrec}
            self.results.append(rec)
            log_dist(f"autotune: mb={mb} remat={pol}: {tput:.0f} tok/s")
            if best is None or tput > best["throughput"]:
                best = rec
        if best is None:
            raise RuntimeError("autotuning found no runnable configuration")
        return best

    def _sweep_tiles(self, best: Dict[str, Any]) -> Dict[str, Any]:
        """Phases 2+3: the flash tile sweep on the winning (mb, remat).
        Tile shapes are plan-invariant (the traced program does not
        change with kernel block sizes), so this stays a measured
        refinement in planner mode too."""
        # records carry the winner's zero section so every rec keeps
        # round-tripping through result_to_config_patch
        zrec = (
            {"zero_optimization": best["zero_optimization"]}
            if "zero_optimization" in best else {}
        )
        # phase 2: flash tile sweep on the winning (mb, remat)
        if self._flash_tunable():
            for blocks in FLASH_BLOCKS[1:]:
                tput = self._measure(
                    best["micro_batch"], best["remat_policy"], blocks
                )
                if tput is None:
                    continue
                rec = {
                    "micro_batch": best["micro_batch"],
                    "remat_policy": best["remat_policy"],
                    "flash_block_q": blocks[0],
                    "flash_block_k": blocks[1],
                    "throughput": tput,
                    **zrec,
                }
                self.results.append(rec)
                log_dist(
                    f"autotune: blocks={blocks}: {tput:.0f} tok/s"
                )
                if tput > best["throughput"]:
                    best = rec
            # phase 3: backward tiles on the winner — the dq/dkv kernels'
            # operand mix differs from the fwd's, so their best shape is
            # its own small search (0,0 = inherit fwd, the phase-2 result)
            fwd = (best.get("flash_block_q", 0), best.get("flash_block_k", 0))
            for bwd in FLASH_BLOCKS_BWD:
                blocks = (*fwd, *bwd)
                tput = self._measure(
                    best["micro_batch"], best["remat_policy"], blocks
                )
                if tput is None:
                    continue
                rec = {
                    "micro_batch": best["micro_batch"],
                    "remat_policy": best["remat_policy"],
                    "flash_block_q": fwd[0], "flash_block_k": fwd[1],
                    "flash_block_q_bwd": bwd[0], "flash_block_k_bwd": bwd[1],
                    "throughput": tput,
                    **zrec,
                }
                self.results.append(rec)
                log_dist(f"autotune: bwd blocks={bwd}: {tput:.0f} tok/s")
                if tput > best["throughput"]:
                    best = rec
        return best


def result_to_config_patch(rec: Dict[str, Any]) -> Dict[str, Any]:
    """A tuner record → ds_config fragment, mergeable into any base config
    (the round-trip contract: sweep/tune output feeds straight back into
    `deepspeed_tpu.initialize(config=...)`)."""
    patch: Dict[str, Any] = {
        "train_micro_batch_size_per_gpu": int(rec["micro_batch"]),
        "activation_checkpointing": {"policy": rec["remat_policy"]},
    }
    bq, bk = rec.get("flash_block_q", 0), rec.get("flash_block_k", 0)
    if bq or bk:
        patch["tpu_kernels"] = {"flash_block_q": int(bq),
                                "flash_block_k": int(bk)}
    bqb = rec.get("flash_block_q_bwd", 0)
    bkb = rec.get("flash_block_k_bwd", 0)
    if bqb or bkb:
        patch.setdefault("tpu_kernels", {}).update(
            flash_block_q_bwd=int(bqb), flash_block_k_bwd=int(bkb)
        )
    if "zero_optimization" in rec:
        patch["zero_optimization"] = dict(rec["zero_optimization"])
    if "tensor_parallel" in rec:
        # planner-mode records carry the full section the candidate
        # measured (tp_size + the decided overlap_comm), so the
        # wholesale-replace merge semantics stay lossless
        patch["tensor_parallel"] = dict(rec["tensor_parallel"])
    return patch


def autotune(model, base_config, topology=None, sample_batch_fn=None):
    return Autotuner(model, base_config, topology, sample_batch_fn).tune()
