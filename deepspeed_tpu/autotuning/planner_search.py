"""Planner-driven candidate search: shardplan as the zero-compile cost model.

The phase-0/1 ladder used to discover configurations by compiling and
timing every candidate, pruning on XLA's RESOURCE_EXHAUSTED. shardplan
(analysis/cost) already predicts HBM peak, ICI bytes and a roofline step
time from an ``abstract_init=True`` trace in under a second, so the
search inverts: enumerate the WHOLE candidate space statically, let rule
R6 prune everything that cannot fit before anything compiles, rank the
survivors by predicted throughput, and compile + measure only a top-k
("Automatic Cross-Replica Sharding of Weight Update", arXiv:2004.13336 —
derive the placement, don't search it by trial; ZeRO++ arXiv:2306.10209
prices the ladder's collective traffic analytically the same way).

Candidate axes:

- zero stage × offload (the phase-0 ladder rungs, enriched with the
  user's non-conflicting zero keys exactly like the runtime ladder);
- remat policy × micro-batch (powers of two up to the configured max);
- tp-overlap on/off when the config runs tensor parallelism — the
  roofline's ``max()`` neutralizes ring bytes that hide under compute,
  so an overlapped leg never loses rank for declaring its wire traffic
  while a serial leg's GSPMD collectives stay invisible;
- moe-a2a on/off when the config runs expert parallelism, and stage-3
  layer-prefetch on/off on stage-3 rungs (ISSUE 10): both priced through
  the same R6/R8 static gate BEFORE any compile — R8 rejects a rung
  whose declared-overlapped stream cannot hide in the compute window;
- the wire-codec axis (ISSUE 12, comm/wires.py): stage x grad_wire x
  param_wire — grad-reduce-scatter codecs on stage>=1 rungs, stage-3
  param-gather codecs on stage-3 rungs, each candidate's analytic
  grad_wire/param_wire streams priced statically (``wire_codecs``
  constructor arg; ("fp32",) collapses the axis);
- serving ``token_budget`` for serving-enabled configs (the slot step
  is traced through ``lint_serving_config`` instead of a train step),
  crossed with the serving-side moe-a2a form (stock vs chunked decode
  exchange, ISSUE 14) when the config serves a MoE model expert-parallel
  — static-only, the PR-7 serving-measurement refusal stands;
- mesh shape (dp×tp factorizations) for capacity dryruns — CLI-only,
  ``tools/autoplan.py --dryrun-mesh``; a ``dcn_dp*fsdp x tp`` spelling
  enumerates hybrid dp-factorizations (ISSUE 17), each priced through
  per-link bandwidths (``Plan.dcn_s``) with the 2-hop-vs-flat grad RS
  (``zero_optimization.hierarchical_wire`` — the existing knob, no new
  one) as a search axis on those rungs;
- flash tiles are enumerable but *plan-invariant* (the traced program
  does not change with kernel block shapes), so the search carries them
  only when asked and the measured tile sweep stays the tuner's
  refinement phase on the winner.

Every pruned rung records WHY it lost (``tools/autoplan.py --explain``),
and R6 stays the primary pruner only statically: the runtime OOM catch
in ``Autotuner._measure`` remains the backstop for what the estimate
misses.

Memoized fast pruning (the ``_is_oom`` hardening): once a (zero, remat)
group's rung is statically over budget at micro=m, every larger micro in
the group is derived by scaling the traced plan's batch-linear terms
(:func:`analysis.cost.scale_plan_micro`) instead of tracing again —
``n_traced`` counts real traces so tests can hold the line.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..utils.logging import log_dist

_GIB = float(1 << 30)

DEFAULT_TOP_K = 3
DEFAULT_TOKEN_BUDGETS = (8, 16, 32)


@dataclass(frozen=True)
class Candidate:
    """One point of the search space. ``zero`` is the settled
    zero_optimization section for a ladder rung as canonical JSON (a
    hashable spelling — sections nest offload dicts; None = the user's
    own section rides); the optional axes default to "not an axis
    here"."""

    zero: Optional[str] = None
    remat: str = "none"
    micro: int = 1
    flash_blocks: Tuple[int, ...] = (0, 0)
    tp_overlap: Optional[bool] = None
    moe_a2a: Optional[bool] = None       # decomposed MoE a2a on/off
    z3_prefetch: Optional[bool] = None   # stage-3 layer prefetch on/off
    grad_wire: Optional[str] = None      # grad RS codec (stage >= 1 rungs)
    param_wire: Optional[str] = None     # stage-3 param gather codec
    hier_wire: Optional[bool] = None     # 2-hop vs flat grad RS (hybrid mesh)
    token_budget: Optional[int] = None
    # (dp, tp) flat, or (dcn_dp, fsdp, tp) for a hybrid dp-factorization
    mesh: Optional[Tuple[int, ...]] = None

    @property
    def zero_dict(self) -> Optional[Dict[str, Any]]:
        return json.loads(self.zero) if self.zero is not None else None

    @property
    def stage(self) -> int:
        z = self.zero_dict or {}
        return int(z.get("stage", 0))

    def group_key(self) -> Tuple:
        """Everything but micro — the memoization group whose plans
        scale batch-linearly."""
        return (self.zero, self.remat, self.flash_blocks, self.tp_overlap,
                self.moe_a2a, self.z3_prefetch, self.grad_wire,
                self.param_wire, self.hier_wire, self.token_budget,
                self.mesh)

    def label(self) -> str:
        z = self.zero_dict
        if z is None:
            zs = "zuser"
        else:
            zs = f"z{z.get('stage', 0)}"
            if "offload_optimizer" in z or "offload_param" in z:
                zs += "off"
        parts = [zs, self.remat, f"mb{self.micro}"]
        if self.tp_overlap is not None:
            parts.append("tpov" if self.tp_overlap else "tpser")
        if self.moe_a2a is not None:
            parts.append("a2aov" if self.moe_a2a else "a2aser")
        if self.z3_prefetch is not None:
            parts.append("z3pf" if self.z3_prefetch else "z3ser")
        if self.grad_wire is not None and self.grad_wire != "fp32":
            parts.append(f"gw-{self.grad_wire}")
        if self.param_wire is not None and self.param_wire != "fp32":
            parts.append(f"pw-{self.param_wire}")
        if self.hier_wire is not None:
            parts.append("rs2hop" if self.hier_wire else "rsflat")
        if self.token_budget is not None:
            parts = [f"serve-tb{self.token_budget}"]
            if self.moe_a2a is not None:
                parts.append("a2achunk" if self.moe_a2a else "a2astock")
        if self.mesh is not None:
            if len(self.mesh) == 3:
                parts.append(
                    f"dp{self.mesh[0]}dcnxfsdp{self.mesh[1]}xtp{self.mesh[2]}"
                )
            else:
                parts.append(f"dp{self.mesh[0]}xtp{self.mesh[1]}")
        if any(self.flash_blocks):
            parts.append("x".join(str(b) for b in self.flash_blocks))
        return "/".join(parts)


@dataclass
class PlannedCandidate:
    """A candidate with its static verdict attached."""

    cand: Candidate
    plan: Any = None                 # analysis.cost.Plan (None: untraceable)
    pruned: bool = False
    reason: str = ""                 # why it lost (R6 message / skip note)
    traced: bool = False             # False → derived via scale_plan_micro
    derived_from_micro: Optional[int] = None
    tokens_per_step: float = 0.0

    @property
    def predicted_step_s(self) -> Optional[float]:
        return None if self.plan is None else float(self.plan.est_step_s)

    @property
    def predicted_tput(self) -> Optional[float]:
        if self.plan is None or self.plan.est_step_s <= 0:
            return None
        return self.tokens_per_step / self.plan.est_step_s

    def row(self) -> Dict[str, Any]:
        out = {
            "config": self.cand.label(),
            "micro_batch": self.cand.micro,
            "remat_policy": self.cand.remat,
            "pruned": self.pruned,
            "traced": self.traced,
            "reason": self.reason,
        }
        z = self.cand.zero_dict
        if z is not None:
            out["zero_optimization"] = z
        if self.plan is not None:
            out.update(
                peak_hbm_gib=round(self.plan.peak_hbm_bytes / _GIB, 3),
                est_step_s=round(self.plan.est_step_s, 6),
                predicted_tokens_per_s=round(self.predicted_tput or 0.0, 1),
            )
        if self.derived_from_micro is not None:
            out["derived_from_micro"] = self.derived_from_micro
        return out


@dataclass
class SearchResult:
    planned: List[PlannedCandidate] = field(default_factory=list)
    survivors: List[PlannedCandidate] = field(default_factory=list)
    top_k: List[PlannedCandidate] = field(default_factory=list)
    n_traced: int = 0
    budget_bytes: Optional[float] = None

    @property
    def pruned(self) -> List[PlannedCandidate]:
        return [p for p in self.planned if p.pruned]

    def explain(self) -> str:
        """The --explain table: every candidate, ranked survivors first,
        each pruned rung naming why it lost."""
        lines = []
        budget = (f"{self.budget_bytes / _GIB:.2f}G"
                  if self.budget_bytes else "-")
        head = (f"{'rank':<5}{'config':<30}{'peak':>9}{'budget':>9}"
                f"{'est step':>12}{'pred tok/s':>12}  verdict")
        lines.append(head)
        lines.append("-" * len(head))

        def fmt(pc: PlannedCandidate, rank: str, verdict: str) -> str:
            peak = (f"{pc.plan.peak_hbm_bytes / _GIB:.2f}G"
                    if pc.plan is not None else "-")
            step = (f"{pc.plan.est_step_s:.4g}s"
                    if pc.plan is not None else "-")
            tput = (f"{pc.predicted_tput:,.0f}"
                    if pc.predicted_tput else "-")
            return (f"{rank:<5}{pc.cand.label()[:29]:<30}{peak:>9}"
                    f"{budget:>9}{step:>12}{tput:>12}  {verdict}")

        best = self.survivors[0].predicted_tput if self.survivors else None
        for i, pc in enumerate(self.survivors):
            verdict = "compile+measure" if pc in self.top_k else (
                "ranked out"
                + (f": {100 * (1 - (pc.predicted_tput or 0) / best):.0f}% "
                   f"behind the predicted winner" if best else "")
            )
            lines.append(fmt(pc, str(i + 1), verdict))
        for pc in self.pruned:
            why = pc.reason
            if not pc.traced and pc.derived_from_micro is not None:
                why += (f" [derived from mb={pc.derived_from_micro} "
                        "without re-tracing]")
            lines.append(fmt(pc, "-", f"pruned: {why}"))
        lines.append(
            f"{len(self.survivors)} survivors / {len(self.planned)} "
            f"candidates, {self.n_traced} traced, top-{len(self.top_k)} "
            "compiled"
        )
        return "\n".join(lines)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "budget_bytes": self.budget_bytes,
            "n_candidates": len(self.planned),
            "n_traced": self.n_traced,
            "survivors": [p.row() for p in self.survivors],
            "pruned": [p.row() for p in self.pruned],
            "top_k": [p.row() for p in self.top_k],
        }


class PlannerSearch:
    """Enumerate → plan (abstract trace, memoized) → R6-prune → rank.

    Shares the Autotuner's config builder so a planned candidate and a
    measured candidate are byte-identical ds_configs — the search cannot
    drift from what the probes actually run."""

    def __init__(self, model, base_config: Dict[str, Any], topology=None,
                 *, top_k: int = DEFAULT_TOP_K,
                 hbm_budget_bytes: Optional[float] = None,
                 hardware=None,
                 mesh_shapes: Optional[Sequence[Tuple[int, int]]] = None,
                 token_budgets: Sequence[int] = DEFAULT_TOKEN_BUDGETS,
                 include_tiles: bool = False,
                 wire_codecs: Sequence[str] = ("fp32", "int8"),
                 remat_policies: Optional[Sequence[str]] = None,
                 tuner=None):
        from .autotuner import Autotuner

        self.model = model
        self.base_config = dict(base_config)
        self.topology = topology
        self.top_k = int(top_k)
        self.hardware = hardware
        self.mesh_shapes = list(mesh_shapes or [])
        self.token_budgets = tuple(token_budgets)
        self.include_tiles = include_tiles
        # remat axis restriction (the campaign pins ("none",) so the
        # lattice stays about the overlap/wire/prefetch knobs the default
        # table ships); None = the full REMAT_POLICIES ladder as before
        self.remat_policies = (tuple(remat_policies)
                               if remat_policies is not None else None)
        # the wire-codec axis (ISSUE 12, comm/wires.py): grad_wire on
        # stage>=1 rungs, param_wire on stage-3 rungs — every combination
        # priced statically before any compile. ("fp32",) collapses it.
        self.wire_codecs = tuple(wire_codecs)
        self.tuner = tuner or Autotuner(
            model, base_config, topology=topology, sample_batch_fn=None
        )
        if hbm_budget_bytes is None:
            at = dict(self.base_config.get("autotuning") or {})
            if at.get("hbm_gb") is not None:
                hbm_budget_bytes = float(at["hbm_gb"]) * _GIB
        self.budget_bytes = hbm_budget_bytes
        self.n_traced = 0

    # ------------------------------------------------------------ enumerate
    def _zero_axis(self) -> List[Optional[str]]:
        from .autotuner import ZERO_LADDER

        if not self.tuner.tune_zero:
            return [None]
        pipe = dict(self.base_config.get("pipeline") or {})
        ladder = ZERO_LADDER
        if int(pipe.get("stages", 1)) > 1:
            ladder = tuple(z for z in ladder if z["stage"] <= 1)
        return [
            json.dumps(self.tuner._settled_zero(z), sort_keys=True)
            for z in ladder
        ]

    def candidates(self) -> List[Candidate]:
        from ..config import DeepSpeedConfig
        from .autotuner import FLASH_BLOCKS, REMAT_POLICIES

        ds = DeepSpeedConfig(dict(self.base_config))
        if getattr(ds.serving, "enabled", False):
            # serving-side moe-a2a axis (ISSUE 14): stock vs chunked
            # decode exchange, enumerated only when an expert exchange
            # exists (MoE model + ep > 1). Static-only, like every
            # serving candidate — the PR-7 refusal semantics hold:
            # Autotuner._measure raises loudly on serving configs, so
            # the axis is ranked by the planner and never compiled here.
            # the same ep clamp the serving trace applies (ONE
            # definition — serving_ep_size against the MODEL config,
            # the source of truth): an ep that serves dense-replicated
            # traces the identical program for both forms, and
            # enumerating the axis there would rank duplicate plans
            # (the PR-12 grad_wire-axis lesson)
            from ..serving.engine import serving_ep_size

            serve_moe = serving_ep_size(
                ds.moe, getattr(self.model, "config", None)
            ) > 1
            serve_a2a: List[Optional[bool]] = (
                [False, True] if serve_moe else [None]
            )
            return [
                Candidate(token_budget=tb, moe_a2a=a2a)
                for tb in self.token_budgets
                for a2a in serve_a2a
            ]
        mbs = []
        m = 1
        while m <= self.tuner.max_micro:
            mbs.append(m)
            m *= 2
        tp = max(int(ds.tensor_parallel.tp_size), 1)
        overlap_axis: List[Optional[bool]] = (
            [False, True] if tp > 1 else [None]
        )
        # decomposed MoE a2a: an axis only where an expert exchange exists
        moe_on = bool(getattr(ds.moe, "enabled", False)) and int(
            getattr(ds.moe, "ep_size", 1)
        ) > 1
        a2a_axis: List[Optional[bool]] = (
            [False, True] if moe_on else [None]
        )
        tiles = FLASH_BLOCKS if self.include_tiles else ((0, 0),)
        meshes: List[Optional[Tuple[int, int]]] = (
            list(self.mesh_shapes) if self.mesh_shapes else [None]
        )
        base_stage = int(ds.zero_config.stage)
        # the hybrid dp-factorization axis (ISSUE 17): a 3-tuple mesh
        # (dcn_dp, fsdp, tp) or a session topology whose dp axis is
        # DCN-tagged makes the 2-hop-vs-flat grad RS an enumerable form —
        # the existing zero_optimization.hierarchical_wire bool IS the
        # knob, the search just flips it and lets per-link pricing
        # (dcn_s in the roofline max) rank the factorizations
        topo_kinds = dict(getattr(self.topology, "link_kinds", None) or {})
        topo_hybrid = (
            "dcn" in topo_kinds.values()
            and self.topology.sizes["dp"] > 1
            and self.topology.sizes["fsdp"] > 1
        ) if self.topology is not None else False
        out = []
        for mesh in meshes:
            mesh_hybrid = (mesh is not None and len(mesh) == 3
                           and mesh[0] > 1 and mesh[1] > 1)
            hybrid = mesh_hybrid or (mesh is None and topo_hybrid)
            for zero in self._zero_axis():
                # stage-3 layer prefetch: an axis only on stage-3 rungs
                # (the knob is a no-op elsewhere — enumerating it would
                # double-count identical plans)
                stage = (json.loads(zero).get("stage", 0)
                         if zero is not None else base_stage)
                z3_axis: List[Optional[bool]] = (
                    [False, True] if int(stage) == 3 else [None]
                )
                # wire-codec axis (stage x grad_wire x param_wire): the
                # grad reduce-scatter codec exists from stage 1, the
                # param gather codec only at stage 3. Rungs where the
                # engine's wired reduction is a KNOWN no-op from the
                # base config (pipeline parallelism, the 1-bit wire
                # optimizer, a mesh with no >1-size data axis) skip the
                # grad axis — enumerating it would trace duplicate
                # identical plans (group_key differs, memoization
                # cannot collapse them)
                wires = self.wire_codecs
                opt_name = (ds.optimizer.type or "").lower().replace(
                    "_", ""
                )
                data_live = self.topology is None or any(
                    self.topology.sizes[a] > 1 for a in ("dp", "fsdp")
                )
                gw_ok = (
                    int(ds.pipeline.stages) <= 1
                    and opt_name not in ("onebitadam", "onebitlamb")
                    and data_live
                )
                gw_axis: List[Optional[str]] = (
                    list(wires)
                    if int(stage) >= 1 and len(wires) > 1 and gw_ok
                    else [None]
                )
                pw_axis: List[Optional[str]] = (
                    list(wires)
                    if int(stage) == 3 and len(wires) > 1 and data_live
                    else [None]
                )
                # 2-hop vs flat grad RS: an axis only on hybrid
                # factored meshes with a wired reduction to decompose
                # (stage >= 1, same no-op exclusions as the grad axis)
                hw_axis: List[Optional[bool]] = (
                    [False, True]
                    if hybrid and int(stage) >= 1 and gw_ok
                    else [None]
                )
                for pol in (self.remat_policies
                            if self.remat_policies is not None
                            else REMAT_POLICIES):
                    for mb in mbs:
                        for ov in overlap_axis:
                            for a2a in a2a_axis:
                                for z3 in z3_axis:
                                    for gw in gw_axis:
                                        for pw in pw_axis:
                                            for hw in hw_axis:
                                                for blocks in tiles:
                                                    out.append(Candidate(
                                                        zero=zero,
                                                        remat=pol,
                                                        micro=mb,
                                                        flash_blocks=tuple(
                                                            blocks
                                                        ),
                                                        tp_overlap=ov,
                                                        moe_a2a=a2a,
                                                        z3_prefetch=z3,
                                                        grad_wire=gw,
                                                        param_wire=pw,
                                                        hier_wire=hw,
                                                        mesh=mesh,
                                                    ))
        return out

    # ----------------------------------------------------------------- plan
    def _candidate_config(self, cand: Candidate) -> Dict[str, Any]:
        prev = self.tuner._zero_patch
        try:
            self.tuner._zero_patch = cand.zero_dict
            cfg = self.tuner._candidate_config(
                cand.micro, cand.remat, cand.flash_blocks
            )
        finally:
            self.tuner._zero_patch = prev
        if cand.tp_overlap is not None:
            tp = dict(cfg.get("tensor_parallel") or {})
            # the base may spell the knob as a bool or "auto" (shorthand
            # section) — the axis value replaces it either way
            oc = tp.get("overlap_comm")
            oc = dict(oc) if isinstance(oc, dict) else {}
            oc["enabled"] = bool(cand.tp_overlap)
            tp["overlap_comm"] = oc
            cfg["tensor_parallel"] = tp
        if cand.moe_a2a is not None:
            if cand.token_budget is not None:
                # serving candidates: the knob is the serving-side form
                # (stock vs chunked decode exchange), not the training
                # overlap_a2a scope
                sv = dict(cfg.get("serving") or {})
                sv["moe_a2a"] = "chunked" if cand.moe_a2a else "stock"
                cfg["serving"] = sv
            else:
                moe = dict(cfg.get("moe") or {})
                oa = moe.get("overlap_a2a")
                oa = dict(oa) if isinstance(oa, dict) else {}
                oa["enabled"] = bool(cand.moe_a2a)
                moe["overlap_a2a"] = oa
                cfg["moe"] = moe
        if cand.z3_prefetch is not None:
            zo = dict(cfg.get("zero_optimization") or {})
            zo["stage3_layer_prefetch"] = bool(cand.z3_prefetch)
            cfg["zero_optimization"] = zo
        if cand.grad_wire is not None:
            zo = dict(cfg.get("zero_optimization") or {})
            zo["grad_wire"] = cand.grad_wire
            cfg["zero_optimization"] = zo
        if cand.param_wire is not None:
            zo = dict(cfg.get("zero_optimization") or {})
            zo["param_wire"] = cand.param_wire
            cfg["zero_optimization"] = zo
        if cand.hier_wire is not None:
            zo = dict(cfg.get("zero_optimization") or {})
            zo["hierarchical_wire"] = bool(cand.hier_wire)
            cfg["zero_optimization"] = zo
        if cand.mesh is not None and len(cand.mesh) == 3:
            # the config stays self-describing: the topology section
            # names the DCN factorization so the campaign's topology_key
            # cannot conflate flat dp=8 with dp=4x2 rows
            cfg["topology"] = dict(
                cfg.get("topology") or {}, dcn_dp=int(cand.mesh[0])
            )
        if cand.token_budget is not None:
            sv = dict(cfg.get("serving") or {})
            sv["token_budget"] = int(cand.token_budget)
            cfg["serving"] = sv
        return cfg

    def _topology_for(self, cand: Candidate):
        if cand.mesh is None:
            return self.topology
        from ..comm.topology import MeshTopology, ParallelDims

        if len(cand.mesh) == 3:
            dcn_dp, fsdp, tp = cand.mesh
            return MeshTopology.hybrid(
                dims=ParallelDims(dp=dcn_dp, fsdp=fsdp, tp=tp)
            )
        dp, tp = cand.mesh
        return MeshTopology(dims=ParallelDims(dp=dp, tp=tp))

    def _tokens_per_step(self, cand: Candidate, cfg: Dict[str, Any]) -> float:
        if cand.token_budget is not None:
            return float(cand.token_budget)
        S = getattr(getattr(self.model, "config", None), "max_seq_len", 1)
        B = cfg.get("train_batch_size") or cand.micro
        return float(B) * float(S)

    def _plan_one(self, cand: Candidate) -> PlannedCandidate:
        import deepspeed_tpu.comm as comm
        from ..analysis import lint_config

        cfg = self._candidate_config(cand)
        pc = PlannedCandidate(cand=cand)
        try:
            if self.topology is None or cand.mesh is not None:
                comm.destroy_process_group()
            report = lint_config(
                cfg, model=self.model, topology=self._topology_for(cand),
                only=["R6"], hbm_budget_bytes=self.budget_bytes,
                collect_plan=True, source=cand.label(),
                hardware=self.hardware,
            )
        except NotImplementedError as e:
            pc.pruned = True
            pc.reason = f"untraceable on this jax: {str(e).splitlines()[0][:120]}"
            return pc
        except Exception as e:  # noqa: BLE001 — an unbuildable candidate
            # (config validation, batch triangle) loses with its reason
            # instead of killing the search
            pc.pruned = True
            pc.reason = (str(e).splitlines() or [repr(e)])[0][:160]
            return pc
        self.n_traced += 1
        pc.traced = True
        pc.plan = report.plans[0] if report.plans else None
        pc.tokens_per_step = self._tokens_per_step(cand, cfg)
        r6 = [f for f in report.findings if f.rule == "R6"]
        if r6:
            pc.pruned = True
            pc.reason = r6[0].message.split(" — ")[0]
        return pc

    def _derive_scaled(self, cand: Candidate,
                       prior: PlannedCandidate) -> PlannedCandidate:
        from ..analysis.cost import scale_plan_micro

        f = cand.micro / prior.cand.micro
        plan = scale_plan_micro(prior.plan, f, source=cand.label())
        pc = PlannedCandidate(
            cand=cand, plan=plan, pruned=True, traced=False,
            derived_from_micro=prior.cand.micro,
            tokens_per_step=prior.tokens_per_step * f,
        )
        pc.reason = (
            f"estimated peak HBM {plan.peak_hbm_bytes / _GIB:.2f} GiB "
            f"exceeds the {self.budget_bytes / _GIB:.2f} GiB budget"
        )
        return pc

    # --------------------------------------------------------------- search
    def search(self) -> SearchResult:
        result = SearchResult(budget_bytes=self.budget_bytes)
        memo: Dict[Tuple, PlannedCandidate] = {}  # group → last pruned trace
        # ordering only needs each group contiguous with micro ascending
        # (the memoized-scaling invariant); repr gives a total order over
        # group keys that mix None with bools/tuples across mesh rungs
        for cand in sorted(self.candidates(),
                           key=lambda c: (repr(c.group_key()), c.micro)):
            prior = memo.get(cand.group_key())
            if (prior is not None and prior.pruned and prior.plan is not None
                    and cand.micro > prior.cand.micro):
                # the smaller micro already failed R6 statically; a larger
                # one only grows the batch-linear terms — skip the trace
                result.planned.append(self._derive_scaled(cand, prior))
                continue
            pc = self._plan_one(cand)
            result.planned.append(pc)
            if pc.pruned and pc.traced and pc.plan is not None:
                memo[cand.group_key()] = pc
        result.n_traced = self.n_traced
        survivors = [p for p in result.planned if not p.pruned]
        # roofline throughput is micro-invariant (tokens and seconds both
        # scale), so ties break toward the lower stage (less collective
        # traffic — the ladder's own preference) and the LARGER micro
        # (fewer dispatches per token, the direction every measured sweep
        # has confirmed)
        survivors.sort(key=lambda p: (
            -(p.predicted_tput or 0.0), p.cand.stage, -p.cand.micro
        ))
        result.survivors = survivors
        result.top_k = survivors[:max(self.top_k, 1)]
        log_dist(
            f"planner_search: {len(result.planned)} candidates, "
            f"{len(result.pruned)} statically pruned, {self.n_traced} "
            f"traced, top-{len(result.top_k)} to compile"
        )
        return result


def search_config(model, base_config, topology=None, **kw) -> SearchResult:
    """One-call spelling (tools/autoplan.py): enumerate + plan + rank a
    config's candidate space without compiling anything."""
    return PlannerSearch(model, base_config, topology, **kw).search()
