"""autoplan --campaign: measure the knob lattice, ship default tables.

The existing ``"auto"`` machinery grew one point solution at a time
(grad_wire/param_wire legacy resolution, serving kv_cache_dtype, the
moe_a2a payload threshold). A *campaign* generalizes it to every
overlap/wire/spec/paged knob at once:

1. **Enumerate** the full knob lattice through
   :class:`~.planner_search.PlannerSearch` — R6-pruned statically before
   anything compiles, ranked by roofline, exactly the machinery
   ``Autotuner._tune_planner`` already trusts;
2. **Measure** only the ranked top-k through ``Autotuner._measure`` (the
   one compile+measure loop — the ≤ top-k compile contract holds for a
   campaign exactly as it does for a tune), banking every (predicted,
   measured) pair in the drift ledger tagged ``campaign`` so campaign
   rows keep their own band bookkeeping (:func:`analysis.cost.drift
   .entry_tag`) and never pollute ad-hoc medians;
3. **Gate** every knob the measured winner flips on: knobs with a
   declared :func:`analysis.parity.config_parity_pairs` FormPair must
   pass :func:`analysis.parity.prove_parity` on the flipped form before
   their table entry is written; knobs with no static pair (stage-3
   prefetch, offload double-buffer, spec decode — spec is deliberately
   unprovable statically, it is the prover's own seeded-divergence
   smoke) record the named bitwise oracle test that covers them;
4. **Emit** one default-table row keyed by ``(gen, mesh topology, model
   class)`` — the table ``cost/hardware.py`` ships as data
   (``knob_defaults.json``) and :func:`config.resolve_auto_knobs`
   consults whenever a knob is ``"auto"``. Staleness is enforced at
   RESOLVE time (drift bands + jax version), so a landed row degrades to
   the conservative off default when the machine changes, never crashes.

On CPU-only sessions the whole pipeline runs end-to-end against the
``cpu`` generation on the tiny 410m-lite legs (tier-1 budget) — the
rows it emits are plumbing evidence (GEN_FALLBACKS never transfers a
cpu row to a chip), but every moving part is exercised.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..utils.logging import log_dist

CAMPAIGN_TAG = "campaign"

#: Candidate axis → (dotted knob path, parity gate). A string gate names
#: the declared FormPair prove_parity must certify before the flipped
#: entry lands; an ``oracle:`` gate names the bitwise test that stands in
#: where no static pair exists (documented split: docs/autotuning.md).
AXIS_KNOBS: Dict[str, Tuple[str, str]] = {
    "tp_overlap": ("tensor_parallel.overlap_comm", "train/tp-ring-vs-xla"),
    "moe_a2a": ("moe.overlap_a2a", "train/moe-a2a-stock-vs-chunked"),
    "z3_prefetch": ("zero_optimization.stage3_layer_prefetch",
                    "oracle:tests/test_zero3_prefetch.py"),
    "grad_wire": ("zero_optimization.grad_wire",
                  "train/wire-codec-vs-full-width"),
    "param_wire": ("zero_optimization.param_wire",
                   "train/wire-codec-vs-full-width"),
    "hier_wire": ("zero_optimization.hierarchical_wire",
                  "train/grad-rs-2hop-vs-flat"),
}
#: serving-side spelling of the moe_a2a axis (token_budget candidates)
SERVE_A2A_KNOB = ("serving.moe_a2a", "serving/moe-a2a-stock-vs-chunked")
#: knobs the campaign A/Bs outside the lattice (identical abstract plans
#: — the PR-12 duplicate-plan lesson keeps them off the candidate axes)
DIRECT_AB_KNOBS = {
    "zero_optimization.offload_double_buffer":
        "oracle:tests/test_engine.py (bucketed-offload bitwise parity)",
    "serving.spec": "oracle:tests/test_serving_spec.py (lossless replay)",
    "serving.paged": "serving/paged-vs-contiguous",
}


def _jax_major_minor() -> Optional[str]:
    try:
        import jax

        return ".".join(str(jax.__version__).split(".")[:2])
    except Exception:  # noqa: BLE001
        return None


class _TopoSizes:
    """Duck-typed stand-in for MeshTopology in topology_key(): the key
    must name the mesh the measured engines ACTUALLY ran on, and the
    campaign usually passes topology=None (initialize() derives the mesh
    from each candidate config) — so derive the same sizes here without
    touching the global mesh."""

    def __init__(self, sizes: Dict[str, int], world_size: int,
                 link_kinds: Optional[Dict[str, str]] = None):
        self.sizes = sizes
        self.world_size = world_size
        self.link_kinds = dict(link_kinds or {})


def config_topology(cfg) -> _TopoSizes:
    """The mesh ``initialize()`` would build for this config (the same
    fsdp/pp/ep/sp/tp derivation, plus the topology section's DCN dp
    factorization), resolved over the visible devices. ``cfg`` is a
    DeepSpeedConfig or a raw ds_config dict. The link kinds ride along
    so :func:`analysis.cost.topology_key` spells the hybrid
    factorization ("dp2dcnxfsdp2x...") — a flat dp=8 row and a dp=4x2
    hybrid row must never conflate."""
    import jax

    from ..comm.topology import ParallelDims
    from ..config import DeepSpeedConfig

    ds = cfg if isinstance(cfg, DeepSpeedConfig) else DeepSpeedConfig(
        dict(cfg)
    )
    fsdp = 1
    if ds.zero_config.zero_hpz_partition_size > 1:
        fsdp = ds.zero_config.zero_hpz_partition_size
    elif ds.zero_config.mics_shard_size > 0:
        fsdp = ds.zero_config.mics_shard_size
    dcn_dp = int(getattr(ds.topology, "dcn_dp", 0) or 0)
    dims = ParallelDims(
        dp=dcn_dp if dcn_dp > 1 else 0,
        fsdp=fsdp, pp=ds.pipeline.stages,
        ep=ds.moe.ep_size if ds.moe.enabled else 1,
        sp=ds.sequence_parallel.sp_size, tp=ds.tensor_parallel.tp_size,
    )
    world = max(len(jax.devices()), 1)
    kinds = {a: "dcn" for a in getattr(ds.topology, "dcn_axes", tuple)()}
    return _TopoSizes(dims.resolve(world), world, kinds)


def candidate_knobs(cand) -> Dict[str, Any]:
    """The dotted knob settings one lattice candidate pins (only axes
    that are live for it — None fields are "not an axis here")."""
    knobs: Dict[str, Any] = {}
    for axis, (path, _gate) in AXIS_KNOBS.items():
        v = getattr(cand, axis)
        if v is None:
            continue
        if axis == "moe_a2a" and cand.token_budget is not None:
            knobs[SERVE_A2A_KNOB[0]] = "chunked" if v else "stock"
        else:
            knobs[path] = v
    return knobs


def _knob_gate(path: str) -> str:
    for axis, (p, gate) in AXIS_KNOBS.items():
        if p == path:
            return gate
    if path == SERVE_A2A_KNOB[0]:
        return SERVE_A2A_KNOB[1]
    return DIRECT_AB_KNOBS.get(path, "oracle:unspecified")


def _is_on(value) -> bool:
    """Is this knob value a flip away from the conservative default?"""
    if isinstance(value, bool):
        return value
    return value not in (None, "fp32", "stock", "off")


def prove_knob_parity(path: str, cfg_dict: Dict[str, Any], model
                      ) -> Tuple[bool, str]:
    """(ok, gate_name) for one flipped-on knob of the winner config.

    Declared FormPairs run the PR-15 prover on the winner's EXACT config
    (the flipped form's contract, trace thunks and rewrites all come from
    ``config_parity_pairs``); oracle-gated knobs pass by naming their
    bitwise test — the campaign never writes an ungated entry."""
    gate = _knob_gate(path)
    if gate.startswith("oracle:"):
        return True, gate
    from ..analysis.parity import config_parity_pairs, prove_parity
    from ..config import DeepSpeedConfig

    try:
        cfg = DeepSpeedConfig(dict(cfg_dict))
        pairs = [p for p in config_parity_pairs(cfg, model)
                 if p.name == gate]
        if not pairs:
            # the flipped form declared no pair under this config (e.g.
            # the wire axis resolved to fp32 after all) — nothing to
            # certify means nothing to gate
            return True, f"{gate} (no pair declared — form inert here)"
        cert = prove_parity(pairs[0])
        return bool(cert.ok), gate
    except Exception as e:  # noqa: BLE001 — a prover crash must read as
        # "not certified", never as a campaign crash
        log_dist(f"campaign: parity prover failed for {path}: {e}")
        return False, gate


def _winner_twin(planned, winner_pc, path: str):
    """The winner's twin on one knob axis: the planned candidate whose
    settings equal the winner's everywhere EXCEPT ``path``. Both arms
    always exist statically (the lattice is a full cross product), so a
    twin that was ranked out of the measured top-k still contributes its
    PREDICTED step time as evidence."""
    want = candidate_knobs(winner_pc.cand)
    for pc in planned:
        if pc.cand is winner_pc.cand or pc.plan is None:
            continue
        k = candidate_knobs(pc.cand)
        if set(k) != set(want):
            continue
        if k.get(path) == want.get(path):
            continue
        if all(k[p] == want[p] for p in want if p != path):
            if (pc.cand.zero == winner_pc.cand.zero
                    and pc.cand.remat == winner_pc.cand.remat
                    and pc.cand.micro == winner_pc.cand.micro
                    and pc.cand.token_budget == winner_pc.cand.token_budget):
                return pc
    return None


class Campaign:
    """One end-to-end campaign over a (model, base_config, topology)."""

    def __init__(self, model, base_config: Dict[str, Any], topology=None,
                 *, sample_batch_fn=None, hardware=None,
                 top_k: Optional[int] = None,
                 hbm_budget_bytes: Optional[float] = None,
                 wire_codecs: Sequence[str] = ("fp32", "int8"),
                 remat_policies: Sequence[str] = ("none",),
                 drift_ledger_path: Optional[str] = None):
        from ..analysis.cost import HardwareModel
        from .autotuner import Autotuner

        self.model = model
        self.base_config = dict(base_config)
        self.topology = topology
        self.hardware = hardware or HardwareModel.detect()
        self.tuner = Autotuner(model, self.base_config, topology=topology,
                               sample_batch_fn=sample_batch_fn)
        self.top_k = int(top_k if top_k is not None else self.tuner.top_k)
        self.hbm_budget_bytes = hbm_budget_bytes
        self.wire_codecs = tuple(wire_codecs)
        self.remat_policies = tuple(remat_policies)
        self.drift_ledger_path = (drift_ledger_path
                                  or self.tuner.drift_ledger_path)

    # ------------------------------------------------------------------ run
    def run(self) -> Dict[str, Any]:
        """enumerate → measure top-k → bank tagged pairs → gate → row.

        Returns ``{"search", "measured", "row", "banked", "skipped"}``;
        ``row`` is the default-table row (or None when nothing measured),
        ready for :func:`emit_table`."""
        from ..analysis.cost import drift
        from .planner_search import PlannerSearch

        search = PlannerSearch(
            self.model, self.base_config, self.topology,
            top_k=self.top_k,
            hbm_budget_bytes=(self.hbm_budget_bytes
                              if self.hbm_budget_bytes is not None
                              else self.tuner._resolved_budget()),
            hardware=self.hardware,
            wire_codecs=self.wire_codecs,
            remat_policies=self.remat_policies,
            tuner=self.tuner,
        )
        result = search.search()
        if not result.survivors:
            raise RuntimeError(
                "campaign: every lattice rung is statically over the HBM "
                "budget — nothing to measure\n" + result.explain()
            )
        ledger = drift.DriftLedger(self.drift_ledger_path)
        measured: List[Dict[str, Any]] = []
        banked = 0
        for pc in result.top_k:
            cfg = search._candidate_config(pc.cand)
            tput = self.tuner._measure(pc.cand.micro, pc.cand.remat, cfg=cfg)
            if tput is None:
                log_dist(f"campaign: {pc.cand.label()} OOMed at runtime "
                         "(backstop prune)")
                continue
            measured_step_s = pc.tokens_per_step / tput
            rec = {
                "pc": pc, "cfg": cfg, "throughput": tput,
                "measured_step_s": measured_step_s,
                "knobs": candidate_knobs(pc.cand),
            }
            measured.append(rec)
            try:  # the ledger is evidence, never a point of failure
                ledger.append(drift.make_entry(
                    pc.plan, measured_step_s,
                    source=f"campaign:{pc.cand.label()}",
                    extra={"tag": CAMPAIGN_TAG,
                           "throughput": round(tput, 1),
                           "knobs": rec["knobs"]},
                ))
                banked += 1
            except Exception as e:  # noqa: BLE001
                log_dist(f"campaign: drift ledger append failed: {e}")
        out: Dict[str, Any] = {
            "search": result, "measured": measured, "banked": banked,
            "row": None, "skipped": {},
        }
        if not measured:
            log_dist("campaign: no lattice rung survived measurement — "
                     "no table row emitted")
            return out
        winner = max(measured, key=lambda r: r["throughput"])
        out["row"] = self._emit_row(winner, measured, result.planned,
                                    out["skipped"])
        return out

    # ------------------------------------------------------------- evidence
    def _emit_row(self, winner, measured, planned, skipped) -> Dict[str, Any]:
        """One table row from the measured winner: its knob settings plus
        per-knob evidence (the winner's banked pair; the twin arm's
        measured pair when the twin made top-k, its predicted step time
        otherwise — both arms always exist statically)."""
        from ..analysis.cost import model_class, topology_key

        pc = winner["pc"]
        knobs = dict(winner["knobs"])
        evidence: Dict[str, Dict[str, Any]] = {}
        measured_by_cand = {id(r["pc"].cand): r for r in measured}
        for path, value in list(knobs.items()):
            ok, gate = (True, _knob_gate(path))
            if _is_on(value):
                ok, gate = prove_knob_parity(path, winner["cfg"], self.model)
                if not ok:
                    # gate 1 failed: the flipped default never lands —
                    # drop the knob from the row (resolution then takes
                    # the conservative off default) and say why
                    skipped[path] = f"parity not certified ({gate})"
                    log_dist(f"campaign: {path}={value!r} NOT shipped — "
                             f"parity gate {gate} failed")
                    del knobs[path]
                    continue
            ev: Dict[str, Any] = {
                "predicted_step_s": pc.predicted_step_s,
                "measured_step_s": round(winner["measured_step_s"], 6),
                "parity": gate,
            }
            twin = _winner_twin(planned, pc, path)
            if twin is not None:
                trec = measured_by_cand.get(id(twin.cand))
                ev["twin"] = {
                    "value": candidate_knobs(twin.cand).get(path),
                    "predicted_step_s": twin.predicted_step_s,
                    "measured_step_s": (round(trec["measured_step_s"], 6)
                                        if trec else None),
                    "evidence": "measured" if trec else "predicted",
                }
            evidence[path] = ev
        row = {
            "gen": self.hardware.gen,
            # key on the mesh the measured engines actually ran on: when
            # the campaign let initialize() derive the topology from the
            # config, derive the identical sizes here — a fresh engine
            # resolving later must hit this row, not a "dp8" mismatch
            "topology": topology_key(
                self.topology if self.topology is not None
                else config_topology(winner["cfg"])
            ),
            "model_class": model_class(getattr(self.model, "config", None)),
            "knobs": knobs,
            "evidence": evidence,
            "winner": pc.cand.label(),
            "throughput": round(winner["throughput"], 1),
            "jax": _jax_major_minor(),
            "created": round(time.time(), 1),
        }
        return row


def run_campaign(model, base_config, topology=None, **kw) -> Dict[str, Any]:
    """One-call spelling (tools/autoplan.py --campaign)."""
    return Campaign(model, base_config, topology, **kw).run()


# --------------------------------------------------------------------- table
def emit_table(rows: Sequence[Dict[str, Any]], path: str) -> Dict[str, Any]:
    """Merge campaign rows into the table at ``path`` (same-key rows are
    replaced, everything else kept) and write it back. Returns the
    merged table."""
    from ..analysis.cost import load_knob_table

    table = load_knob_table(path) if os.path.exists(path) else {
        "version": 1, "entries": []
    }
    def key(r):
        return (r.get("gen"), r.get("topology"), r.get("model_class"))

    fresh = {key(r): r for r in rows}
    entries = [r for r in table.get("entries", [])
               if key(r) not in fresh]
    entries.extend(rows)
    table["entries"] = entries
    table.setdefault("version", 1)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(table, f, indent=1, sort_keys=True)
        f.write("\n")
    return table


def verify_roundtrip(base_config: Dict[str, Any], table_path: str,
                     model=None, topology=None, hardware=None
                     ) -> Dict[str, Any]:
    """The campaign's closing assertion: a FRESH all-"auto" config
    resolved against the emitted table must land on the campaign's
    winner settings. Returns the resolution report plus the resolved
    knob values keyed by dotted path — the caller (CLI / CI) compares
    them against the emitted row."""
    from ..analysis.cost import load_knob_table
    from ..config import AUTO, DeepSpeedConfig, resolve_auto_knobs

    cfg_dict = dict(base_config)
    cfg_dict.pop("autotuning", None)
    # spell every campaign-owned bool knob "auto"
    tp = dict(cfg_dict.get("tensor_parallel") or {})
    if int(tp.get("tp_size", 1)) > 1:
        tp["overlap_comm"] = AUTO
        cfg_dict["tensor_parallel"] = tp
    zo = dict(cfg_dict.get("zero_optimization") or {})
    if zo:
        zo["stage3_layer_prefetch"] = AUTO
        zo["offload_double_buffer"] = AUTO
        zo["grad_wire"] = AUTO
        zo["param_wire"] = AUTO
        cfg_dict["zero_optimization"] = zo
    moe = dict(cfg_dict.get("moe") or {})
    if moe.get("enabled"):
        moe["overlap_a2a"] = AUTO
        cfg_dict["moe"] = moe
    sv = dict(cfg_dict.get("serving") or {})
    if sv.get("enabled"):
        sv["paged"] = AUTO
        sv["spec"] = AUTO
        sv["moe_a2a"] = AUTO
        cfg_dict["serving"] = sv
    cfg = DeepSpeedConfig(cfg_dict)
    report = resolve_auto_knobs(
        cfg, hardware=hardware,
        model_config=getattr(model, "config", None),
        # same mesh derivation as the campaign's row key / initialize()
        topology=topology if topology is not None else config_topology(cfg),
        table=load_knob_table(table_path),
    )
    resolved = {
        "tensor_parallel.overlap_comm":
            cfg.tensor_parallel.overlap_comm.enabled,
        "zero_optimization.offload_double_buffer":
            cfg.zero_config.offload_double_buffer,
        "zero_optimization.stage3_layer_prefetch":
            cfg.zero_config.stage3_layer_prefetch,
        "zero_optimization.grad_wire": cfg.zero_config.grad_wire,
        "zero_optimization.param_wire": cfg.zero_config.param_wire,
        "moe.overlap_a2a": cfg.moe.overlap_a2a.enabled,
        "serving.spec": cfg.serving.spec.enabled,
        "serving.paged": cfg.serving.paged,
        "serving.moe_a2a": cfg.serving.moe_a2a,
    }
    return {"report": report, "resolved": resolved, "config": cfg}


# ---------------------------------------------------------------- serving AB
def serving_ab(model, serving_section: Dict[str, Any], knob: str,
               values: Sequence[Any] = (False, True), *,
               requests: int = 8, new_tokens: int = 8,
               engine_kwargs: Optional[Dict[str, Any]] = None
               ) -> Dict[str, Any]:
    """A/B one serving knob: two ServingEngines, identical replayed
    request sets, wall-clock tokens/s per arm. The campaign's serving
    legs and ``tools/bench_serve.py --campaign-ab`` both call this — one
    loop, two front doors."""
    import numpy as np

    from ..serving import Request, ServingEngine

    arms = []
    for v in values:
        sv = dict(serving_section)
        if knob == "spec":
            spec = dict(sv.get("spec") or {})
            spec["enabled"] = v
            sv["spec"] = spec
        else:
            sv[knob] = v
        srv = ServingEngine(model=model, serving=sv,
                            **dict(engine_kwargs or {}))
        rng = np.random.RandomState(0)
        reqs = [
            Request(request_id=f"r{i}",
                    prompt=[int(t) for t in rng.randint(
                        1, 100, size=4 + (i % 3))],
                    max_new_tokens=new_tokens, temperature=0.0)
            for i in range(requests)
        ]
        t0 = time.perf_counter()
        for r in reqs:
            srv.submit(r)
        finished = srv.run_until_idle()
        dt = time.perf_counter() - t0
        toks = sum(len(st.tokens) for st in finished)
        arms.append({
            "value": v,
            "tokens": toks,
            "dt_s": round(dt, 6),
            "tokens_per_s": round(toks / dt, 1) if dt > 0 else None,
            "tokens_by_request": {
                st.request.request_id: list(st.tokens) for st in finished
            },
        })
    same = (arms[0]["tokens_by_request"] == arms[1]["tokens_by_request"]
            if len(arms) == 2 else None)
    return {"knob": knob, "arms": arms, "tokens_equal": same}
