from .autotuner import Autotuner, autotune, result_to_config_patch  # noqa: F401
