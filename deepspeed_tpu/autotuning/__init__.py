from .autotuner import Autotuner, autotune, result_to_config_patch  # noqa: F401
from .planner_search import (  # noqa: F401
    Candidate,
    PlannedCandidate,
    PlannerSearch,
    SearchResult,
    search_config,
)
