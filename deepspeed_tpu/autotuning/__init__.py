from .autotuner import Autotuner, autotune, result_to_config_patch  # noqa: F401
from .campaign import (  # noqa: F401
    Campaign,
    candidate_knobs,
    emit_table,
    run_campaign,
    serving_ab,
    verify_roundtrip,
)
from .planner_search import (  # noqa: F401
    Candidate,
    PlannedCandidate,
    PlannerSearch,
    SearchResult,
    search_config,
)
