"""Compression: weight quantization, pruning, layer reduction.

Parity: deepspeed/compression/ (compress.py, basic_layer.py, helper.py) and
the "compression_training" config section. The reference wraps torch modules
with QuantLinear/PruneLinear shims; here compression is a *pure function on
the param pytree* — masks and fake-quant are applied to the stacked [L, ...]
weights, so the same jitted train step runs compressed training with zero
graph changes (XLA folds the masks into the matmuls).

- weight_quantization: symmetric int8/int4 groupwise fake-quant (QAT
  forward; ops/quantizer.py does the rounding).
- sparse_pruning: magnitude mask at the configured density.
- head_pruning: L2-norm ranking of attention heads on wo rows.
- row_pruning: row-norm ranking of MLP wi columns... rows of wo.
- layer_reduction: keep a teacher-selected subset of layers (distill init).
- redundancy_clean: bake masks into the weights (the reference's
  final cleanup pass before export).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.quantizer import quantize_dequantize

MATMUL_WEIGHTS = ("wq", "wk", "wv", "wo", "wi", "wg")


def _leaf_name(path) -> str:
    last = path[-1]
    return last.key if hasattr(last, "key") else str(last)


def weight_fake_quant(params, bits: int = 8, group_size: int = 128,
                      targets: Tuple[str, ...] = MATMUL_WEIGHTS):
    """QAT forward pass weights (reference: WeightQuantization.forward)."""

    def q(path, leaf):
        if _leaf_name(path) in targets and leaf.ndim >= 2:
            return quantize_dequantize(leaf, block=group_size, bits=bits)
        return leaf

    return jax.tree_util.tree_map_with_path(q, params)


def ste_fake_quant(params, bits: int = 8, group_size: int = 128,
                   targets: Tuple[str, ...] = MATMUL_WEIGHTS):
    """Straight-through-estimator fake quant for the QAT *forward*.

    The forward sees quantized weights; the backward passes gradients through
    to the full-precision masters unchanged (``round`` has zero gradient, so
    the identity-plus-stopped-residual form is required). This is the engine
    hook equivalent of the reference's QuantLinear.forward.
    """

    def q(path, leaf):
        if _leaf_name(path) in targets and leaf.ndim >= 2:
            qdq = quantize_dequantize(leaf, block=group_size, bits=bits)
            return leaf + jax.lax.stop_gradient(qdq - leaf)
        return leaf

    return jax.tree_util.tree_map_with_path(q, params)


def quantization_settings(compression_config) -> Optional[Tuple[int, int]]:
    """(bits, group_size) when weight_quantization is enabled, else None.

    Per-group bit widths are collapsed to the minimum (the stacked [L, ...]
    layout quantizes all layers with one setting); the collapse is logged by
    the caller paths that apply it."""
    wq = dict(compression_config.weight_quantization or {})
    shared = dict(wq.get("shared_parameters") or {})
    if not shared.get("enabled"):
        return None
    gs = int(shared.get("group_size", shared.get("quantize_groups", 0)) or 128)
    all_bits = [
        int((g.get("params") or {}).get("target_bits",
                                        (g.get("params") or {}).get("bits", 8)))
        for g in (wq.get("different_groups") or {}).values()
    ] or [8]
    if len(set(all_bits)) > 1:
        from ..utils.logging import log_dist

        log_dist(
            f"compression: per-group bit widths {sorted(set(all_bits))} not "
            f"yet differentiated on the stacked layout; using min "
            f"(most conservative) = {min(all_bits)}"
        )
    return min(all_bits), gs


def _collapsed_ratio(section: Dict[str, Any], kind: str) -> float:
    """One dense_ratio for a pruning section; logs per-group collapse."""
    ratios = [
        float((g.get("params") or {}).get("dense_ratio", 0.5))
        for g in (section.get("different_groups") or {}).values()
    ] or [0.5]
    if len(set(ratios)) > 1:
        from ..utils.logging import log_dist

        log_dist(
            f"compression: {kind} per-group dense_ratios {sorted(set(ratios))} "
            f"not differentiated on the stacked layout; using min "
            f"(most pruned) = {min(ratios)}"
        )
    return min(ratios)


def sparse_pruning_mask(w: jax.Array, density: float) -> jax.Array:
    """Keep the top-|density| fraction by magnitude (unstructured)."""
    k = max(1, int(round(density * w.size)))
    flat = jnp.abs(w).reshape(-1)
    thresh = jnp.sort(flat)[-k]
    return (jnp.abs(w) >= thresh).astype(w.dtype)


def head_pruning_mask(wo: jax.Array, num_heads: int, ratio: float) -> jax.Array:
    """Mask whole attention heads of a [H*hd, d] output projection.

    Heads ranked by L2 norm of their wo rows; lowest (1-ratio) fraction
    masked. Returns a [H*hd, 1]-broadcastable mask."""
    Hhd, d = wo.shape
    hd = Hhd // num_heads
    norms = jnp.linalg.norm(wo.reshape(num_heads, hd * d), axis=1)
    keep = max(1, int(round(ratio * num_heads)))
    thresh = jnp.sort(norms)[-keep]
    head_mask = (norms >= thresh).astype(wo.dtype)  # [H]
    return jnp.repeat(head_mask, hd)[:, None]


def row_pruning_mask(wi: jax.Array, ratio: float) -> jax.Array:
    """Mask ffn rows (columns of [d, f] wi) by norm; [1, f] mask."""
    norms = jnp.linalg.norm(wi, axis=0)
    keep = max(1, int(round(ratio * wi.shape[1])))
    thresh = jnp.sort(norms)[-keep]
    return (norms >= thresh).astype(wi.dtype)[None, :]


def apply_layer_reduction(params, keep_layers) -> Any:
    """Distill-init: slice the stacked [L, ...] layer params to a subset.

    Parity: compression layer_reduction (teacher_layer list + keep_number).
    """
    idx = jnp.asarray(list(keep_layers), jnp.int32)

    def slice_layers(leaf):
        return jnp.take(leaf, idx, axis=0)

    out = dict(params)
    out["layers"] = jax.tree.map(slice_layers, params["layers"])
    return out


def init_compression(params, compression_config, model_config=None,
                     qat_in_forward: bool = False):
    """Apply the "compression_training" section to a param pytree.

    Returns (params, masks) — masks are reapplied after each optimizer step
    during compressed training (engine hook) and baked in by
    :func:`redundancy_clean`. With ``qat_in_forward=True`` (the engine path)
    the init-time fake-quant is skipped: the engine applies
    :func:`ste_fake_quant` inside each forward instead, keeping the masters
    full-precision exactly like the reference's QuantLinear."""
    cc = compression_config
    masks: Dict[str, Any] = {}

    if not qat_in_forward:  # engine path resolves settings itself (one log)
        qs = quantization_settings(cc)
        if qs is not None:
            bits, gs = qs
            params = weight_fake_quant(params, bits=bits, group_size=gs)

    sp = dict(cc.sparse_pruning or {})
    if (sp.get("shared_parameters") or {}).get("enabled"):
        density = _collapsed_ratio(sp, "sparse_pruning")
        # stacked layout: weights are [L, in, out] (ndim>=3); [L, f] biases
        # must not be magnitude-pruned (reference prunes weights only)
        layer_masks = jax.tree_util.tree_map_with_path(
            lambda p, w: (
                sparse_pruning_mask(w, density)
                if _leaf_name(p) in MATMUL_WEIGHTS and w.ndim >= 3
                else None
            ),
            params["layers"]["mlp"],
        )
        masks["sparse"] = layer_masks
        params = dict(params)
        params["layers"] = dict(params["layers"])
        params["layers"]["mlp"] = jax.tree.map(
            lambda w, m: w if m is None else w * m,
            params["layers"]["mlp"],
            layer_masks,
            is_leaf=lambda x: x is None or hasattr(x, "ndim"),
        )

    hp = dict(cc.head_pruning or {})
    if (hp.get("shared_parameters") or {}).get("enabled") and model_config is not None:
        ratio = _collapsed_ratio(hp, "head_pruning")
        wo = params["layers"]["attn"]["wo"]  # [L, H*hd, d]
        mask = jnp.stack([
            head_pruning_mask(wo[l], model_config.num_heads, ratio)
            for l in range(wo.shape[0])
        ])
        masks["head"] = mask
        params = dict(params)
        params["layers"] = dict(params["layers"])
        params["layers"]["attn"] = dict(params["layers"]["attn"])
        params["layers"]["attn"]["wo"] = wo * mask

    rp = dict(cc.row_pruning or {})
    if (rp.get("shared_parameters") or {}).get("enabled"):
        ratio = _collapsed_ratio(rp, "row_pruning")
        wi = params["layers"]["mlp"]["wi"]  # [L, d, f]
        mask = jnp.stack([row_pruning_mask(wi[l], ratio) for l in range(wi.shape[0])])
        masks["row"] = mask
        params = dict(params)
        params["layers"] = dict(params["layers"])
        params["layers"]["mlp"] = dict(params["layers"]["mlp"])
        params["layers"]["mlp"]["wi"] = wi * mask

    lr = dict(cc.layer_reduction or {})
    if lr.get("enabled"):
        keep = lr.get("teacher_layer") or list(
            range(int(lr.get("keep_number", 0)))
        )
        params = apply_layer_reduction(params, keep)

    return params, masks


def redundancy_clean(params, masks):
    """Bake pruning masks into weights (reference: redundancy_clean)."""
    out = dict(params)
    out["layers"] = jax.tree.map(lambda x: x, params["layers"])
    if "head" in masks:
        out["layers"]["attn"]["wo"] = out["layers"]["attn"]["wo"] * masks["head"]
    if "row" in masks:
        out["layers"]["mlp"]["wi"] = out["layers"]["mlp"]["wi"] * masks["row"]
    if "sparse" in masks:
        out["layers"]["mlp"] = jax.tree.map(
            lambda w, m: w if m is None else w * m,
            out["layers"]["mlp"],
            masks["sparse"],
            is_leaf=lambda x: x is None or hasattr(x, "ndim"),
        )
    return out
