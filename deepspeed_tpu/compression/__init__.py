from .compress import (  # noqa: F401
    apply_layer_reduction,
    head_pruning_mask,
    init_compression,
    redundancy_clean,
    row_pruning_mask,
    sparse_pruning_mask,
)
