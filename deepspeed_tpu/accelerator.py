"""Accelerator abstraction: the `get_accelerator()` user surface.

Parity: deepspeed.accelerator.get_accelerator() /
real_accelerator.py — the device-portable API DeepSpeed user code calls
for device name/count, memory stats, synchronization, and rng seeding
instead of hardcoding `torch.cuda`. The TPU translation answers from the
jax backend; collective-free process-local queries only, so it is safe
anywhere (including before comm.init_distributed).

Reference call sites this mirrors: device_name(), device_count(),
current_device()/current_device_name(), memory_allocated/
max_memory_allocated/total_memory, empty_cache, synchronize,
manual_seed, is_available, communication_backend_name.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax


class TpuAccelerator:
    """Process-local accelerator facade over the jax backend."""

    _name: Optional[str] = None

    # -------------------------------------------------------------- identity
    def device_name(self, device_index: Optional[int] = None) -> str:
        kind = self._platform()
        if device_index is None:
            return kind
        return f"{kind}:{device_index}"

    def _platform(self) -> str:
        if self._name is None:
            try:
                self._name = jax.default_backend()
            except Exception:
                self._name = "cpu"
        return self._name

    def is_available(self) -> bool:
        return self.device_count() > 0

    def device_count(self) -> int:
        try:
            return jax.local_device_count()
        except Exception:
            return 0

    def current_device(self) -> int:
        # SPMD: the process drives all its local devices; 0 is the
        # canonical "current" one (the reference returns the bound ordinal)
        return 0

    def current_device_name(self) -> str:
        return self.device_name(0)

    def communication_backend_name(self) -> str:
        return "xla"  # collectives are XLA ops over the mesh, not a library

    def on_accelerator(self, tensor) -> bool:
        try:
            return isinstance(tensor, jax.Array)
        except Exception:
            return False

    # ---------------------------------------------------------------- memory
    def _check_index(self, device_index: int) -> int:
        n = self.device_count()
        if not 0 <= device_index < max(n, 1):
            raise ValueError(
                f"device_index {device_index} out of range "
                f"({n} local devices)"
            )
        return device_index

    def _stats(self, device_index: int = 0) -> dict:
        from .utils.memory import _device_stats

        return _device_stats(self._check_index(device_index))

    def memory_allocated(self, device_index: int = 0) -> int:
        return int(self._stats(device_index)["bytes_in_use"])

    def max_memory_allocated(self, device_index: int = 0) -> int:
        s = self._stats(device_index)
        return int(s["peak_bytes_in_use"] or s["bytes_in_use"])

    def total_memory(self, device_index: int = 0) -> int:
        return int(self._stats(device_index)["bytes_limit"])

    def available_memory(self, device_index: int = 0) -> int:
        s = self._stats(device_index)
        return max(int(s["bytes_limit"]) - int(s["bytes_in_use"]), 0)

    def empty_cache(self) -> None:
        # XLA's allocator is not user-flushable; live buffers are freed by
        # dropping references (functional state). No-op by design.
        return None

    def synchronize(self, device_index: Optional[int] = None) -> None:
        """Block until all dispatched device work completes.

        A TPU device executes programs in enqueue order, so completing a
        later-enqueued tiny COMPUTATION (not a bare transfer — PJRT runs
        h2d transfers on their own stream) implies everything enqueued
        before it has finished."""
        try:
            devs = jax.local_devices()
        except Exception:
            return
        if not devs:
            return
        if device_index is not None:
            devs = [devs[self._check_index(device_index)]]
        fence = jax.jit(lambda x: x + 1)
        for d in devs:
            try:
                jax.block_until_ready(fence(jax.device_put(0, d)))
            except Exception:
                pass

    # ------------------------------------------------------------------- rng
    def manual_seed(self, seed: int):
        """Returns a jax PRNG key (functional rng: the key IS the seed
        state; there is no global generator to set)."""
        return jax.random.PRNGKey(int(seed))

    manual_seed_all = manual_seed

    # ----------------------------------------------------------------- dtype
    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True  # emulated via loss scaling; bf16 is the native type


_ACCEL: Optional[TpuAccelerator] = None
_LOCK = threading.Lock()


def get_accelerator() -> TpuAccelerator:
    global _ACCEL
    with _LOCK:
        if _ACCEL is None:
            _ACCEL = TpuAccelerator()
    return _ACCEL
