from .sharded_moe import moe_layer, top_k_gating  # noqa: F401
