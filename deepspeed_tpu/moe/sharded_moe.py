"""Expert-parallel mixture-of-experts layer.

Parity: deepspeed/moe/sharded_moe.py (TopKGate + MOELayer with its NCCL
all-to-all dispatch). TPU-native design is the GShard/Switch dense-dispatch
formulation: routing builds one-hot dispatch/combine tensors and the
dispatch/combine "all-to-all" is an einsum whose output is sharding-
constrained onto the ``ep`` mesh axis — XLA lowers the resharding to the
same all-to-all the reference hand-codes, but fused and overlapped.

Top-1/top-k gating with capacity factor, token dropping, load-balance aux
loss and router z-loss match the reference's TopKGate semantics.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..models.sharding import constrain


def top_k_gating(
    logits: jax.Array,  # [N, E] fp32
    top_k: int,
    capacity: int,
    rng: Optional[jax.Array],
    train: bool,
    noise_std: float = 0.0,
) -> Tuple[jax.Array, jax.Array, Dict[str, jax.Array]]:
    """Returns (dispatch [N,E,C] bool-ish, combine [N,E,C], aux metrics).

    Parity: TopKGate.forward (deepspeed/moe/sharded_moe.py top1gating/top2gating):
    softmax gates, top-k experts per token, positions via cumsum, overflow
    tokens dropped, load-balance loss = E * mean(gate_frac * token_frac).
    """
    N, E = logits.shape
    if train and noise_std > 0.0 and rng is not None:
        logits = logits + jax.random.normal(rng, logits.shape) * noise_std
    gates = jax.nn.softmax(logits, axis=-1)  # [N, E]

    combine = jnp.zeros((N, E, capacity), jnp.float32)
    dispatch = jnp.zeros((N, E, capacity), jnp.bool_)
    # running per-expert fill count is carried across the k selection rounds
    fill = jnp.zeros((E,), jnp.int32)
    masked_gates = gates
    me = jnp.mean(gates, axis=0)  # gate fraction per expert
    ce_acc = jnp.zeros((E,), jnp.float32)

    for _ in range(top_k):
        idx = jnp.argmax(masked_gates, axis=-1)  # [N]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # [N, E]
        # position of each token within its chosen expert (this round)
        pos_in_round = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # [N, E]
        pos = pos_in_round + fill[None, :] * onehot
        pos_tok = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)  # [N]
        keep = pos_tok < capacity
        gate_val = jnp.sum(gates * onehot, axis=-1)  # [N]
        pos_oh = jax.nn.one_hot(jnp.where(keep, pos_tok, capacity), capacity + 1)[:, :capacity]
        contrib = onehot[:, :, None] * pos_oh[:, None, :]  # [N, E, C]
        combine = combine + contrib * gate_val[:, None, None] * keep[:, None, None]
        dispatch = dispatch | (contrib > 0) & keep[:, None, None]
        fill = fill + jnp.sum(onehot * keep[:, None], axis=0).astype(jnp.int32)
        ce_acc = ce_acc + jnp.mean(onehot, axis=0)
        masked_gates = masked_gates * (1.0 - onehot)  # exclude chosen expert next round

    # renormalize combine weights over selected experts (top-2 reference behavior)
    denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
    combine = jnp.where(denom > 0, combine / jnp.maximum(denom, 1e-9), combine)

    aux_loss = E * jnp.sum(me * (ce_acc / top_k))
    z_loss = jnp.mean(jnp.square(jax.nn.logsumexp(logits, axis=-1)))
    dropped = 1.0 - jnp.sum(dispatch.astype(jnp.float32)) / (N * top_k)
    metrics = {"aux_loss": aux_loss, "z_loss": z_loss, "drop_fraction": dropped}
    return dispatch.astype(jnp.float32), combine, metrics


def moe_layer(cfg, p: Dict, x: jax.Array, rng: Optional[jax.Array], train: bool):
    """Routed expert MLP. x: [B, S, D] → ([B, S, D], aux_loss scalar).

    Expert compute is laid out [E, C, D] and constrained to the ``ep`` axis;
    combined aux = load-balance + z-loss (coefs applied by caller/config).
    """
    B, S, D = x.shape
    E = cfg.num_experts
    N = B * S
    cap_factor = cfg.moe_capacity_factor if train else max(cfg.moe_capacity_factor, 2.0)
    capacity = max(4, int(math.ceil(cap_factor * cfg.moe_top_k * N / E)))

    tokens = x.reshape(N, D)
    router_logits = jnp.einsum(
        "nd,de->ne", tokens.astype(jnp.float32), p["router"].astype(jnp.float32)
    )
    dispatch, combine, metrics = top_k_gating(
        router_logits, cfg.moe_top_k, capacity, rng, train
    )

    # dispatch: [N,E,C] x [N,D] -> [E,C,D], sharded over ep
    expert_in = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), tokens)
    expert_in = constrain(expert_in, "ep", None, None)

    h = jnp.einsum("ecd,edf->ecf", expert_in, p["wi"])
    if cfg.activation == "swiglu":
        g = jnp.einsum("ecd,edf->ecf", expert_in, p["wg"])
        h = jax.nn.silu(g) * h
    else:
        h = jax.nn.gelu(h)
    h = constrain(h, "ep", None, "tp")
    expert_out = jnp.einsum("ecf,efd->ecd", h, p["wo"])
    expert_out = constrain(expert_out, "ep", None, None)

    out = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), expert_out)
    aux = metrics["aux_loss"] + (cfg.moe_z_loss_coef / max(cfg.moe_aux_loss_coef, 1e-9)) * metrics["z_loss"]
    out = out.reshape(B, S, D)

    if cfg.moe_use_residual:
        # Residual/PR-MoE (reference: deepspeed/moe/layer.py use_residual):
        # a dense MLP runs on every token and a learned per-token 2-way
        # softmax coefficient mixes dense vs routed outputs — the routed
        # branch acts as a correction on top of the always-on dense expert.
        h = jnp.einsum("bsd,df->bsf", x, p["res_wi"])
        if cfg.activation == "swiglu":
            h = jax.nn.silu(jnp.einsum("bsd,df->bsf", x, p["res_wg"])) * h
        else:
            h = jax.nn.gelu(h)
        h = constrain(h, ("dp", "fsdp"), "sp", "tp")
        dense = jnp.einsum("bsf,fd->bsd", h, p["res_wo"])
        coef = jax.nn.softmax(
            jnp.einsum(
                "bsd,dc->bsc", x.astype(jnp.float32), p["coef"].astype(jnp.float32)
            ),
            axis=-1,
        ).astype(x.dtype)
        out = dense * coef[..., 0:1] + out * coef[..., 1:2]
    return out, aux
