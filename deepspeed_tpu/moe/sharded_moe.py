"""Expert-parallel mixture-of-experts layer.

Parity: deepspeed/moe/sharded_moe.py (TopKGate + MOELayer with its NCCL
all-to-all dispatch). TPU-native design is the GShard/Switch dense-dispatch
formulation: routing builds one-hot dispatch/combine tensors and the
dispatch/combine "all-to-all" is an einsum whose output is sharding-
constrained onto the ``ep`` mesh axis — XLA lowers the resharding to the
same all-to-all the reference hand-codes, but fused and overlapped.

Top-1/top-k gating with capacity factor, token dropping, load-balance aux
loss and router z-loss match the reference's TopKGate semantics.

Two dispatch formulations share one gating loop (``moe_dispatch``):
- "einsum" (default): one-hot dispatch/combine dots — GShard-style, rides
  the MXU, sharding-friendly.
- "gather": index tables drive plain gathers — the one-hot dots are
  permutations written as dense matmuls (O(N·E·C·D) flops to move O(N·D)
  values; at 16k tokens / 8 experts / cap 2 that is ~1 TFLOP of pure data
  movement per layer per direction), so the gather form trades MXU flops
  for HBM bytes. A/B on-chip via the model config; parity-tested.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..models.sharding import constrain, current_topology


def _a2a_overlap_active(B: int, S: int, E: int, F: int):
    """(overlap_cfg, topology) when the decomposed-a2a scope is active AND
    the shapes divide the mesh (moe.overlap_a2a — parallel/a2a_overlap.py);
    (None, None) otherwise, and the serial GSPMD path runs."""
    from ..parallel.a2a_overlap import current_a2a, moe_a2a_applicable

    cfg = current_a2a()
    if cfg is None:
        return None, None
    topo = current_topology()
    if topo is None or not moe_a2a_applicable(topo, B=B, S=S, E=E, F=F):
        return None, None
    return cfg, topo


def _gating_rounds(logits, top_k, capacity, rng, train, noise_std,
                   valid=None):
    """The shared top-k selection loop: per-round (expert idx, slot pos,
    keep mask, raw gate value) plus the aux metrics. ONE implementation so
    the einsum and gather dispatch paths cannot diverge.

    The inference path accepts ``rng=None`` without consuming a key:
    router noise is only ever sampled when TRAINING with
    ``noise_std > 0`` — gating at eval is bitwise identical with and
    without an rng, so serving's deterministic per-request RNG discipline
    never threads a key through the router (unit-tested in
    tests/test_moe.py).

    ``valid`` ([N] bool, optional) is the serving engine's null-expert
    contract: rows marked invalid (padded chunk tails, idle slots, done
    requests) never enter the selection — they occupy no capacity slot,
    shift no other token's cumsum position, and carry zero combine
    weight — so routing of the REAL tokens is independent of batch
    occupancy and the one fixed-shape step never recompiles (or drops
    differently) as occupancy changes."""
    N, E = logits.shape
    if train and noise_std > 0.0 and rng is not None:
        logits = logits + jax.random.normal(rng, logits.shape) * noise_std
    if valid is not None:
        # zeroed (not -inf) logits: invalid rows route through finite
        # uniform gates, so no NaN/inf can leak out of garbage hidden
        # states into the masked arithmetic below
        logits = jnp.where(valid[:, None], logits, 0.0)
    gates = jax.nn.softmax(logits, axis=-1)  # [N, E]

    fill = jnp.zeros((E,), jnp.int32)
    masked_gates = gates
    me = jnp.mean(gates, axis=0)  # gate fraction per expert
    ce_acc = jnp.zeros((E,), jnp.float32)
    rounds = []
    kept_total = jnp.zeros((), jnp.float32)

    for _ in range(top_k):
        idx = jnp.argmax(masked_gates, axis=-1)  # [N]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # [N, E]
        if valid is not None:
            onehot = onehot * valid[:, None].astype(onehot.dtype)
        # position of each token within its chosen expert (this round)
        pos_in_round = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # [N, E]
        pos = pos_in_round + fill[None, :] * onehot
        pos_tok = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)  # [N]
        keep = pos_tok < capacity
        if valid is not None:
            keep = keep & valid
        gate_val = jnp.sum(gates * onehot, axis=-1)  # [N]
        rounds.append((idx, pos_tok, keep, gate_val))
        fill = fill + jnp.sum(onehot * keep[:, None], axis=0).astype(jnp.int32)
        ce_acc = ce_acc + jnp.mean(onehot, axis=0)
        kept_total = kept_total + jnp.sum(keep.astype(jnp.float32))
        masked_gates = masked_gates * (1.0 - onehot)  # exclude chosen expert

    aux_loss = E * jnp.sum(me * (ce_acc / top_k))
    z_loss = jnp.mean(jnp.square(jax.nn.logsumexp(logits, axis=-1)))
    n_routed = (
        jnp.sum(valid.astype(jnp.float32)) if valid is not None
        else jnp.asarray(float(N))
    )
    dropped = jnp.where(
        n_routed > 0, 1.0 - kept_total / jnp.maximum(n_routed * top_k, 1.0),
        0.0,
    )
    metrics = {
        "aux_loss": aux_loss,
        "z_loss": z_loss,
        "drop_fraction": dropped,
        # serving load-balance observability: tokens that actually landed
        # a capacity slot, per expert (the fill counters)
        "tokens_per_expert": fill,
        "routed_tokens": kept_total.astype(jnp.int32),
    }
    return rounds, metrics


def top_k_gating(
    logits: jax.Array,  # [N, E] fp32
    top_k: int,
    capacity: int,
    rng: Optional[jax.Array],
    train: bool,
    noise_std: float = 0.0,
    valid: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, Dict[str, jax.Array]]:
    """Returns (dispatch [N,E,C] bool-ish, combine [N,E,C], aux metrics).

    Parity: TopKGate.forward (deepspeed/moe/sharded_moe.py top1gating/top2gating):
    softmax gates, top-k experts per token, positions via cumsum, overflow
    tokens dropped, load-balance loss = E * mean(gate_frac * token_frac).
    ``valid`` is the serving null-expert mask (see :func:`_gating_rounds`).
    """
    N, E = logits.shape
    rounds, metrics = _gating_rounds(logits, top_k, capacity, rng, train,
                                     noise_std, valid=valid)
    combine = jnp.zeros((N, E, capacity), jnp.float32)
    dispatch = jnp.zeros((N, E, capacity), jnp.bool_)
    for idx, pos_tok, keep, gate_val in rounds:
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        pos_oh = jax.nn.one_hot(
            jnp.where(keep, pos_tok, capacity), capacity + 1
        )[:, :capacity]
        contrib = onehot[:, :, None] * pos_oh[:, None, :]  # [N, E, C]
        combine = combine + contrib * gate_val[:, None, None] * keep[:, None, None]
        dispatch = dispatch | (contrib > 0) & keep[:, None, None]

    # renormalize combine weights over selected experts (top-2 reference behavior)
    denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
    combine = jnp.where(denom > 0, combine / jnp.maximum(denom, 1e-9), combine)
    return dispatch.astype(jnp.float32), combine, metrics


def top_k_gating_indices(
    logits: jax.Array,  # [N, E] fp32
    top_k: int,
    capacity: int,
    rng: Optional[jax.Array],
    train: bool,
    noise_std: float = 0.0,
    valid: Optional[jax.Array] = None,
):
    """Index-table form of :func:`top_k_gating` (same selection loop).

    Returns (tok_of_slot [E,C] int32, slot_valid [E,C] bool,
    slot_of_tok [N,K] int32 flat e*C+c, w_of_tok [N,K] fp32, metrics).
    The one-hot dispatch/combine einsums are permutations written as dense
    dots — O(N·E·C·D) MXU flops to move O(N·D) values; these tables drive
    plain gathers instead (O(N·D·K) bytes), the sort-based formulation TPU
    MoE stacks use (and the reference's all-to-all ordering implies).
    ``valid`` is the serving null-expert mask (see :func:`_gating_rounds`):
    invalid rows never occupy a slot and carry zero combine weight."""
    N, E = logits.shape
    rounds, metrics = _gating_rounds(logits, top_k, capacity, rng, train,
                                     noise_std, valid=valid)
    # one extra dummy slot soaks up dropped tokens' scatter writes
    tok_flat = jnp.zeros((E * capacity + 1,), jnp.int32)
    valid_flat = jnp.zeros((E * capacity + 1,), jnp.bool_)
    slot_of_tok = []
    w_raw = []
    arange_n = jnp.arange(N, dtype=jnp.int32)
    for idx, pos_tok, keep, gate_val in rounds:
        flat = idx * capacity + jnp.minimum(pos_tok, capacity - 1)
        target = jnp.where(keep, flat, E * capacity)
        tok_flat = tok_flat.at[target].set(arange_n)
        valid_flat = valid_flat.at[target].set(True)
        slot_of_tok.append(jnp.where(keep, flat, 0))
        w_raw.append(gate_val * keep)
    # (the dummy slot E*capacity is sliced off below — its contents never
    # reach the gather path)
    w = jnp.stack(w_raw, axis=1)  # [N, K]
    denom = jnp.sum(w, axis=1, keepdims=True)
    w = jnp.where(denom > 0, w / jnp.maximum(denom, 1e-9), w)
    return (
        tok_flat[:-1].reshape(E, capacity),
        valid_flat[:-1].reshape(E, capacity),
        jnp.stack(slot_of_tok, axis=1),
        w,
        metrics,
    )


def eval_capacity(cfg, n_tokens: int) -> int:
    """Per-expert capacity at inference for a program that feeds at most
    ``n_tokens`` real tokens: ``max(4, ceil(max(capacity_factor, 2.0) ·
    top_k · n_tokens / E))`` — the reference TopKGate eval rule. STATIC
    given static shapes, which is what keeps the serving step at one
    compile: the slot engine passes its token budget W (the scheduler
    never packs more than W real tokens per step), so occupancy changes
    never change capacity. No-drop guarantee: with
    ``max(capacity_factor, 2.0) · top_k >= E`` even the adversarial
    all-tokens-to-one-expert step fits, and per-token routing becomes
    independent of batch composition (the spec-on == spec-off and
    serving == generate parities for MoE need exactly that)."""
    cap_factor = max(cfg.moe_capacity_factor, 2.0)
    return max(4, int(math.ceil(cap_factor * cfg.moe_top_k * n_tokens
                                / cfg.num_experts)))


def _expert_proj(x: jax.Array, w) -> jax.Array:
    """Batched per-expert projection x[E, C, d] @ w[E, d, n] → [E, C, n].

    Dense expert banks take the plain einsum. PackedWeight banks
    (weight-only int8/int4 expert weights, [L, E, d, n] packed by the
    inference engine) stream through the Pallas matvec per expert
    (ops/pallas/quantized_matmul.packed_expert_proj — per-shard under a
    full-manual shard_map when the bank is ep/tp-sharded, the PR-3 tp
    path applied to experts) when the row count fits the streaming
    threshold; larger shapes dequantize once and ride the MXU."""
    from ..ops.quantizer import PackedWeight

    if isinstance(w, PackedWeight):
        from ..ops.pallas.quantized_matmul import packed_expert_proj

        y = packed_expert_proj(x, w)
        if y is not None:
            return y
        return jnp.einsum("ecd,edf->ecf", x, w.dequantize())
    return jnp.einsum("ecd,edf->ecf", x, w)


def _expert_ffn(cfg, p: Dict, expert_in: jax.Array) -> jax.Array:
    """The expert FFN stack on [E, C, D] capacity rows — ONE
    implementation shared by the training layer, the serving routed path
    and (structurally mirrored) the decode a2a ring, so the paths cannot
    diverge. Handles PackedWeight expert banks via :func:`_expert_proj`."""
    h = _expert_proj(expert_in, p["wi"])
    if cfg.activation == "swiglu":
        g = _expert_proj(expert_in, p["wg"])
        h = jax.nn.silu(g) * h
    else:
        h = jax.nn.gelu(h)
    h = constrain(h, "ep", None, "tp")
    expert_out = _expert_proj(h, p["wo"])
    return constrain(expert_out, "ep", None, None)


def _residual_mix(cfg, p: Dict, x: jax.Array, out: jax.Array) -> jax.Array:
    """Residual/PR-MoE (reference: deepspeed/moe/layer.py use_residual):
    a dense MLP runs on every token and a learned per-token 2-way
    softmax coefficient mixes dense vs routed outputs — the routed
    branch acts as a correction on top of the always-on dense expert.
    ONE implementation shared by the training layer and the serving
    path, so the mixes cannot diverge."""
    h = jnp.einsum("bsd,df->bsf", x, p["res_wi"])
    if cfg.activation == "swiglu":
        h = jax.nn.silu(jnp.einsum("bsd,df->bsf", x, p["res_wg"])) * h
    else:
        h = jax.nn.gelu(h)
    h = constrain(h, ("dp", "fsdp"), "sp", "tp")
    dense = jnp.einsum("bsf,fd->bsd", h, p["res_wo"])
    coef = jax.nn.softmax(
        jnp.einsum(
            "bsd,dc->bsc", x.astype(jnp.float32),
            p["coef"].astype(jnp.float32),
        ),
        axis=-1,
    ).astype(x.dtype)
    return dense * coef[..., 0:1] + out * coef[..., 1:2]


def _experts_packed(p: Dict) -> bool:
    """Whether this layer's expert bank is weight-only quantized packed
    storage (the a2a rings fall back to stock collectives for packed
    leaves, exactly like the PR-3 tp rings do)."""
    from ..ops.quantizer import PackedWeight

    return any(
        isinstance(p.get(k), PackedWeight) for k in ("wi", "wg", "wo")
    )


def moe_layer(cfg, p: Dict, x: jax.Array, rng: Optional[jax.Array], train: bool):
    """Routed expert MLP. x: [B, S, D] → ([B, S, D], aux_loss scalar).

    Expert compute is laid out [E, C, D] and constrained to the ``ep`` axis;
    combined aux = load-balance + z-loss (coefs applied by caller/config).
    """
    B, S, D = x.shape
    E = cfg.num_experts
    N = B * S
    if train:
        capacity = max(4, int(math.ceil(cfg.moe_capacity_factor
                                        * cfg.moe_top_k * N / E)))
    else:
        capacity = eval_capacity(cfg, N)

    tokens = x.reshape(N, D)
    router_logits = jnp.einsum(
        "nd,de->ne", tokens.astype(jnp.float32), p["router"].astype(jnp.float32)
    )
    dispatch_mode = getattr(cfg, "moe_dispatch", "einsum")
    if dispatch_mode not in ("einsum", "gather"):
        # an A/B sweep typo must not silently benchmark the wrong path
        raise ValueError(
            f"moe_dispatch {dispatch_mode!r} (must be 'einsum' or 'gather')"
        )
    use_gather = dispatch_mode == "gather"
    # decomposed-a2a overlap (moe.overlap_a2a): when the scope is active
    # and shapes divide, the dispatch/combine exchanges run as chunked
    # ppermute rings whose hops hide under the per-chunk expert FFN
    # (parallel/a2a_overlap.py); the serial GSPMD path below otherwise
    ov, otopo = _a2a_overlap_active(B, S, E, p["wi"].shape[-1])
    if _experts_packed(p):
        # packed int8/int4 expert banks stream through the Pallas matvec
        # path; the decomposed ring moves dense chunks — fall back to the
        # stock exchange (the PR-3 tp-ring rule applied to experts)
        ov, otopo = None, None
    if use_gather:
        # permutation as gathers, not one-hot dots: O(N·D·K) moved bytes
        # instead of O(N·E·C·D) MXU flops each way
        tok_of_slot, slot_valid, slot_of_tok, w_of_tok, metrics = (
            top_k_gating_indices(router_logits, cfg.moe_top_k, capacity, rng,
                                 train)
        )
    else:
        dispatch, combine, metrics = top_k_gating(
            router_logits, cfg.moe_top_k, capacity, rng, train
        )
    if ov is not None:
        from ..parallel.a2a_overlap import moe_a2a_ffn

        K = cfg.moe_top_k
        gating = (
            ("gather", tok_of_slot, slot_valid,
             slot_of_tok.reshape(B, S, K), w_of_tok.reshape(B, S, K))
            if use_gather
            else ("einsum",
                  dispatch.astype(x.dtype).reshape(B, S, E, capacity),
                  combine.astype(x.dtype).reshape(B, S, E, capacity))
        )
        out = moe_a2a_ffn(
            x, gating,
            (p["wi"], p.get("wg") if cfg.activation == "swiglu" else None,
             p["wo"]),
            otopo, chunks=int(ov.chunks),
            bidirectional=bool(ov.bidirectional),
        )
    else:
        if use_gather:
            expert_in = (
                jnp.take(tokens, tok_of_slot.reshape(-1), axis=0)
                .reshape(E, capacity, D)
                * slot_valid[..., None].astype(x.dtype)
            )
        else:
            # dispatch: [N,E,C] x [N,D] -> [E,C,D], sharded over ep
            expert_in = jnp.einsum(
                "nec,nd->ecd", dispatch.astype(x.dtype), tokens
            )
        expert_in = constrain(expert_in, "ep", None, None)
        expert_out = _expert_ffn(cfg, p, expert_in)

        if use_gather:
            picked = jnp.take(
                expert_out.reshape(E * capacity, D), slot_of_tok.reshape(-1),
                axis=0,
            ).reshape(N, cfg.moe_top_k, D)
            out = jnp.sum(picked * w_of_tok[..., None].astype(x.dtype), axis=1)
        else:
            out = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), expert_out)
    aux = metrics["aux_loss"] + (cfg.moe_z_loss_coef / max(cfg.moe_aux_loss_coef, 1e-9)) * metrics["z_loss"]
    out = out.reshape(B, S, D)

    if cfg.moe_use_residual:
        out = _residual_mix(cfg, p, x, out)
    return out, aux


def moe_serving_mlp(cfg, p: Dict, x: jax.Array,
                    token_valid: Optional[jax.Array] = None,
                    budget_tokens: Optional[int] = None):
    """Routed expert MLP for the decode/serving path (ISSUE 14):
    x [B, S, D] → (out [B, S, D], load-balance stats).

    The serving engine's contract, end to end:

    - **capacity from the static token budget** — ``budget_tokens`` is
      the most REAL tokens the caller can feed (the slot engine's
      token_budget W; ``B·S`` for the lockstep engine where every
      position is real), so :func:`eval_capacity` is static and the ONE
      ``[max_slots, token_budget]`` step never recompiles as occupancy
      changes;
    - **null-expert padding** — ``token_valid`` [B, S] marks the real
      positions; padded chunk tails, idle slots and done rows route to
      no expert at all (zero capacity, zero combine weight, zero cumsum
      shift — :func:`_gating_rounds`);
    - **slot-ragged gather dispatch** — :func:`top_k_gating_indices`
      index tables drive plain gathers (O(N·D·K) bytes), not the one-hot
      dots (O(N·E·C·D) flops of data movement — decode steps are
      latency-bound);
    - **ep-sharded experts** — the FFN runs on [E, C, D] rows
      constrained onto the ``ep`` mesh axis (stock collectives), or
      through the decode-shaped chunked-ppermute ring
      (parallel/a2a_overlap.moe_decode_a2a) when the ``a2a_scope`` is
      active and shapes divide — both produce the FULL expert-output
      tensor, so the combine below is ONE shared implementation and
      ep-sharded output is bitwise the dense-replicated output;
    - **packed int8/int4 expert weights** stream through the Pallas
      matvec (:func:`_expert_proj`); packed banks always take the stock
      exchange (the tp-ring fallback rule).

    Returns ``(out, stats)`` with stats = {"tokens_per_expert" [E] i32,
    "routed_tokens" i32, "drop_fraction" f32} — the serving metrics
    counters (serving/metrics.py ``on_moe``)."""
    B, S, D = x.shape
    E = cfg.num_experts
    N = B * S
    K = cfg.moe_top_k
    if budget_tokens is None:
        budget_tokens = S if token_valid is not None else N
    capacity = eval_capacity(cfg, int(budget_tokens))

    tokens = x.reshape(N, D)
    valid = token_valid.reshape(N) if token_valid is not None else None
    router_logits = jnp.einsum(
        "nd,de->ne", tokens.astype(jnp.float32),
        p["router"].astype(jnp.float32),
    )
    tok_of_slot, slot_valid, slot_of_tok, w_of_tok, metrics = (
        top_k_gating_indices(router_logits, K, capacity, rng=None,
                             train=False, valid=valid)
    )

    ring_cfg = None
    topo = current_topology()
    if topo is not None and not _experts_packed(p):
        from ..parallel.a2a_overlap import (current_a2a,
                                            moe_decode_a2a_applicable)

        ov = current_a2a()
        if ov is not None and moe_decode_a2a_applicable(
            topo, E=E, F=p["wi"].shape[-1], n_tokens=N
        ):
            ring_cfg = ov
    if ring_cfg is not None:
        # the chunked-ppermute decode ring runs dispatch + FFN + combine
        # per ep member (each member emits its own token block, the
        # stock combine expression verbatim — bitwise the stock path)
        from ..parallel.a2a_overlap import moe_decode_a2a

        out = moe_decode_a2a(
            tokens, tok_of_slot, slot_valid, slot_of_tok, w_of_tok,
            (p["wi"], p.get("wg") if cfg.activation == "swiglu" else None,
             p["wo"]),
            topo, chunks=int(ring_cfg.chunks),
            bidirectional=bool(ring_cfg.bidirectional),
        )
    else:
        expert_in = (
            jnp.take(tokens, tok_of_slot.reshape(-1), axis=0)
            .reshape(E, capacity, D)
            * slot_valid[..., None].astype(x.dtype)
        )
        expert_in = constrain(expert_in, "ep", None, None)
        expert_out = _expert_ffn(cfg, p, expert_in)
        # combine: dropped/invalid tokens carry w == 0, so their slot-0
        # fallback gather contributes exact zeros
        picked = jnp.take(
            expert_out.reshape(E * capacity, D), slot_of_tok.reshape(-1),
            axis=0,
        ).reshape(N, K, D)
        out = jnp.sum(picked * w_of_tok[..., None].astype(x.dtype), axis=1)
    out = out.reshape(B, S, D)

    if cfg.moe_use_residual:
        out = _residual_mix(cfg, p, x, out)

    # routed_tokens stays derivable (tokens_per_expert.sum()) — the
    # metrics layer re-derives it, so the step ships no redundant scalar
    stats = {
        "tokens_per_expert": metrics["tokens_per_expert"],
        "drop_fraction": metrics["drop_fraction"],
    }
    return out, stats
