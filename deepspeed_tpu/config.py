"""DeepSpeed-compatible configuration.

Parity: deepspeed/runtime/config.py (DeepSpeedConfig) and the per-section
config dataclasses under deepspeed/runtime/*/config.py. Accepts the same
``ds_config.json`` schema (a dict or a path), validates the batch-size
triangle, and exposes typed sections.

TPU-first notes: ``train_micro_batch_size_per_gpu`` keeps its reference name
but means per-*dp-shard* micro batch; ``"auto"`` values are resolved at
``initialize()`` time like the HF integration does in the reference.
"""

from __future__ import annotations

import copy
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

AUTO = "auto"


class DeepSpeedConfigError(ValueError):
    pass


def _get(d: Dict[str, Any], key: str, default=None):
    v = d.get(key, default)
    return default if v == AUTO else v


def _tristate(v):
    """Normalize a bool-or-"auto" knob: "auto" (and any other string)
    survives parsing — strings are judged by ``_check_tristate`` at
    validation so a typo like "ture" raises instead of silently
    coercing to True; non-strings collapse to bool (JSON 0/1)."""
    return v if isinstance(v, str) else bool(v)


def _check_tristate(name: str, v) -> None:
    if not (isinstance(v, bool) or v == AUTO):
        raise DeepSpeedConfigError(
            f"{name} must be true, false or \"auto\", got {v!r}"
        )


@dataclass
class OptimizerConfig:
    """Parity: "optimizer" section (deepspeed/runtime/config.py)."""

    type: str = "adamw"
    params: Dict[str, Any] = field(default_factory=dict)

    @property
    def lr(self) -> float:
        return float(self.params.get("lr", 1e-3))

    @property
    def betas(self) -> Tuple[float, float]:
        betas = self.params.get("betas", (0.9, 0.999))
        return (float(betas[0]), float(betas[1]))

    @property
    def eps(self) -> float:
        return float(self.params.get("eps", 1e-8))

    @property
    def weight_decay(self) -> float:
        return float(self.params.get("weight_decay", 0.0))


@dataclass
class SchedulerConfig:
    type: Optional[str] = None
    params: Dict[str, Any] = field(default_factory=dict)


@dataclass
class FP16Config:
    """Parity: "fp16" section incl. dynamic loss scaling knobs."""

    enabled: bool = False
    loss_scale: float = 0.0  # 0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0

    @property
    def dynamic(self) -> bool:
        return self.loss_scale == 0.0

    @property
    def initial_scale(self) -> float:
        if not self.dynamic:
            return float(self.loss_scale)
        return float(2.0 ** self.initial_scale_power)


@dataclass
class BF16Config:
    enabled: bool = False
    # reference: bf16 grad accumulation dtype option (accumulate_grads_in_fp32)
    accumulate_grads_in_fp32: bool = True


@dataclass
class OffloadConfig:
    """Parity: "offload_optimizer"/"offload_param" subsections."""

    device: str = "none"  # none | cpu | nvme
    nvme_path: Optional[str] = None
    pin_memory: bool = True
    buffer_count: int = 4
    buffer_size: int = 100 * 2**20
    max_in_cpu: int = 10**9

    @property
    def enabled(self) -> bool:
        return self.device not in ("none", None)


@dataclass
class ZeroConfig:
    """Parity: deepspeed/runtime/zero/config.py (DeepSpeedZeroConfig)."""

    stage: int = 0
    allgather_partitions: bool = True
    overlap_comm: bool = True
    reduce_scatter: bool = True
    contiguous_gradients: bool = True
    reduce_bucket_size: int = 5 * 10**8
    allgather_bucket_size: int = 5 * 10**8
    sub_group_size: int = 10**9
    # double-buffer the bucketed per-layer offload update: prefetch layer
    # i+1's pinned-host optimizer state while layer i's math runs, write
    # layer i-1's result back concurrently (runtime/bucketed_opt.py).
    # Costs one extra layer slice of HBM; off until on-chip parity + A/B
    # land. "sub_group_prefetch" is accepted as an alias. "auto" defers to
    # the measured knob-default table (resolve_auto_knobs).
    offload_double_buffer: Any = False  # bool | "auto"
    # one-layer-ahead stage-3 parameter all-gather prefetch: the layer
    # scan carries a rotating two-slot gathered-params buffer (the PR-1
    # offload_double_buffer pattern applied to the fwd/bwd scan), so
    # layer i+1's all-gather is issued under layer i's math instead of
    # stalling layer i+1's compute on its own fetch
    # (runtime/zero/prefetch.py). Persistence-threshold (replicated)
    # params are excluded automatically — their "gather" is a no-op.
    # Off by default pending an on-chip A/B; "zero3_prefetch" is
    # accepted as an alias. Ignored (with a log line) when stage != 3.
    # "auto" defers to the measured knob-default table.
    stage3_layer_prefetch: Any = False  # bool | "auto"
    offload_optimizer: OffloadConfig = field(default_factory=OffloadConfig)
    offload_param: OffloadConfig = field(default_factory=OffloadConfig)
    stage3_max_live_parameters: int = 10**9
    stage3_max_reuse_distance: int = 10**9
    stage3_prefetch_bucket_size: int = 5 * 10**7
    stage3_param_persistence_threshold: int = 10**5
    stage3_gather_16bit_weights_on_model_save: bool = False
    # ZeRO++ knobs (reference: zero_quantized_* / zero_hpz_partition_size)
    zero_quantized_weights: bool = False
    zero_quantized_gradients: bool = False
    zero_hpz_partition_size: int = 1
    # MiCS-style sub-partitioning
    mics_shard_size: int = -1
    mics_hierarchical_params_gather: bool = False
    # ---- wire codecs (comm/wires.py, docs/wires.md) ----------------------
    # grad_wire: codec for the ZeRO gradient reduce-scatter on the data
    # axes (qgZ — blocks quantize ONCE before the exchange, the
    # accumulate runs after dequant in f32 master precision). Applies at
    # stages 1/2 (explicit wire reduction replaces the GSPMD-implicit
    # one) and stage 3 (the gather's backward). "auto" resolves from the
    # legacy bool: int8 when zero_quantized_gradients, else fp32.
    grad_wire: str = "auto"   # auto | fp32 | bf16 | int8 | int4
    # param_wire: codec for the stage-3 parameter all-gathers (qwZ),
    # composing with stage3_layer_prefetch (the prefetched gather then
    # moves codec bytes). "auto": int8 when zero_quantized_weights.
    param_wire: str = "auto"  # auto | fp32 | bf16 | int8 | int4
    # hierarchical_wire: 2-hop collectives over a factored (dp, fsdp)
    # mesh — intra-group (fsdp) hops run full width on the fast links,
    # inter-group (dp) hops move codec bytes (ZeRO++ hgZ / EQuARX).
    # Ignored (with a log line) when dp or fsdp is not live.
    hierarchical_wire: bool = False

    _WIRE_CODECS = ("auto", "fp32", "bf16", "int8", "int4")

    def resolved_grad_wire(self) -> str:
        if self.grad_wire != "auto":
            return self.grad_wire
        return "int8" if self.zero_quantized_gradients else "fp32"

    def resolved_param_wire(self) -> str:
        if self.param_wire != "auto":
            return self.param_wire
        return "int8" if self.zero_quantized_weights else "fp32"

    def validate(self) -> None:
        if self.stage not in (0, 1, 2, 3):
            raise DeepSpeedConfigError(f"zero_optimization.stage must be 0-3, got {self.stage}")
        for knob in ("offload_double_buffer", "stage3_layer_prefetch"):
            _check_tristate(f"zero_optimization.{knob}", getattr(self, knob))
        for off in (self.offload_optimizer, self.offload_param):
            if off.device not in ("none", "cpu", "nvme", None):
                raise DeepSpeedConfigError(f"offload device must be none|cpu|nvme, got {off.device}")
            if off.device == "nvme" and not off.nvme_path:
                raise DeepSpeedConfigError("nvme offload requires nvme_path")
        if self.offload_param.enabled and self.stage != 3:
            raise DeepSpeedConfigError("offload_param requires ZeRO stage 3")
        if (self.zero_quantized_weights or self.zero_quantized_gradients) and (
            self.stage != 3
        ):
            raise DeepSpeedConfigError(
                "zero_quantized_weights/gradients (ZeRO++) require stage 3"
            )
        for knob in ("grad_wire", "param_wire"):
            v = getattr(self, knob)
            if v not in self._WIRE_CODECS:
                raise DeepSpeedConfigError(
                    f"zero_optimization.{knob} must be one of "
                    f"{self._WIRE_CODECS}, got {v!r}"
                )
        if self.resolved_grad_wire() != "fp32" and self.stage < 1:
            raise DeepSpeedConfigError(
                "zero_optimization.grad_wire requires ZeRO stage >= 1 "
                "(stage 0 has no data-axis gradient reduce-scatter to "
                "compress — the DDP psum stays full width)"
            )
        if self.resolved_param_wire() != "fp32" and self.stage != 3:
            raise DeepSpeedConfigError(
                "zero_optimization.param_wire requires ZeRO stage 3 "
                "(below it parameters are never gathered over a wire)"
            )
        if self.hierarchical_wire and self.stage < 1:
            raise DeepSpeedConfigError(
                "zero_optimization.hierarchical_wire requires ZeRO stage "
                ">= 1 (stage 0 has no data-axis wire collectives to run "
                "the 2-hop forms over)"
            )


@dataclass
class ActivationCheckpointingConfig:
    """Parity: "activation_checkpointing" section; `policy` is TPU-native
    (maps to jax.checkpoint policies) replacing partition_activations et al."""

    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    policy: str = "none"  # none | full | dots_saveable | dots_flash | attn_only | offload_host

    def validate(self) -> None:
        # reject unknown policies at construction — otherwise the typo only
        # surfaces as a KeyError deep inside the traced train step
        from .runtime.activation_checkpointing import _POLICIES

        if self.policy not in (None, "none") and self.policy not in _POLICIES:
            raise DeepSpeedConfigError(
                f"activation_checkpointing.policy {self.policy!r} is unknown; "
                f"have none, {', '.join(sorted(_POLICIES))}"
            )


@dataclass
class PipelineConfig:
    """Parity: PipelineEngine config (runtime/pipe/engine.py kwargs)."""

    stages: int = 1
    partition_method: str = "parameters"  # parameters | uniform | type:<regex>
    seed_layers: bool = False
    activation_checkpoint_interval: int = 0
    pipe_schedule: str = "1f1b"  # 1f1b | gpipe (memory policy; grads identical)
    tick_chunk: int = 0  # 1f1b ckpt-chunk size in ticks; 0 = auto (~sqrt)


@dataclass
class TopologyConfig:
    """"topology" section — the physical fabric under the mesh.

    ``dcn_dp``: the data-parallel axis rides the inter-pod DCN fabric
    with this many pods (0/1 = flat single-pod ICI mesh). When > 1 the
    engine builds a two-level hybrid mesh (``MeshTopology.hybrid``: the
    DCN-tagged dp axis outermost, ICI axes inside), the cost planner
    prices dp-crossing collectives at ``hardware.dcn_bw`` and rules
    R12/R13 arm. This describes the fabric, not a tuning choice: the
    2-hop hierarchical split is the planner's job to pick
    (docs/memory_planner.md "Per-link pricing").
    """

    dcn_dp: int = 0

    def validate(self) -> None:
        if self.dcn_dp < 0:
            raise DeepSpeedConfigError(
                f"topology.dcn_dp must be >= 0, got {self.dcn_dp}"
            )

    def dcn_axes(self) -> tuple:
        return ("dp",) if self.dcn_dp > 1 else ()


@dataclass
class MoEOverlapA2AConfig:
    """"moe.overlap_a2a" — decomposed MoE all-to-all
    (parallel/a2a_overlap.py): the GSPMD dispatch/combine exchanges at the
    expert boundary decompose into chunked ppermute hops on the ep-axis
    ring whose wire time hides under the per-chunk expert FFN matmuls —
    each expert shard starts computing as soon as a capacity chunk lands
    instead of waiting for the whole [E, C, D] exchange. Default OFF until
    an on-chip A/B lands (the same protocol as
    tensor_parallel.overlap_comm / zero_optimization.offload_double_buffer);
    numerics of the rings are oracle-verified BITWISE against the module's
    pure-XLA reference path on CPU meshes for both dispatch modes
    (tests/test_moe_a2a_overlap.py)."""

    enabled: Any = False  # bool | "auto" (measured knob-default table)
    # capacity chunks per exchange (the ring/FFN pipelining granularity:
    # chunk k+1's hops fly while chunk k's expert matmuls run); uneven
    # splits allowed, never changes numerics for top_k <= 2
    chunks: int = 1
    # halves of each capacity chunk ride both ring directions at once
    # (full-duplex ICI halves per-hop wire time, same hop count)
    bidirectional: bool = False

    def validate(self) -> None:
        _check_tristate("moe.overlap_a2a.enabled", self.enabled)
        if int(self.chunks) < 1:
            raise DeepSpeedConfigError(
                f"moe.overlap_a2a.chunks must be >= 1, got {self.chunks}"
            )


@dataclass
class MoEConfig:
    enabled: bool = False
    ep_size: int = 1
    num_experts: int = 1
    top_k: int = 2
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    aux_loss_coef: float = 0.01
    z_loss_coef: float = 1e-3
    drop_tokens: bool = True
    use_residual: bool = False
    overlap_a2a: MoEOverlapA2AConfig = field(
        default_factory=MoEOverlapA2AConfig
    )

    def __post_init__(self):
        # _parse_dc is shallow: the nested section arrives as a dict (or a
        # bare bool / "auto", the overlap_comm spelling) — normalize here
        if isinstance(self.overlap_a2a, bool) or self.overlap_a2a == AUTO:
            self.overlap_a2a = MoEOverlapA2AConfig(enabled=self.overlap_a2a)
        elif isinstance(self.overlap_a2a, dict):
            self.overlap_a2a = _parse_dc(MoEOverlapA2AConfig,
                                         self.overlap_a2a)


@dataclass
class OverlapCommConfig:
    """"tensor_parallel.overlap_comm" — decomposed (ring) collective
    matmul at the TP projection boundaries (parallel/tensor_overlap.py):
    the Megatron all-gather/reduce-scatter pair decomposes into ppermute
    rings whose hops hide under the per-chunk matmuls (T3, arXiv
    2401.16677). Default OFF until an on-chip A/B lands (the same
    protocol as zero_optimization.offload_double_buffer); numerics of the
    unquantized rings are oracle-verified bitwise against the XLA
    reference path on a CPU mesh (tests/test_tp_overlap.py)."""

    enabled: Any = False  # bool | "auto" (measured knob-default table)
    # matmul sub-chunks per ring step (scheduling granularity for the
    # DMA/MXU overlap; never changes numerics — uneven splits allowed)
    chunks: int = 1
    # send half the payload around each ring direction simultaneously:
    # full-duplex ICI halves per-hop wire time at the same hop count
    bidirectional: bool = False
    # int8 + fp32 lane-scale hop wire (ZeRO++ qwZ composition). Gather
    # wires quantize once at the source; scatter accumulators re-quantize
    # per hop (error O(tp) — see docs/collective_matmul.md). Forward-only:
    # in training the backward runs the unquantized transpose
    # (straight-through — int8 casts would otherwise zero the activation
    # cotangents), mirroring ZeRO++'s qwZ/qgZ split.
    quantized_hops: bool = False

    def validate(self) -> None:
        _check_tristate("tensor_parallel.overlap_comm.enabled", self.enabled)
        if int(self.chunks) < 1:
            raise DeepSpeedConfigError(
                f"tensor_parallel.overlap_comm.chunks must be >= 1, got "
                f"{self.chunks}"
            )


@dataclass
class TensorParallelConfig:
    """Parity: autotp / "tensor_parallel" section."""

    tp_size: int = 1
    overlap_comm: OverlapCommConfig = field(default_factory=OverlapCommConfig)


@dataclass
class SpecDecodeConfig:
    """"serving.spec" section — speculative decoding inside the slot
    engine (deepspeed_tpu/serving/spec.py, docs/serving.md). Each active
    decode slot proposes up to ``max_draft`` draft tokens host-side
    (n-gram/prompt-lookup over its own token buffer); the ONE jitted
    step verifies every slot's window at once. A spec decode slot
    consumes ``max_draft + 1`` budget rows; the SplitFuse planner
    shrinks the draft count toward 0 under budget pressure, so the step
    shape — and the zero-recompiles contract — never changes. Lossless:
    spec-on reproduces spec-off token-for-token (greedy AND
    sampled-with-shared-keys)."""

    enabled: Any = False  # bool | "auto" (measured knob-default table)
    max_draft: int = 4     # k: draft tokens per decode slot per step (the
                           # verify window is k+1 rows of the slot's chunk)
    draft: str = "ngram"   # draft source; "ngram" = host-side n-gram /
                           # prompt-lookup over the slot's token buffer
    ngram_n: int = 3       # context length of the n-gram match

    def validate(self) -> None:
        if int(self.max_draft) < 1:
            raise DeepSpeedConfigError(
                f"serving.spec.max_draft must be >= 1, got {self.max_draft}"
            )
        if self.draft != "ngram":
            raise DeepSpeedConfigError(
                'serving.spec.draft must be "ngram" (host-side n-gram / '
                f"prompt-lookup), got {self.draft!r}"
            )
        if int(self.ngram_n) < 1:
            raise DeepSpeedConfigError(
                f"serving.spec.ngram_n must be >= 1, got {self.ngram_n}"
            )


@dataclass
class FleetConfig:
    """"serving.fleet" section — the disaggregated, replicated serving
    tier (deepspeed_tpu/serving/fleet/, docs/serving.md "Fleet"). A
    :class:`~deepspeed_tpu.serving.fleet.Router` owns a fleet-level
    bounded admission gate and dispatches requests across ``replicas``
    data-parallel ServingEngine replicas (one process, shared params),
    with prefix-cache-aware routing over the chained-crc32 block keys,
    optional DistServe-style prefill/decode disaggregation (dedicated
    prefill replicas hand finished prefills' KV to decode replicas as a
    page transfer), session affinity and load shedding. Correctness
    anchor: ANY routing of a trace replays token-for-token equal to a
    single-replica serial replay (the deterministic per-request RNG
    chain), including across a prefill→decode handoff."""

    enabled: bool = False
    replicas: int = 2            # data-parallel ServingEngine replicas
    prefill_replicas: int = 0    # of those, dedicated prefill replicas
                                 # (0 = every replica serves mixed
                                 # prefill+decode; > 0 needs serving.paged
                                 # — the KV handoff is a page transfer)
    routing: str = "prefix"      # prefix | least_loaded | round_robin
                                 # ("prefix" routes to the replica whose
                                 # PrefixCache holds the longest matching
                                 # block chain, falling back to load)
    affinity: bool = True        # session_id -> replica stickiness (a
                                 # session's KV reuse stays local)
    queue_limit: int = 0         # fleet-wide shed threshold: total queued
                                 # across replicas at admission; 0 = only
                                 # the per-replica bounds shed
    shed_ttft_p95_s: float = 0.0  # shed new arrivals while the fleet's
                                 # recent p95 TTFT exceeds this; 0 = off
    prefix_balance_slack: int = -1  # cache-locality vs load-balance
                                 # trade: a prefix match only wins while
                                 # the matched replica's load exceeds the
                                 # idlest replica's by at most this many
                                 # requests (a fully-shared system prompt
                                 # must not pile the whole fleet's
                                 # traffic on one replica); -1 = auto
                                 # (max(1, max_slots // 2))

    ROUTING_POLICIES = ("prefix", "least_loaded", "round_robin")

    def validate(self) -> None:
        if int(self.replicas) < 1:
            raise DeepSpeedConfigError(
                f"serving.fleet.replicas must be >= 1, got {self.replicas}"
            )
        if int(self.prefill_replicas) < 0:
            raise DeepSpeedConfigError(
                "serving.fleet.prefill_replicas must be >= 0, got "
                f"{self.prefill_replicas}"
            )
        if int(self.prefill_replicas) >= int(self.replicas):
            raise DeepSpeedConfigError(
                f"serving.fleet.prefill_replicas {self.prefill_replicas} "
                f"must be < replicas {self.replicas}: every prefill "
                "replica hands its KV to a decode replica, so at least "
                "one decode replica must exist"
            )
        if self.routing not in self.ROUTING_POLICIES:
            raise DeepSpeedConfigError(
                "serving.fleet.routing must be one of "
                f"{'|'.join(self.ROUTING_POLICIES)}, got {self.routing!r}"
            )
        if int(self.queue_limit) < 0:
            raise DeepSpeedConfigError(
                "serving.fleet.queue_limit must be >= 0 (0 = per-replica "
                f"bounds only), got {self.queue_limit}"
            )
        if float(self.shed_ttft_p95_s) < 0:
            raise DeepSpeedConfigError(
                "serving.fleet.shed_ttft_p95_s must be >= 0 (0 = off), "
                f"got {self.shed_ttft_p95_s}"
            )
        if int(self.prefix_balance_slack) < -1:
            raise DeepSpeedConfigError(
                "serving.fleet.prefix_balance_slack must be >= -1 "
                f"(-1 = auto), got {self.prefix_balance_slack}"
            )


@dataclass
class ServingConfig:
    """"serving" section — the continuous-batching runtime
    (deepspeed_tpu/serving/). Parity: DeepSpeed-MII / FastGen's
    continuous batching + Dynamic SplitFuse scheduling, TPU-native: one
    jitted step of fixed shape [max_slots, token_budget] serves arbitrary
    arrival patterns with zero recompiles after warmup."""

    enabled: bool = False
    max_slots: int = 8           # concurrent in-flight requests (KV slots)
    token_budget: int = 64       # tokens processed per engine step (the
                                 # SplitFuse chunk width; prompts longer
                                 # than this prefill across steps)
    queue_limit: int = 64        # bounded admission queue; 0 = unbounded
    request_timeout_s: float = 60.0   # queued longer than this → EVICTED
    eviction_backoff_s: float = 1.0   # retry-after hint: backoff * 2**attempts
    max_tokens: int = 1024       # per-request prompt+output cap (slot KV
                                 # capacity; clamped to model max_seq_len)
    kv_cache_dtype: str = "auto"  # auto | bf16 | bfloat16 | int8
    paged: Any = False           # block-paged KV arena (vLLM / FastGen
                                 # blocked-KV): a global page pool + per-slot
                                 # page tables replaces the contiguous
                                 # [max_slots, capacity] regions. bool |
                                 # "auto" (measured knob-default table;
                                 # forced True under fleet disaggregation)
    page_size: int = 16          # tokens per KV page (paged mode)
    num_pages: int = 0           # physical pages in the pool; 0 = auto
                                 # (max_slots * pages_per_slot — no
                                 # overcommit). Lower it to overcommit HBM;
                                 # shardplan prices the pool (R6)
    prefix_cache: bool = True    # hash-of-prefix → shared read-only pages
                                 # with refcounts + copy-on-write (paged
                                 # mode only)
    host_pages: int = 0          # tiered KV (ISSUE 18): pinned-host page
                                 # capacity behind the HBM pool. 0 = off;
                                 # > 0 demotes cold/evicted pages to host
                                 # (codec-compressed at rest) and promotes
                                 # them back through the in-step staging
                                 # buffer — paged mode only
    spill_codec: str = "fp32"    # at-rest codec for demoted pages
                                 # (comm/wires.py): fp32 = bitwise spill,
                                 # int8 = 4x smaller within the codec's
                                 # lane-wise bound; int8-quantized pools
                                 # spill their q arrays raw either way
    spill_dir: Optional[str] = None  # optional NVMe third tier: host-
                                 # overflowed pages stream to .bin files
                                 # here through ops/aio (same interface)
    moe_a2a: str = "auto"        # decode-shaped expert-exchange form for
                                 # MoE models served expert-parallel
                                 # (ep > 1): "stock" = GSPMD collectives
                                 # (the latency-bound small-step default),
                                 # "chunked" = the a2a_overlap chunked-
                                 # ppermute ring (hops hide under per-
                                 # chunk expert FFNs), "auto" = stock
                                 # below a per-hop payload threshold,
                                 # chunked above it. Bitwise-equal forms;
                                 # planner_search enumerates the axis.
    spec: SpecDecodeConfig = field(default_factory=SpecDecodeConfig)
                                 # speculative decoding (draft-then-verify
                                 # per decode slot); see SpecDecodeConfig
    fleet: FleetConfig = field(default_factory=FleetConfig)
                                 # replicated serving tier behind a
                                 # prefix-aware router; see FleetConfig

    def __post_init__(self):
        # _parse_dc is shallow: the nested "spec"/"fleet" sections arrive
        # as dicts both from DeepSpeedConfig and from ServingEngine(
        # serving={...}) — normalize here so every consumer sees the
        # dataclasses
        if isinstance(self.spec, bool) or self.spec == AUTO:
            # bare bool / "auto" spelling, like overlap_comm
            self.spec = SpecDecodeConfig(enabled=self.spec)
        if isinstance(self.spec, dict):
            self.spec = _parse_dc(SpecDecodeConfig, self.spec)
        if isinstance(self.fleet, dict):
            self.fleet = _parse_dc(FleetConfig, self.fleet)

    def pages_per_slot(self, max_tokens: Optional[int] = None) -> int:
        """Logical pages per slot: covers the per-request token cap plus
        the token_budget write margin (padded chunk tails never leave the
        mapped range). The ENGINE passes its clamped
        ``min(serving.max_tokens, model max)`` — that value is
        authoritative; without it this is the config-level upper bound."""
        span = int(max_tokens if max_tokens is not None
                   else self.max_tokens) + int(self.token_budget)
        return -(-span // int(self.page_size))

    def validate(self) -> None:
        if int(self.max_slots) < 1:
            raise DeepSpeedConfigError(
                f"serving.max_slots must be >= 1, got {self.max_slots}"
            )
        if int(self.token_budget) < 1:
            raise DeepSpeedConfigError(
                f"serving.token_budget must be >= 1, got {self.token_budget}"
            )
        if int(self.queue_limit) < 0:
            raise DeepSpeedConfigError(
                f"serving.queue_limit must be >= 0, got {self.queue_limit}"
            )
        if float(self.request_timeout_s) <= 0:
            raise DeepSpeedConfigError(
                "serving.request_timeout_s must be > 0, got "
                f"{self.request_timeout_s}"
            )
        if self.kv_cache_dtype not in ("auto", "int8", "bf16", "bfloat16"):
            raise DeepSpeedConfigError(
                "serving.kv_cache_dtype must be auto|bf16|bfloat16|int8, "
                f"got {self.kv_cache_dtype!r}"
            )
        if int(self.page_size) < 1:
            raise DeepSpeedConfigError(
                f"serving.page_size must be >= 1, got {self.page_size}"
            )
        if int(self.num_pages) < 0:
            raise DeepSpeedConfigError(
                f"serving.num_pages must be >= 0 (0 = auto), got "
                f"{self.num_pages}"
            )
        if self.moe_a2a not in ("auto", "stock", "chunked"):
            raise DeepSpeedConfigError(
                "serving.moe_a2a must be auto|stock|chunked, got "
                f"{self.moe_a2a!r}"
            )
        if int(self.host_pages) < 0:
            raise DeepSpeedConfigError(
                f"serving.host_pages must be >= 0 (0 = untiered), got "
                f"{self.host_pages}"
            )
        if int(self.host_pages) > 0 and self.paged is False:
            # "auto" is fine: resolve_auto_knobs runs before the engine
            # reads paged, and a tiered config forces it on there
            raise DeepSpeedConfigError(
                "serving.host_pages > 0 requires serving.paged: the host "
                "tier demotes/promotes PAGES of the block-paged arena "
                "(docs/serving.md \"KV tiering\")"
            )
        from .comm.wires import WIRE_NAMES
        if self.spill_codec not in WIRE_NAMES:
            raise DeepSpeedConfigError(
                f"serving.spill_codec must be one of "
                f"{'|'.join(WIRE_NAMES)}, got {self.spill_codec!r}"
            )
        _check_tristate("serving.spec.enabled", self.spec.enabled)
        _check_tristate("serving.paged", self.paged)
        if self.spec.enabled is True:
            # a disabled (or still-"auto") spec section is inert (the
            # engine maps it to max_draft = 0; "auto" only resolves on
            # when the budget fits), so its field ranges only matter on
            self.spec.validate()
            if int(self.spec.max_draft) + 1 > int(self.token_budget):
                raise DeepSpeedConfigError(
                    f"serving.spec.max_draft {self.spec.max_draft} needs "
                    f"max_draft + 1 <= token_budget {self.token_budget}: a "
                    "spec decode slot's verify window is max_draft + 1 rows "
                    "of the one fixed-shape step"
                )
        if self.fleet.enabled:
            self.fleet.validate()
            if int(self.fleet.prefill_replicas) > 0 and self.paged is False:
                # "auto" is fine here: resolve_auto_knobs forces paged on
                # under prefill/decode disaggregation before the engine
                # reads it
                raise DeepSpeedConfigError(
                    "serving.fleet.prefill_replicas > 0 requires "
                    "serving.paged: the prefill→decode KV handoff is a "
                    "page-table + page-payload transfer through the "
                    "block-paged arena (docs/serving.md)"
                )
        # NOTE: the num_pages liveness floor (num_pages >= pages_per_slot)
        # depends on the ENGINE-clamped max_tokens (min with the model's
        # max_seq_len), so ServingEngine.__init__ / trace_serving_step
        # enforce it — config validation alone cannot know the model.


@dataclass
class SteptraceConfig:
    """"steptrace" section — structured span tracing + the process-global
    metrics registry (profiling/steptrace.py, docs/observability.md).
    Host-side only: spans bracket dispatches and fence with
    ``jax.block_until_ready`` at close; nothing is traced inside jitted
    programs. MUST be zero-overhead when disabled — engines keep
    ``tracer = None`` and allocate no spans."""

    enabled: bool = False
    max_spans: int = 100_000   # registry bound (spans / async events /
                               # metric samples each); beyond it entries
                               # are counted in ``dropped``, not stored
    export_path: Optional[str] = None  # default target of
                               # ``engine.trace_export()`` (Chrome
                               # trace-event JSON)

    def validate(self) -> None:
        if int(self.max_spans) < 1:
            raise DeepSpeedConfigError(
                f"steptrace.max_spans must be >= 1, got {self.max_spans}"
            )


@dataclass
class HealthwatchConfig:
    """"healthwatch" section — always-on goodput accounting, anomaly
    watchdogs and the flight-recorder postmortem
    (profiling/healthwatch.py, docs/observability.md "healthwatch").
    Enabling healthwatch implies steptrace (the goodput buckets are
    classified off the engine's own spans). MUST be zero-overhead when
    disabled: engines keep ``healthwatch = None``, no ring buffer is
    allocated, no span is added and no device scalar is read — the loss
    trajectory is bitwise identical to a no-healthwatch engine."""

    enabled: bool = False
    ring_steps: int = 64       # flight-recorder depth: last K steps of
                               # spans/metrics/watchdog evaluations
    rules: Dict[str, Any] = field(default_factory=dict)
                               # per-rule overrides merged over
                               # healthwatch.DEFAULT_RULES, e.g.
                               # {"queue_depth_breach": {"threshold": 32,
                               #                         "action": "dump"}}
    export_path: Optional[str] = None  # metrics export target; "*.prom"
                               # writes Prometheus textfile format,
                               # anything else appends JSON-lines
    export_interval_s: float = 10.0    # min seconds between flushes
                               # (0 = flush every step)
    postmortem_path: Optional[str] = None  # default dump target
                               # (healthwatch_postmortem_<source>.json)
    install_signal_handler: bool = True  # chain SIGTERM + excepthook so
                               # preemption/crash still dumps evidence

    def validate(self) -> None:
        if int(self.ring_steps) < 1:
            raise DeepSpeedConfigError(
                f"healthwatch.ring_steps must be >= 1, got "
                f"{self.ring_steps}"
            )
        if float(self.export_interval_s) < 0:
            raise DeepSpeedConfigError(
                "healthwatch.export_interval_s must be >= 0, got "
                f"{self.export_interval_s}"
            )
        if not isinstance(self.rules, dict):
            raise DeepSpeedConfigError(
                f"healthwatch.rules must be a dict, got "
                f"{type(self.rules).__name__}"
            )
        from .profiling.healthwatch import (ACTIONS, DEFAULT_RULES,
                                            SEVERITIES)

        for name, params in self.rules.items():
            if name not in DEFAULT_RULES:
                raise DeepSpeedConfigError(
                    f"healthwatch.rules: unknown rule {name!r} "
                    f"(known: {sorted(DEFAULT_RULES)})"
                )
            if isinstance(params, bool):
                continue
            if not isinstance(params, dict):
                raise DeepSpeedConfigError(
                    f"healthwatch.rules.{name} must be a dict or bool, "
                    f"got {type(params).__name__}"
                )
            action = params.get("action")
            if action is not None and action not in ACTIONS:
                raise DeepSpeedConfigError(
                    f"healthwatch.rules.{name}.action must be one of "
                    f"{ACTIONS}, got {action!r}"
                )
            sev = params.get("severity")
            if sev is not None and sev not in SEVERITIES:
                raise DeepSpeedConfigError(
                    f"healthwatch.rules.{name}.severity must be one of "
                    f"{SEVERITIES}, got {sev!r}"
                )


@dataclass
class FlopsProfilerConfig:
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


@dataclass
class CommsLoggerConfig:
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = field(default_factory=list)


@dataclass
class MonitorConfig:
    tensorboard: Dict[str, Any] = field(default_factory=dict)
    wandb: Dict[str, Any] = field(default_factory=dict)
    csv_monitor: Dict[str, Any] = field(default_factory=dict)

    @property
    def enabled(self) -> bool:
        return any(
            bool(sec.get("enabled", False))
            for sec in (self.tensorboard, self.wandb, self.csv_monitor)
        )


@dataclass
class CurriculumConfig:
    enabled: bool = False
    curriculum_type: str = "seqlen"
    min_difficulty: int = 8
    max_difficulty: int = 1024
    schedule_type: str = "fixed_linear"
    schedule_config: Dict[str, Any] = field(default_factory=dict)


@dataclass
class RandomLTDConfig:
    enabled: bool = False
    total_layer_num: int = 0
    random_ltd_layer_num: int = 0
    random_ltd_layer_id: List[int] = field(default_factory=list)
    model_mask_name: Optional[str] = None
    model_type: str = "decoder"
    hidden_state_order: str = "batch_seq_dim"
    random_ltd_schedule: Dict[str, Any] = field(default_factory=dict)


@dataclass
class DataEfficiencyConfig:
    enabled: bool = False
    seed: int = 1234
    curriculum_learning: CurriculumConfig = field(default_factory=CurriculumConfig)
    random_ltd: RandomLTDConfig = field(default_factory=RandomLTDConfig)


@dataclass
class CompressionConfig:
    weight_quantization: Dict[str, Any] = field(default_factory=dict)
    activation_quantization: Dict[str, Any] = field(default_factory=dict)
    sparse_pruning: Dict[str, Any] = field(default_factory=dict)
    head_pruning: Dict[str, Any] = field(default_factory=dict)
    row_pruning: Dict[str, Any] = field(default_factory=dict)
    channel_pruning: Dict[str, Any] = field(default_factory=dict)
    layer_reduction: Dict[str, Any] = field(default_factory=dict)


@dataclass
class AutotuningConfig:
    enabled: bool = False
    fast: bool = True
    metric: str = "throughput"
    start_profile_step: int = 3
    end_profile_step: int = 5
    max_train_micro_batch_size_per_gpu: int = 64
    tuner_type: str = "gridsearch"


@dataclass
class ElasticityConfig:
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 20
    prefer_larger_batch: bool = True
    ignore_non_elastic_batch_info: bool = False
    version: float = 0.1


@dataclass
class ProgressiveLayerDropConfig:
    """Parity: "progressive_layer_drop" section (PLD paper schedule)."""

    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001


@dataclass
class SequenceParallelConfig:
    sp_size: int = 1
    mode: str = "ulysses"  # ulysses | ring


@dataclass
class CheckpointConfig:
    """Parity: the "checkpoint" section + the reference's pluggable
    checkpoint_engine (torch-native / nebula → native shard files / Orbax)."""

    engine: str = "native"  # native (shard .npy files) | orbax
    # async snapshot pipeline (runtime/ckpt): overlap the shard write with
    # the next step's math; the snapshot fence is the only synchronous cost
    async_save: bool = False
    # keep only the newest N committed tags (0 = keep everything)
    keep_last: int = 0
    # declared save cadence (every N global steps, 0 = no periodic saves):
    # the train loop's contract, and the amortization window the
    # ckpt_snapshot analytic stream prices against the roofline
    save_interval_steps: int = 0
    # SIGTERM (preemption) behavior once a save_dir is known:
    # "save" chains a final sync save in front of healthwatch's postmortem
    on_preempt: str = "save"  # save | none

    def validate(self) -> None:
        if self.engine not in ("native", "orbax"):
            raise DeepSpeedConfigError(
                f"checkpoint.engine must be 'native' or 'orbax', got {self.engine!r}"
            )
        if self.keep_last < 0:
            raise DeepSpeedConfigError(
                f"checkpoint.keep_last must be >= 0, got {self.keep_last}"
            )
        if self.save_interval_steps < 0:
            raise DeepSpeedConfigError(
                f"checkpoint.save_interval_steps must be >= 0, got "
                f"{self.save_interval_steps}"
            )
        if self.on_preempt not in ("save", "none"):
            raise DeepSpeedConfigError(
                f"checkpoint.on_preempt must be 'save' or 'none', got "
                f"{self.on_preempt!r}"
            )
        if self.async_save and self.engine == "orbax":
            raise DeepSpeedConfigError(
                "checkpoint.async_save requires the native engine (orbax "
                "keeps its own sync path)"
            )


@dataclass
class SparseAttentionConfig:
    """Parity: the "sparse_attention" ds_config section
    (deepspeed/ops/sparse_attention/sparsity_config.py schemas)."""

    mode: str = "none"  # none | dense | fixed | bigbird | bslongformer | variable
    block: int = 128  # TPU tile granularity (reference default 16 is GPU)
    num_local_blocks: int = 4
    num_global_blocks: int = 1
    num_sliding_window_blocks: int = 3
    num_random_blocks: int = 1
    global_block_indices: List[int] = field(default_factory=lambda: [0])

    def validate(self) -> None:
        modes = ("none", "dense", "fixed", "bigbird", "bslongformer", "variable")
        if self.mode not in modes:
            raise DeepSpeedConfigError(
                f"sparse_attention.mode must be one of {modes}, got {self.mode!r}"
            )


@dataclass
class TpuKernelsConfig:
    """TPU-native section: which Pallas kernels replace the XLA defaults.

    Parity: the reference's builder/op toggles (deepspeed/ops/op_builder) —
    where it JIT-compiles CUDA extensions, we flip registered Pallas kernels.
    "auto" resolves to on for TPU backends, off elsewhere (kernels still run
    under interpret=True in tests that force them on).
    """

    flash_attention: Any = AUTO  # auto | True | False
    fused_rmsnorm: Any = False  # covers rmsnorm AND layernorm; opt-in
    fused_adam: Any = False  # optax update already fuses into the step
    flash_block_q: int = 0  # 0 => kernel default
    flash_block_k: int = 0
    flash_block_q_bwd: int = 0  # 0 => inherit the fwd tile (dq/dkv kernels)
    flash_block_k_bwd: int = 0
    # vocab-chunked cross-entropy (ops/cross_entropy.py): the [B,S,V] logit
    # tensor never materializes. auto => on for TPU (tp=1 meshes only; the
    # vocab-parallel dense path handles tp>1)
    fused_ce: Any = AUTO
    ce_chunk: int = 4096

    def resolve(self, on_tpu: bool) -> "TpuKernelsConfig":
        def res(v):
            return on_tpu if v == AUTO else bool(v)

        return TpuKernelsConfig(
            flash_attention=res(self.flash_attention),
            fused_rmsnorm=res(self.fused_rmsnorm),
            fused_adam=res(self.fused_adam),
            flash_block_q=int(self.flash_block_q),
            flash_block_k=int(self.flash_block_k),
            flash_block_q_bwd=int(self.flash_block_q_bwd),
            flash_block_k_bwd=int(self.flash_block_k_bwd),
            fused_ce=res(self.fused_ce),
            ce_chunk=int(self.ce_chunk),
        )


class DeepSpeedConfig:
    """Parsed + validated ds_config. Accepts dict or json path.

    Parity: deepspeed.runtime.config.DeepSpeedConfig — including the
    batch-triangle resolution: train_batch_size =
    micro_batch_per_gpu * gradient_accumulation_steps * dp_world_size.
    """

    def __init__(self, config, dp_world_size: Optional[int] = None):
        if isinstance(config, (str, os.PathLike)):
            with open(config, "r") as f:
                config = json.load(f)
        if not isinstance(config, dict):
            raise DeepSpeedConfigError(f"config must be dict or path, got {type(config)}")
        self.raw: Dict[str, Any] = copy.deepcopy(config)
        d = self.raw

        # ---- batch triangle -------------------------------------------------
        self.train_batch_size = _get(d, "train_batch_size")
        self.train_micro_batch_size_per_gpu = _get(d, "train_micro_batch_size_per_gpu")
        self.gradient_accumulation_steps = _get(d, "gradient_accumulation_steps")
        self._dp_world_size = dp_world_size
        if dp_world_size is not None:
            self._resolve_batch_triangle(dp_world_size)

        self.steps_per_print = int(_get(d, "steps_per_print", 10) or 10)
        self.wall_clock_breakdown = bool(_get(d, "wall_clock_breakdown", False))
        self.dump_state = bool(_get(d, "dump_state", False))
        self.prescale_gradients = bool(_get(d, "prescale_gradients", False))
        self.gradient_predivide_factor = float(_get(d, "gradient_predivide_factor", 1.0) or 1.0)
        self.gradient_clipping = float(_get(d, "gradient_clipping", 0.0) or 0.0)
        self.communication_data_type = _get(d, "communication_data_type")
        self.seed = int(_get(d, "seed", 1234) or 1234)
        self.memory_breakdown = bool(_get(d, "memory_breakdown", False))
        self.zero_allow_untested_optimizer = bool(_get(d, "zero_allow_untested_optimizer", True))

        # ---- sections -------------------------------------------------------
        opt = d.get("optimizer") or {}
        self.optimizer = OptimizerConfig(
            type=str(opt.get("type", "adamw")).lower(), params=dict(opt.get("params", {}))
        )
        sched = d.get("scheduler") or {}
        self.scheduler = SchedulerConfig(
            type=(sched.get("type") or None), params=dict(sched.get("params", {}))
        )
        self.fp16 = _parse_dc(FP16Config, d.get("fp16"))
        self.bf16 = _parse_dc(BF16Config, d.get("bf16"))
        zo = dict(d.get("zero_optimization") or {})
        if "sub_group_prefetch" in zo:  # alias (sub_group_size kin)
            zo.setdefault("offload_double_buffer", zo["sub_group_prefetch"])
        zo["offload_double_buffer"] = _tristate(
            zo.get("offload_double_buffer", False)
        )
        if "zero3_prefetch" in zo:  # alias (the ROADMAP/ISSUE spelling)
            zo.setdefault("stage3_layer_prefetch", zo.pop("zero3_prefetch"))
        zo["stage3_layer_prefetch"] = _tristate(
            zo.get("stage3_layer_prefetch", False)
        )
        zo["offload_optimizer"] = _parse_dc(OffloadConfig, zo.get("offload_optimizer"))
        zo["offload_param"] = _parse_dc(OffloadConfig, zo.get("offload_param"))
        self.zero_config = _parse_dc(ZeroConfig, zo)
        self.activation_checkpointing = _parse_dc(
            ActivationCheckpointingConfig, d.get("activation_checkpointing")
        )
        pipe = dict(d.get("pipeline") or {})
        if "stages" not in pipe and "num_stages" in pipe:
            pipe["stages"] = pipe.pop("num_stages")
        self.pipeline = _parse_dc(PipelineConfig, pipe)
        self.topology = _parse_dc(TopologyConfig, d.get("topology"))
        self.moe = _parse_dc(MoEConfig, d.get("moe"))
        tp = dict(d.get("tensor_parallel") or {})
        if "autotp_size" in tp and "tp_size" not in tp:
            # alias only — the rest of the section (overlap_comm) survives
            tp["tp_size"] = tp.pop("autotp_size")
        oc = tp.get("overlap_comm")
        if isinstance(oc, bool) or oc == AUTO:
            # the spelling zero_optimization.overlap_comm users expect
            # ("auto" rides the same shorthand)
            oc = {"enabled": oc}
        tp["overlap_comm"] = _parse_dc(OverlapCommConfig, oc)
        self.tensor_parallel = _parse_dc(TensorParallelConfig, tp)
        self.serving = _parse_dc(ServingConfig, d.get("serving"))
        sp = d.get("sequence_parallel") or {}
        if "sequence_parallel_size" in d:
            sp.setdefault("sp_size", d["sequence_parallel_size"])
        self.sequence_parallel = _parse_dc(SequenceParallelConfig, sp)
        self.tpu_kernels = _parse_dc(TpuKernelsConfig, d.get("tpu_kernels"))
        self.sparse_attention = _parse_dc(
            SparseAttentionConfig, d.get("sparse_attention")
        )
        self.checkpoint = _parse_dc(CheckpointConfig, d.get("checkpoint"))
        self.steptrace = _parse_dc(SteptraceConfig, d.get("steptrace"))
        self.healthwatch = _parse_dc(HealthwatchConfig, d.get("healthwatch"))
        self.flops_profiler = _parse_dc(FlopsProfilerConfig, d.get("flops_profiler"))
        self.comms_logger = _parse_dc(CommsLoggerConfig, d.get("comms_logger"))
        self.monitor = MonitorConfig(
            tensorboard=dict(d.get("tensorboard") or {}),
            wandb=dict(d.get("wandb") or {}),
            csv_monitor=dict(d.get("csv_monitor") or {}),
        )
        de = dict(d.get("data_efficiency") or {})
        de_types = dict(de.get("data_routing") or {})
        cl = dict((de.get("data_sampling") or {}).get("curriculum_learning") or {})
        self.data_efficiency = DataEfficiencyConfig(
            enabled=bool(de.get("enabled", False)),
            seed=int(de.get("seed", 1234)),
            curriculum_learning=_parse_dc(CurriculumConfig, cl or d.get("curriculum_learning")),
            random_ltd=_parse_dc(RandomLTDConfig, de_types.get("random_ltd")),
        )
        self.compression = _parse_dc(CompressionConfig, d.get("compression_training"))
        self.autotuning = _parse_dc(AutotuningConfig, d.get("autotuning"))
        self.elasticity = _parse_dc(ElasticityConfig, d.get("elasticity"))
        self.progressive_layer_drop = _parse_dc(
            ProgressiveLayerDropConfig, d.get("progressive_layer_drop")
        )

        self._validate()

    # -- helpers --------------------------------------------------------------
    def _resolve_batch_triangle(self, dp_world_size: int) -> None:
        tb, mb, ga = (
            self.train_batch_size,
            self.train_micro_batch_size_per_gpu,
            self.gradient_accumulation_steps,
        )
        if tb is not None and mb is not None and ga is not None:
            if tb != mb * ga * dp_world_size:
                raise DeepSpeedConfigError(
                    f"train_batch_size {tb} != micro_batch {mb} * grad_accum {ga} * dp {dp_world_size}"
                )
        elif tb is not None and mb is not None:
            if tb % (mb * dp_world_size) != 0:
                raise DeepSpeedConfigError(
                    f"train_batch_size {tb} not divisible by micro_batch {mb} * dp {dp_world_size}"
                )
            ga = tb // (mb * dp_world_size)
        elif tb is not None and ga is not None:
            if tb % (ga * dp_world_size) != 0:
                raise DeepSpeedConfigError(
                    f"train_batch_size {tb} not divisible by grad_accum {ga} * dp {dp_world_size}"
                )
            mb = tb // (ga * dp_world_size)
        elif mb is not None:
            ga = ga or 1
            tb = mb * ga * dp_world_size
        elif tb is not None:
            ga = 1
            if tb % dp_world_size != 0:
                raise DeepSpeedConfigError(
                    f"train_batch_size {tb} not divisible by dp world size {dp_world_size}"
                )
            mb = tb // dp_world_size
        else:
            tb, mb, ga = dp_world_size, 1, 1
        self.train_batch_size, self.train_micro_batch_size_per_gpu = int(tb), int(mb)
        self.gradient_accumulation_steps = int(ga)

    def resolve_batch_sizes(self, dp_world_size: int) -> None:
        self._dp_world_size = dp_world_size
        self._resolve_batch_triangle(dp_world_size)

    def _validate(self) -> None:
        if self.fp16.enabled and self.bf16.enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")
        self.zero_config.validate()
        if self.gradient_clipping < 0:
            raise DeepSpeedConfigError("gradient_clipping must be >= 0")
        if self.pipeline.stages < 1:
            raise DeepSpeedConfigError("pipeline.stages must be >= 1")
        if self.pipeline.pipe_schedule not in ("1f1b", "gpipe"):
            raise DeepSpeedConfigError(
                "pipeline.pipe_schedule must be 1f1b or gpipe, got "
                f"{self.pipeline.pipe_schedule!r}"
            )
        if self.zero_config.stage >= 2 and self.pipeline.stages > 1:
            # reference: PipelineEngine asserts ZeRO-2/3 unsupported with pipeline
            raise DeepSpeedConfigError(
                "ZeRO stages 2/3 are incompatible with pipeline parallelism (reference parity)"
            )
        if self.progressive_layer_drop.enabled and self.pipeline.stages > 1:
            raise DeepSpeedConfigError(
                "progressive_layer_drop is not supported with pipeline "
                "parallelism (the stochastic layer gate would have to cross "
                "pp stage boundaries)"
            )
        self.tensor_parallel.overlap_comm.validate()
        self.moe.overlap_a2a.validate()
        self.serving.validate()
        if (
            self.tensor_parallel.overlap_comm.enabled is True
            and self.pipeline.stages > 1
        ):
            # "auto" is exempt: resolve_auto_knobs gates the flip on
            # pp <= 1, so an auto knob can never resolve into this state
            raise DeepSpeedConfigError(
                "tensor_parallel.overlap_comm is not supported with pipeline "
                "parallelism (the decomposed matmul is a full-manual "
                "shard_map and cannot nest inside the pipeline's manual "
                "schedule); the runtime also falls back per call site"
            )
        if self.moe.overlap_a2a.enabled is True and self.pipeline.stages > 1:
            raise DeepSpeedConfigError(
                "moe.overlap_a2a is not supported with pipeline parallelism "
                "(the decomposed all-to-all is a full-manual shard_map and "
                "cannot nest inside the pipeline's manual schedule); the "
                "runtime also falls back per call site"
            )
        if self.data_efficiency.random_ltd.enabled and self.pipeline.stages > 1:
            raise DeepSpeedConfigError(
                "random_ltd is not supported with pipeline parallelism (the "
                "token-subset gather would cross pp stage boundaries)"
            )
        self.activation_checkpointing.validate()
        self.sparse_attention.validate()
        self.topology.validate()
        self.checkpoint.validate()
        self.steptrace.validate()
        self.healthwatch.validate()
        if self.sparse_attention.mode not in ("none", "dense") and (
            self.sequence_parallel.sp_size > 1
        ):
            raise DeepSpeedConfigError(
                "sparse_attention is not supported together with sequence "
                "parallelism (the block layout assumes full-sequence tiles)"
            )
        if self.sparse_attention.mode not in ("none", "dense") and (
            self.data_efficiency.random_ltd.enabled
        ):
            raise DeepSpeedConfigError(
                "sparse_attention is not supported together with random_ltd "
                "(LTD layers attend over gathered token subsets whose length "
                "is not block-aligned with the sparse layout)"
            )
        if self.sequence_parallel.mode not in ("ulysses", "ring"):
            raise DeepSpeedConfigError(
                f"sequence_parallel.mode must be 'ulysses' or 'ring', got "
                f"{self.sequence_parallel.mode!r}"
            )

    # dtype policy ------------------------------------------------------------
    @property
    def compute_dtype(self):
        import jax.numpy as jnp

        if self.bf16.enabled:
            return jnp.bfloat16
        if self.fp16.enabled:
            return jnp.float16
        return jnp.float32

    @property
    def zero_enabled(self) -> bool:
        return self.zero_config.stage > 0

    def to_dict(self) -> Dict[str, Any]:
        return copy.deepcopy(self.raw)


def _parse_dc(cls, section):
    """Build dataclass ``cls`` from dict ``section``, ignoring unknown keys."""
    section = dict(section or {})
    names = {f.name for f in cls.__dataclass_fields__.values()} if hasattr(cls, "__dataclass_fields__") else set()
    known = {}
    for k, v in section.items():
        if k in names:
            known[k] = v
    try:
        return cls(**known)
    except TypeError as e:  # pragma: no cover
        raise DeepSpeedConfigError(f"bad config section for {cls.__name__}: {e}")


# ---------------------------------------------------------------------------
# "auto" knob resolution against the measured per-topology default table
# (analysis/cost/knob_defaults.json, emitted by tools/autoplan.py
# --campaign). ONE resolver for every overlap/wire/spec/paged knob,
# generalizing the point solutions that grew one at a time
# (resolved_grad_wire, kv_cache_dtype-"auto", resolve_moe_a2a_form):
# initialize() and ServingEngine.__init__ call it once, before any
# engine code reads the knobs, so a knob is either a concrete value or
# a deliberate downstream "auto" (wires / kv dtype / serving moe_a2a
# keep their existing late resolution when the table has no fresh row).
#
# Trust model: a table value only applies when (a) the knob is
# applicable to this config (an inapplicable flip silently stays off —
# a dp-only mesh can't use tp overlap no matter what a row says),
# (b) the row's recorded evidence is FRESH — its (predicted, measured)
# pair still sits inside the generation's drift band (drift.check_pair)
# and its recorded jax major.minor matches the running one. Stale rows
# resolve to the conservative off default with a one-time named
# warning, never a crash.
# ---------------------------------------------------------------------------

#: every knob path resolve_auto_knobs() owns (docs/autotuning.md
#: "Campaign mode" documents the schema these dotted paths key into)
AUTO_KNOB_PATHS = (
    "tensor_parallel.overlap_comm",
    "zero_optimization.offload_double_buffer",
    "zero_optimization.stage3_layer_prefetch",
    "zero_optimization.grad_wire",
    "zero_optimization.param_wire",
    "moe.overlap_a2a",
    "serving.spec",
    "serving.paged",
    "serving.moe_a2a",
    "serving.kv_cache_dtype",
)

_AUTO_WARNED: set = set()


def _jax_major_minor() -> Optional[str]:
    try:
        import jax

        return ".".join(str(jax.__version__).split(".")[:2])
    except Exception:  # noqa: BLE001
        return None


def _warn_once(key: str, msg: str) -> None:
    if key in _AUTO_WARNED:
        return
    _AUTO_WARNED.add(key)
    try:
        from .utils.logging import logger

        logger.warning(msg)
    except Exception:  # noqa: BLE001 — never block resolution on logging
        pass


def _fresh_table_value(row, provenance: str, path: str, gen: str):
    """(value, source) for one knob path out of a table row, applying the
    staleness gate; (None, reason) when the row has nothing fresh."""
    from .analysis.cost import drift

    if row is None or path not in (row.get("knobs") or {}):
        return None, "miss"
    value = row["knobs"][path]
    jx = row.get("jax")
    now = _jax_major_minor()
    if jx and now and jx != now:
        _warn_once(
            f"{path}:{provenance}:jax",
            f"auto knob {path}: {provenance} was measured on jax {jx} but "
            f"this is jax {now} — using the conservative off default "
            "(re-run tools/autoplan.py --campaign to refresh the table)",
        )
        return None, f"stale-jax:{provenance}"
    ev = (row.get("evidence") or {}).get(path) or {}
    pred = ev.get("predicted_step_s")
    meas = ev.get("measured_step_s")
    if meas is not None:
        verdict = drift.check_pair(pred, meas, row.get("gen", gen))
        if not verdict["ok"]:
            _warn_once(
                f"{path}:{provenance}:band",
                f"auto knob {path}: {provenance} evidence is outside the "
                f"{verdict['gen']} drift band {verdict['band']} (ratio "
                f"{verdict['ratio']}) — using the conservative off default "
                "(re-run tools/autoplan.py --campaign to refresh the table)",
            )
            return None, f"stale-band:{provenance}"
    return value, provenance


def resolve_auto_knobs(cfg, hardware=None, model_config=None,
                       topology=None, table=None) -> Dict[str, Dict[str, Any]]:
    """Resolve every ``"auto"`` knob on ``cfg`` in place from the measured
    knob-default table; returns (and attaches as ``cfg.auto_resolution``)
    a ``{path: {"value", "source"}}`` report.

    ``cfg`` is a :class:`DeepSpeedConfig` (training + serving knobs) or a
    bare :class:`ServingConfig` (serving knobs only). Explicit values are
    never touched — only knobs spelled ``"auto"`` resolve, and only to a
    table value that is applicable AND fresh (see the module comment);
    everything else lands on the conservative off default. Idempotent:
    a second call is a no-op because nothing is "auto" anymore (except
    the deliberately-deferred wire/kv/moe_a2a autos, whose downstream
    resolution is itself deterministic).
    """
    report: Dict[str, Dict[str, Any]] = {}
    full = isinstance(cfg, DeepSpeedConfig)
    srv = cfg.serving if full else (cfg if isinstance(cfg, ServingConfig)
                                    else None)

    def pending() -> List[str]:
        p = []
        if full:
            if cfg.tensor_parallel.overlap_comm.enabled == AUTO:
                p.append("tensor_parallel.overlap_comm")
            zc = cfg.zero_config
            if zc.offload_double_buffer == AUTO:
                p.append("zero_optimization.offload_double_buffer")
            if zc.stage3_layer_prefetch == AUTO:
                p.append("zero_optimization.stage3_layer_prefetch")
            if zc.grad_wire == AUTO:
                p.append("zero_optimization.grad_wire")
            if zc.param_wire == AUTO:
                p.append("zero_optimization.param_wire")
            if cfg.moe.overlap_a2a.enabled == AUTO:
                p.append("moe.overlap_a2a")
        if srv is not None:
            if srv.spec.enabled == AUTO:
                p.append("serving.spec")
            if srv.paged == AUTO:
                p.append("serving.paged")
            if srv.moe_a2a == AUTO:
                p.append("serving.moe_a2a")
            if srv.kv_cache_dtype == AUTO:
                p.append("serving.kv_cache_dtype")
        return p

    pend = pending()
    if not pend:
        if full:
            cfg.auto_resolution = report
        return report

    from .analysis.cost import hardware as hwmod

    hw = hardware if hardware is not None else hwmod.HardwareModel.detect()
    tab = table if table is not None else hwmod.load_knob_table()
    row, provenance = hwmod.lookup_knob_row(
        tab, hw.gen, hwmod.topology_key(topology), hwmod.model_class(model_config)
    )

    def fresh(path):
        return _fresh_table_value(row, provenance, path, hw.gen)

    def resolve_bool(path: str, applicable: bool, apply) -> None:
        value, source = fresh(path)
        if not applicable:
            apply(False)
            report[path] = {"value": False, "source": "inapplicable"}
            return
        if isinstance(value, bool):
            apply(value)
            report[path] = {"value": value, "source": source}
        else:
            apply(False)
            report[path] = {"value": False, "source": f"off-default:{source}"}

    if full:
        tp_live = int(cfg.tensor_parallel.tp_size) > 1
        pp_live = int(cfg.pipeline.stages) > 1
        zc = cfg.zero_config
        moe = cfg.moe
        if "tensor_parallel.overlap_comm" in pend:
            resolve_bool(
                "tensor_parallel.overlap_comm",
                tp_live and not pp_live,
                lambda v: setattr(cfg.tensor_parallel.overlap_comm,
                                  "enabled", v),
            )
        if "zero_optimization.offload_double_buffer" in pend:
            resolve_bool(
                "zero_optimization.offload_double_buffer",
                bool(zc.offload_optimizer.enabled),
                lambda v: setattr(zc, "offload_double_buffer", v),
            )
        if "zero_optimization.stage3_layer_prefetch" in pend:
            resolve_bool(
                "zero_optimization.stage3_layer_prefetch",
                int(zc.stage) == 3,
                lambda v: setattr(zc, "stage3_layer_prefetch", v),
            )
        if "moe.overlap_a2a" in pend:
            resolve_bool(
                "moe.overlap_a2a",
                bool(moe.enabled) and int(moe.ep_size) > 1 and not pp_live,
                lambda v: setattr(moe.overlap_a2a, "enabled", v),
            )
        # wire codecs: a fresh measured codec wins; otherwise "auto"
        # survives for the legacy resolution (resolved_grad_wire /
        # resolved_param_wire — zero_quantized_* spellings), which is
        # already deterministic and fp32-conservative
        for path, attr, applicable in (
            ("zero_optimization.grad_wire", "grad_wire", int(zc.stage) >= 1),
            ("zero_optimization.param_wire", "param_wire",
             int(zc.stage) == 3),
        ):
            if path not in pend:
                continue
            value, source = fresh(path)
            if (applicable and isinstance(value, str)
                    and value in ZeroConfig._WIRE_CODECS and value != AUTO):
                setattr(zc, attr, value)
                report[path] = {"value": value, "source": source}
            else:
                report[path] = {
                    "value": getattr(zc, f"resolved_{attr}")(),
                    "source": "legacy-auto" if applicable
                    else "inapplicable",
                }

    if srv is not None:
        if "serving.spec" in pend:
            budget_fits = (int(srv.spec.max_draft) + 1
                           <= int(srv.token_budget))
            resolve_bool(
                "serving.spec",
                budget_fits,
                lambda v: setattr(srv.spec, "enabled", v),
            )
        if "serving.paged" in pend:
            if srv.fleet.enabled and int(srv.fleet.prefill_replicas) > 0:
                # prefill/decode disaggregation REQUIRES the paged arena
                # (the KV handoff is a page-table transfer) — forced on
                # regardless of the table
                srv.paged = True
                report["serving.paged"] = {
                    "value": True, "source": "forced:fleet-disaggregation"
                }
            elif int(srv.host_pages) > 0:
                # KV tiering demotes/promotes PAGES of the block-paged
                # arena; a host tier without a paged pool is meaningless
                # — forced on regardless of the table
                srv.paged = True
                report["serving.paged"] = {
                    "value": True, "source": "forced:kv-tiering"
                }
            else:
                resolve_bool("serving.paged", True,
                             lambda v: setattr(srv, "paged", v))
        if "serving.moe_a2a" in pend:
            value, source = fresh("serving.moe_a2a")
            if value in ("stock", "chunked"):
                srv.moe_a2a = value
                report["serving.moe_a2a"] = {"value": value, "source": source}
            else:
                # the payload-threshold resolution in serving/engine.py
                # (resolve_moe_a2a_form) stays authoritative
                report["serving.moe_a2a"] = {"value": AUTO,
                                             "source": "threshold-auto"}
        if "serving.kv_cache_dtype" in pend:
            value, source = fresh("serving.kv_cache_dtype")
            if value in ("int8", "bf16", "bfloat16"):
                srv.kv_cache_dtype = value
                report["serving.kv_cache_dtype"] = {"value": value,
                                                    "source": source}
            else:
                # engine default (bf16 KV) stays authoritative
                report["serving.kv_cache_dtype"] = {"value": AUTO,
                                                    "source": "engine-auto"}

    if full:
        cfg.auto_resolution = report
    return report
