from .engine import InferenceEngine, init_inference  # noqa: F401
