"""Inference engine: TP-sharded serving with a static KV cache.

Parity: deepspeed/inference/engine.py (InferenceEngine) + deepspeed
__init__.init_inference. The reference swaps torch modules for fused CUDA
blocks ("kernel injection") and walks an eager token loop; TPU-native:

- one jitted prefill (full-prompt forward that fills the cache) and one
  jitted ``lax.while_loop`` decode program — every step identical shapes,
  compiled once, KV cache donated through the loop;
- tensor parallelism is the model's partition_specs placed on the mesh
  (weights sharded column/row over tp); XLA inserts the serving
  collectives;
- ``replace_with_kernel_inject`` maps to selecting the Pallas flash
  attention path for prefill (the decode matvec is already MXU-shaped);
- ``dtype=int8`` / quantize flags use ops/quantizer.py weight-only block
  quantization; decode-shaped projections run the Pallas streaming kernel
  (ops/pallas/quantized_matmul.py) so HBM reads int8/int4 bytes — the
  dequantize-then-dot alternative materializes full-width weights every
  decode step (measured 3x slower at 410M).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..comm.topology import MeshTopology, ParallelDims
from ..models.decoding import forward_with_cache, init_cache
from ..models.sharding import use_topology
from ..ops.quantizer import (PackedWeight, pack_quantize_blockwise,
                             packed_partition_specs, packed_sharding_ok,
                             quantize_dequantize)
from ..utils.logging import log_dist


def _align_cache(n: int, mult: int = 128) -> int:
    """KV-cache capacity rounded up so the Pallas decode kernel always has
    an aligned block divisor (a 132-row cache has none and silently fell
    back to the XLA path — observed in the r4 decode bench logs). Capacity
    padding rows are position-masked by cache_len, so results are
    unchanged; the cost is a few KB of HBM per layer."""
    return max(-(-n // mult) * mult, mult)


def _bucket_prompt(n: int, mult: int = 32) -> int:
    """Prompt-width bucket for the compile cache. The KV cache itself
    keeps the 128 alignment (_align_cache — the Pallas block contract);
    the PREFILL WIDTH has no such constraint, so a finer granule wastes
    less padded prefill compute on short prompts while still collapsing
    the ragged-length neighborhood onto a handful of programs."""
    return _align_cache(n, mult)


def apply_repetition_penalty(logits, seen, penalty, active=None):
    """HF-convention repetition penalty: for tokens in ``seen`` [B, V],
    positive logits divide by the penalty, negative multiply.

    ``active`` ([B] or [B, 1] bool, optional) masks ragged-batch rows:
    padded/inactive slots keep their logits untouched instead of
    attending whatever stale ``seen`` garbage their row holds.

    ``logits`` may also be a [B, S, V] verify WINDOW (the serving step's
    speculative form): the one [B, V] ``seen`` matrix then applies to
    every window position — same elementwise math, so the S = 1 window
    is bitwise the 2-D path."""
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    mask = seen if logits.ndim == 2 else seen[:, None, :]
    if active is not None:
        shape = (-1, 1) if logits.ndim == 2 else (-1, 1, 1)
        mask = mask & jnp.reshape(active, shape)
    return jnp.where(mask, penalized, logits)


def init_inference(
    model,
    tensor_parallel: Optional[Dict[str, Any]] = None,
    tp_size: int = 1,
    ep_size: int = 1,
    dtype=jnp.bfloat16,
    replace_with_kernel_inject: bool = False,
    quantize_bits: Optional[int] = None,
    max_tokens: int = 1024,
    kv_cache_dtype: str = "auto",
    draft_model=None,
    draft_params=None,
    checkpoint=None,
    topology: Optional[MeshTopology] = None,
    params=None,
    rng: Optional[jax.Array] = None,
    matvec_max_rows: Optional[int] = None,
    config: Optional[Dict[str, Any]] = None,
    **kwargs,
) -> "InferenceEngine":
    """Parity: deepspeed.init_inference(model, tp_size, dtype, ...).

    ``matvec_max_rows`` (also accepted as ``config={"matvec_max_rows": N}``
    — the "inference.matvec_max_rows" knob) widens the row threshold under
    which packed int8/int4 projections take the Pallas streaming matvec:
    e.g. the k=9 speculative verify window is 10 rows and needs ≥ 10.

    ``ep_size`` > 1 serves a MoE model EXPERT-PARALLEL: the mesh grows an
    ``ep`` axis (tp_size · ep_size devices), expert banks shard E over it
    per the model's partition specs, and the decode MLP's expert exchange
    runs over that axis (docs/serving.md "MoE serving").
    """
    if config:
        if matvec_max_rows is None and "matvec_max_rows" in config:
            matvec_max_rows = int(config["matvec_max_rows"])
        extras = sorted(set(config) - {"matvec_max_rows"})
        if extras:
            log_dist(
                f"init_inference: ignoring unsupported config keys {extras}"
            )
    if kwargs:
        log_dist(
            f"init_inference: ignoring unsupported arguments {sorted(kwargs)} "
            f"(reference-surface kwargs with no TPU equivalent)"
        )
    overlap_comm = None
    if tensor_parallel:
        tp_size = tensor_parallel.get("tp_size", tp_size)
        if tensor_parallel.get("overlap_comm"):
            # same section schema as the training config's
            # tensor_parallel.overlap_comm (decomposed collective matmul);
            # a bare boolean means {"enabled": bool}
            from ..config import OverlapCommConfig, _parse_dc

            oc = tensor_parallel["overlap_comm"]
            if isinstance(oc, bool):
                oc = {"enabled": oc}
            overlap_comm = _parse_dc(OverlapCommConfig, oc)
            overlap_comm.validate()
    if checkpoint is not None:
        if params is not None:
            raise ValueError("pass either checkpoint= or params=, not both")
        from ..runtime.checkpointing import load_params

        template = jax.eval_shape(
            lambda k: model.init(k), jax.random.PRNGKey(0)
        )
        params = load_params(checkpoint, template)
    if dtype in ("int8", jnp.int8):
        dtype = jnp.bfloat16
        quantize_bits = quantize_bits or 8
    elif dtype == "int4":  # weight-only 4-bit (reference: quantize_bits=4)
        dtype = jnp.bfloat16
        quantize_bits = quantize_bits or 4
    if topology is None:
        ep_size = max(int(ep_size), 1)
        n = max(tp_size, 1) * ep_size
        topology = MeshTopology(
            dims=ParallelDims(tp=tp_size, ep=ep_size),
            devices=jax.devices()[:n],
        )
    return InferenceEngine(
        model,
        topology=topology,
        dtype=dtype,
        kernel_inject=replace_with_kernel_inject,
        quantize_bits=quantize_bits,
        max_tokens=max_tokens,
        kv_cache_dtype=kv_cache_dtype,
        draft_model=draft_model,
        draft_params=draft_params,
        params=params,
        rng=rng,
        matvec_max_rows=matvec_max_rows,
        overlap_comm=overlap_comm,
    )


class InferenceEngine:
    def __init__(
        self,
        model,
        topology: MeshTopology,
        dtype=jnp.bfloat16,
        kernel_inject: bool = False,
        quantize_bits: Optional[int] = None,
        max_tokens: int = 1024,
        kv_cache_dtype: str = "auto",
        draft_model=None,
        draft_params=None,
        params=None,
        rng: Optional[jax.Array] = None,
        matvec_max_rows: Optional[int] = None,
        overlap_comm=None,
    ):
        self.model = model
        self.config = model.config
        self.topology = topology
        self.dtype = dtype
        self.max_tokens = min(max_tokens, self.config.max_seq_len)
        self.kernel_inject = kernel_inject
        # int8 KV cache: halves KV HBM for long-context serving; per-token
        # scales dequantize at read (in-kernel on the Pallas decode path)
        if kv_cache_dtype not in ("auto", "int8", "bf16", "bfloat16"):
            raise ValueError(
                f"kv_cache_dtype must be auto|bf16|bfloat16|int8, got "
                f"{kv_cache_dtype!r}"
            )
        self.kv_cache_quantized = kv_cache_dtype == "int8"
        self.kv_cache_storage_dtype = (
            jnp.bfloat16 if kv_cache_dtype in ("bf16", "bfloat16") else dtype
        )
        # "kernel injection" parity (reference: replace_with_kernel_inject
        # swaps torch blocks for fused CUDA blocks, csrc/transformer/
        # inference). The TPU translation is a fused *composition*, not one
        # mega-kernel: Pallas flash prefill + Pallas cached-KV decode
        # attention (models/decoding.py) + Pallas rmsnorm, with XLA fusing
        # the matmul/elementwise chains between them. Scoped via context
        # managers so other engines' kernel choices are untouched.
        on_tpu = topology.mesh.devices.flat[0].platform == "tpu"
        # inference.matvec_max_rows: per-engine streaming-matvec threshold
        # (None → kernel default). Applied as a trace-time scope below so
        # engines with different settings in one process don't fight.
        self.matvec_max_rows = (
            int(matvec_max_rows) if matvec_max_rows is not None else None
        )
        # decomposed TP collective matmul for the serving projections
        # (tensor_parallel.overlap_comm — parallel/tensor_overlap.py): the
        # decode out-projections take the feature-scatter ring (S=1 cannot
        # seq-shard), prefill takes the Megatron-SP pair when shapes divide
        self.tp_overlap = (
            overlap_comm
            if (
                overlap_comm is not None
                and getattr(overlap_comm, "enabled", False)
                and topology.tp_size > 1
            )
            else None
        )
        if self.tp_overlap is not None:
            from ..parallel.tensor_overlap import static_widths_divide

            reason = None
            if quantize_bits:
                # every big projection is a PackedWeight — the ring
                # dispatchers always fall back for packed leaves, so the
                # scope would only buy residual-layout churn
                reason = f"packed int{quantize_bits} weights take the " \
                         "streaming-matvec path, not the rings"
            elif not static_widths_divide(self.config, topology.tp_size):
                reason = (
                    "a projection width does not divide "
                    f"tp={topology.tp_size}"
                )
            if reason:
                log_dist(
                    f"tensor_parallel.overlap_comm disabled: {reason}"
                )
                self.tp_overlap = None

        def _impl_scopes():
            from contextlib import ExitStack

            from ..ops.pallas.quantized_matmul import matvec_max_rows_scope
            from ..parallel.tensor_overlap import overlap_scope

            stack = ExitStack()
            stack.enter_context(matvec_max_rows_scope(self.matvec_max_rows))
            stack.enter_context(overlap_scope(self.tp_overlap))
            if kernel_inject:
                from ..ops.attention import attention_impl
                from ..ops.normalization import pallas_rmsnorm_scope

                stack.enter_context(attention_impl("auto"))  # flash on TPU
                stack.enter_context(pallas_rmsnorm_scope(on_tpu))
            return stack

        self._impl_ctx = _impl_scopes

        tp_specs = (
            model.partition_specs(topology)
            if hasattr(model, "partition_specs")
            else None
        )
        if params is None:
            params = model.init(
                rng if rng is not None else jax.random.PRNGKey(0), dtype=dtype
            )
        cast = lambda a: (
            a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a
        )
        params = jax.tree.map(cast, params)
        if quantize_bits:
            params = self._quantize_weights(params, quantize_bits, tp_specs)
        if tp_specs is not None and topology.world_size > 1:
            mesh = topology.mesh

            def to_sharding(spec, leaf):
                if isinstance(leaf, PackedWeight):
                    qs, ss = packed_partition_specs(spec, len(leaf.shape))
                    return PackedWeight(
                        NamedSharding(mesh, qs), NamedSharding(mesh, ss),
                        leaf.shape, leaf.bits, leaf.dtype, leaf.nibbles,
                        leaf.pspec,
                    )
                return NamedSharding(mesh, spec)

            shardings = jax.tree.map(
                to_sharding,
                tp_specs,
                params,
                is_leaf=lambda x: isinstance(x, P),
            )
            params = jax.device_put(params, shardings)
        else:
            # commit to the serving device: params= may arrive as host
            # numpy arrays (e.g. exported from a training engine), and an
            # uncommitted tree re-uploads per jitted call — on a relayed
            # backend that is tens of seconds of transfer per generate()
            params = jax.device_put(params, topology.devices[0])
        self.params = params
        # speculative decoding (greedy, B=1): a draft proposes, the main
        # model verifies a whole window per forward. draft_model="ngram"
        # self-drafts by n-gram lookup in the token buffer (prompt-lookup
        # decoding) — zero extra parameters, zero extra HBM streams
        self.draft_model = draft_model
        self.draft_params = None
        self.spec_ngram_n = 3  # context length for the "ngram" draft
        if isinstance(draft_model, str):
            if draft_model != "ngram":
                raise ValueError(
                    f"draft_model={draft_model!r}: the only string draft is "
                    '"ngram" (prompt-lookup self-drafting); otherwise pass '
                    "a model"
                )
        elif draft_model is not None:
            if draft_model.config.vocab_size != self.config.vocab_size:
                raise ValueError(
                    "draft model must share the main model's vocabulary "
                    f"({draft_model.config.vocab_size} != "
                    f"{self.config.vocab_size})"
                )
            if draft_params is None:
                draft_params = draft_model.init(
                    jax.random.PRNGKey(1), dtype=dtype
                )
            self.draft_params = jax.tree.map(cast, draft_params)
        self._decode_fns: Dict[Any, Any] = {}
        # recompile observability (serving warmup): programs are keyed on
        # bucketed (B, prompt, total) shapes (prompt at 32, total at the
        # cache's 128), so this counts one compile per shape bucket — a
        # replayed ragged trace stays flat after warmup instead of
        # growing per exact length
        self.num_compiles = 0
        n_params = sum(
            int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params)
        )
        log_dist(
            f"InferenceEngine: {n_params / 1e6:.1f}M params, dtype="
            f"{jnp.dtype(dtype).name}, tp={topology.tp_size}, "
            f"quant={quantize_bits or 'off'}, kernel_inject={kernel_inject}"
        )

    def _quantize_weights(self, params, bits: int, tp_specs=None):
        """Weight-only block quantization of the big matmul weights.

        PACKED storage (ops/quantizer.PackedWeight) — HBM holds int8/int4
        + scales; the PackedWeight leaves flow into the jitted decode
        loop intact, where each projection runs the Pallas streaming
        kernel (ops/pallas/quantized_matmul.packed_proj) that dequantizes
        in VMEM — HBM traffic stays at the quantized byte count instead
        of a per-step full-width dequant temp. Under tp>1 the packed pair
        shards along
        the weight's own partition spec (packed_partition_specs: blocks
        stay whole — the contraction dim is stored (G, B) and only G
        shards), and the leaf remembers that spec (PackedWeight.pspec) so
        packed_proj's full-manual shard_map wrapper can run the streaming
        kernel PER SHARD — under tp>1 the decode matvec streams quantized
        bytes instead of dequantizing full-width weights every step (a
        bare pallas_call has no GSPMD partitioning rule, which is why the
        wrapper exists; leaves without a usable pspec still fall back to
        dequantize-then-dot). A
        leaf whose block/nibble geometry does not divide over the mesh
        falls back to the fake-quant roundtrip (numerics identical either
        way — same q/dq values), logged by name."""
        big = {"wq", "wk", "wv", "wo", "wi", "wg"}
        sharded = tp_specs is not None and self.topology.world_size > 1

        def q(path, leaf, spec=None):
            name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
            if name not in big or leaf.ndim < 2:
                return leaf
            if leaf.ndim > 3:
                # MoE expert banks [L, E, d, f] PACK since ISSUE 14: the
                # decode dispatch path consumes PackedWeight natively
                # (moe/sharded_moe._expert_proj → the per-expert Pallas
                # streaming matvec, per-shard under ep/tp meshes) and the
                # training/apply path dequantizes once (bitwise the old
                # fake-quant roundtrip — same q/dq values)
                if leaf.ndim != 4 or (
                    sharded and not self._expert_bank_sharding_ok(
                        leaf.shape, spec, bits
                    )
                ):
                    log_dist(
                        f"quantize: expert bank {name} falls back to "
                        f"fake-quant (geometry {leaf.shape} does not pack "
                        f"over mesh spec {spec})"
                    )
                    return quantize_dequantize(leaf, block=128, bits=bits)
                pw = pack_quantize_blockwise(leaf, block=128, bits=bits)
                if sharded:
                    pw.pspec = spec
                return pw
            if sharded and not packed_sharding_ok(
                leaf.shape, spec, self.topology.mesh, block=128, bits=bits
            ):
                log_dist(
                    f"quantize: {name} falls back to fake-quant (packed "
                    f"geometry {leaf.shape} does not divide over mesh "
                    f"spec {spec})"
                )
                return quantize_dequantize(leaf, block=128, bits=bits)
            pw = pack_quantize_blockwise(leaf, block=128, bits=bits)
            if sharded:
                pw.pspec = spec  # trace-time spec for the shard_map wrapper
            return pw

        if sharded:
            return jax.tree_util.tree_map_with_path(q, params, tp_specs)
        return jax.tree_util.tree_map_with_path(q, params)

    def _expert_bank_sharding_ok(self, shape, spec, bits: int) -> bool:
        """Whether a stacked expert bank [L, E, d, f] packs under this
        mesh spec: the trailing (d, f) dims obey the shared
        packed_sharding_ok block/nibble rules, expert shards keep whole
        experts (E divides the dim -3 extent), and the stacked layer dim
        stays unsharded (a scanned per-layer slice must be a whole
        bank)."""
        from ..ops.quantizer import _axis_size

        if spec is None:
            return True
        if not packed_sharding_ok(
            shape, spec, self.topology.mesh, block=128, bits=bits
        ):
            return False
        s = tuple(spec) + (None,) * (len(shape) - len(tuple(spec)))
        if any(e is not None for e in s[:-3]):
            return False
        try:
            e_extent = _axis_size(self.topology.mesh, s[-3])
        except KeyError:
            return False
        return shape[-3] % max(e_extent, 1) == 0

    # ------------------------------------------------- planner metadata
    def analytic_streams(self, batch: int = 1, seq: Optional[int] = None,
                         include_potential: bool = False):
        """Declared analytic streams, same schema as the training
        engine's (the shared planner / comms-logger / R8 contract). The
        serving engine has one: the decomposed-TP ring hops of the
        forward projections (no backward — the fwd wire figure)."""
        streams = {}
        if self.tp_overlap is not None:
            from ..parallel.tensor_overlap import ring_wire_bytes_per_step

            ring = ring_wire_bytes_per_step(
                self.config,
                self.topology,
                self.tp_overlap,
                batch=batch,
                seq=seq if seq is not None else self.config.max_seq_len,
                itemsize=jnp.dtype(self.dtype).itemsize,
            )
            if ring:
                # ring carries a fwd+bwd "bytes_per_step"; the serving
                # stream is fwd-only, so the overrides come AFTER the
                # spread
                streams["tp_ring"] = {
                    **ring,
                    "kind": "ici",
                    "bytes_per_step": ring["fwd_bytes_per_step"],
                    "per_device_bytes_per_step": ring["fwd_bytes_per_step"],
                    "overlapped": True,
                }
        return streams

    # -------------------------------------------------------------- forward
    def forward(self, input_ids):
        """Plain logits forward (no cache) — reference engine __call__."""
        if not hasattr(self, "_jit_forward"):  # jit once, not per call
            self._jit_forward = jax.jit(
                lambda p, ids: self.model.apply(
                    p, ids, dtype=self.dtype
                )
            )
        with use_topology(self.topology), self._impl_ctx():
            logits, _ = self._jit_forward(self.params, jnp.asarray(input_ids))
        return logits

    __call__ = forward

    # -------------------------------------------------- speculative decode
    def _build_spec_decode(self, prompt_bucket: int, total_bucket: int,
                           k: int):
        """Greedy speculative decoding, B=1 (the latency-bound serving case).

        Reference-era DeepSpeed ships this in its serving stack; TPU-native
        form: ONE jitted program — a small draft model proposes k-1 tokens
        autoregressively, the main model scores the whole window in a single
        cached forward, and the longest matching prefix (+1 "bonus" token
        from the verifier) is accepted. Greedy acceptance makes the output
        token-for-token IDENTICAL to plain greedy decoding of the main
        model — the oracle the tests assert — while the main model runs
        ~new_tokens/(accepted+1) times instead of new_tokens times.

        Cache discipline: every verify writes its full k-token window at the
        accepted position, so entries from rejected drafts are always
        overwritten before any later query can attend them (windows are
        contiguous and advance by >= 1 per round).

        draft_model="ngram" replaces the draft forward with a vectorized
        n-gram lookup over the token buffer (prompt-lookup decoding): the
        most recent earlier occurrence of the last n tokens supplies the
        proposed continuation, falling back to the buffer's stale verifier
        predictions past ``pos``. Proposal cost is a few VPU ops — and
        since batch-1 decode is HBM-bound, verifying k tokens streams the
        same weight bytes as decoding one, so every accepted draft token
        is nearly free throughput.

        Since ISSUE 9 the draft lookup and the acceptance math live in
        ``serving/spec.py`` (ngram_propose / longest_accepted_prefix /
        clamp_advance_at_eos) — ONE implementation shared with the slot
        engine's batched verify; this builder is the thin lockstep
        caller.

        Shapes are BUCKETED (``prompt_bucket`` at 32, ``total_bucket`` at
        the cache's 128); the actual ``prompt_len``/``total_len`` ride as
        traced operands, so every request whose lengths round to the same
        buckets reuses one compiled program. Padding beyond the real prompt holds the eos
        fill; its cache writes sit beyond the frontier and are rewritten
        before any query can attend them.
        """
        from ..serving.spec import (clamp_advance_at_eos,
                                    longest_accepted_prefix, ngram_propose)

        cfg = self.config
        ngram = isinstance(self.draft_model, str)
        m = int(self.spec_ngram_n)
        dcfg = None if ngram else self.draft_model.config
        # margin so last-round writes stay in-bounds
        total_alloc = total_bucket + k

        def spec_generate(params, dparams, tokens_buf, prompt_len, total_len,
                          eos_id):
            main_cache = init_cache(
                cfg, 1, _align_cache(total_alloc),
                self.kv_cache_storage_dtype,
                quantized=self.kv_cache_quantized,
            )
            draft_cache = (
                jnp.zeros((), jnp.int32) if ngram
                else init_cache(dcfg, 1, _align_cache(total_alloc), self.dtype)
            )
            prompt = tokens_buf[:, :prompt_bucket]
            logits, main_cache = forward_with_cache(
                cfg, params, prompt,
                main_cache, 0, dtype=self.dtype
            )
            # last REAL prompt position (the bucket tail is padding)
            last = lax.dynamic_slice_in_dim(logits, prompt_len - 1, 1, 1)
            n0 = jnp.argmax(last[:, 0], axis=-1)  # token at position P
            tokens_buf = lax.dynamic_update_slice(
                tokens_buf, n0[:, None], (0, prompt_len)
            )
            if not ngram:
                _, draft_cache = forward_with_cache(
                    dcfg, dparams, prompt, draft_cache, 0, dtype=self.dtype
                )

            def cond(state):
                _, _, _, pos, done, _ = state
                return (pos < total_len - 1) & ~done

            def body(state):
                tokens_buf, main_cache, draft_cache, pos, done, rounds = state
                start_tok = lax.dynamic_slice(tokens_buf, (0, pos), (1, 1))
                if ngram:
                    # shared prompt-lookup draft (serving/spec.py): the
                    # no-match fallback slice past ``pos`` reads the
                    # previous rejected window's stale verifier
                    # predictions — free, plausible proposals
                    cand = jnp.concatenate(
                        [start_tok.astype(jnp.int32),
                         ngram_propose(tokens_buf[0], pos, k - 1, m)[None, :]],
                        axis=1,
                    )
                else:
                    # --- draft k-1 tokens autoregressively --------------
                    # the loop runs k steps (one past the last proposal):
                    # the extra step's token is discarded but its forward
                    # writes the draft-cache row at pos+k-1, which a fully-
                    # accepting round (adv = k) would otherwise leave as
                    # zeros forever — collapsing acceptance for the rest
                    # of the generation
                    cand0 = jnp.zeros((1, k + 1), jnp.int32)
                    cand0 = lax.dynamic_update_slice(cand0, start_tok, (0, 0))

                    def dstep(i, carry):
                        cand, dcache = carry
                        tok = lax.dynamic_slice(cand, (0, i), (1, 1))
                        dlog, dcache = forward_with_cache(
                            dcfg, dparams, tok, dcache, pos + i,
                            dtype=self.dtype
                        )
                        nxt = jnp.argmax(dlog[:, -1], axis=-1).astype(jnp.int32)
                        cand = lax.dynamic_update_slice(
                            cand, nxt[:, None], (0, i + 1)
                        )
                        return cand, dcache

                    cand, draft_cache = lax.fori_loop(
                        0, k, dstep, (cand0, draft_cache)
                    )
                    cand = cand[:, :k]  # the k-th draft is never proposed
                # --- verify the whole window in one main forward --------
                # packed weights stream via the Pallas matvec kernel only
                # while the verify window fits the engine's matvec row
                # threshold (default 8; inference.matvec_max_rows): the
                # banked k=9 sweep's 10-row verify takes the
                # dequantize-then-MXU path at the default — same numerics,
                # but full-width HBM traffic for that forward. Set
                # matvec_max_rows >= k+1 to keep it streaming; making that
                # the default needs an on-chip win at 10+ rows first
                # (unmeasured).
                vlog, main_cache = forward_with_cache(
                    cfg, params, cand,
                    main_cache, pos, dtype=self.dtype
                )
                targets = jnp.argmax(vlog, axis=-1).astype(jnp.int32)  # [1,k]
                # shared acceptance math (serving/spec.py): longest
                # matching draft prefix + the verifier bonus token, the
                # advance clamped at an emitted eos
                n_acc = longest_accepted_prefix(
                    cand[0, 1:] == targets[0, : k - 1]
                )
                adv, has_eos = clamp_advance_at_eos(
                    targets[0], n_acc + 1, eos_id
                )
                tokens_buf = lax.dynamic_update_slice(
                    tokens_buf, targets, (0, pos + 1)
                )
                return (
                    tokens_buf, main_cache, draft_cache, pos + adv,
                    done | has_eos, rounds + 1,
                )

            done0 = (n0 == eos_id)[0]
            tokens_buf, _, _, pos, _, rounds = lax.while_loop(
                cond,
                body,
                (tokens_buf, main_cache, draft_cache,
                 jnp.asarray(prompt_len), done0, jnp.asarray(0)),
            )
            # positions past the last accepted token hold rejected-window
            # garbage: restore the eos fill the buffer started with
            fill = jnp.where(eos_id >= 0, eos_id, 0)
            idx = jnp.arange(total_alloc)[None, :]
            tokens_buf = jnp.where(idx <= pos, tokens_buf, fill)
            # rounds = verifier forwards: acceptance observability (a perfect
            # draft needs ceil((new_tokens-1)/k) rounds). The caller trims
            # the bucketed buffer to the real total_len.
            return tokens_buf, rounds

        return jax.jit(spec_generate)

    # ------------------------------------------------------------- generate
    def _build_decode(self, B: int, prompt_bucket: int, total_bucket: int):
        """One decode program per BUCKETED (B, prompt, total) shape
        (prompt at 32, total at the cache's 128): the exact
        ``prompt_len``/``total_len`` are traced operands, so the whole
        ragged-length neighborhood shares a compile (the serving warmup
        stops scaling with distinct request lengths)."""
        cfg = self.config

        def prefill(params, tokens_buf, prompt_len):
            cache = init_cache(
                cfg, B, _align_cache(total_bucket),
                self.kv_cache_storage_dtype,
                quantized=self.kv_cache_quantized,
            )
            prompt = tokens_buf[:, :prompt_bucket]
            logits, cache = forward_with_cache(
                cfg, params, prompt, cache,
                0, dtype=self.dtype
            )
            # last REAL prompt position (the bucket tail is eos padding)
            last = lax.dynamic_slice_in_dim(logits, prompt_len - 1, 1, 1)
            return last[:, 0], cache

        def sample(logits, key, temperature, top_k, top_p):
            logits = logits / jnp.maximum(temperature, 1e-6)
            if top_k > 0:
                kth = lax.top_k(logits, top_k)[0][:, -1][:, None]
                logits = jnp.where(logits < kth, -1e30, logits)
            if top_p < 1.0:
                # nucleus: keep the smallest prefix of the sorted distribution
                # whose mass reaches top_p (the top-1 token always survives)
                sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
                probs = jax.nn.softmax(sorted_desc, axis=-1)
                cum = jnp.cumsum(probs, axis=-1)
                keep = (cum - probs) < top_p
                keep = keep.at[:, 0].set(True)  # top-1 survives even top_p=0
                kth = jnp.min(
                    jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True
                )
                logits = jnp.where(logits < kth, -1e30, logits)
            greedy = jnp.argmax(logits, axis=-1)
            sampled = jax.random.categorical(key, logits, axis=-1)
            return jnp.where(temperature == 0.0, greedy, sampled)

        def generate(params, tokens_buf, prompt_len, total_len, rng,
                     temperature, top_k, top_p, rep_penalty, use_penalty,
                     eos_id):
            V = cfg.vocab_size
            rows = jnp.arange(B)

            def step_sample(logits, seen, key, live=None):
                if use_penalty:
                    logits = apply_repetition_penalty(
                        logits, seen, rep_penalty, active=live
                    )
                return sample(logits, key, temperature, top_k, top_p)

            # seen-token mask carried through the loop: built once from the
            # prompt, then one O(B) scatter per generated token (not a full
            # (B,V) rebuild per step)
            if use_penalty:
                prompt_live = jnp.arange(total_bucket)[None, :] < prompt_len
                seen = jnp.zeros((B, V), jnp.bool_).at[
                    rows[:, None], tokens_buf
                ].max(prompt_live)
            else:
                seen = jnp.zeros((B, 1), jnp.bool_)  # unused placeholder

            last_logits, cache = prefill(params, tokens_buf, prompt_len)
            key, rng = jax.random.split(rng)
            nxt = step_sample(last_logits, seen, key)
            if use_penalty:
                seen = seen.at[rows, nxt].set(True)
            tokens_buf = lax.dynamic_update_slice(
                tokens_buf, nxt[:, None], (0, prompt_len)
            )
            done = nxt == eos_id

            def cond(state):
                _, _, pos, _, done, _ = state
                return (pos < total_len - 1) & ~jnp.all(done)

            def body(state):
                tokens_buf, cache, pos, rng, done, seen = state
                tok = lax.dynamic_slice(tokens_buf, (0, pos), (B, 1))
                # packed weights stay packed: each projection streams
                # int8/int4 from HBM through the Pallas matvec kernel
                logits, cache = forward_with_cache(
                    self.config, params,
                    tok, cache, pos, dtype=self.dtype
                )
                key, rng = jax.random.split(rng)
                nxt = step_sample(logits[:, -1], seen, key, live=~done)
                nxt = jnp.where(done, jnp.full_like(nxt, eos_id), nxt)
                if use_penalty:
                    # ragged-batch hazard fix: rows already done emit
                    # forced eos padding — never book it as "seen" (and
                    # never scatter a negative eos sentinel)
                    seen = seen.at[rows, jnp.clip(nxt, 0, V - 1)].max(~done)
                tokens_buf = lax.dynamic_update_slice(
                    tokens_buf, nxt[:, None], (0, pos + 1)
                )
                done = done | (nxt == eos_id)
                return (tokens_buf, cache, pos + 1, rng, done, seen)

            tokens_buf, _, _, _, _, _ = lax.while_loop(
                cond, body,
                (tokens_buf, cache, jnp.asarray(prompt_len), rng, done, seen),
            )
            return tokens_buf

        # top_k/top_p/use_penalty static (each gates a sort/scatter); the
        # penalty VALUE and the real lengths stay traced so sweeping them
        # doesn't recompile
        return jax.jit(generate, static_argnums=(6, 7, 9))

    def generate(
        self,
        input_ids,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        repetition_penalty: float = 1.0,
        eos_token_id: int = -1,
        num_draft_tokens: int = 4,
        rng: Optional[jax.Array] = None,
    ):
        """Greedy (temperature=0) or top-k / top-p sampled decoding, with
        an optional HF-convention repetition penalty. With a draft model
        attached (init_inference(draft_model=...)), greedy B=1 generation
        runs speculatively: ``num_draft_tokens`` proposals per verifier
        forward, output identical to plain greedy.

        Returns [B, prompt + max_new_tokens] token ids (eos-padded).
        """
        ids = np.asarray(input_ids)
        B, prompt_len = ids.shape
        if max_new_tokens <= 0:
            # nothing to generate: echo the prompt (the decode program would
            # otherwise clamp its first write onto the last prompt token)
            return ids.astype(np.int32)
        if prompt_len >= self.max_tokens:
            raise ValueError(
                f"prompt length {prompt_len} leaves no room to generate under "
                f"max_tokens={self.max_tokens} (model max_seq_len="
                f"{self.config.max_seq_len}); truncate the prompt or raise "
                f"max_tokens"
            )
        total_len = min(prompt_len + max_new_tokens, self.max_tokens)
        # bucketed program shapes (prompt at 32, total at the cache's 128):
        # the exact lengths ride as traced operands, so a ragged arrival
        # trace compiles once per bucket
        pb, tb = _bucket_prompt(prompt_len), _align_cache(total_len)
        fill = eos_token_id if eos_token_id >= 0 else 0
        speculative = (
            self.draft_model is not None
            and temperature == 0.0
            and B == 1
            and repetition_penalty == 1.0
            and num_draft_tokens >= 1
        )
        if speculative:
            k = int(num_draft_tokens) + 1  # window = drafts + bonus slot
            key = ("spec", pb, tb, k)
            if key not in self._decode_fns:
                self.num_compiles += 1
                log_dist(
                    f"inference compile #{self.num_compiles}: spec decode "
                    f"bucket (prompt<={pb}, total<={tb}, k={k})"
                )
                self._decode_fns[key] = self._build_spec_decode(pb, tb, k)
            buf = np.full((1, tb + k), fill, dtype=np.int32)
            buf[:, :prompt_len] = ids
            with use_topology(self.topology), self._impl_ctx():
                out, rounds = self._decode_fns[key](
                    self.params, self.draft_params, jnp.asarray(buf),
                    prompt_len, total_len, eos_token_id,
                )
            self.last_spec_rounds = int(rounds)  # verifier calls this generate
            return np.asarray(out)[:, :total_len]
        statics = (top_k, float(top_p), float(repetition_penalty) != 1.0)
        key = (B, pb, tb) + statics
        if key not in self._decode_fns:
            self.num_compiles += 1
            log_dist(
                f"inference compile #{self.num_compiles}: decode bucket "
                f"(B={B}, prompt<={pb}, total<={tb}, "
                f"top_k={statics[0]}, top_p={statics[1]}, "
                f"penalty={statics[2]})"
            )
            self._decode_fns[key] = self._build_decode(B, pb, tb)
        buf = np.full((B, tb), fill, dtype=np.int32)
        buf[:, :prompt_len] = ids
        with use_topology(self.topology), self._impl_ctx():
            out = self._decode_fns[key](
                self.params,
                jnp.asarray(buf),
                prompt_len,
                total_len,
                rng if rng is not None else jax.random.PRNGKey(0),
                jnp.asarray(temperature, jnp.float32),
                statics[0],
                statics[1],
                jnp.asarray(repetition_penalty, jnp.float32),
                statics[2],
                eos_token_id,
            )
        return np.asarray(out)[:, :total_len]
