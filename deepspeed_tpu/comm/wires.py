"""Wire codecs + wire-collective forms — the first-class comm layer.

PRs 3 and 10 proved int8 quantized-hop wires and decomposed rings inside
individual call sites (the TP projection rings, the MoE a2a, the stage-3
prefetch); the codec logic lived buried in those modules and could not
reach the biggest remaining wires — the ZeRO gradient reduce-scatter and
the stage-3 parameter all-gathers. This module factors it out (ZeRO++
qgZ/hgZ, arXiv 2306.10209; EQuARX's topology-aware split):

**Codecs** (:data:`CODECS`): fp32 / bf16 / int8 / int4, each a
:class:`WireCodec` declaring its wire bytes per element and a documented,
property-tested error bound. Quantized codecs use symmetric lane-wise
scales — ONE fp32 scale per lane, quantizing over the row axis — the
exact scheme the TP rings and ZeRO++ gather shipped with (bitwise
compatible: ``quantize_lanewise`` here IS the old
``runtime/zero/quantized._quantize_lanewise``). Canonical payload shape
is ``[blocks, rows, lanes]``; scales are ``[blocks, 1, lanes]``.

===== ====================== ==========================================
codec wire bytes / element   |decode(encode(x)) - x| bound (per lane)
===== ====================== ==========================================
fp32  itemsize (identity)    0 (bitwise)
bf16  2                      ``|x| * 2**-8`` (bitwise for bf16 inputs)
int8  1 (+ 4 per lane scale) ``scale / 2``, scale = max(amax,1e-12)/127
int4  0.5 (+ 4 per lane)     ``scale / 2``, scale = max(amax,1e-12)/7
===== ====================== ==========================================

Zero and denormal lanes are covered by the ``max(amax, 1e-12)`` floor:
a lane whose magnitudes all sit below the floor rounds to zero codes and
the bound still holds (|x| <= 1e-12/254 is false only when |x| <= bound
anyway — tests/test_wires.py pins this on actual denormals).

**Wire collectives**: composable forms built on the qgZ all-to-all
formulation — values quantize at most ONCE, the reduction runs AFTER
dequant, in f32, in pinned member order (so the fp32-codec wire is the
bitwise full-width baseline the oracles compare against):

- :func:`rs_wire_local` — reduce-scatter: split the local array into one
  block per member, encode per block (per-(block, lane) scales), one
  all-to-all, dequant, f32 member-order accumulate.
- :func:`ag_wire_local` — all-gather: encode the local shard once, one
  all-gather of payload + scales, dequant on arrival (error is one
  fake-quant round trip, hop-count independent).
- :func:`rs_wire_hier_local` / :func:`ag_wire_hier_local` — the 2-hop
  hierarchical variants over a FACTORED mesh axis pair (outer, inner),
  e.g. ``("dp", "fsdp")``: the intra-group hop runs full width over the
  fast inner links, the inter-group hop moves codec bytes over the slow
  outer links (ZeRO++ hgZ / EQuARX). Block ordering is outer-major —
  exactly the layout ``PartitionSpec((outer, inner))`` assigns — so the
  hierarchical form drops into any sharding the single-hop form serves.

The ``*_local`` forms run INSIDE an existing ``shard_map`` (the ZeRO
runtimes' partial-manual per-leaf maps, the rings' full-manual maps);
:func:`all_gather_wire` / :func:`reduce_scatter_wire` are global-array
wrappers (full-manual shard_map over the whole mesh) — the CPU-mesh
oracle surface and the documented reference semantics.

Every payload that crosses the wire routes through
``collectives._record`` so the comms logger sees the REAL (encoded)
bytes, and the engine prices each wire statically through
``analytic_streams()`` (:func:`rs_wire_nbytes` / :func:`ag_wire_nbytes`)
so shardplan R8 sees the win before anything compiles. shardlint R5
keeps the f32 master path honest: codec decode is ALWAYS to f32 before
any accumulate — the master update never consumes sub-32-bit data
directly (docs/wires.md).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from . import collectives

AxisName = Union[str, Tuple[str, ...]]

__all__ = [
    "WireCodec",
    "CODECS",
    "WIRE_NAMES",
    "get_codec",
    "quantize_lanewise",
    "dequantize_lanewise",
    "ag_wire_local",
    "rs_wire_local",
    "ag_wire_hier_local",
    "rs_wire_hier_local",
    "all_gather_wire",
    "reduce_scatter_wire",
    "ag_wire_nbytes",
    "rs_wire_nbytes",
    "hier_rs_nbytes",
    "hier_ag_nbytes",
    "hier_axes",
]


# ------------------------------------------------------------------- codecs
class WireCodec:
    """One wire format. Canonical operand shape is ``[B, R, L]`` (blocks,
    rows, lanes); quantized codecs reduce over R with one fp32 scale per
    (block, lane). ``wire_bits`` is the payload width per element
    (scales priced separately by :meth:`payload_nbytes`)."""

    name: str = "?"
    wire_bits: int = 32
    lossless: bool = False

    def encode(self, x3: jax.Array) -> Dict[str, jax.Array]:
        raise NotImplementedError

    def decode(self, payload: Dict[str, jax.Array], rows: int,
               dtype) -> jax.Array:
        raise NotImplementedError

    def bound(self, x3: jax.Array) -> jax.Array:
        """Per-element upper bound on ``|decode(encode(x)) - x|`` (f32,
        broadcastable against x3) — the documented, property-tested
        contract of the codec."""
        raise NotImplementedError

    def payload_nbytes(self, blocks: int, rows: int, lanes: int,
                       itemsize: int = 4) -> int:
        """Wire bytes of one encoded ``[blocks, rows, lanes]`` operand,
        INCLUDING the fp32 lane scales quantized codecs ride with.
        Polymorphic — a codec that doesn't declare its bytes cannot be
        priced and must not silently inherit another codec's formula."""
        raise NotImplementedError

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"WireCodec({self.name})"


class _Fp32(WireCodec):
    """Identity wire — the full-width baseline. Bitwise for any input
    dtype (a bf16 compute array stays bf16 on the wire: 'fp32' names the
    POLICY — never truncate — not a cast)."""

    name = "fp32"
    wire_bits = 32
    lossless = True

    def encode(self, x3):
        return {"x": x3}

    def decode(self, payload, rows, dtype):
        return payload["x"].astype(dtype)

    def bound(self, x3):
        return jnp.zeros((), jnp.float32)

    def payload_nbytes(self, blocks, rows, lanes, itemsize=4):
        return blocks * rows * lanes * itemsize


class _Bf16(WireCodec):
    """Truncate-to-bf16 wire. Round-to-nearest-even: error <= |x| * 2**-8
    for normal f32 inputs (+1e-38 absolute slack for the denormal tail);
    bitwise identity when the input is already bf16."""

    name = "bf16"
    wire_bits = 16

    def encode(self, x3):
        return {"x": x3.astype(jnp.bfloat16)}

    def decode(self, payload, rows, dtype):
        return payload["x"].astype(jnp.float32).astype(dtype)

    def bound(self, x3):
        # the absolute slack covers the denormal tail and must itself be
        # a NORMAL f32 (1.2e-38 > min normal ~1.175e-38): a denormal
        # literal would flush to zero under XLA FTZ and the bound would
        # read 0 exactly where it needs the slack
        return jnp.abs(x3.astype(jnp.float32)) * (2.0 ** -8) + 1.2e-38

    def payload_nbytes(self, blocks, rows, lanes, itemsize=4):
        return blocks * rows * lanes * 2


def _lane_scale(x3: jax.Array, levels: float) -> jax.Array:
    """[B, 1, L] symmetric scale over the row axis — the csrc/quantization
    layout the repo has shipped since PR 3 (amax/levels with a 1e-12
    floor so all-zero lanes stay finite)."""
    amax = jnp.max(jnp.abs(x3.astype(jnp.float32)), axis=1, keepdims=True)
    return jnp.maximum(amax, 1e-12) / levels


class _Int8(WireCodec):
    """int8 symmetric lane-wise wire (ZeRO++ qwZ/qgZ). amax maps to
    exactly +/-127 so clipping never adds error: the bound is pure
    rounding, scale/2 per element."""

    name = "int8"
    wire_bits = 8

    def encode(self, x3):
        scale = _lane_scale(x3, 127.0)
        q = jnp.clip(
            jnp.round(x3.astype(jnp.float32) / scale), -127, 127
        ).astype(jnp.int8)
        return {"q": q, "scale": scale}

    def decode(self, payload, rows, dtype):
        return (
            payload["q"].astype(jnp.float32) * payload["scale"]
        ).astype(dtype)

    def bound(self, x3):
        return _lane_scale(x3, 127.0) * 0.5

    def payload_nbytes(self, blocks, rows, lanes, itemsize=4):
        return blocks * rows * lanes + blocks * lanes * 4

    def quantize(self, x3):
        """(q, scale) without the dict wrapper — the 2-D lanewise entry
        the TP rings and ZeRO++ gather use directly."""
        p = self.encode(x3)
        return p["q"], p["scale"]


class _Int4(WireCodec):
    """int4 symmetric lane-wise wire, genuinely bit-packed: two [-7, 7]
    codes per int8 byte along the row axis (odd row counts pad one zero
    row — decode slices it back off). Half the int8 wire at double the
    rounding step."""

    name = "int4"
    wire_bits = 4

    def encode(self, x3):
        scale = _lane_scale(x3, 7.0)
        q = jnp.clip(
            jnp.round(x3.astype(jnp.float32) / scale), -7, 7
        ).astype(jnp.int8)
        r = q.shape[1]
        if r % 2:
            q = jnp.pad(q, ((0, 0), (0, 1), (0, 0)))
        lo = q[:, 0::2]
        hi = q[:, 1::2]
        packed = (lo & jnp.int8(0x0F)) | (hi << 4)
        return {"q": packed.astype(jnp.int8), "scale": scale}

    def decode(self, payload, rows, dtype):
        p = payload["q"]
        # arithmetic shifts sign-extend the two's-complement nibbles
        lo = (p << 4).astype(jnp.int8) >> 4
        hi = p >> 4
        q = jnp.stack([lo, hi], axis=2).reshape(
            p.shape[0], 2 * p.shape[1], p.shape[2]
        )[:, :rows]
        return (q.astype(jnp.float32) * payload["scale"]).astype(dtype)

    def bound(self, x3):
        return _lane_scale(x3, 7.0) * 0.5

    def payload_nbytes(self, blocks, rows, lanes, itemsize=4):
        # two codes per byte, rows padded to even, fp32 lane scales
        return blocks * (-(-rows // 2)) * lanes + blocks * lanes * 4


CODECS: Dict[str, WireCodec] = {
    "fp32": _Fp32(),
    "bf16": _Bf16(),
    "int8": _Int8(),
    "int4": _Int4(),
}
WIRE_NAMES: Tuple[str, ...] = tuple(CODECS)


def get_codec(codec: Union[str, WireCodec]) -> WireCodec:
    if isinstance(codec, WireCodec):
        return codec
    try:
        return CODECS[codec]
    except KeyError:
        raise ValueError(
            f"unknown wire codec {codec!r} (known: {WIRE_NAMES})"
        ) from None


# ------------------------------------------------- legacy lanewise entries
def quantize_lanewise(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """int8 symmetric quant over axis 0, one fp32 scale per remaining
    lane — THE shared implementation the TP-overlap rings and the ZeRO++
    gather both used privately before this module existed (bitwise
    identical to both)."""
    x3 = x.reshape((1, x.shape[0], -1))
    q, scale = CODECS["int8"].quantize(x3)
    return q.reshape(x.shape), scale.reshape((1,) + x.shape[1:])


def dequantize_lanewise(q: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    return (q.astype(jnp.float32) * scale).astype(dtype)


# ------------------------------------------------------------ shape helpers
def _to_blocks(x: jax.Array, n: int, dim: int) -> Tuple[jax.Array, Tuple]:
    """[..., d, ...] -> ([n, d//n, lanes], restore-shape) splitting ``dim``
    into n member blocks; lanes collapse every trailing element (the
    per-(block, lane) scale granularity of the qgZ exchange)."""
    xm = jnp.moveaxis(x, dim, 0)
    d = xm.shape[0]
    if d % n:
        raise ValueError(
            f"wire reduce-scatter: dim {dim} (size {d}) does not divide "
            f"the {n} members"
        )
    return xm.reshape(n, d // n, -1), xm.shape


def _from_block(blk: jax.Array, full_shape: Tuple, n: int,
                dim: int) -> jax.Array:
    """[chunk, lanes] -> the caller's layout with ``dim`` shrunk n-fold."""
    out = blk.reshape((full_shape[0] // n,) + tuple(full_shape[1:]))
    return jnp.moveaxis(out, 0, dim)


def _ordered_sum(dec: jax.Array) -> jax.Array:
    """f32 accumulate over axis 0 in pinned member order — the ONE
    reduction-order definition every wire form shares, so fp32-codec
    wires stay bitwise comparable across forms."""
    acc = dec[0].astype(jnp.float32)
    for s in range(1, dec.shape[0]):
        acc = acc + dec[s].astype(jnp.float32)
    return acc


# ------------------------------------------------------- local (in-map) ops
def ag_wire_local(x: jax.Array, axis: AxisName, n: int,
                  codec: Union[str, WireCodec], *, dim: int = 0,
                  dtype=None) -> jax.Array:
    """All-gather the local shard ``x`` along ``dim`` over mesh ``axis``
    (total size ``n``) moving codec bytes. Runs inside a shard_map.
    Error: one encode/decode round trip per element, independent of n."""
    codec = get_codec(codec)
    dtype = dtype or x.dtype
    xm = jnp.moveaxis(x, dim, 0)
    r = xm.shape[0]
    p = codec.encode(xm.reshape(1, r, -1))
    collectives._record("all_gather", axis, p)
    g = {
        k: lax.all_gather(v, axis, axis=0, tiled=False) for k, v in p.items()
    }
    # [n, 1, ...] -> [n, ...]: each member's block decodes against its
    # own gathered scales
    g = {k: v.reshape((n,) + v.shape[2:]) for k, v in g.items()}
    full3 = codec.decode(g, r, dtype)
    full = full3.reshape((n * r,) + tuple(xm.shape[1:]))
    return jnp.moveaxis(full, 0, dim)


def rs_wire_local(x: jax.Array, axis: AxisName, n: int,
                  codec: Union[str, WireCodec], *, dim: int = 0,
                  dtype=None) -> jax.Array:
    """Reduce-scatter the local contribution ``x`` along ``dim`` over
    ``axis`` (size ``n``), qgZ form: one encode per member block, one
    all-to-all, dequant, f32 member-order accumulate (dequant-accumulate
    in master precision — never a quantized sum). Error <= the sum of
    the n contributors' per-block bounds."""
    codec = get_codec(codec)
    dtype = dtype or x.dtype
    x3, full_shape = _to_blocks(x, n, dim)
    p = codec.encode(x3)
    collectives._record("all_to_all", axis, p)
    ex = {
        k: lax.all_to_all(v, axis, split_axis=0, concat_axis=0, tiled=False)
        for k, v in p.items()
    }
    dec = codec.decode(ex, x3.shape[1], jnp.float32)
    return _from_block(_ordered_sum(dec).astype(dtype), full_shape, n, dim)


def ag_wire_hier_local(x: jax.Array, outer: str, inner: str, n_o: int,
                       n_i: int, codec: Union[str, WireCodec], *,
                       inner_codec: Union[str, WireCodec] = "fp32",
                       dim: int = 0, dtype=None) -> jax.Array:
    """Hierarchical 2-hop all-gather over the factored axis pair
    ``(outer, inner)``: hop 1 gathers full width (``inner_codec``,
    default fp32) over the fast intra-group links; hop 2 encodes the
    group's gathered block ONCE and moves codec bytes over the slow
    inter-group links. Result ordering is outer-major — identical to a
    single-hop gather over ``(outer, inner)``."""
    codec = get_codec(codec)
    dtype = dtype or x.dtype
    # hop 1 (intra): the group's n_i shards, full width on fast links
    intra = ag_wire_local(x, inner, n_i, inner_codec, dim=dim, dtype=dtype)
    # hop 2 (inter): one encode of the group block, codec bytes on the wire
    return ag_wire_local(intra, outer, n_o, codec, dim=dim, dtype=dtype)


def rs_wire_hier_local(x: jax.Array, outer: str, inner: str, n_o: int,
                       n_i: int, codec: Union[str, WireCodec], *,
                       inner_codec: Union[str, WireCodec] = "fp32",
                       dim: int = 0, dtype=None) -> jax.Array:
    """Hierarchical 2-hop reduce-scatter (hgZ): hop 1 reduce-scatters
    full width within each group (fast links — and it SHRINKS what the
    slow hop must move n_i-fold); hop 2 reduce-scatters the group
    partials over the outer axis in codec bytes. Member (o, i) ends with
    global block ``o * n_i + i`` — the outer-major layout
    ``PartitionSpec((outer, inner))`` expects. Quantization still
    happens at most once per value (only the inter hop encodes; the
    intra hop is full width), so the error bound is the single-hop
    bound over the n_o inter-group contributors."""
    codec = get_codec(codec)
    dtype = dtype or x.dtype
    n = n_o * n_i
    x3, full_shape = _to_blocks(x, n, dim)  # [n_o * n_i, chunk, L]
    chunk = x3.shape[1]
    # regroup [n_o, n_i, chunk, L] -> inner blocks [n_i, n_o * chunk, L]:
    # hop 1 scatters the inner-block axis within the group (full width)
    xb = x3.reshape(n_o, n_i, chunk, x3.shape[2])
    inner_blocks = jnp.moveaxis(xb, 1, 0).reshape(
        n_i, n_o * chunk, x3.shape[2]
    )
    ic = get_codec(inner_codec)
    p1 = ic.encode(inner_blocks)
    collectives._record("all_to_all", inner, p1)
    ex1 = {
        k: lax.all_to_all(v, inner, split_axis=0, concat_axis=0,
                          tiled=False)
        for k, v in p1.items()
    }
    dec1 = ic.decode(ex1, inner_blocks.shape[1], jnp.float32)
    y = _ordered_sum(dec1).reshape(n_o, chunk, x3.shape[2])
    # hop 2 (inter): member (o, i) holds inner block i reduced over its
    # group; scatter its n_o outer blocks in codec bytes, f32 accumulate
    p2 = codec.encode(y)
    collectives._record("all_to_all", outer, p2)
    ex2 = {
        k: lax.all_to_all(v, outer, split_axis=0, concat_axis=0,
                          tiled=False)
        for k, v in p2.items()
    }
    dec2 = codec.decode(ex2, chunk, jnp.float32)
    return _from_block(_ordered_sum(dec2).astype(dtype), full_shape, n, dim)


# -------------------------------------------------------- global wrappers
def _shard_map_full(body, topo, in_specs, out_specs):
    from ..utils.jax_compat import shard_map

    return shard_map(
        body,
        mesh=topo.mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        axis_names=set(topo.mesh.axis_names),
        check_vma=False,
    )


def _axes_tuple(axes) -> Tuple[str, ...]:
    return tuple(axes) if isinstance(axes, (tuple, list)) else (axes,)


def hier_axes(topo, axes) -> Optional[Tuple[str, int, str, int]]:
    """(outer, n_outer, inner, n_inner) when ``axes`` is a live factored
    pair this topology can run the 2-hop forms over (outer first — the
    slower, outermost mesh axis); None otherwise (single-hop territory:
    one live axis, or a pair with a dead member)."""
    axes = _axes_tuple(axes)
    if len(axes) != 2:
        return None
    n_o, n_i = topo.sizes[axes[0]], topo.sizes[axes[1]]
    if n_o <= 1 or n_i <= 1:
        return None
    return axes[0], n_o, axes[1], n_i


def all_gather_wire(shards: jax.Array, topo, axes=("dp",),
                    codec: Union[str, WireCodec] = "int8", *,
                    hierarchical: bool = False) -> jax.Array:
    """Global-array all-gather wire: ``shards`` is the stacked
    ``[n, chunk, ...]`` per-member shard array (sharded over ``axes`` on
    dim 0); returns the gathered ``[n * chunk, ...]`` array, replicated
    over ``axes``. The oracle surface: fp32 codec == ``jnp.concatenate``
    of the shards, bitwise; every other codec within its stated bound."""
    axes = _axes_tuple(axes)
    n = int(np.prod([topo.sizes[a] for a in axes]))
    hier = hier_axes(topo, axes) if hierarchical else None

    def body(s):
        local = s[0]  # [chunk, ...]
        if hier is not None:
            o, n_o, i, n_i = hier
            return ag_wire_hier_local(local, o, i, n_o, n_i, codec)
        return ag_wire_local(local, axes if len(axes) > 1 else axes[0], n,
                             codec)

    ax_entry = axes if len(axes) > 1 else axes[0]
    return _shard_map_full(body, topo, (P(ax_entry),), P())(shards)


def reduce_scatter_wire(contribs: jax.Array, topo, axes=("dp",),
                        codec: Union[str, WireCodec] = "int8", *,
                        hierarchical: bool = False) -> jax.Array:
    """Global-array reduce-scatter wire: ``contribs`` is the stacked
    ``[n, d, ...]`` per-member contribution array (sharded over ``axes``
    on dim 0); returns the stacked scattered sums ``[n, d // n, ...]``
    (member m's row is block m of the f32 member-order sum). fp32 codec
    == the serial blocked sum, bitwise; every other codec within n x its
    per-block bound."""
    axes = _axes_tuple(axes)
    n = int(np.prod([topo.sizes[a] for a in axes]))
    hier = hier_axes(topo, axes) if hierarchical else None

    def body(c):
        local = c[0]  # [d, ...]
        if hier is not None:
            o, n_o, i, n_i = hier
            out = rs_wire_hier_local(local, o, i, n_o, n_i, codec)
        else:
            out = rs_wire_local(local, axes if len(axes) > 1 else axes[0],
                                n, codec)
        return out[None]

    ax_entry = axes if len(axes) > 1 else axes[0]
    return _shard_map_full(body, topo, (P(ax_entry),), P(ax_entry))(contribs)


# ---------------------------------------------------------- byte accounting
def ag_wire_nbytes(shard_shape: Sequence[int], n: int,
                   codec: Union[str, WireCodec], itemsize: int = 2,
                   *, dim: int = 0) -> int:
    """Per-device wire bytes of ONE codec all-gather of a ``shard_shape``
    local shard over ``n`` members: each device receives the other n-1
    members' encoded shards (ring/tree topologies move the same total)."""
    codec = get_codec(codec)
    shape = tuple(int(d) for d in shard_shape)
    rows = shape[dim]
    lanes = int(np.prod(shape)) // max(rows, 1)
    per_member = codec.payload_nbytes(1, rows, lanes, itemsize)
    return per_member * (n - 1)


def rs_wire_nbytes(full_shape: Sequence[int], n: int,
                   codec: Union[str, WireCodec], itemsize: int = 4,
                   *, dim: int = 0) -> int:
    """Per-device wire bytes of ONE codec reduce-scatter of a
    ``full_shape`` contribution over ``n`` members: the all-to-all sends
    n-1 of each member's n encoded blocks."""
    codec = get_codec(codec)
    shape = tuple(int(d) for d in full_shape)
    rows = shape[dim] // max(n, 1)
    lanes = int(np.prod(shape)) // max(shape[dim], 1)
    per_block = codec.payload_nbytes(1, max(rows, 1), lanes, itemsize)
    return per_block * (n - 1)


def hier_rs_nbytes(full_shape: Sequence[int], n_o: int, n_i: int,
                   codec: Union[str, WireCodec], itemsize: int = 4,
                   *, dim: int = 0,
                   inner_codec: Union[str, WireCodec] = "fp32",
                   ) -> Tuple[int, int]:
    """(inter, intra) per-device wire bytes of one 2-hop reduce-scatter
    (:func:`rs_wire_hier_local`): the intra hop scatters the full
    contribution over the n_i group members at ``inner_codec`` (full
    width by default), the inter hop scatters the 1/n_i group partial
    over the n_o groups at ``codec`` — ONE pricing of the split rule,
    shared by every analytic stream that declares a 2-hop wire."""
    intra = rs_wire_nbytes(full_shape, n_i, inner_codec, itemsize, dim=dim)
    shrunk = list(int(d) for d in full_shape)
    shrunk[dim] //= n_i
    inter = rs_wire_nbytes(shrunk, n_o, codec, itemsize, dim=dim)
    return inter, intra


def hier_ag_nbytes(full_shape: Sequence[int], n_o: int, n_i: int,
                   codec: Union[str, WireCodec], itemsize: int = 4,
                   *, dim: int = 0,
                   inner_codec: Union[str, WireCodec] = "fp32",
                   ) -> Tuple[int, int]:
    """(inter, intra) per-device wire bytes of one 2-hop all-gather
    (:func:`ag_wire_hier_local`): the intra hop gathers the n_i member
    shards at ``inner_codec``, the inter hop moves each group's
    1/n_o block once at ``codec``."""
    shard = list(int(d) for d in full_shape)
    shard[dim] //= n_o * n_i
    intra = ag_wire_nbytes(shard, n_i, inner_codec, itemsize, dim=dim)
    group = list(int(d) for d in full_shape)
    group[dim] //= n_o
    inter = ag_wire_nbytes(group, n_o, codec, itemsize, dim=dim)
    return inter, intra
