"""Functional collectives for use inside ``shard_map``.

Parity: deepspeed/comm/comm.py op surface (all_reduce, all_gather,
reduce_scatter, broadcast, all_to_all_single, send/recv) — rebuilt on
``jax.lax`` collectives so XLA schedules them over ICI. The reference's
NCCL process groups become mesh axis names.

Every op routes through :func:`_record` so the communication logger
(deepspeed_tpu.profiling.comm_logger) sees op name, bytes, and axis —
parity with the reference's comms_logger hooks in deepspeed/comm.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisName = Union[str, Sequence[str]]

_COMM_HOOKS = []


def register_comm_hook(fn: Callable) -> None:
    """fn(op_name, axis_name, nbytes) — used by the comms logger."""
    _COMM_HOOKS.append(fn)


def unregister_comm_hook(fn: Callable) -> None:
    """Remove one subscriber; other loggers' hooks stay registered."""
    try:
        _COMM_HOOKS.remove(fn)
    except ValueError:
        pass


def clear_comm_hooks() -> None:
    _COMM_HOOKS.clear()


def _record(op: str, axis: AxisName, x) -> None:
    if not _COMM_HOOKS:
        return
    nbytes = 0
    for leaf in jax.tree_util.tree_leaves(x):
        if hasattr(leaf, "size") and hasattr(leaf, "dtype"):
            nbytes += leaf.size * jnp.dtype(leaf.dtype).itemsize
    for hook in _COMM_HOOKS:
        hook(op, axis, nbytes)


# -- reduction ops -------------------------------------------------------------
def all_reduce(x, axis_name: AxisName, op: str = "sum"):
    """Parity: deepspeed.comm.all_reduce (inside shard_map)."""
    _record("all_reduce", axis_name, x)
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unsupported reduce op {op}")


def reduce_scatter(x, axis_name: AxisName, scatter_dimension: int = 0, tiled: bool = True):
    """Parity: deepspeed.comm.reduce_scatter_tensor. Sum-reduces across the
    axis and leaves each shard with its slice along ``scatter_dimension``."""
    _record("reduce_scatter", axis_name, x)
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension, tiled=tiled)


def all_gather(x, axis_name: AxisName, gather_dimension: int = 0, tiled: bool = True):
    """Parity: deepspeed.comm.all_gather_into_tensor."""
    _record("all_gather", axis_name, x)
    return lax.all_gather(x, axis_name, axis=gather_dimension, tiled=tiled)


def broadcast(x, axis_name: AxisName, src: int = 0):
    """Parity: deepspeed.comm.broadcast — select src's value on every member.

    Implemented as a masked psum (XLA lowers to an efficient broadcast)."""
    _record("broadcast", axis_name, x)
    idx = lax.axis_index(axis_name)
    mask = (idx == src).astype(x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32)
    masked = x * mask if jnp.issubdtype(x.dtype, jnp.floating) else (x * mask.astype(x.dtype))
    return lax.psum(masked, axis_name)


def all_to_all(x, axis_name: AxisName, split_axis: int, concat_axis: int, tiled: bool = True):
    """Parity: deepspeed.comm.all_to_all_single — the MoE dispatch/combine and
    DS-Ulysses head↔sequence exchange primitive."""
    _record("all_to_all", axis_name, x)
    return lax.all_to_all(x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled)


def check_permutation(perm, axis_size: int):
    """Problems with a ppermute permutation (empty list == well-formed).

    Re-exported from analysis/rules/topology.py — ONE implementation is
    both the static lint (shardlint R3) and the construction-time guard
    below, so "passes the hook" and "passes the lint" can never drift."""
    from ..analysis.rules.topology import check_permutation as _check

    return _check(perm, axis_size)


def permute(x, axis_name: AxisName, perm, *, validate: bool = True):
    """Parity: deepspeed.comm send/recv pairs in the pipeline engine — a
    static ring/permutation shift via collective-permute over ICI.

    Ring/chain contract (the same one shardlint R3 certifies and
    runtime/pipe/schedule.neighbor_chain states): ``perm`` must be an
    injective partial map with no self-loops whose cycle structure is
    either pure chains (the pipeline neighbor hop) or ONE full ring
    covering the whole axis — anything else (disjoint sub-rings, a ring
    plus stray edges, duplicate endpoints) is not a wrong answer on real
    ICI but a *hang*. With ``validate=True`` (default) the contract is
    enforced at construction time via
    :func:`analysis.rules.topology.check_permutation`, so callers like
    parallel/tensor_overlap's decomposed-matmul rings are lint-guaranteed
    the moment they trace, not only when shardlint later walks the jaxpr.
    Validation needs the static axis size; where it cannot be determined
    (outside any mapped context) the check is skipped and shardlint
    remains the backstop."""
    if validate:
        n = None
        try:
            from ..utils.jax_compat import axis_size

            n = int(axis_size(axis_name))
        except Exception:  # noqa: BLE001 — unbound/odd axis env: lint-only
            n = None
        if n is not None:
            problems = check_permutation(perm, n)
            if problems:
                raise ValueError(
                    f"malformed ppermute permutation over axis "
                    f"{axis_name!r} (size {n}): " + "; ".join(problems)
                    + " — this hangs or deadlocks on real ICI"
                )
    _record("ppermute", axis_name, x)
    return lax.ppermute(x, axis_name, perm=perm)


def send_forward(x, axis_name: AxisName, axis_size: int, wrap: bool = False):
    """Shift +1 along the axis (pipeline 'send to next stage').

    With ``wrap=False`` the first member receives zeros (like a recv with no
    sender); with ``wrap=True`` it is a ring rotation."""
    n = axis_size
    perm = [(i, (i + 1) % n) for i in range(n)] if wrap else [(i, i + 1) for i in range(n - 1)]
    return permute(x, axis_name, perm)


def send_backward(x, axis_name: AxisName, axis_size: int, wrap: bool = False):
    n = axis_size
    perm = [(i, (i - 1) % n) for i in range(n)] if wrap else [(i + 1, i) for i in range(n - 1)]
    return permute(x, axis_name, perm)


def axis_index(axis_name: AxisName):
    return lax.axis_index(axis_name)


def barrier(axis_name: AxisName):
    """Parity: deepspeed.comm.barrier — a no-data psum forces a sync point."""
    _record("barrier", axis_name, jnp.zeros(()))
    return lax.psum(jnp.zeros(()), axis_name)
