"""Device-mesh topology.

Parity: deepspeed/runtime/pipe/topology.py (ProcessTopology,
PipeModelDataParallelTopology, PipelineParallelGrid) — except rebuilt around
``jax.sharding.Mesh``. Where the reference enumerates process ranks into
NCCL groups, a TPU mesh *is* the group structure: each named axis is a
communicator, and XLA routes its collectives over ICI along that axis.

Axis order fixes ICI locality: later axes are laid out over adjacent devices,
so the most bandwidth-hungry axis (tp) is innermost and dp — which may ride
DCN in multi-pod jobs — is outermost.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# Canonical axis order, outermost → innermost.
AXIS_ORDER: Tuple[str, ...] = ("dp", "fsdp", "pp", "ep", "sp", "tp")

# Per-axis link classes: "ici" (intra-pod, fast) or "dcn" (inter-pod, slow).
LINK_KINDS: Tuple[str, ...] = ("ici", "dcn")

# DeepSpeed name → ours (reference topology axes are pipe/data/model).
AXIS_ALIASES = {"data": "dp", "pipe": "pp", "model": "tp", "expert": "ep", "sequence": "sp"}


def _canon(axis: str) -> str:
    return AXIS_ALIASES.get(axis, axis)


@dataclass(frozen=True)
class ParallelDims:
    """Requested parallel degrees; dp is inferred when left at 0."""

    dp: int = 0
    fsdp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def resolve(self, world_size: int) -> Dict[str, int]:
        sizes = {"fsdp": self.fsdp, "pp": self.pp, "ep": self.ep, "sp": self.sp, "tp": self.tp}
        known = int(np.prod(list(sizes.values())))
        if self.dp:
            sizes["dp"] = self.dp
            if self.dp * known != world_size:
                raise ValueError(
                    f"parallel dims {sizes} do not multiply to world size {world_size}"
                )
        else:
            if world_size % known != 0:
                raise ValueError(
                    f"world size {world_size} not divisible by non-dp dims product {known}"
                )
            sizes["dp"] = world_size // known
        return {ax: sizes[ax] for ax in AXIS_ORDER}


class MeshTopology:
    """An N-d named device mesh with DeepSpeed-style rank/coord queries."""

    def __init__(
        self,
        dims: Optional[ParallelDims] = None,
        devices: Optional[Sequence[jax.Device]] = None,
        link_kinds: Optional[Dict[str, str]] = None,
        **axis_sizes: int,
    ):
        if dims is None:
            dims = ParallelDims(**{_canon(k): v for k, v in axis_sizes.items()})
        self.devices = list(devices if devices is not None else jax.devices())
        self.world_size = len(self.devices)
        self.sizes = dims.resolve(self.world_size)
        self.axes: Tuple[str, ...] = tuple(ax for ax in AXIS_ORDER)
        self.link_kinds: Dict[str, str] = {
            ax: (link_kinds or {}).get(_canon(ax), "ici") for ax in self.axes
        }
        for ax, kind in self.link_kinds.items():
            if kind not in LINK_KINDS:
                raise ValueError(
                    f"link_kinds[{ax!r}] must be one of {LINK_KINDS}, got {kind!r}"
                )
        grid = np.asarray(self.devices, dtype=object).reshape(
            [self.sizes[ax] for ax in self.axes]
        )
        self.mesh = Mesh(grid, self.axes)

    @classmethod
    def hybrid(
        cls,
        dims: Optional[ParallelDims] = None,
        devices: Optional[Sequence[jax.Device]] = None,
        *,
        dcn_axes: Sequence[str] = ("dp",),
        **axis_sizes: int,
    ) -> "MeshTopology":
        """Two-level DCN×ICI mesh (``mesh_utils.create_hybrid_device_mesh``).

        The DCN-tagged axes are the slice dimensions: each coordinate along
        them selects one ICI-connected pod, so they must be *outermost*
        (slowest-varying over the device list) — collectives along them ride
        the slow inter-pod fabric, everything else stays on ICI. On a real
        multi-slice TPU backend the grid comes from
        ``create_hybrid_device_mesh`` (slices discovered via
        ``device.slice_index``); everywhere else — the tier-1 CPU box — the
        row-major reshape over the flat device list is exactly the emulated
        layout (DCN axes lead ``AXIS_ORDER``), so hybrid shapes build and
        trace without TPU hardware.
        """
        if dims is None:
            dims = ParallelDims(**{_canon(k): v for k, v in axis_sizes.items()})
        devs = list(devices if devices is not None else jax.devices())
        dcn = tuple(_canon(a) for a in dcn_axes)
        for a in dcn:
            if a not in AXIS_ORDER:
                raise ValueError(f"unknown DCN axis {a!r}; have {AXIS_ORDER}")
        sizes = dims.resolve(len(devs))
        live_ici = [
            ax for ax in AXIS_ORDER if sizes[ax] > 1 and ax not in dcn
        ]
        for a in dcn:
            inner = [i for i in live_ici if AXIS_ORDER.index(i) < AXIS_ORDER.index(a)]
            if inner:
                raise ValueError(
                    f"DCN axis {a!r} must be outermost (slowest-varying); "
                    f"ICI axes {inner} precede it in {AXIS_ORDER}"
                )
        if devs and getattr(devs[0], "platform", "cpu") == "tpu" and any(
            getattr(d, "slice_index", 0) for d in devs
        ):
            # real multi-slice backend: let jax group devices by slice
            from jax.experimental import mesh_utils

            ici_shape = [1 if ax in dcn else sizes[ax] for ax in AXIS_ORDER]
            dcn_shape = [sizes[ax] if ax in dcn else 1 for ax in AXIS_ORDER]
            grid = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devs
            )
            devs = list(grid.reshape(-1))
        kinds = {ax: ("dcn" if ax in dcn else "ici") for ax in AXIS_ORDER}
        return cls(dims, devices=devs, link_kinds=kinds)

    @property
    def dcn_axes(self) -> Tuple[str, ...]:
        """Live axes whose links ride the slow inter-pod fabric."""
        return tuple(
            ax for ax in self.axes
            if self.sizes[ax] > 1 and self.link_kinds.get(ax) == "dcn"
        )

    @property
    def is_hybrid(self) -> bool:
        return bool(self.dcn_axes)

    # -- DeepSpeed ProcessTopology parity -------------------------------------
    def get_dim(self, axis: str) -> int:
        return self.sizes[_canon(axis)]

    @property
    def dp_size(self) -> int:
        return self.sizes["dp"]

    @property
    def fsdp_size(self) -> int:
        return self.sizes["fsdp"]

    @property
    def pp_size(self) -> int:
        return self.sizes["pp"]

    @property
    def tp_size(self) -> int:
        return self.sizes["tp"]

    @property
    def sp_size(self) -> int:
        return self.sizes["sp"]

    @property
    def ep_size(self) -> int:
        return self.sizes["ep"]

    @property
    def data_shard_size(self) -> int:
        """Total ways the global batch is split (dp × fsdp share the batch)."""
        return self.sizes["dp"] * self.sizes["fsdp"]

    def get_coord(self, rank: int) -> Dict[str, int]:
        shape = [self.sizes[ax] for ax in self.axes]
        coords = np.unravel_index(rank, shape)
        return {ax: int(c) for ax, c in zip(self.axes, coords)}

    def get_rank(self, **coords: int) -> int:
        coords = {_canon(k): v for k, v in coords.items()}
        full = [coords.get(ax, 0) for ax in self.axes]
        shape = [self.sizes[ax] for ax in self.axes]
        return int(np.ravel_multi_index(full, shape))

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Ranks grouped into communicators along ``axis`` (reference parity)."""
        axis = _canon(axis)
        others = [ax for ax in self.axes if ax != axis]
        lists = []
        ranges = [range(self.sizes[ax]) for ax in others]
        for combo in itertools.product(*ranges):
            fixed = dict(zip(others, combo))
            lists.append([self.get_rank(**{**fixed, axis: i}) for i in range(self.sizes[axis])])
        return lists

    # -- sharding helpers -----------------------------------------------------
    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    def batch_spec(self) -> PartitionSpec:
        """Global-batch partitioning: batch over (dp, fsdp), seq over sp."""
        axes: Tuple = tuple(a for a in ("dp", "fsdp") if self.sizes[a] > 1)
        batch_axes = axes if axes else None
        seq_axes = "sp" if self.sizes["sp"] > 1 else None
        return PartitionSpec(batch_axes, seq_axes)

    def __repr__(self) -> str:
        dims = "x".join(
            f"{ax}={self.sizes[ax]}"
            + ("[dcn]" if self.link_kinds.get(ax) == "dcn" else "")
            for ax in self.axes
            if self.sizes[ax] > 1
        )
        return f"MeshTopology({dims or 'single-device'}, world={self.world_size})"


# Reference-compatible constructor names ---------------------------------------
def PipeModelDataParallelTopology(num_pp: int, num_mp: int, num_dp: int, **kw) -> MeshTopology:
    """Parity: deepspeed.runtime.pipe.topology.PipeModelDataParallelTopology."""
    return MeshTopology(ParallelDims(dp=num_dp, pp=num_pp, tp=num_mp), **kw)


def PipeDataParallelTopology(num_pp: int, num_dp: int, **kw) -> MeshTopology:
    return MeshTopology(ParallelDims(dp=num_dp, pp=num_pp), **kw)
