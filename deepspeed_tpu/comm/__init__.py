"""deepspeed_tpu.comm — the XLA-collective communication backend.

Parity: deepspeed/comm/__init__.py + deepspeed/comm/comm.py. The reference
maintains NCCL/CCL process groups and exposes torch.distributed-style ops;
here the "backend" is the XLA runtime itself: ``init_distributed`` wires up
multi-host JAX (the NCCL-bootstrap equivalent), builds the global
:class:`MeshTopology`, and the op surface in :mod:`collectives` runs inside
``shard_map`` where XLA lowers psum/all_gather/reduce_scatter/ppermute/
all_to_all onto ICI/DCN.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from ..utils.logging import log_dist, logger
from . import collectives, wires  # noqa: F401  (wires: codec comm layer)
from .collectives import (  # noqa: F401  (re-export op surface)
    all_gather,
    all_reduce,
    all_to_all,
    axis_index,
    barrier,
    broadcast,
    permute,
    reduce_scatter,
    register_comm_hook,
    send_backward,
    send_forward,
)
from .topology import (  # noqa: F401
    AXIS_ORDER,
    MeshTopology,
    ParallelDims,
    PipeDataParallelTopology,
    PipeModelDataParallelTopology,
)

_TOPOLOGY: Optional[MeshTopology] = None
_INITIALIZED = False


def is_initialized() -> bool:
    return _INITIALIZED


def init_distributed(
    dist_backend: str = "xla",
    topology: Optional[MeshTopology] = None,
    dims: Optional[ParallelDims] = None,
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    dcn_axes=None,
    **_ignored,
) -> MeshTopology:
    """Parity: deepspeed.init_distributed().

    Multi-host: if coordinator env/args are present, calls
    ``jax.distributed.initialize`` (the reference's torch.distributed init).
    Then builds the global mesh topology over all visible devices.
    ``dcn_axes`` (e.g. ``("dp",)``) builds a two-level hybrid mesh
    (:meth:`MeshTopology.hybrid`) whose named axes carry link metadata —
    the static layer prices and lints inter-pod traffic off it.
    """
    global _TOPOLOGY, _INITIALIZED
    if dist_backend not in ("xla", "tpu", "auto"):
        logger.warning(f"dist_backend={dist_backend!r} ignored; TPU build always uses XLA")
    coord = coordinator_address or os.environ.get("DSTPU_COORDINATOR")
    nproc = num_processes or int(os.environ.get("DSTPU_NUM_PROCESSES", "1"))
    if coord and nproc > 1 and not _INITIALIZED:
        # NB: must run before anything touches a jax backend (even
        # jax.process_count() locks it in) — so gate on the distributed
        # client's own state, and let genuine failures (coordinator
        # unreachable, backend already locked) raise loudly rather than
        # silently degrading the job to single-process.
        from jax._src import distributed as _jax_distributed

        if getattr(_jax_distributed.global_state, "client", None) is None:
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=nproc,
                process_id=process_id
                if process_id is not None
                else int(os.environ.get("DSTPU_PROCESS_ID", "0")),
            )
        else:
            logger.warning("jax.distributed already initialized; reusing it")
    if topology is not None:
        _TOPOLOGY = topology
    elif dims is not None or _TOPOLOGY is None:
        if dcn_axes:
            _TOPOLOGY = MeshTopology.hybrid(
                dims or ParallelDims(), dcn_axes=tuple(dcn_axes)
            )
        else:
            _TOPOLOGY = MeshTopology(dims or ParallelDims())
    _INITIALIZED = True
    log_dist(f"init_distributed: {_TOPOLOGY}")
    return _TOPOLOGY


def barrier(name: str = "barrier") -> None:
    """Cross-process barrier (parity: deepspeed.comm.barrier). No-op in a
    single-process job; multi-host it rides sync_global_devices."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def set_topology(topology: MeshTopology) -> None:
    global _TOPOLOGY, _INITIALIZED
    _TOPOLOGY = topology
    _INITIALIZED = True


def get_topology() -> MeshTopology:
    global _TOPOLOGY
    if _TOPOLOGY is None:
        init_distributed()
    return _TOPOLOGY


def get_mesh():
    return get_topology().mesh


def get_world_size(group: Optional[str] = None) -> int:
    """Parity: deepspeed.comm.get_world_size. ``group`` is a mesh axis name."""
    topo = get_topology()
    if group is None:
        return topo.world_size
    return topo.get_dim(group)


def get_rank() -> int:
    """Global device-0 rank of this *process* (SPMD: one program, many chips)."""
    return jax.process_index()


def get_local_rank() -> int:
    return 0


def destroy_process_group() -> None:
    global _TOPOLOGY, _INITIALIZED
    _TOPOLOGY = None
    _INITIALIZED = False
