"""Shared constants. Parity: deepspeed/constants.py + runtime/constants.py."""

ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"

# Mesh axis names (see comm.topology.AXIS_ORDER for ordering rationale).
DP_AXIS = "dp"
FSDP_AXIS = "fsdp"
PP_AXIS = "pp"
EP_AXIS = "ep"
SP_AXIS = "sp"
TP_AXIS = "tp"

# Gradient-reduction dtype default (reference: communication_data_type).
DEFAULT_COMM_DTYPE = None  # None => same as compute dtype

TORCH_DISTRIBUTED_DEFAULT_PORT = 29500  # kept for launcher arg parity
