"""Attention op registry.

Parity: the reference's attention kernels live in csrc/transformer and
csrc/flash_attn-style fused ops; here the default is an XLA einsum softmax
(fuses well on TPU already), and ``set_attention_impl("flash")`` swaps in the
Pallas flash kernel (ops/pallas/flash_attention.py) without touching models.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

_IMPLS: Dict[str, Callable] = {}
_CURRENT = "auto"  # auto => flash on TPU, xla elsewhere

NEG_INF = -1e30


def register_attention_impl(name: str, fn: Callable) -> None:
    _IMPLS[name] = fn


def set_attention_impl(name: str) -> None:
    global _CURRENT
    if name != "auto" and name not in _IMPLS:
        raise KeyError(f"unknown attention impl {name!r}; have {sorted(_IMPLS)}")
    _CURRENT = name


_override_stack: list = []


class attention_impl:
    """Scoped impl override (no global mutation): with attention_impl("flash").

    Accepts a registered impl name or a callable with the attention
    signature (engine-built wrappers, e.g. block-sparse layouts)."""

    def __init__(self, name):
        if (
            isinstance(name, str)
            and name != "auto"
            and name not in _IMPLS
        ):
            raise KeyError(f"unknown attention impl {name!r}; have {sorted(_IMPLS)}")
        self.name = name

    def __enter__(self):
        _override_stack.append(self.name)
        return self

    def __exit__(self, *exc):
        _override_stack.pop()


def _resolve():
    cur = _override_stack[-1] if _override_stack else _CURRENT
    if callable(cur):
        return cur
    if cur != "auto":
        return cur
    if jax.default_backend() == "tpu" and "flash" in _IMPLS:
        return "flash"
    return "xla"


def get_attention_impl() -> str:
    return _CURRENT


def resolve_attention_impl():
    """The impl that would run right now: a registered name, or the scoped
    callable override. ("auto" resolves: flash on TPU, xla elsewhere.)"""
    return _resolve()


def xla_attention(q, k, v, *, causal=True, bias=None, segment_ids=None,
                  alibi_slopes=None):
    """Reference attention. q: [B,S,H,hd], k/v: [B,S,KV,hd] (GQA aware).

    fp32 softmax accumulation; returns [B,S,H,hd] in q.dtype.
    ``alibi_slopes`` [H] materializes the dense -slope*|Δpos| bias here (the
    flash kernel computes it in-kernel without the [B,H,S,S] tensor).
    """
    B, S, H, hd = q.shape
    KV = k.shape[2]
    if KV != H:
        assert H % KV == 0, f"GQA heads {H} not divisible by kv heads {KV}"
        # materialized repeat, deliberately: a grouped 5-D einsum avoids the
        # copy but its [B,S,KV,G,hd] reshape adds involuntary-remat
        # reshardings under Ulysses meshes (measured: 7 warnings vs 5). The
        # flash kernel is the perf path; this reference impl optimizes for
        # sharding fidelity.
        k = jnp.repeat(k, H // KV, axis=2)
        v = jnp.repeat(v, H // KV, axis=2)
    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if alibi_slopes is not None:
        pos = jnp.arange(S, dtype=jnp.float32)
        rel = -jnp.abs(pos[:, None] - pos[None, :])  # [S, S]
        slopes = jnp.asarray(alibi_slopes, jnp.float32)
        logits = logits + slopes[None, :, None, None] * rel[None, None]
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        qpos = jnp.arange(S)[:, None]
        kpos = jnp.arange(S)[None, :]
        logits = jnp.where((kpos > qpos)[None, None], NEG_INF, logits)
    if segment_ids is not None:
        same = segment_ids[:, :, None] == segment_ids[:, None, :]
        logits = jnp.where(same[:, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


register_attention_impl("xla", xla_attention)


def attention(q, k, v, *, causal=True, bias=None, segment_ids=None,
              alibi_slopes=None):
    impl = _resolve()
    fn = impl if callable(impl) else _IMPLS[impl]
    return fn(
        q, k, v, causal=causal, bias=bias, segment_ids=segment_ids,
        alibi_slopes=alibi_slopes,
    )
