"""Vocab-chunked fused cross-entropy (custom VJP).

Parity: the reference's fused softmax/xent CUDA kernels (csrc/transformer
softmax + the inference logit kernels). TPU-native design: the [tokens, V]
logit matrix is the single largest activation in LM training (fp32 logits
are ~4x the size of every per-layer residual combined at V=32k, d=1k) —
instead of materializing it, scan over vocab chunks with an online
logsumexp in the forward and recompute each chunk's logits in the backward
(one extra [N,d]x[d,chunk] matmul per chunk, ~2% of step FLOPs, for ~2-4GB
of HBM back at micro-batch 4-8).

Everything is jnp/lax — the MXU work is plain matmuls XLA tiles itself; a
Pallas kernel would only re-derive what the compiler already does here.

Scope-gated like ops.attention/ops.normalization: the engine enables it per
config (tpu_kernels.fused_ce) while tracing; default path elsewhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_scope_stack: list = []


class fused_ce_scope:
    """Scoped enable (no global mutation), entered by TpuEngine._kernel_scope."""

    def __init__(self, flag: bool, chunk: int = 4096):
        self.val = (bool(flag), int(chunk))

    def __enter__(self):
        _scope_stack.append(self.val)
        return self

    def __exit__(self, *exc):
        _scope_stack.pop()


def fused_ce_config():
    """(enabled, chunk) for the current trace scope."""
    return _scope_stack[-1] if _scope_stack else (False, 4096)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _chunked_nll(y2, head, labels2, chunk):
    """Per-token -log p(label) without materializing [N, V] logits.

    y2 [N, d] compute dtype; head [d, V] fp32; labels2 [N] int (garbage rows
    allowed — mask via zero cotangent). Returns nll [N] fp32."""
    nll, _ = _chunked_fwd(y2, head, labels2, chunk)
    return nll


def _logits_chunk(y2, head, c, chunk):
    hc = lax.dynamic_slice(head, (0, c * chunk), (head.shape[0], chunk))
    # bf16 operands at full MXU rate, fp32 accumulation — same contract as
    # models/transformer.lm_head_logits
    return jnp.einsum(
        "nd,dc->nc", y2, hc.astype(y2.dtype),
        preferred_element_type=jnp.float32,
    ), hc


def _piece_bounds(V, chunk):
    """Full chunks + one static ragged tail (V need not divide by chunk)."""
    nchunks, tail = divmod(V, chunk)
    return nchunks, tail


def _piece_fwd_update(carry, lc, labels2, start, size):
    m, s, gold = carry
    m_new = jnp.maximum(m, lc.max(axis=-1))
    s = s * jnp.exp(m - m_new) + jnp.exp(lc - m_new[:, None]).sum(axis=-1)
    in_c = (labels2 >= start) & (labels2 < start + size)
    idx = jnp.clip(labels2 - start, 0, size - 1)
    g = jnp.take_along_axis(lc, idx[:, None], axis=-1)[:, 0]
    gold = jnp.where(in_c, g, gold)
    return (m_new, s, gold)


def _chunked_fwd(y2, head, labels2, chunk):
    N = y2.shape[0]
    V = head.shape[1]
    nchunks, tail = _piece_bounds(V, chunk)
    neg = jnp.float32(-1e30)

    def body(carry, c):
        lc, _ = _logits_chunk(y2, head, c, chunk)
        return _piece_fwd_update(carry, lc, labels2, c * chunk, chunk), None

    init = (
        jnp.full((N,), neg, jnp.float32),
        jnp.zeros((N,), jnp.float32),
        jnp.zeros((N,), jnp.float32),
    )
    carry = init
    if nchunks:
        carry, _ = lax.scan(body, carry, jnp.arange(nchunks))
    if tail:
        lt = jnp.einsum(
            "nd,dc->nc", y2,
            lax.slice_in_dim(head, V - tail, V, axis=1).astype(y2.dtype),
            preferred_element_type=jnp.float32,
        )
        carry = _piece_fwd_update(carry, lt, labels2, V - tail, tail)
    m, s, gold = carry
    lse = m + jnp.log(s)
    return lse - gold, (y2, head, labels2, lse)


def _piece_bwd(y2, hc, lc, labels2, lse, gf, start, size):
    """(dy_increment, dhead_chunk) for one vocab piece."""
    p = jnp.exp(lc - lse[:, None])  # softmax over the full vocab
    in_c = (labels2 >= start) & (labels2 < start + size)
    idx = jnp.clip(labels2 - start, 0, size - 1)
    onehot = (
        jax.nn.one_hot(idx, size, dtype=jnp.float32)
        * in_c[:, None].astype(jnp.float32)
    )
    dl = (p - onehot) * gf[:, None]  # [N, size] fp32
    dy_inc = jnp.einsum(
        "nc,dc->nd", dl.astype(y2.dtype), hc.astype(y2.dtype),
        preferred_element_type=jnp.float32,
    )
    dhc = jnp.einsum(
        "nd,nc->dc", y2, dl.astype(y2.dtype),
        preferred_element_type=jnp.float32,
    )
    return dy_inc, dhc


def _chunked_bwd(chunk, res, g):
    y2, head, labels2, lse = res
    d = head.shape[0]
    V = head.shape[1]
    nchunks, tail = _piece_bounds(V, chunk)
    gf = g.astype(jnp.float32)

    def body(carry, c):
        dy, dhead = carry
        lc, hc = _logits_chunk(y2, head, c, chunk)
        dy_inc, dhc = _piece_bwd(
            y2, hc, lc, labels2, lse, gf, c * chunk, chunk
        )
        dhead = lax.dynamic_update_slice(dhead, dhc, (0, c * chunk))
        return (dy + dy_inc, dhead), None

    carry = (
        jnp.zeros((y2.shape[0], d), jnp.float32),
        jnp.zeros((d, V), jnp.float32),
    )
    if nchunks:
        carry, _ = lax.scan(body, carry, jnp.arange(nchunks))
    dy, dhead = carry
    if tail:
        hc = lax.slice_in_dim(head, V - tail, V, axis=1)
        lt = jnp.einsum(
            "nd,dc->nc", y2, hc.astype(y2.dtype),
            preferred_element_type=jnp.float32,
        )
        dy_inc, dhc = _piece_bwd(y2, hc, lt, labels2, lse, gf, V - tail, tail)
        dy = dy + dy_inc
        dhead = lax.dynamic_update_slice(dhead, dhc, (0, V - tail))
    return dy.astype(y2.dtype), dhead.astype(head.dtype), None


_chunked_nll.defvjp(lambda y2, h, l, c: _chunked_fwd(y2, h, l, c),
                    _chunked_bwd)


def chunked_masked_ce(y, head, labels, chunk: int = 4096):
    """Masked mean NLL over [..., S] tokens; labels < 0 ignored (HF -100).

    y [..., S, d]; head [d, V] (pass the fp32 master — cast to compute dtype
    happens inside the chunk matmuls). Returns (ce, total_valid_tokens) with
    the same semantics as models.transformer.masked_ce."""
    d = y.shape[-1]
    y2 = y.reshape(-1, d)
    labels2 = labels.reshape(-1)
    mask = (labels2 >= 0).astype(jnp.float32)
    nll = _chunked_nll(y2, head, jnp.maximum(labels2, 0), int(chunk))
    denom = jnp.maximum(mask.sum(), 1.0)
    return (nll * mask).sum() / denom, denom


def fused_ce_applicable(V: int, chunk: int, topo) -> bool:
    """The chunked path assumes the vocab dim is unsharded (tp==1): under
    Megatron vocab-parallel TP the dense vocab-parallel logsumexp path
    (lm_head_logits + masked_ce with a "tp" constraint) stays in charge.
    Any vocab size works — a ragged tail runs as one static extra piece."""
    return V > chunk and (topo is None or topo.tp_size == 1)
