"""Block-sparse attention.

Parity: csrc/sparse_attention/ + deepspeed/ops/sparse_attention/ (SparseSelfAttention,
sparsity_config.py). The reference builds triton/CUDA block-sparse matmuls
from a layout tensor; here the same block layout feeds the Pallas flash
kernel's compacted grid (ops/pallas/flash_attention.py `block_mask`): the
layout becomes scalar-prefetch compaction tables, the kernel grid walks
only each row's active blocks, and masked tiles are neither computed NOR
fetched from HBM — both the MXU work and the DMA bandwidth scale with the
layout's density, like the reference's triton lut-driven sdd/dsd kernels.
No separate sdd/dsd/dds matmul trio needed; XLA/Mosaic fuse the rest.

Patterns mirror the reference's sparsity_config classes: Fixed (local +
periodic global), BigBird (window + global + random), BSLongformer (sliding
window + global blocks), Dense. Layouts are per-model static numpy tables:
one [nq, nk] 0/1 mask at kernel-block granularity.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np


@dataclass
class SparsityConfig:
    """Base: block size must equal the flash kernel's tile size."""

    block: int = 128

    def make_layout(self, seq_len: int) -> np.ndarray:  # pragma: no cover
        raise NotImplementedError

    def _n(self, seq_len: int) -> int:
        if seq_len % self.block != 0:
            raise ValueError(
                f"seq_len {seq_len} not divisible by sparsity block {self.block}"
            )
        return seq_len // self.block


@dataclass
class DenseSparsityConfig(SparsityConfig):
    """Parity: DenseSparsityConfig — all blocks visible (debug/reference)."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self._n(seq_len)
        return np.ones((n, n), np.int32)


@dataclass
class FixedSparsityConfig(SparsityConfig):
    """Parity: FixedSparsityConfig — each block attends to its local window
    of ``num_local_blocks`` and to the last ``num_global_blocks`` of every
    preceding window (the "summary" blocks other windows expose)."""

    num_local_blocks: int = 4
    num_global_blocks: int = 1

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self._n(seq_len)
        nl, ng = self.num_local_blocks, self.num_global_blocks
        layout = np.zeros((n, n), np.int32)
        for qi in range(n):
            window = qi // nl
            layout[qi, window * nl : (window + 1) * nl] = 1  # local window
            for w in range(window):  # global summary blocks of prior windows
                lo = (w + 1) * nl - ng
                layout[qi, max(lo, 0) : (w + 1) * nl] = 1
        return layout


@dataclass
class BigBirdSparsityConfig(SparsityConfig):
    """Parity: BigBirdSparsityConfig — sliding window + global + random."""

    num_sliding_window_blocks: int = 3
    num_global_blocks: int = 1
    num_random_blocks: int = 1
    seed: int = 0

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self._n(seq_len)
        w = self.num_sliding_window_blocks // 2
        layout = np.zeros((n, n), np.int32)
        for qi in range(n):
            layout[qi, max(0, qi - w) : min(n, qi + w + 1)] = 1  # window
        layout[:, : self.num_global_blocks] = 1  # global cols
        layout[: self.num_global_blocks, :] = 1  # global rows
        rng = np.random.RandomState(self.seed)
        for qi in range(n):
            for ki in rng.choice(n, size=min(self.num_random_blocks, n), replace=False):
                layout[qi, ki] = 1
        return layout


@dataclass
class BSLongformerSparsityConfig(SparsityConfig):
    """Parity: BSLongformerSparsityConfig — sliding window + chosen global
    block indices that everyone attends to (and that attend to everyone)."""

    num_sliding_window_blocks: int = 3
    global_block_indices: List[int] = field(default_factory=lambda: [0])

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self._n(seq_len)
        w = self.num_sliding_window_blocks // 2
        layout = np.zeros((n, n), np.int32)
        for qi in range(n):
            layout[qi, max(0, qi - w) : min(n, qi + w + 1)] = 1
        for g in self.global_block_indices:
            if g < n:
                layout[:, g] = 1
                layout[g, :] = 1
        return layout


@dataclass
class VariableSparsityConfig(SparsityConfig):
    """Parity: VariableSparsityConfig — local windows of varying width
    (``local_window_blocks``, last entry repeats), chosen global block
    indices, plus random blocks."""

    num_random_blocks: int = 0
    local_window_blocks: List[int] = field(default_factory=lambda: [4])
    global_block_indices: List[int] = field(default_factory=lambda: [0])
    seed: int = 0

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self._n(seq_len)
        layout = np.zeros((n, n), np.int32)
        # tile variable-width local windows over the block axis
        start = 0
        widths = list(self.local_window_blocks) or [1]
        wi = 0
        while start < n:
            w = widths[min(wi, len(widths) - 1)]
            end = min(start + w, n)
            layout[start:end, start:end] = 1
            start = end
            wi += 1
        for g in self.global_block_indices:
            if g < n:
                layout[:, g] = 1
                layout[g, :] = 1
        rng = np.random.RandomState(self.seed)
        for qi in range(n):
            if self.num_random_blocks:
                for ki in rng.choice(
                    n, size=min(self.num_random_blocks, n), replace=False
                ):
                    layout[qi, ki] = 1
        return layout


def causal_trim(layout: np.ndarray) -> np.ndarray:
    """Zero strictly-upper block diagonals (the kernel also causal-masks
    inside diagonal blocks; this just documents the block-level layout)."""
    return np.asarray(np.tril(np.ones_like(layout)) * layout, np.int32)


def sparse_attention(q, k, v, config: SparsityConfig, *, causal: bool = True,
                     segment_ids=None, alibi_slopes=None,
                     interpret: Optional[bool] = None):
    """Block-sparse attention in model layout q[B,S,H,D] → [B,S,H,D].

    Parity surface: SparseSelfAttention.forward. The layout is built once
    per (config, seq_len) and drives tile predication in the flash kernel.
    """
    from .pallas.flash_attention import flash_attention

    S = q.shape[1]
    layout = config.make_layout(S)
    if causal:
        layout = causal_trim(layout)
    return flash_attention(
        q, k, v, causal=causal, segment_ids=segment_ids,
        alibi_slopes=alibi_slopes, block_mask=layout,
        block_q=config.block, block_k=config.block, interpret=interpret,
    )


def dense_blocksparse_reference(q, k, v, layout, block, *, causal=True):
    """Oracle: dense attention with the block mask expanded to tokens."""
    import jax.numpy as jnp

    from .attention import xla_attention

    S = q.shape[1]
    n = S // block
    tok_mask = np.kron(np.asarray(layout)[:n, :n], np.ones((block, block)))
    bias = jnp.where(jnp.asarray(tok_mask) > 0, 0.0, -1e30)[None, None]
    return xla_attention(q, k, v, causal=causal, bias=bias)


def from_ds_config(sa_cfg) -> Optional[SparsityConfig]:
    """ds_config "sparse_attention" section → SparsityConfig (None = off).

    Parity: deepspeed/ops/sparse_attention get_sparse_attention_config."""
    mode = getattr(sa_cfg, "mode", "none")
    if mode in ("none", None):
        return None
    if mode == "dense":
        return DenseSparsityConfig(block=sa_cfg.block)
    if mode == "fixed":
        return FixedSparsityConfig(
            block=sa_cfg.block,
            num_local_blocks=sa_cfg.num_local_blocks,
            num_global_blocks=sa_cfg.num_global_blocks,
        )
    if mode == "bigbird":
        return BigBirdSparsityConfig(
            block=sa_cfg.block,
            num_sliding_window_blocks=sa_cfg.num_sliding_window_blocks,
            num_global_blocks=sa_cfg.num_global_blocks,
            num_random_blocks=sa_cfg.num_random_blocks,
        )
    if mode == "bslongformer":
        return BSLongformerSparsityConfig(
            block=sa_cfg.block,
            num_sliding_window_blocks=sa_cfg.num_sliding_window_blocks,
            global_block_indices=list(sa_cfg.global_block_indices),
        )
    if mode == "variable":
        return VariableSparsityConfig(
            block=sa_cfg.block,
            num_random_blocks=sa_cfg.num_random_blocks,
            local_window_blocks=[sa_cfg.num_local_blocks],
            global_block_indices=list(sa_cfg.global_block_indices),
        )
    raise ValueError(f"unknown sparse_attention mode {mode!r}")


def make_attention_impl(config: SparsityConfig):
    """An attention-signature callable for the engine's scoped impl stack."""

    def impl(q, k, v, *, causal=True, bias=None, segment_ids=None,
             alibi_slopes=None):
        if bias is not None:
            raise ValueError(
                "sparse_attention cannot compose with a dense attention bias"
            )
        return sparse_attention(
            q, k, v, config, causal=causal, segment_ids=segment_ids,
            alibi_slopes=alibi_slopes,
        )

    return impl
