"""ctypes bindings for the C++ async file-IO backend (csrc/aio).

Parity: deepspeed/ops/aio (AsyncIOBuilder + aio_handle). Built on first use
with g++ (no pybind11 in this image); the .so is cached next to the source.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Optional

import numpy as np

_CSRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc", "aio")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None


def _build_lib() -> str:
    src = os.path.abspath(os.path.join(_CSRC, "aio.cpp"))
    out = os.path.abspath(os.path.join(_CSRC, "libdsaio.so"))
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-pthread", src, "-o", out]
    subprocess.run(cmd, check=True, capture_output=True)
    return out


def _lib() -> ctypes.CDLL:
    global _LIB
    with _LOCK:
        if _LIB is None:
            lib = ctypes.CDLL(_build_lib())
            lib.dsaio_create.restype = ctypes.c_void_p
            lib.dsaio_create.argtypes = [ctypes.c_int, ctypes.c_int]
            lib.dsaio_destroy.argtypes = [ctypes.c_void_p]
            lib.dsaio_submit.restype = ctypes.c_int64
            lib.dsaio_submit.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
            ]
            lib.dsaio_wait.restype = ctypes.c_int
            lib.dsaio_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
            lib.dsaio_poll.restype = ctypes.c_int
            lib.dsaio_poll.argtypes = [ctypes.c_void_p, ctypes.c_int64]
            lib.dsaio_pending.restype = ctypes.c_int
            lib.dsaio_pending.argtypes = [ctypes.c_void_p]
            _LIB = lib
    return _LIB


class AsyncIOHandle:
    """Parity surface: deepspeed.ops.aio.aio_handle (submit/wait model).

    Buffers must be kept alive by the caller until their request is waited —
    this class pins them in ``_inflight``.
    """

    def __init__(self, num_threads: int = 4, use_direct: bool = False):
        self._lib = _lib()
        self._h = self._lib.dsaio_create(num_threads, int(use_direct))
        self._inflight: Dict[int, np.ndarray] = {}

    def submit_write(self, path: str, array: np.ndarray, offset: int = 0) -> int:
        arr = np.ascontiguousarray(array)
        req = self._lib.dsaio_submit(
            self._h, path.encode(), arr.ctypes.data_as(ctypes.c_void_p),
            arr.nbytes, offset, 1,
        )
        self._inflight[req] = arr
        return req

    def submit_read(self, path: str, array: np.ndarray, offset: int = 0) -> int:
        assert array.flags["C_CONTIGUOUS"], "read target must be contiguous"
        req = self._lib.dsaio_submit(
            self._h, path.encode(), array.ctypes.data_as(ctypes.c_void_p),
            array.nbytes, offset, 0,
        )
        self._inflight[req] = array
        return req

    def wait(self, req: int) -> None:
        rc = self._lib.dsaio_wait(self._h, req)
        self._inflight.pop(req, None)
        if rc != 0:
            raise OSError(-rc, os.strerror(-rc))

    def poll(self, req: int) -> bool:
        return bool(self._lib.dsaio_poll(self._h, req))

    def wait_all(self) -> None:
        for req in list(self._inflight):
            self.wait(req)

    def close(self) -> None:
        if self._h is not None:
            self.wait_all()
            self._lib.dsaio_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
