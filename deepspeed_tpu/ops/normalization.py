"""Normalization ops. XLA path here; Pallas fused kernels in ops/pallas/
register themselves on TPU (reference parity: csrc fused layer_norm kernels).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_USE_PALLAS = False
_scope_stack: list = []


def enable_pallas(flag: bool = True) -> None:
    """Process-wide default (tests); engines use the scoped form below."""
    global _USE_PALLAS
    _USE_PALLAS = flag


class pallas_rmsnorm_scope:
    """Scoped kernel selection (no global mutation): active while tracing an
    engine's step, so two engines with different tpu_kernels configs don't
    fight — same pattern as ops.attention.attention_impl."""

    def __init__(self, flag: bool):
        self.flag = bool(flag)

    def __enter__(self):
        _scope_stack.append(self.flag)
        return self

    def __exit__(self, *exc):
        _scope_stack.pop()


def rmsnorm(x: jax.Array, scale: jax.Array, eps: float = 1e-5) -> jax.Array:
    use_pallas = _scope_stack[-1] if _scope_stack else _USE_PALLAS
    if use_pallas:
        from .pallas.rmsnorm import rmsnorm as pallas_rmsnorm

        return pallas_rmsnorm(x, scale, eps)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * lax.rsqrt(var + eps) * scale


def layernorm(
    x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float = 1e-5
) -> jax.Array:
    """LayerNorm over the last dim; same scoped Pallas dispatch as rmsnorm
    (one tpu_kernels knob covers both norm flavors — a model uses only one)."""
    use_pallas = _scope_stack[-1] if _scope_stack else _USE_PALLAS
    if use_pallas:
        from .pallas.layernorm import layernorm as pallas_layernorm

        return pallas_layernorm(x, scale, bias, eps)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * lax.rsqrt(var + eps) * scale + bias
