"""Normalization ops. XLA path here; Pallas fused kernels in ops/pallas/
register themselves on TPU (reference parity: csrc fused layer_norm kernels).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_USE_PALLAS = False


def enable_pallas(flag: bool = True) -> None:
    global _USE_PALLAS
    _USE_PALLAS = flag


def rmsnorm(x: jax.Array, scale: jax.Array, eps: float = 1e-5) -> jax.Array:
    if _USE_PALLAS:
        from .pallas.rmsnorm import rmsnorm as pallas_rmsnorm

        return pallas_rmsnorm(x, scale, eps)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * lax.rsqrt(var + eps) * scale
