"""Pallas fused RMSNorm (fwd + custom-vjp bwd).

Parity: csrc/transformer layer-norm kernels (the reference fuses norm into
its transformer CUDA blocks). One VMEM pass per row-block computes the
mean-square and the normalized output; backward recomputes rstd and fuses
dx/dscale. XLA already fuses simple norms well, so the payoff is on long
rows (hidden >= 4k) where the fp32 accumulation + single HBM pass matters.

Layout: x [..., D] flattened to [rows, D]; D padded to 128 lanes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

BLOCK_ROWS = 256


def _fwd_kernel(x_ref, s_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    o_ref[:] = (x * rstd * s_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _bwd_kernel(x_ref, s_ref, g_ref, dx_ref, ds_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    s = s_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    D = x.shape[-1]
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    xhat = x * rstd
    gs = g * s
    # dx = rstd * (gs - xhat * mean(gs * xhat))
    dot = jnp.mean(gs * xhat, axis=-1, keepdims=True)
    dx_ref[:] = (rstd * (gs - xhat * dot)).astype(dx_ref.dtype)
    # dscale: TPU grid runs sequentially, so accumulate into one (8, D)
    # block (min sublane tile); host reads row 0
    @pl.when(pl.program_id(0) == 0)
    def _zero():
        ds_ref[:] = jnp.zeros_like(ds_ref)

    partial = jnp.sum(g * xhat, axis=0, keepdims=True)  # (1, D)
    ds_ref[:] = ds_ref[:] + jnp.broadcast_to(partial, ds_ref.shape)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pad_rows(a, block):
    """Zero-pad rows to a whole number of blocks: zero rows contribute zero
    to the dscale partial (g=0), so no masking is needed in-kernel."""
    rows = a.shape[0]
    pad = (-rows) % block
    return (jnp.pad(a, ((0, pad), (0, 0))) if pad else a), rows


def _run_fwd(x2, scale, eps):
    block = min(x2.shape[0], BLOCK_ROWS)
    x2, valid_rows = _pad_rows(x2, block)
    rows, D = x2.shape
    grid = (rows // block,)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block, D), lambda i: (i, 0)),
            pl.BlockSpec((1, D), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block, D), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, D), x2.dtype),
        interpret=_interpret(),
    )(x2, scale.reshape(1, D))[:valid_rows]


def _run_bwd(x2, scale, g2, eps):
    block = min(x2.shape[0], BLOCK_ROWS)
    x2, valid_rows = _pad_rows(x2, block)
    g2, _ = _pad_rows(g2, block)
    rows, D = x2.shape
    nblocks = rows // block
    dx, ds_acc = pl.pallas_call(
        functools.partial(_bwd_kernel, eps=eps),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((block, D), lambda i: (i, 0)),
            pl.BlockSpec((1, D), lambda i: (0, 0)),
            pl.BlockSpec((block, D), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block, D), lambda i: (i, 0)),
            pl.BlockSpec((8, D), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, D), x2.dtype),
            jax.ShapeDtypeStruct((8, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(x2, scale.reshape(1, D), g2)
    return dx[:valid_rows], ds_acc[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rmsnorm(x, scale, eps: float = 1e-5):
    """Fused RMSNorm over the last dim. x [..., D], scale [D]."""
    out, _ = _rmsnorm_fwd(x, scale, eps)
    return out


def _rmsnorm_fwd(x, scale, eps):
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    out = _run_fwd(x2, scale, eps)
    return out.reshape(shape), (x, scale)


def _rmsnorm_bwd(eps, res, g):
    x, scale = res
    shape = x.shape
    dx, ds = _run_bwd(
        x.reshape(-1, shape[-1]), scale, g.reshape(-1, shape[-1]), eps
    )
    return dx.reshape(shape), ds.astype(scale.dtype)


rmsnorm.defvjp(lambda x, s, eps: _rmsnorm_fwd(x, s, eps), _rmsnorm_bwd)
