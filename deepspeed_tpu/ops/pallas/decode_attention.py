"""Pallas cached-KV decode attention (single-token step).

Parity: csrc/transformer/inference attention kernels (the latency-critical
decode matvec). The XLA fallback (models/decoding.py) expands the GQA cache
to fp32 [B,Smax,H,hd] every step; this kernel streams the cache in its
storage dtype, one [block_s, hd] tile per grid step, with fp32 online
softmax in VMEM and per-tile predication that skips blocks beyond the
current cache length — so a 64-token cache in a 4096-slot buffer does 1/64
of the work.

Layouts: q [B, KV, G, hd] (G = H/KV query heads per cache head — the GQA
group shares one cache tile), k/v cache [B, Smax, KV, hd] (the engine's
storage layout; no transpose on the hot path). cache_len rides in SMEM.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...utils.jax_compat import pallas_tpu_compiler_params

_CompilerParams = pallas_tpu_compiler_params()

LANES = 128
NEG_INF = -1e30
DEFAULT_BLOCK_S = 256


def _tile_update(q, k, v, ks, vs, start, cl, scale, m_scr, l_scr, acc_scr):
    """One [block_s, hd] K/V tile's contribution to the fp32 online
    softmax (shared by the dense and paged kernels): dequantize when
    scales ride along, mask past the row's frontier, fold into the
    running (max, sum, acc) scratches."""
    if ks is not None:
        # int8 cache: dequantize the tile with its per-token scales
        k = (k.astype(jnp.float32) * ks[:, :1]).astype(q.dtype)
        v = (v.astype(jnp.float32) * vs[:, :1]).astype(q.dtype)
    elif k.dtype != q.dtype:
        # mixed storage (kv_cache_dtype="bf16" on an fp32 engine): the
        # MXU matmul needs matching operand dtypes
        k = k.astype(q.dtype)
        v = v.astype(q.dtype)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [G, block_s]
    kpos = start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(kpos <= cl, s, NEG_INF)

    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    m_safe = jnp.where(m_new <= NEG_INF, 0.0, m_new)
    p = jnp.exp(s - m_safe)
    corr = jnp.exp(m_prev - m_safe)
    l_scr[:] = jnp.broadcast_to(
        l_scr[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True), l_scr.shape
    )
    acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)


def _finalize_out(o_ref, l_scr, acc_scr):
    l = l_scr[:, :1]
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def _decode_kernel(*refs, scale, block_s, has_scales=False):
    if has_scales:
        (q_ref, k_ref, v_ref, ks_ref, vs_ref, cl_ref, o_ref,
         m_scr, l_scr, acc_scr) = refs
    else:
        q_ref, k_ref, v_ref, cl_ref, o_ref, m_scr, l_scr, acc_scr = refs
        ks_ref = vs_ref = None
    si = pl.program_id(2)
    ns = pl.num_programs(2)
    # this batch row's new-token position == its cached-token count (the
    # cl operand is per-row [B, 1]; the grid's b axis picks the row)
    cl = cl_ref[0, 0]

    @pl.when(si == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    start = si * block_s

    @pl.when(start <= cl)  # skip tiles entirely past the live cache
    def _body():
        _tile_update(
            q_ref[0, 0], k_ref[0], v_ref[0],
            ks_ref[0, 0] if has_scales else None,
            vs_ref[0, 0] if has_scales else None,
            start, cl, scale, m_scr, l_scr, acc_scr,
        )

    @pl.when(si == ns - 1)
    def _finalize():
        _finalize_out(o_ref, l_scr, acc_scr)


def _paged_decode_kernel(*refs, scale, page_size, has_scales=False):
    """Paged twin of :func:`_decode_kernel`: the grid's third axis walks a
    slot's LOGICAL pages; the page table rides as a scalar-prefetch
    operand so the BlockSpec index maps fetch each physical K/V page
    directly from the pool — no per-slot contiguous view ever
    materializes in HBM. Per-row frontier predication is unchanged
    (logical position = si * page_size + offset)."""
    if has_scales:
        (pt_ref, cl_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
         m_scr, l_scr, acc_scr) = refs
    else:
        (pt_ref, cl_ref, q_ref, k_ref, v_ref, o_ref,
         m_scr, l_scr, acc_scr) = refs
        ks_ref = vs_ref = None
    del pt_ref  # consumed by the index maps
    b = pl.program_id(0)
    si = pl.program_id(2)
    ns = pl.num_programs(2)
    cl = cl_ref[b]

    @pl.when(si == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    start = si * page_size

    @pl.when(start <= cl)  # pages past the frontier are unmapped — skip
    def _body():
        _tile_update(
            q_ref[0, 0], k_ref[0], v_ref[0],
            ks_ref[0, 0] if has_scales else None,
            vs_ref[0, 0] if has_scales else None,
            start, cl, scale, m_scr, l_scr, acc_scr,
        )

    @pl.when(si == ns - 1)
    def _finalize():
        _finalize_out(o_ref, l_scr, acc_scr)


def _pick_block(S: int, preferred: int) -> Optional[int]:
    for cand in (preferred, 512, 256, 128):
        if cand <= S and S % cand == 0:
            return cand
    return S if S % 8 == 0 else None


def decode_attention_kernel(q, k_cache, v_cache, cache_len, *,
                            k_scale=None, v_scale=None,
                            block_s: int = DEFAULT_BLOCK_S,
                            interpret: Optional[bool] = None):
    """q [B,1,H,hd] new-token queries vs k/v_cache [B,Smax,KV,hd].

    cache_len: int32 scalar — or a per-row [B] vector for ragged serving
    slot batches — the new token's position (tokens already cached).
    Returns [B,1,H,hd]. Caller guarantees the new token's k/v are already
    written at ``cache_len``. int8 caches pass per-token scales in the
    storage layout [B,KV,Smax,SCALE_LANES]; dequant happens on the tile
    in VMEM.
    """
    B, one, H, hd = q.shape
    assert one == 1, "decode kernel is single-token"
    Smax, KV = k_cache.shape[1], k_cache.shape[2]
    G = H // KV
    bs = _pick_block(Smax, block_s)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = 1.0 / (hd**0.5)
    qg = q.reshape(B, KV, G, hd)
    # per-row [B, 1] in SMEM: scalars broadcast so every row predicates
    # on the same frontier, serving batches bring one frontier per slot
    cl = jnp.broadcast_to(
        jnp.asarray(cache_len, jnp.int32).reshape(-1, 1), (B, 1)
    )
    ns = Smax // bs
    has_scales = k_scale is not None

    # The TPU lowering requires each block's last-two dims to be (8,128)-
    # divisible or equal to the array dims, so a per-head [bs, hd] tile of a
    # [B, Smax, KV, hd] cache is illegal (head block 1 < KV). Instead view
    # the cache as [B, Smax, KV*hd] — a free contiguous reshape — and slice
    # head kv as the hd-wide column block at index kv, which is lane-aligned
    # whenever hd % 128 == 0 (or KV == 1, where the block spans the row).
    operands = [
        qg,
        k_cache.reshape(B, Smax, KV * hd),
        v_cache.reshape(B, Smax, KV * hd),
    ]
    in_specs = [
        pl.BlockSpec((1, 1, G, hd), lambda b, kv, si: (b, kv, 0, 0)),
        pl.BlockSpec((1, bs, hd), lambda b, kv, si: (b, si, kv)),
        pl.BlockSpec((1, bs, hd), lambda b, kv, si: (b, si, kv)),
    ]
    if has_scales:
        # scales arrive pre-transposed as [B, KV, Smax, SL] (the cache's
        # storage layout — see models/decoding.init_cache), giving a legal
        # (bs, SL) trailing block (SL equals the array dim) with no
        # per-token relayout on the decode path
        SL = k_scale.shape[-1]
        operands += [k_scale, v_scale]
        in_specs += [
            pl.BlockSpec((1, 1, bs, SL), lambda b, kv, si: (b, kv, si, 0)),
            pl.BlockSpec((1, 1, bs, SL), lambda b, kv, si: (b, kv, si, 0)),
        ]
    operands.append(cl)
    in_specs.append(
        pl.BlockSpec((1, 1), lambda b, kv, si: (b, 0), memory_space=pltpu.SMEM)
    )

    out = pl.pallas_call(
        functools.partial(
            _decode_kernel, scale=scale, block_s=bs, has_scales=has_scales
        ),
        grid=(B, KV, ns),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, G, hd), lambda b, kv, si: (b, kv, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, KV, G, hd), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((G, LANES), jnp.float32),
            pltpu.VMEM((G, LANES), jnp.float32),
            pltpu.VMEM((G, hd), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)
    return out.reshape(B, 1, H, hd)


def paged_decode_attention_kernel(q, k_pool, v_pool, cache_len, page_table,
                                  *, k_scale=None, v_scale=None,
                                  interpret: Optional[bool] = None):
    """q [B,1,H,hd] new-token queries vs a block-paged KV pool
    k/v_pool [P+1, page_size, KV, hd] addressed through per-slot page
    tables [B, max_pages] (int32 physical page per logical page; unmapped
    entries point at the NULL page and are predicated off by the
    frontier). ``cache_len`` is the per-row [B] frontier. The page table
    and frontier ride as scalar-prefetch operands
    (pltpu.PrefetchScalarGridSpec) so the block index maps gather each
    K/V page straight from the pool — the paged analogue of vLLM's
    block-table attention, per-row online softmax unchanged. int8 pools
    pass per-token scales [P+1, KV, page_size, SCALE_LANES].
    """
    B, one, H, hd = q.shape
    assert one == 1, "paged decode kernel is single-token"
    P1, ps, KV = k_pool.shape[0], k_pool.shape[1], k_pool.shape[2]
    mp = page_table.shape[1]
    G = H // KV
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = 1.0 / (hd**0.5)
    qg = q.reshape(B, KV, G, hd)
    pt = jnp.asarray(page_table, jnp.int32)
    cl = jnp.broadcast_to(
        jnp.asarray(cache_len, jnp.int32).reshape(-1), (B,)
    )
    has_scales = k_scale is not None

    # flat head-column view of the pool (same lane-alignment contract as
    # the dense kernel); a (1, ps, hd) block's trailing dims equal the
    # array dims, so any 8-aligned page_size tiles legally
    operands = [
        qg,
        k_pool.reshape(P1, ps, KV * hd),
        v_pool.reshape(P1, ps, KV * hd),
    ]
    in_specs = [
        pl.BlockSpec((1, 1, G, hd), lambda b, kv, si, pt, cl: (b, kv, 0, 0)),
        pl.BlockSpec((1, ps, hd),
                     lambda b, kv, si, pt, cl: (pt[b, si], 0, kv)),
        pl.BlockSpec((1, ps, hd),
                     lambda b, kv, si, pt, cl: (pt[b, si], 0, kv)),
    ]
    if has_scales:
        SL = k_scale.shape[-1]
        operands += [k_scale, v_scale]
        in_specs += [
            pl.BlockSpec((1, 1, ps, SL),
                         lambda b, kv, si, pt, cl: (pt[b, si], kv, 0, 0)),
            pl.BlockSpec((1, 1, ps, SL),
                         lambda b, kv, si, pt, cl: (pt[b, si], kv, 0, 0)),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page_table, cache_len
        grid=(B, KV, mp),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, G, hd),
                               lambda b, kv, si, pt, cl: (b, kv, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((G, LANES), jnp.float32),
            pltpu.VMEM((G, LANES), jnp.float32),
            pltpu.VMEM((G, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_decode_kernel, scale=scale, page_size=ps,
            has_scales=has_scales,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, G, hd), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(pt, cl, *operands)
    return out.reshape(B, 1, H, hd)


def decode_attention(q, k_cache, v_cache, cache_len, *,
                     k_scale=None, v_scale=None, page_table=None,
                     interpret: Optional[bool] = None):
    """Shard-map-aware wrapper: cache heads over tp, batch over dp/fsdp —
    mirrors flash_attention's serving layout. Returns None if the shapes
    don't fit the kernel (caller falls back to the XLA matvec).

    ``page_table`` [B, max_pages] switches to the block-paged form:
    k/v_cache are then page POOLS [P+1, page_size, KV, hd] (int8 scales
    [P+1, KV, page_size, SL]) and the kernel gathers pages through the
    table instead of streaming a contiguous per-slot region."""
    from ...models.sharding import current_topology

    B, one, H, hd = q.shape
    paged = page_table is not None
    if paged:
        ps, KV = k_cache.shape[1], k_cache.shape[2]
        Smax = page_table.shape[1] * ps
    else:
        Smax, KV = k_cache.shape[1], k_cache.shape[2]
    topo = current_topology()
    distributed = topo is not None and topo.world_size > 1
    tp = topo.tp_size if distributed else 1
    interp = interpret if interpret is not None else (
        jax.default_backend() != "tpu"
    )
    reasons = []
    if one != 1:
        reasons.append(f"{one} query tokens (kernel is single-token)")
    if H % KV != 0:
        reasons.append(f"H={H} not a multiple of KV={KV}")
    if hd % 8 != 0:
        reasons.append(f"head_dim {hd} not 8-aligned")
    if paged and ps % 8 != 0:
        reasons.append(f"page_size {ps} not 8-aligned")
    if not paged and _pick_block(Smax, DEFAULT_BLOCK_S) is None:
        reasons.append(f"cache length {Smax} has no 8-aligned block")
    if not interp and hd % LANES != 0 and KV // max(tp, 1) != 1:
        # the flat head-column view needs lane-aligned per-head offsets on
        # the real TPU lowering (interpret mode has no such constraint)
        reasons.append(
            f"head_dim {hd} not {LANES}-aligned with {KV // max(tp, 1)} "
            "local cache heads"
        )
    if distributed and (H % tp != 0 or KV % tp != 0):
        reasons.append(f"H={H}/KV={KV} not divisible by tp={tp}")
    elif distributed and (H // tp) % max(KV // tp, 1) != 0:
        reasons.append(f"GQA group uneven under tp={tp}")
    if reasons:
        from ...utils.logging import log_fallback_once

        log_fallback_once("decode_attention", reasons)
        return None

    if not distributed:
        if paged:
            return paged_decode_attention_kernel(
                q, k_cache, v_cache, cache_len, page_table,
                k_scale=k_scale, v_scale=v_scale, interpret=interp,
            )
        return decode_attention_kernel(
            q, k_cache, v_cache, cache_len,
            k_scale=k_scale, v_scale=v_scale, interpret=interp,
        )

    from jax.sharding import PartitionSpec as P

    from ...utils.jax_compat import shard_map

    batch_axes = tuple(a for a in ("dp", "fsdp") if topo.sizes[a] > 1)
    b_ax = batch_axes if batch_axes else None
    h_ax = "tp" if tp > 1 else None
    has_scales = k_scale is not None
    if paged:
        # page pools are slot-agnostic: heads over tp, pages replicated;
        # the table and frontier ride with the (slot) batch
        kv_spec = P(None, None, h_ax, None)
        scale_spec = P(None, h_ax, None, None)
        q_spec = P(b_ax, None, h_ax, None)
    else:
        kv_spec = P(b_ax, None, h_ax, None)
        scale_spec = P(b_ax, h_ax, None, None)
        q_spec = P(b_ax, None, h_ax, None)
    operands = [q, k_cache, v_cache]
    in_specs = [q_spec, kv_spec, kv_spec]
    if has_scales:
        # dense scales are [B, KV, Smax, SL] (head dim 1 follows tp);
        # paged scales [P+1, KV, ps, SL] shard the same head dim
        operands += [k_scale, v_scale]
        in_specs += [scale_spec, scale_spec]
    # the frontier rides as a per-row [B] vector sharded with the batch
    # (a scalar cache_len broadcasts — every shard sees the same value)
    operands.append(jnp.broadcast_to(
        jnp.asarray(cache_len, jnp.int32).reshape(-1), (B,)
    ))
    in_specs.append(P(b_ax))
    if paged:
        operands.append(jnp.asarray(page_table, jnp.int32))
        in_specs.append(P(b_ax, None))

    def body(q, kc, vc, *rest):
        rest = list(rest)
        pt = rest.pop() if paged else None
        if has_scales:
            ks, vs, cl = rest
        else:
            (cl,) = rest
            ks = vs = None
        if paged:
            return paged_decode_attention_kernel(
                q, kc, vc, cl, pt,
                k_scale=ks, v_scale=vs, interpret=interp,
            )
        return decode_attention_kernel(
            q, kc, vc, cl, k_scale=ks, v_scale=vs, interpret=interp
        )

    return shard_map(
        body,
        mesh=topo.mesh,
        in_specs=tuple(in_specs),
        out_specs=P(b_ax, None, h_ax, None),
        check_vma=False,
    )(*operands)
